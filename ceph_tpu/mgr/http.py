"""mgr HTTP frontends: the prometheus exporter endpoint and the
restful module's JSON API (pybind/mgr/prometheus/module.py serving
/metrics on its own port; pybind/mgr/restful/ read surface).

``MgrHttp.handle()`` is a pure (method, path) -> (status, headers,
body) function like the rgw frontend, so the routes are testable
without sockets; ``serve()`` wraps it in a threaded stdlib server.

Read surface (restful module's GET routes at lite scale):
  /metrics          prometheus text exposition
  /health           {"health": ..., "checks": {...}}
  /mon              monmap entries
  /osd              per-osd up/in/weight/stats
  /osd/<id>         one osd
  /pool             pools with pg/size/flags
  /pool/<id>        one pool
  /pg               pg summary by state
  /crush/rule       crush rules
  /server           the hosting daemon list (mon/mgr names)
  /request          the balancer's proposal history (the command-log
                    role; read-only here)
"""
from __future__ import annotations

import json
from typing import Dict, Optional, Tuple


class MgrHttp:
    def __init__(self, mgr, cluster=None, perf_collection=None):
        self.mgr = mgr
        self.cluster = cluster
        self.perf_collection = perf_collection

    # ---- route table -------------------------------------------------------
    def handle(self, method: str, path: str,
               headers: Optional[Dict[str, str]] = None,
               body: bytes = b"",
               query: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, str], bytes]:
        if method != "GET":
            return self._err(405, "method not allowed")
        parts = [p for p in path.split("/") if p]

        def one_id() -> Optional[int]:
            # the single <id> segment; None -> caller 400s
            try:
                return int(parts[1])
            except ValueError:
                return None

        if parts == ["metrics"]:
            from ..common import g_kernel_timer
            from ..fault import g_breakers
            from ..trace import g_perf_histograms
            slow = {o.name: o.op_tracker.num_slow_ops
                    for o in self.cluster.osds.values()} \
                if self.cluster is not None else None
            self.mgr.check_degraded_codecs()
            text = self.mgr.prometheus_metrics(
                self.perf_collection, histograms=g_perf_histograms,
                kernel_timer=g_kernel_timer, slow_ops=slow,
                breakers=g_breakers)
            return 200, {"Content-Type":
                         "text/plain; version=0.0.4"}, text.encode()
        if not parts or parts == ["health"]:
            return self._json(self._health())
        if parts == ["mon"]:
            return self._json(self._mons())
        if parts == ["osd"]:
            return self._json(self._osds())
        if parts[0] == "osd" and len(parts) == 2:
            oid = one_id()
            if oid is None:
                return self._err(400, "bad id")
            want = [o for o in self._osds() if o["osd"] == oid]
            if not want:
                return self._err(404, "no such osd")
            return self._json(want[0])
        if parts == ["pool"]:
            return self._json(self._pools())
        if parts[0] == "pool" and len(parts) == 2:
            pid = one_id()
            if pid is None:
                return self._err(400, "bad id")
            want = [p for p in self._pools() if p["pool"] == pid]
            if not want:
                return self._err(404, "no such pool")
            return self._json(want[0])
        if parts == ["pg"]:
            return self._json(self._pgs())
        if parts == ["crush", "rule"]:
            return self._json(self._crush_rules())
        if parts == ["server"]:
            return self._json(self._servers())
        if parts == ["request"]:
            return self._json(self.mgr.proposal_log)
        return self._err(404, "unknown route")

    # ---- renderers ---------------------------------------------------------
    @staticmethod
    def _json(doc) -> Tuple[int, Dict[str, str], bytes]:
        return 200, {"Content-Type": "application/json"}, \
            (json.dumps(doc, indent=2, sort_keys=True) + "\n").encode()

    @staticmethod
    def _err(status: int, msg: str) -> Tuple[int, Dict[str, str],
                                             bytes]:
        return status, {"Content-Type": "application/json"}, \
            (json.dumps({"error": msg}) + "\n").encode()

    def _health(self):
        s = self.mgr.status()
        checks = dict(s["health_checks"])
        if self.cluster is not None:
            # the cluster-wide verdict carries its reason; surface it
            # machine-readably too so 'health' and 'checks' agree
            health = self.cluster.health()
            if health != "HEALTH_OK" and "CLUSTER" not in checks:
                checks["CLUSTER"] = health
        else:
            health = "HEALTH_OK" if not checks else "HEALTH_WARN"
        return {"health": health, "checks": checks,
                "epoch": s["epoch"]}

    def _mons(self):
        mon = self.mgr.mon
        mm = getattr(mon, "monmap", None)
        if mm is None:
            return [{"name": mon.name, "rank": 0}]
        return [{"name": n, "addr": a, "rank": r}
                for r, (n, a) in enumerate(mm.ranks())]

    def _osds(self):
        m = self.mgr.osdmap
        out = []
        for o in range(m.max_osd):
            if not m.exists(o):
                continue
            stats = self.mgr.osd_stats.get(o)
            ent = {"osd": o, "up": int(m.is_up(o)),
                   "in": int(m.osd_weight[o] > 0),
                   "weight": m.osd_weight[o] / 0x10000}
            if stats:
                ent["store_bytes"], ent["store_capacity"] = stats
            out.append(ent)
        return out

    def _pools(self):
        m = self.mgr.osdmap
        out = []
        for pid, pool in sorted(m.pools.items()):
            out.append({
                "pool": pid, "pool_name": m.pool_name.get(pid, ""),
                "type": "erasure" if pool.is_erasure()
                        else "replicated",
                "size": pool.size, "min_size": pool.min_size,
                "pg_num": pool.pg_num, "pgp_num": pool.pgp_num,
                "crush_rule": pool.crush_rule,
                "erasure_code_profile": pool.erasure_code_profile,
            })
        return out

    def _pgs(self):
        states = self.cluster.pg_states() \
            if self.cluster is not None else {}
        return {"pg_states": states,
                "num_pgs": sum(p.pg_num for p in
                               self.mgr.osdmap.pools.values())}

    def _crush_rules(self):
        cw = self.mgr.osdmap.crush
        out = []
        for i, r in enumerate(cw.crush.rules):
            if r is None:
                continue
            out.append({"rule_id": i,
                        "rule_name": cw.rule_name_map.get(i, f"rule{i}"),
                        "steps": len(r.steps)})
        return out

    def _servers(self):
        names = [self.mgr.name]
        mon = self.mgr.mon
        mm = getattr(mon, "monmap", None)
        if mm is not None:
            names += [n for n, _ in mm.ranks()]
        else:
            names.append(mon.name)
        return [{"hostname": n} for n in names]


def serve(frontend: MgrHttp, port: int = 0):
    """Threaded stdlib HTTP server; returns (server, port)."""
    from ..common.http_serve import serve_frontend
    return serve_frontend(frontend.handle, port)
