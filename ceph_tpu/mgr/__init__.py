from .mgr import Manager
from .telemetry import (SLO_ADMISSION, SLO_CHECKS, SLO_COPY, SLO_OPLAT,
                        Telemetry)

__all__ = ["Manager", "Telemetry", "SLO_OPLAT", "SLO_COPY",
           "SLO_ADMISSION", "SLO_CHECKS"]
