from .mgr import Manager

__all__ = ["Manager"]
