"""Symmetric crypto for the auth tier, from hashlib primitives only.

The reference's cephx uses AES-CBC via its crypto plugins
(src/auth/Crypto.cc); this environment ships no AES bindings, so the
equivalent here is a SHA-256 keystream cipher with encrypt-then-MAC:

    ct  = nonce || (plaintext XOR keystream(key, nonce))
    tag = HMAC-SHA256(key, ct)[:16]

The keystream blocks are SHA256(key || nonce || counter); the MAC makes
the blob tamper-evident, which is the property every protocol check in
cephx.py rests on (a forged or bit-flipped ticket fails decrypt()).
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
import os
import struct


class AuthError(Exception):
    """Authentication failure (EACCES role)."""


SECRET_LEN = 16
NONCE_LEN = 16
TAG_LEN = 16


def make_secret() -> bytes:
    return os.urandom(SECRET_LEN)


def hmac_tag(key: bytes, data: bytes, n: int = TAG_LEN) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()[:n]


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = bytearray()
    ctr = 0
    while len(out) < n:
        out.extend(hashlib.sha256(
            key + nonce + struct.pack("<Q", ctr)).digest())
        ctr += 1
    return bytes(out[:n])


def encrypt(key: bytes, plaintext: bytes) -> bytes:
    nonce = os.urandom(NONCE_LEN)
    ct = nonce + bytes(a ^ b for a, b in
                       zip(plaintext, _keystream(key, nonce,
                                                 len(plaintext))))
    return ct + hmac_tag(key, ct)


def decrypt(key: bytes, blob: bytes) -> bytes:
    if len(blob) < NONCE_LEN + TAG_LEN:
        raise AuthError("auth blob truncated")
    ct, tag = blob[:-TAG_LEN], blob[-TAG_LEN:]
    if not _hmac.compare_digest(hmac_tag(key, ct), tag):
        raise AuthError("auth blob failed integrity check")
    nonce, body = ct[:NONCE_LEN], ct[NONCE_LEN:]
    return bytes(a ^ b for a, b in
                 zip(body, _keystream(key, nonce, len(body))))
