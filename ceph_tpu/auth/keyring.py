"""Keyring: entity name -> base64 secret (src/auth/KeyRing.cc role).

File format mirrors the reference's keyring ini shape::

    [osd.0]
        key = <base64>

The mon process loads the full keyring (it is the KDC); every other
daemon/client needs only its own entry.
"""
from __future__ import annotations

import base64
from typing import Dict, Optional

from .crypto import make_secret


class Keyring:
    def __init__(self) -> None:
        self.keys: Dict[str, bytes] = {}
        # entity -> {subsystem: capability string}; written as the
        # reference's `caps <subsys> = "<grant>"` keyring lines
        self.caps: Dict[str, Dict[str, str]] = {}

    def create(self, entity: str) -> bytes:
        """Generate-or-get a secret for *entity* (ceph auth get-or-create)."""
        if entity not in self.keys:
            self.keys[entity] = make_secret()
        return self.keys[entity]

    def get(self, entity: str) -> Optional[bytes]:
        return self.keys.get(entity)

    def set_caps(self, entity: str, caps: Dict[str, str]) -> None:
        """Replace the entity's full cap set (KeyRing::set_caps — the
        reference's --cap replaces all previous caps, cap-overwrite.t)."""
        self.caps[entity] = dict(caps)

    # ---- file io -----------------------------------------------------------
    def lines(self) -> list:
        out = []
        for entity in sorted(self.keys):
            out.append(f"[{entity}]")
            key64 = base64.b64encode(self.keys[entity]).decode()
            out.append(f"\tkey = {key64}")
            for subsys in sorted(self.caps.get(entity, {})):
                out.append(f'\tcaps {subsys} = '
                           f'"{self.caps[entity][subsys]}"')
        return out

    def save(self, path: str) -> None:
        lines = self.lines()
        with open(path, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))

    @classmethod
    def load(cls, path: str) -> "Keyring":
        kr = cls()
        entity = None
        with open(path) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("[") and line.endswith("]"):
                    entity = line[1:-1]
                elif "=" in line and entity is not None:
                    k, v = (s.strip() for s in line.split("=", 1))
                    if k == "key":
                        kr.keys[entity] = base64.b64decode(v)
                    elif k.startswith("caps "):
                        subsys = k[len("caps "):].strip()
                        kr.caps.setdefault(entity, {})[subsys] = \
                            v.strip().strip('"')
        return kr
