"""Keyring: entity name -> base64 secret (src/auth/KeyRing.cc role).

File format mirrors the reference's keyring ini shape::

    [osd.0]
        key = <base64>

The mon process loads the full keyring (it is the KDC); every other
daemon/client needs only its own entry.
"""
from __future__ import annotations

import base64
from typing import Dict, Optional

from .crypto import make_secret


class Keyring:
    def __init__(self) -> None:
        self.keys: Dict[str, bytes] = {}

    def create(self, entity: str) -> bytes:
        """Generate-or-get a secret for *entity* (ceph auth get-or-create)."""
        if entity not in self.keys:
            self.keys[entity] = make_secret()
        return self.keys[entity]

    def get(self, entity: str) -> Optional[bytes]:
        return self.keys.get(entity)

    # ---- file io -----------------------------------------------------------
    def save(self, path: str) -> None:
        lines = []
        for entity in sorted(self.keys):
            lines.append(f"[{entity}]")
            key64 = base64.b64encode(self.keys[entity]).decode()
            lines.append(f"\tkey = {key64}")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")

    @classmethod
    def load(cls, path: str) -> "Keyring":
        kr = cls()
        entity = None
        with open(path) as f:
            for raw in f:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                if line.startswith("[") and line.endswith("]"):
                    entity = line[1:-1]
                elif "=" in line and entity is not None:
                    k, v = (s.strip() for s in line.split("=", 1))
                    if k == "key":
                        kr.keys[entity] = base64.b64decode(v)
        return kr
