"""cephx-style protocol: KDC, tickets, authorizers, rotating keys.

Shape mirrors the reference (src/auth/cephx/CephxProtocol.h,
CephxKeyServer in src/auth/cephx/CephxKeyServer.cc):

1. **Authenticate to the mon (KDC).**  Challenge/response: the client
   proves knowledge of its keyring secret with
   ``proof = HMAC(secret, server_challenge || client_challenge)``
   (CEPHX_GET_AUTH_SESSION_KEY role).  The reply — encrypted with the
   entity secret — carries the mon session key, one (session_key,
   ticket) pair per reachable service, and, for daemon entities, the
   rotating per-service secrets (so an OSD can verify tickets minted
   for the "osd" service without calling home).
2. **Connect to a service.**  The connector presents an authorizer:
   the opaque ticket (encrypted with the service's rotating secret —
   the connector cannot read or forge it) plus a nonce proof under the
   ticket's session key.  The service decrypts the ticket, checks the
   proof and expiry, and answers ``HMAC(session_key, nonce+1)`` so the
   connector knows the service really holds the rotating secret
   (mutual auth, CephxAuthorizeHandler role).
3. Every subsequent wire frame is HMAC-signed with the connection's
   session key (cephx_sign_messages; applied in msg/tcp.py).

Rotating secrets follow KeyServer's current/next pair per service and
carry numeric ids so tickets survive one rotation.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Dict, Optional, Tuple

from ..msg.wire import decode_blob, encode_blob
from .crypto import AuthError, decrypt, encrypt, hmac_tag, make_secret
from .keyring import Keyring

TICKET_TTL = 3600.0          # auth_service_ticket_ttl
ROTATION_PERIOD = 3600.0     # auth_rotating_secrets period
CHALLENGE_TTL = 60.0
MAX_CHALLENGES = 1024        # un-authed HELLO floods evict the oldest
RENEW_MARGIN = 60.0          # re-run the KDC exchange this early
# "client" is a ticket-bearing service here (unlike the reference)
# because replies flow over daemon->client connections in this
# transport, so clients must verify inbound connecting daemons too.
SERVICES = ("mon", "osd", "mgr", "client")


def entity_service(entity: str) -> str:
    """osd.3 -> osd; client.x -> client."""
    return entity.split(".", 1)[0]


def _nonce_reply(n: int) -> bytes:
    return struct.pack("<Q", (n + 1) & 0xFFFFFFFFFFFFFFFF)


class CephxServer:
    """The KDC, hosted by the monitor's transport (AuthMonitor +
    CephxKeyServer role).  Holds the full keyring and mints tickets."""

    def __init__(self, keyring: Keyring,
                 rotation_period: float = ROTATION_PERIOD,
                 ticket_ttl: float = TICKET_TTL):
        self.keyring = keyring
        self.rotation_period = rotation_period
        self.ticket_ttl = ticket_ttl
        # service -> {secret_id: (secret, expires)}; current = max id
        self.rotating: Dict[str, Dict[int, Tuple[bytes, float]]] = {}
        self._challenges: Dict[bytes, Tuple[str, float]] = {}
        now = time.time()
        for svc in SERVICES:
            self.rotating[svc] = {1: (make_secret(),
                                      now + 2 * rotation_period)}

    # ---- rotating secrets (KeyServer::_rotate_secret) ----------------------
    def current_secret(self, service: str) -> Tuple[int, bytes]:
        sid = max(self.rotating[service])
        return sid, self.rotating[service][sid][0]

    def rotate(self, now: Optional[float] = None) -> None:
        """Mint the next secret per service; drop fully expired ones."""
        now = time.time() if now is None else now
        for svc, secrets in self.rotating.items():
            sid = max(secrets) + 1
            secrets[sid] = (make_secret(), now + 2 * self.rotation_period)
            for old in [i for i, (_, exp) in secrets.items() if exp <= now]:
                del secrets[old]

    def rotating_bundle(self, service: str) -> Dict:
        """The secrets a daemon of *service* needs to verify tickets."""
        return {sid: [sec, exp]
                for sid, (sec, exp) in self.rotating[service].items()}

    # ---- phase 1: challenge ------------------------------------------------
    def get_challenge(self, entity: str,
                      now: Optional[float] = None) -> bytes:
        """Raises AuthError for entities not in the keyring, and sweeps
        expired challenges, so un-authed HELLO floods can't grow state."""
        now = time.time() if now is None else now
        if self.keyring.get(entity) is None:
            raise AuthError(f"unknown entity {entity!r}")
        for stale in [c for c, (_, exp) in self._challenges.items()
                      if exp < now]:
            del self._challenges[stale]
        # hard cap: a flood inside the TTL evicts its own oldest
        # entries instead of growing mon memory (legit exchanges
        # complete in milliseconds and are unaffected)
        while len(self._challenges) >= MAX_CHALLENGES:
            del self._challenges[next(iter(self._challenges))]
        ch = os.urandom(16)
        self._challenges[ch] = (entity, now + CHALLENGE_TTL)
        return ch

    # ---- phase 2: proof -> session key + tickets ---------------------------
    def authenticate(self, entity: str, server_challenge: bytes,
                     client_challenge: bytes, proof: bytes,
                     now: Optional[float] = None) -> bytes:
        """Verify the proof; return the encrypted auth reply blob.

        Raises AuthError on unknown entity, stale/foreign challenge, or
        a proof that doesn't match the keyring secret.
        """
        now = time.time() if now is None else now
        secret = self.keyring.get(entity)
        if secret is None:
            raise AuthError(f"unknown entity {entity!r}")
        known = self._challenges.pop(server_challenge, None)
        if known is None or known[0] != entity or known[1] < now:
            raise AuthError("stale or foreign server challenge")
        expect = hmac_tag(secret, server_challenge + client_challenge)
        if proof != expect:
            raise AuthError(f"bad proof for {entity!r}")
        # mint per-service session keys + tickets
        tickets: Dict[str, Dict] = {}
        for svc in SERVICES:
            session_key = make_secret()
            sid, svc_secret = self.current_secret(svc)
            ticket = encrypt(svc_secret, encode_blob({
                "entity": entity,
                "session_key": session_key,
                "expires": now + self.ticket_ttl,
            }))
            # "expires" rides in the clear too so the CLIENT knows
            # when to renew (the authoritative copy stays encrypted)
            tickets[svc] = {"session_key": session_key,
                            "secret_id": sid, "ticket": ticket,
                            "expires": now + self.ticket_ttl}
        reply: Dict = {"tickets": tickets}
        svc = entity_service(entity)
        if svc in SERVICES:   # daemons get their service's rotating keys
            reply["rotating"] = {svc: self.rotating_bundle(svc)}
        return encrypt(secret, encode_blob(reply))


class CephxClient:
    """Per-entity client state: proves itself to the KDC, builds
    authorizers for service connections (CephxClientHandler role)."""

    def __init__(self, entity: str, secret: bytes):
        self.entity = entity
        self.secret = secret
        self.tickets: Dict[str, Dict] = {}
        self.rotating: Dict[str, Dict[int, Tuple[bytes, float]]] = {}
        self._client_challenge: Optional[bytes] = None

    # ---- KDC exchange ------------------------------------------------------
    def make_proof(self, server_challenge: bytes) -> Tuple[bytes, bytes]:
        """-> (client_challenge, proof) for the server's challenge."""
        self._client_challenge = os.urandom(16)
        proof = hmac_tag(self.secret,
                         server_challenge + self._client_challenge)
        return self._client_challenge, proof

    def handle_reply(self, blob: bytes) -> None:
        reply = decode_blob(decrypt(self.secret, blob))
        self.tickets = reply["tickets"]
        for svc, bundle in reply.get("rotating", {}).items():
            self.rotating[svc] = {int(sid): (sec, exp)
                                  for sid, (sec, exp) in bundle.items()}

    def authenticated(self) -> bool:
        return bool(self.tickets)

    def needs_renewal(self, now: Optional[float] = None) -> bool:
        """True when any held ticket is at/near expiry — time to re-run
        the KDC exchange (RotatingKeyRing renewal role)."""
        if not self.tickets:
            return True
        now = time.time() if now is None else now
        return any(t.get("expires", 0.0) <= now + RENEW_MARGIN
                   for t in self.tickets.values())

    # ---- service connections ----------------------------------------------
    def build_authorizer(self, service: str,
                         challenge: bytes = b"") -> Tuple[Dict, bytes, int]:
        """-> (authorizer dict, session_key, nonce).

        *challenge* is the connection-specific server challenge mixed
        into the proof so a recorded authorizer cannot re-authenticate
        a new connection (the CVE-2018-1128 fix in real cephx).  The
        caller checks the service's reply via
        ``check_authorizer_reply``."""
        t = self.tickets.get(service)
        if t is None:
            raise AuthError(f"no ticket for service {service!r}")
        # 63-bit so the nonce survives the signed-int64 wire codec
        nonce = struct.unpack("<Q", os.urandom(8))[0] >> 1
        sk = t["session_key"]
        auth = {
            "entity": self.entity,
            "service": service,
            "secret_id": t["secret_id"],
            "ticket": t["ticket"],
            "nonce": nonce,
            "proof": hmac_tag(sk, struct.pack("<Q", nonce) + challenge),
        }
        return auth, sk, nonce

    @staticmethod
    def check_authorizer_reply(session_key: bytes, nonce: int,
                               reply: bytes) -> bool:
        return reply == hmac_tag(session_key, _nonce_reply(nonce))


class CephxServiceVerifier:
    """Service-side ticket verification from rotating secrets
    (CephxAuthorizeHandler::verify_authorizer role)."""

    def __init__(self, service: str,
                 rotating: Dict[int, Tuple[bytes, float]]):
        self.service = service
        self.rotating = dict(rotating)

    def update_rotating(self,
                        rotating: Dict[int, Tuple[bytes, float]]) -> None:
        self.rotating.update(rotating)

    def verify_authorizer(self, auth: Dict,
                          challenge: bytes = b"",
                          now: Optional[float] = None
                          ) -> Tuple[str, bytes, bytes]:
        """-> (entity, session_key, reply_proof); raises AuthError.

        *challenge* must be the value this service issued for THIS
        connection; a replayed authorizer fails the proof check."""
        now = time.time() if now is None else now
        if auth.get("service") != self.service:
            raise AuthError("authorizer for a different service")
        entry = self.rotating.get(int(auth.get("secret_id", -1)))
        if entry is None:
            raise AuthError("unknown rotating secret id "
                            f"{auth.get('secret_id')!r}")
        ticket = decode_blob(decrypt(entry[0], auth["ticket"]))
        if ticket["expires"] < now:
            raise AuthError(f"expired ticket for {ticket['entity']!r}")
        sk = ticket["session_key"]
        nonce = int(auth["nonce"])
        expect = hmac_tag(sk, struct.pack("<Q", nonce) + challenge)
        if auth.get("proof") != expect:
            raise AuthError("authorizer proof mismatch")
        return ticket["entity"], sk, hmac_tag(sk, _nonce_reply(nonce))
