"""Authentication (src/auth/ cephx role).

Two-tier shared-secret auth mirroring cephx's shape:

- the monitor is the KDC (``CephxServer``): entities prove knowledge of
  their keyring secret via challenge/response and receive a session key
  plus *service tickets* (blobs encrypted with rotating per-service
  secrets), so services can verify clients without asking the mon;
- peers present an authorizer (ticket + session-key proof) when they
  connect (``CephxClient`` / ``CephxServiceVerifier``), and every
  subsequent wire frame is HMAC-signed with the connection's session key
  (cephx_sign_messages role).

Secrets never cross the wire in the clear; the ciphers are built from
hashlib-only primitives (see crypto.py) since this environment carries
no AES bindings.
"""
from .crypto import AuthError, decrypt, encrypt, hmac_tag, make_secret
from .keyring import Keyring
from .cephx import (
    CephxClient,
    CephxServer,
    CephxServiceVerifier,
    entity_service,
)

__all__ = [
    "AuthError", "decrypt", "encrypt", "hmac_tag", "make_secret",
    "Keyring", "CephxClient", "CephxServer", "CephxServiceVerifier",
    "entity_service",
]
