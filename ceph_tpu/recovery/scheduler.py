"""Per-OSD recovery scheduler — backfill/repair as a paced, observable,
QoS-classed workload (docs/RECOVERY.md).

Before this subsystem recovery was a side effect: ``run_recovery``
fanned full-stripe reads (k whole chunks per repaired shard) directly
from the cluster tick, invisible to the QoS tiers and unaccounted
beyond a push counter.  This scheduler makes the repair path a
first-class workload:

- **Repair-optimal rounds**: when the pool's codec exposes the
  regenerating repair surface (``minimum_to_decode`` answering a
  single-shard query with d helper sub-chunk requirements,
  ``repair_contribution`` / ``repair``), a lost shard rebuilds from
  d β-sub-chunk helper contributions instead of k whole chunks —
  ~d·chunk/α bytes moved instead of k·chunk.  Any helper failure (or
  the armed ``recovery.repair_read`` chaos site) degrades the round to
  the existing full-stripe decode path: repair optimality costs
  bandwidth to lose, never an object.  With a mesh up, both the
  regenerating repair solve and the full-stripe reconstruct execute
  as survivor-sharded meshed GF matmuls inside the codec's
  ``repair`` / ``decode_batch`` (docs/RECOVERY.md "Mesh-sharded
  repair solves") — a recovery storm rides all chips, and a sick
  mesh degrades to the single-device solve, not a failed round.
- **QoS classing**: each repair round is enqueued on the sharded op
  queue under ``CLASS_RECOVERY``, so the unified ``DmClockArbiter``
  arbitrates recovery against client work in ONE place — the
  recovery-storm scenario's "well-behaved clients stay inside SLO"
  guarantee is the mClock reservation/weight math, not luck.
- **Pacing**: at most ``osd_recovery_max_active`` repair rounds in
  flight per OSD; excess rounds queue and drain as rounds complete
  (deferrals counted).
- **Accounting**: a ``recovery`` perf-counter logger (helper vs
  full-stripe bytes, repaired shards, fallbacks, pacing) +
  ``ceph_daemon_recovery_*`` Prometheus families + per-codec-family
  bytes-moved-per-repaired-shard on ``recovery dump`` — the figure the
  ``ec_recovery_storm`` bench gate watches.  Each round carries a
  ``recovery``-homed stage ledger (helper-read fan → device repair
  call → d2h → shard-write fan → push ack), so `latency dump` shows
  where repair microseconds go exactly like client ops.
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..common.work_queue import CLASS_RECOVERY
from ..fault import g_faults
from ..trace import g_oplat, g_perf_histograms, transfer_size_axes
from ..trace.oplat import OpLedger

# ---- recovery perf counters (perf dump / Prometheus) -----------------------
RECOVERY_FIRST = 98000
l_recovery_repair_rounds = 98001      # sub-chunk repair rounds completed
l_recovery_repaired_shards = 98002    # shards rebuilt (both paths)
l_recovery_helper_reads = 98003       # helper contributions fetched
l_recovery_helper_bytes = 98004       # contribution bytes moved
l_recovery_fullstripe_rounds = 98005  # full-stripe decode rounds
l_recovery_fullstripe_bytes = 98006   # full-stripe source bytes moved
l_recovery_push_bytes = 98007         # rebuilt shard bytes pushed
l_recovery_fallbacks = 98008          # repair rounds degraded to
                                      # full-stripe decode
l_recovery_deferrals = 98009          # rounds parked by pacing
l_recovery_active = 98010             # gauge: rounds in flight
RECOVERY_LAST = 98020

_recovery_pc: Optional[PerfCounters] = None
_recovery_pc_lock = DebugLock("recovery_pc::init")


def recovery_perf_counters() -> PerfCounters:
    """The recovery scheduler's counter logger (perf dump /
    Prometheus ``ceph_daemon_recovery_*``)."""
    global _recovery_pc
    if _recovery_pc is not None:
        return _recovery_pc
    with _recovery_pc_lock:
        if _recovery_pc is None:
            b = PerfCountersBuilder("recovery", RECOVERY_FIRST,
                                    RECOVERY_LAST)
            b.add_u64_counter(l_recovery_repair_rounds, "repair_rounds",
                              "sub-chunk repair rounds completed")
            b.add_u64_counter(l_recovery_repaired_shards,
                              "repaired_shards",
                              "shards rebuilt (repair + full-stripe)")
            b.add_u64_counter(l_recovery_helper_reads, "helper_reads",
                              "helper repair contributions fetched")
            b.add_u64_counter(l_recovery_helper_bytes, "helper_bytes",
                              "repair contribution bytes moved")
            b.add_u64_counter(l_recovery_fullstripe_rounds,
                              "fullstripe_rounds",
                              "full-stripe decode recovery rounds")
            b.add_u64_counter(l_recovery_fullstripe_bytes,
                              "fullstripe_bytes",
                              "full-stripe recovery source bytes moved")
            b.add_u64_counter(l_recovery_push_bytes, "push_bytes",
                              "rebuilt shard bytes pushed to targets")
            b.add_u64_counter(l_recovery_fallbacks, "repair_fallbacks",
                              "repair rounds degraded to full-stripe "
                              "decode")
            b.add_u64_counter(l_recovery_deferrals, "paced_deferrals",
                              "repair rounds parked by "
                              "osd_recovery_max_active pacing")
            b.add_u64(l_recovery_active, "active",
                      "repair rounds currently in flight (gauge)")
            _recovery_pc = b.create_perf_counters()
    return _recovery_pc


def _family_of(ec_impl) -> str:
    sig = getattr(ec_impl, "codec_signature", None)
    if sig is not None:
        return str(sig()[0])
    return type(ec_impl).__name__


# the per-codec-family ledger's key set — ONE definition shared by the
# scheduler's ledger, the cluster aggregation and the bench workload's
# deltas, so a new stat cannot silently drop out of any of them
FAMILY_KEYS = ("repaired_shards", "helper_bytes", "fullstripe_bytes",
               "bytes_moved", "repair_rounds", "fullstripe_rounds",
               "repair_fallbacks")


def derive_bytes_per_shard(ent: Dict[str, float]) -> None:
    """Stamp the storm metric on a family ledger entry in place."""
    shards = max(ent.get("repaired_shards", 0), 1)
    ent["bytes_moved_per_repaired_shard"] = round(
        ent.get("bytes_moved", 0) / shards, 2)


def aggregate_families(osds) -> Dict[str, Dict[str, float]]:
    """Cluster-wide per-codec-family recovery totals (bench/CLI view):
    merge every OSD scheduler's family ledger and derive
    bytes_moved_per_repaired_shard."""
    out: Dict[str, Dict[str, float]] = {}
    for osd in osds:
        sched = getattr(osd, "recovery_sched", None)
        if sched is None:
            continue
        for fam, ent in sched.families().items():
            tgt = out.setdefault(fam, {k: 0 for k in FAMILY_KEYS})
            for key in FAMILY_KEYS:
                tgt[key] += ent.get(key, 0)
    for ent in out.values():
        derive_bytes_per_shard(ent)
    return out


class RecoveryScheduler:
    """One per OSD (``osd.recovery_sched``); drives sub-chunk repair
    rounds and accounts both recovery paths."""

    def __init__(self, osd):
        self.osd = osd
        self._lock = DebugLock(f"RecoveryScheduler::{osd.name}")
        self._active = 0
        self._parked: deque = deque()
        # in-flight round tokens -> start (cluster clock): a helper
        # dying mid-round would otherwise leak its pacing slot forever
        # (its reply never arrives); the tick reaps stale tokens and
        # the claim-once discipline keeps a late reply from double-
        # releasing the slot
        self._tokens: Dict[int, float] = {}
        self._token_seq = 0
        # per-codec-family ledger: bytes moved per repaired shard is
        # THE storm metric (docs/RECOVERY.md)
        self._families: Dict[str, Dict[str, float]] = {}
        self.hist_bytes = g_perf_histograms.get(
            "recovery", "recovery_bytes_per_shard_histogram",
            transfer_size_axes)

    # ---- options -----------------------------------------------------------
    @staticmethod
    def _opts() -> Tuple[bool, int]:
        from ..common.config import g_conf
        return (bool(g_conf.get_val("osd_recovery_repair_reads")),
                int(g_conf.get_val("osd_recovery_max_active")))

    # ---- per-family ledger -------------------------------------------------
    def _fam(self, family: str) -> Dict[str, float]:
        with self._lock:
            ent = self._families.get(family)
            if ent is None:
                ent = {k: 0 for k in FAMILY_KEYS}
                self._families[family] = ent
            return ent

    def families(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {f: dict(e) for f, e in self._families.items()}

    def note_fullstripe(self, ec_impl, src_bytes: int,
                        n_shards: int) -> None:
        """A full-stripe decode round moved *src_bytes* of source
        chunks to rebuild *n_shards* shards (the classic path — and
        the repair path's fallback)."""
        pc = recovery_perf_counters()
        pc.inc(l_recovery_fullstripe_rounds)
        pc.inc(l_recovery_fullstripe_bytes, src_bytes)
        pc.inc(l_recovery_repaired_shards, n_shards)
        fam = self._fam(_family_of(ec_impl))
        with self._lock:
            fam["fullstripe_rounds"] += 1
            fam["fullstripe_bytes"] += src_bytes
            fam["bytes_moved"] += src_bytes
            fam["repaired_shards"] += n_shards
        self.hist_bytes.inc(src_bytes / max(n_shards, 1))

    def note_push(self, nbytes: int) -> None:
        recovery_perf_counters().inc(l_recovery_push_bytes, nbytes)

    # ---- repair entry point ------------------------------------------------
    def try_repair(self, pg, oid: str,
                   targets: Dict[int, Tuple[int, str]],
                   needed: List[int]) -> bool:
        """Attempt a sub-chunk repair round for *oid*; False means the
        caller must run the full-stripe path (codec without a repair
        surface, multi-shard loss, not enough helpers, repair disabled,
        or the armed ``recovery.repair_read`` chaos site)."""
        enabled, _max_active = self._opts()
        if not enabled or len(needed) != 1:
            return False
        be = pg.backend
        if be is None:
            return False
        impl = be.ec_impl
        if not hasattr(impl, "repair_contribution") or \
                not hasattr(impl, "repair"):
            return False
        lost = needed[0]
        pc = recovery_perf_counters()
        if g_faults.site_armed("recovery.repair_read") and \
                g_faults.should_fire("recovery.repair_read",
                                     ctx=f"{pg.pgid}:{oid}"):
            pc.inc(l_recovery_fallbacks)
            fam = self._fam(_family_of(impl))
            with self._lock:
                fam["repair_fallbacks"] += 1
            return False
        acting = pg.acting_shards()
        # helpers must be up AND hold the object: a down-but-not-yet-
        # remapped member would wedge the round until the reap
        avail = {s for s in acting
                 if s != lost and oid not in pg.missing.get(s, {})
                 and self.osd.osdmap.is_up(acting[s])}
        try:
            plan = impl.minimum_to_decode({lost}, avail)
        except IOError:
            return False
        # a REPAIR plan excludes the lost shard and asks each helper
        # for fewer sub-chunks than a whole chunk; a full-k fetch
        # answer means the codec wants the classic path
        alpha = impl.get_sub_chunk_count()
        if lost in plan or any(
                sum(cnt for _off, cnt in subs) >= alpha
                for subs in plan.values()):
            return False
        self._admit(pg, oid, lost, dict(plan), targets)
        return True

    # ---- pacing ------------------------------------------------------------
    def _admit(self, pg, oid, lost, plan, targets) -> None:
        _enabled, max_active = self._opts()
        pc = recovery_perf_counters()

        def run() -> None:
            self._start_round(pg, oid, lost, plan, targets)

        with self._lock:
            if self._active >= max(max_active, 1):
                self._parked.append((pg, run))
                pc.inc(l_recovery_deferrals)
                return
            self._active += 1
        pc.inc(l_recovery_active)
        self._submit(pg, run)

    def _submit(self, pg, fn: Callable[[], None]) -> None:
        """Route the round through the sharded op queue under the
        recovery dmClock class, so client vs repair ordering is the
        arbiter's decision — never FIFO luck."""
        from ..common.config import g_conf
        osd = self.osd
        osd.op_wq.enqueue(pg.pgid, CLASS_RECOVERY, ("recovery", pg, fn))
        if bool(g_conf.get_val("osd_op_queue_batch_intake")):
            if osd.op_tp is not None:
                osd.op_tp.kick()
            return
        osd.drain_ops()

    def _round_done(self) -> None:
        pc = recovery_perf_counters()
        nxt = None
        with self._lock:
            self._active -= 1
            if self._parked and self._active < max(self._opts()[1], 1):
                nxt = self._parked.popleft()
                self._active += 1
        if nxt is None:
            pc.dec(l_recovery_active)
            return
        # a parked round takes the freed slot: gauge unchanged; it
        # re-enters through the recovery-class queue like any round
        self._submit(*nxt)

    def _open_token(self) -> int:
        with self._lock:
            self._token_seq += 1
            token = self._token_seq
            self._tokens[token] = self.osd.now
        return token

    def _claim(self, token: int) -> bool:
        """Exactly-once round completion: the first of {reply path,
        fallback, stale reap} to claim the token owns the slot
        release; later claimants see False and do nothing."""
        with self._lock:
            return self._tokens.pop(token, None) is not None

    # a wedged round (helper died; its reply will never come) frees
    # its slot after this many cluster-clock seconds — past the OSD's
    # own RECOVERY_RETRY re-kick, so the re-driven recovery owns the
    # object by the time the slot recycles
    ROUND_REAP_S = 30.0

    def kick(self) -> None:
        """Tick-driven nudge: reap wedged rounds, then drain parked
        rounds when slots freed up outside the completion path."""
        now = self.osd.now
        with self._lock:
            stale = [t for t, t0 in self._tokens.items()
                     if now - t0 > self.ROUND_REAP_S]
        for t in stale:
            if self._claim(t):
                self._round_done()
        while True:
            nxt = None
            with self._lock:
                if self._parked and \
                        self._active < max(self._opts()[1], 1):
                    nxt = self._parked.popleft()
                    self._active += 1
                    recovery_perf_counters().inc(l_recovery_active)
            if nxt is None:
                return
            self._submit(*nxt)

    # ---- one repair round --------------------------------------------------
    def _start_round(self, pg, oid: str, lost: int, plan,
                     targets) -> None:
        be = pg.backend
        impl = be.ec_impl
        pc = recovery_perf_counters()
        family = _family_of(impl)
        # the round's stage ledger: helper fan -> gather -> device
        # repair call -> d2h -> shard-write fan -> push ack, under the
        # `recovery` daemon in `latency dump` / oplat histograms
        led = OpLedger("recovery")
        token = self._open_token()

        def fallback() -> None:
            pc.inc(l_recovery_fallbacks)
            fam = self._fam(family)
            with self._lock:
                fam["repair_fallbacks"] += 1
            self._round_done()
            self.osd._recover_ec_oid_fullstripe(pg, oid, targets,
                                                [lost])

        def on_contribs(res: int, contribs: Dict[int, bytes],
                        size: int, attrs: Dict[str, bytes]) -> None:
            if res != 0 or len(contribs) != len(plan) or size < 0:
                if self._claim(token):
                    fallback()
                return
            moved = sum(len(b) for b in contribs.values())
            C = be.sinfo.get_chunk_size()
            L = C // impl.get_sub_chunk_count() \
                if impl.get_sub_chunk_count() else C
            try:
                arrays = {h: np.frombuffer(b, dtype=np.uint8)
                          .reshape(-1, L)
                          for h, b in contribs.items()}
                with g_oplat.activate(led):
                    chunk = impl.repair(lost, arrays)
                    led.mark("device_call")
                    chunk_bytes = chunk.tobytes()
                    led.mark("d2h")
            except Exception:
                if self._claim(token):
                    fallback()
                return
            pc.inc(l_recovery_repair_rounds)
            pc.inc(l_recovery_repaired_shards)
            pc.inc(l_recovery_helper_reads, len(contribs))
            pc.inc(l_recovery_helper_bytes, moved)
            self.hist_bytes.inc(moved)
            fam = self._fam(family)
            with self._lock:
                fam["repair_rounds"] += 1
                fam["repaired_shards"] += 1
                fam["helper_bytes"] += moved
                fam["bytes_moved"] += moved
            version = max(v for (v, _op) in targets.values())

            def pushed() -> None:
                led.mark("ack_gather")
                self.osd.dout(
                    5, f"repair push of {oid} shard {lost} acked "
                    f"({moved}B helper bytes vs "
                    f"{be.sinfo.get_chunk_size()}B chunk)")
                from ..osd.osd import L_OSD_RECOVERY_PUSH
                pg.missing.get(lost, {}).pop(oid, None)
                if not pg.missing.get(lost):
                    pg.send_backfill_complete(lost)
                self.osd.perf_counters.inc(L_OSD_RECOVERY_PUSH)
                pg.recovery_done_for(oid)
                if self._claim(token):
                    self._round_done()

            self.note_push(len(chunk_bytes))
            with g_oplat.activate(led):
                be.push_chunks(oid, {lost: chunk_bytes}, size, pushed,
                               version=version,
                               xattrs=attrs if attrs else None)
                led.mark("fan_out")

        self.osd.dout(5, f"repair round {oid} shard {lost} via "
                      f"{sorted(plan)} (pg {pg.pgid})")
        with g_oplat.activate(led):
            be.repair_read(oid, lost, plan, on_contribs)

    # ---- introspection (`recovery dump`) -----------------------------------
    def dump(self) -> Dict:
        enabled, max_active = self._opts()
        with self._lock:
            fams = {f: dict(e) for f, e in self._families.items()}
            active, parked = self._active, len(self._parked)
        for ent in fams.values():
            derive_bytes_per_shard(ent)
        return {
            "options": {"osd_recovery_repair_reads": enabled,
                        "osd_recovery_max_active": max_active},
            "active_rounds": active,
            "parked_rounds": parked,
            "families": fams,
        }
