"""Recovery orchestration — paced, observable, QoS-classed repair.

See scheduler.py (docs/RECOVERY.md for the design).
"""
from .scheduler import (RecoveryScheduler, aggregate_families,
                        recovery_perf_counters,
                        l_recovery_active, l_recovery_deferrals,
                        l_recovery_fallbacks, l_recovery_fullstripe_bytes,
                        l_recovery_fullstripe_rounds,
                        l_recovery_helper_bytes, l_recovery_helper_reads,
                        l_recovery_push_bytes, l_recovery_repair_rounds,
                        l_recovery_repaired_shards)

__all__ = [
    "RecoveryScheduler", "aggregate_families", "recovery_perf_counters",
    "l_recovery_active", "l_recovery_deferrals", "l_recovery_fallbacks",
    "l_recovery_fullstripe_bytes", "l_recovery_fullstripe_rounds",
    "l_recovery_helper_bytes", "l_recovery_helper_reads",
    "l_recovery_push_bytes", "l_recovery_repair_rounds",
    "l_recovery_repaired_shards",
]
