"""Monitor — the cluster control plane, electable and replicated.

Stand-in for the reference's paxos-replicated OSDMonitor
(src/mon/OSDMonitor.cc): the leader owns the authoritative OSDMap, stages
changes in an Incremental, replicates committed epochs to its quorum
(MMonPaxos begin/accept/commit — src/mon/Paxos.cc phases, leader-driven
and simplified), and publishes to every subscriber (MOSDMap).  Leadership
comes from an election among reachable monitors — lowest rank wins
(src/mon/Elector.cc) — driven by keepalive pings; a dead leader is
detected by grace timeout and a surviving quorum re-elects and continues
from its last committed epoch (the collect/last recovery phase syncs
whoever is behind).  A single monitor (the default) is its own quorum
and behaves exactly like the round-1 monitor-lite.

Pool/EC-profile management mirrors the mon flow: a profile is stored in
the map, the plugin is instantiated to validate it and to create the crush
rule (OSDMonitor.cc:5335 get_erasure_code, :5298 crush_rule_create_erasure),
and the pool's stripe_width comes from the plugin's chunk math.  Failure
reports (quorum of 2 reporters) mark OSDs down and publish a new epoch;
peons forward reports to the leader.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Set, Tuple

from ..crush.constants import CRUSH_BUCKET_STRAW2
from ..ec import create_erasure_code
from ..msg import Dispatcher, MOSDFailure, MOSDMap, Message, Network
from ..msg.messages import (
    MLog, MMDSBeacon, MMonElection, MMonPaxos, MMonPing, MMonSubscribe,
    MOSDBoot, MOSDPGTemp,
)
from ..osdmap import (
    CEPH_OSD_IN, Incremental, OSDMap, TYPE_ERASURE, TYPE_REPLICATED,
    pg_pool_t,
)
from ..trace.journal import g_journal

DEFAULT_STRIPE_UNIT = 4096  # osd_pool_erasure_code_stripe_unit
MON_PING_GRACE = 15.0       # leader silent this long -> re-elect
MDS_BEACON_GRACE = 15.0     # active mds silent this long -> failover


class Monitor(Dispatcher):
    def __init__(self, network: Network, name: str = "mon",
                 rank: int = 0, peers: Optional[List[str]] = None,
                 monmap=None):
        self.network = network
        self.name = name
        self.rank = rank
        self.peers = list(peers or [])       # other mon names
        # the epoched mon roster as a first-class map (MonMap.h):
        # built from the quorum membership when not handed one (in-
        # process fabrics have no addresses; ranks synthesize stable
        # loopback ports)
        if monmap is None:
            import uuid as _uuid
            from .monmap import MonMap
            roster = sorted({name, *self.peers})
            # deterministic fsid from the roster: every mon of one
            # cluster derives the SAME cluster identity
            monmap = MonMap(fsid=str(_uuid.uuid5(
                _uuid.NAMESPACE_URL,
                "ceph-tpu://" + ",".join(roster))))
            monmap.epoch = 1
            for i, n in enumerate(roster):
                monmap.add(n, f"127.0.0.1:{6789 + i}/0")
        self.monmap = monmap
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        self.osdmap.epoch = 0
        self.incrementals: List[Incremental] = []
        self.subscribers: List[str] = []
        self._topology_dirty = False  # crush/pools changed since last epoch
        # pg_temp pins primed by placement changes, folded into the
        # next topology publish (snapshot incs don't carry pg_temp)
        self._primed_pg_temp: Dict = {}
        # failure reports per target (mon_osd_min_down_reporters=2 —
        # a single partitioned reporter can't take the cluster down)
        self._failure_reports: Dict[int, set] = {}
        # down->out auto-eviction (mon_osd_down_out_interval, 600 s
        # default): a dead osd is marked out so CRUSH re-places its
        # data; a mere flap that reboots in time keeps its weight
        self.down_out_interval = 600.0
        self._down_stamps: Dict[int, float] = {}
        # ---- election / quorum state (Elector.cc role) --------------------
        self.election_epoch = 0
        self.leader_rank = 0 if not self.peers else -1
        self.quorum: Set[int] = {rank} if not self.peers else set()
        self._election_acks: Set[int] = set()
        self._peer_ranks: Dict[str, int] = {}
        self._last_peer_seen: Dict[int, float] = {}
        self.now = 0.0
        self._last_tick: Optional[float] = None
        # consecutive compensated stalls per liveness stamp since that
        # peer last actually spoke (bounds the compensation)
        self._grace_credit: Dict[int, int] = {}
        self._mds_grace_credit: Dict[str, int] = {}
        # ---- paxos state (Paxos.cc begin/accept/commit) -------------------
        # leader: the value currently awaiting an accept quorum, plus
        # proposals queued behind it (Paxos allows one in flight)
        self._inflight: Optional[Dict] = None
        self._pending_proposals: List[Dict] = []
        # any replica: a value staged at BEGIN but not yet known
        # committed: (pn, epoch, value_dict, locally_prematerialized)
        self._uncommitted: Optional[tuple] = None
        # leader recovery (collect/last): acks seen + best uncommitted
        self._collect_acks: Set[int] = set()
        self._collect_pn = -1
        self._collect_uncommitted: Optional[tuple] = None
        # ---- paxos services sharing the one consensus ---------------------
        # cluster log (LogMonitor role): committed entries, newest last;
        # bounded like the reference's in-memory summary
        self.cluster_log: List[Tuple[float, str, str, str]] = []
        self.cluster_log_max = 10000
        # replicated key-value store (ConfigKeyService role)
        self.config_kv: Dict[str, str] = {}
        # leader: log entries awaiting the next committed epoch, plus
        # recently seen daemon-entry identities (the broadcast fan-in
        # dedup — cleared wholesale when it grows, a cheap rolling set)
        self._pending_log: List[Tuple[float, str, str, str]] = []
        self._recent_log_keys: Set[Tuple[float, str, str, str]] = set()

    # ---- roles -------------------------------------------------------------
    def is_leader(self) -> bool:
        return self.leader_rank == self.rank

    def is_peon(self) -> bool:
        return self.leader_rank >= 0 and not self.is_leader()

    def n_mons(self) -> int:
        return len(self.peers) + 1

    def _majority(self) -> int:
        return self.n_mons() // 2 + 1

    def _peer_name(self, rank: int) -> Optional[str]:
        for name, r in self._peer_ranks.items():
            if r == rank:
                return name
        # fall back to the conventional naming
        cand = f"mon.{rank}"
        return cand if cand in self.peers else None

    # ---- election (Elector.cc: lowest reachable rank wins) ----------------
    def start_election(self) -> None:
        if not self.peers:
            self.leader_rank = self.rank
            self.quorum = {self.rank}
            return
        self._demote_inflight()
        self.election_epoch += 1
        if self.election_epoch % 2 == 0:
            self.election_epoch += 1      # odd = electing
        self.leader_rank = -1
        self._election_acks = {self.rank}
        for p in self.peers:
            self.messenger.send_message(MMonElection(
                op=MMonElection.OP_PROPOSE, epoch=self.election_epoch,
                rank=self.rank), p)

    def _handle_election(self, msg: MMonElection) -> None:
        self._peer_ranks[msg.src] = msg.rank
        if msg.op == MMonElection.OP_PROPOSE:
            if msg.epoch > self.election_epoch:
                self.election_epoch = msg.epoch
            if msg.rank < self.rank:
                # defer to the lower rank — and HOLD OFF our own
                # tick-driven retry while their round runs (Elector.cc
                # defer(): re-proposing the instant after acking storms
                # the election with ever-higher epochs; real processes
                # on a loaded host can storm for dozens of rounds)
                self.leader_rank = -1
                self._election_defer_until = self.now + \
                    max(MON_PING_GRACE / 2.0, 1.0)
                self.messenger.send_message(MMonElection(
                    op=MMonElection.OP_ACK, epoch=msg.epoch,
                    rank=self.rank), msg.src)
            else:
                # we outrank them: counter-propose
                self.start_election()
        elif msg.op == MMonElection.OP_ACK:
            if self.is_leader() and msg.epoch == self.election_epoch - 1:
                # straggler ack for the election we just won: widen the
                # quorum and bring the peer in (Elector expand behavior)
                if msg.rank not in self.quorum:
                    self.quorum.add(msg.rank)
                    self.messenger.send_message(MMonElection(
                        op=MMonElection.OP_VICTORY,
                        epoch=self.election_epoch, rank=self.rank,
                        quorum=sorted(self.quorum)), msg.src)
                    self.messenger.send_message(MMonPaxos(
                        op=MMonPaxos.OP_COLLECT, rank=self.rank,
                        pn=self.election_epoch,
                        last_committed=self.osdmap.epoch), msg.src)
                return
            if msg.epoch != self.election_epoch or self.leader_rank >= 0:
                return
            self._election_acks.add(msg.rank)
            if len(self._election_acks) >= self._majority():
                self._declare_victory()
        elif msg.op == MMonElection.OP_VICTORY:
            if msg.rank > self.rank:
                # lowest-rank-wins: a HIGHER rank declaring victory
                # while we are alive means our own proposal raced its
                # round (our acks were dropped once we "had a leader").
                # Serving under it would deadlock — we'd never propose
                # again (the leader looks alive) and it would keep a
                # quorum excluding us.  Counter-propose instead; the
                # new round converges on us (Elector.cc classic mode:
                # the leader is the lowest live rank).
                if msg.epoch > self.election_epoch:
                    self.election_epoch = msg.epoch
                self.start_election()
                return
            if msg.rank != self.rank:
                self._demote_inflight()
            self.election_epoch = msg.epoch
            self.leader_rank = msg.rank
            self.quorum = set(msg.quorum)
            self._last_peer_seen[msg.rank] = self.now
            self._grace_credit.pop(msg.rank, None)

    def _declare_victory(self) -> None:
        self.election_epoch += 1          # even = decided
        self.leader_rank = self.rank
        self.quorum = set(self._election_acks)
        g_journal.emit(self.name, "mon_election",
                       leader=self.rank, epoch=self.election_epoch,
                       quorum=sorted(self.quorum))
        for p in self.peers:
            self.messenger.send_message(MMonElection(
                op=MMonElection.OP_VICTORY, epoch=self.election_epoch,
                rank=self.rank, quorum=sorted(self.quorum)), p)
        # recovery (collect/last): learn whatever the quorum committed
        # that we missed, and surface any staged-but-uncommitted value —
        # starting with our own — so a possibly-majority-accepted
        # proposal gets finished (Paxos.cc leader recovery)
        self._collect_acks = {self.rank}
        self._collect_pn = self.election_epoch
        self._collect_uncommitted = self._uncommitted
        self._uncommitted = None
        # down->out bookkeeping is leader-local: rebuild it from the
        # map so eviction survives leadership changes (the reference
        # reconstructs down_pending_out the same way)
        for o in range(self.osdmap.max_osd):
            if not self.osdmap.is_up(o) and self.osdmap.osd_weight[o]:
                self._down_stamps.setdefault(o, self.now)
        for r in self.quorum - {self.rank}:
            name = self._peer_name(r)
            if name:
                self.messenger.send_message(MMonPaxos(
                    op=MMonPaxos.OP_COLLECT, rank=self.rank,
                    pn=self.election_epoch,
                    last_committed=self.osdmap.epoch), name)

    # ---- paxos replication (Paxos.cc begin/accept/commit) -----------------
    #
    # A value is committed only after a majority ACCEPTs it: the leader
    # stages it in _inflight and ships OP_BEGIN; peons STAGE it (no map
    # mutation) and ACCEPT; the leader applies + broadcasts OP_COMMIT
    # once accepts (incl. its own) reach a majority.  A leader
    # partitioned mid-BEGIN therefore never exposes the value anywhere;
    # a value a majority staged survives leader death via the
    # collect/LAST recovery re-proposal.

    def _demote_inflight(self) -> None:
        """Leadership lost (or contested): our in-flight proposal is no
        longer ours to commit — keep it staged like a peon would, so
        collect recovery can surface it.  Queued proposals are simply
        dropped; any topology state they materialized in the working map
        must be purged with them (their value can never commit)."""
        fl = self._inflight
        if fl is not None:
            self._inflight = None
            self._uncommitted = (fl["pn"], fl["epoch"], fl["value"],
                                 fl["topology"])
        pending_topology = any(p["topology"]
                               for p in self._pending_proposals)
        self._pending_proposals.clear()
        if pending_topology:
            self._rebuild_from_incrementals()
            if self._uncommitted is not None:
                # the rebuild also reverted the demoted value's own
                # in-place state; its VALUE is a full snapshot dict, so
                # a later re-proposal re-applies it cleanly — the map is
                # no longer dirty with it
                u = self._uncommitted
                self._uncommitted = (u[0], u[1], u[2], False)

    def _discard_uncommitted(self) -> None:
        """Drop the staged value; if it was our own topology proposal
        the working map was mutated in place before the commit — rebuild
        it from the committed history so the ghost state vanishes."""
        u = self._uncommitted
        self._uncommitted = None
        if u is not None and u[3]:
            self._rebuild_from_incrementals()

    def _rebuild_from_incrementals(self) -> None:
        m = OSDMap()
        m.epoch = 0
        self.cluster_log = []
        self.config_kv = {}
        for inc in self.incrementals:
            m.apply_incremental(inc)
            self._apply_service(inc)
        self.osdmap = m
        self._topology_dirty = False

    def _apply_service(self, inc: Incremental) -> None:
        """Fold a committed epoch's service payloads into the local
        LogMonitor/ConfigKeyService state (every mon, every commit path
        — the services are exactly as replicated as the map)."""
        if inc.service_log:
            self.cluster_log.extend(inc.service_log)
            if len(self.cluster_log) > self.cluster_log_max:
                del self.cluster_log[:-self.cluster_log_max]
        for k, v in inc.service_config_kv.items():
            if v is None:
                self.config_kv.pop(k, None)
            else:
                self.config_kv[k] = v

    def _apply_committed_values(self, values: List) -> None:
        from ..osdmap.encoding import incremental_from_dict
        for d in values:
            inc = incremental_from_dict(d)
            if inc.epoch != self.osdmap.epoch + 1:
                continue
            if self._uncommitted is not None and \
                    inc.epoch >= self._uncommitted[1]:
                # the round our staged value hoped to win is decided
                self._discard_uncommitted()
            self.osdmap.apply_incremental(inc)
            self.incrementals.append(inc)
            self._apply_service(inc)

    def _handle_paxos(self, msg: MMonPaxos) -> None:
        from ..osdmap.encoding import incremental_from_dict, \
            incremental_to_dict
        if msg.op == MMonPaxos.OP_COLLECT:
            # new leader asks what we committed past its epoch — a
            # higher proposal number also supersedes our own leadership
            if msg.pn >= self.election_epoch:
                self._demote_inflight()
            deltas = [incremental_to_dict(i) for i in self.incrementals
                      if i.epoch > msg.last_committed]
            u = self._uncommitted
            self.messenger.send_message(MMonPaxos(
                op=MMonPaxos.OP_LAST, rank=self.rank,
                pn=msg.pn, last_committed=self.osdmap.epoch,
                values=deltas,
                uncommitted_pn=u[0] if u else -1,
                uncommitted_value=list(u[1:3]) if u else None), msg.src)
        elif msg.op == MMonPaxos.OP_LAST:
            if not self.is_leader():
                return
            self._apply_committed_values(msg.values)
            # push our surplus back so the peon catches up (these are
            # committed epochs: OP_COMMIT, not a new proposal)
            self._send_commit_surplus(msg.last_committed,
                                      self._peer_name(msg.rank)
                                      or msg.src)
            if msg.pn != getattr(self, "_collect_pn", -1):
                return      # straggler from a superseded collect round
            self._collect_acks.add(msg.rank)
            if msg.uncommitted_value is not None:
                best = self._collect_uncommitted
                if best is None or msg.uncommitted_pn > best[0]:
                    if best is not None and best[3]:
                        # our own superseded topology proposal: purge
                        # its in-place map mutations before replacing
                        self._rebuild_from_incrementals()
                    ep, val = msg.uncommitted_value
                    self._collect_uncommitted = (msg.uncommitted_pn,
                                                 ep, val, False)
            if len(self._collect_acks) >= self._majority():
                self._finish_collect()
        elif msg.op == MMonPaxos.OP_BEGIN:
            # peon: STAGE the proposed value and accept — commitment is
            # the leader's call once a majority accepted.  A stale
            # proposal number (superseded leader) gets no promise.
            if msg.pn < self.election_epoch:
                return
            if msg.values:
                d = msg.values[-1]
                inc = incremental_from_dict(d)
                if inc.epoch > self.osdmap.epoch:
                    if self._uncommitted is not None and \
                            self._uncommitted[0] <= msg.pn:
                        self._discard_uncommitted()
                    self._uncommitted = (msg.pn, inc.epoch, d, False)
            self.messenger.send_message(MMonPaxos(
                op=MMonPaxos.OP_ACCEPT, rank=self.rank, pn=msg.pn,
                last_committed=self.osdmap.epoch), msg.src)
        elif msg.op == MMonPaxos.OP_ACCEPT:
            fl = self._inflight
            if self.is_leader() and fl is not None and msg.pn == fl["pn"]:
                fl["accepts"].add(msg.rank)
                # a lagging accepter also gets the committed surplus
                self._send_commit_surplus(msg.last_committed, msg.src)
                self._maybe_commit()
        elif msg.op == MMonPaxos.OP_COMMIT:
            self._apply_committed_values(msg.values)

    def _send_commit_surplus(self, peer_committed: int,
                             dst: Optional[str]) -> None:
        """Catch a lagging peer up with committed epochs (OP_COMMIT —
        these are decided values, not a proposal)."""
        if dst is None or peer_committed >= self.osdmap.epoch:
            return
        from ..osdmap.encoding import incremental_to_dict
        deltas = [incremental_to_dict(i) for i in self.incrementals
                  if i.epoch > peer_committed]
        self.messenger.send_message(MMonPaxos(
            op=MMonPaxos.OP_COMMIT, rank=self.rank,
            pn=self.election_epoch,
            last_committed=self.osdmap.epoch, values=deltas), dst)

    def _finish_collect(self) -> None:
        """A majority answered the collect: finish any surfaced
        uncommitted value whose round is still undecided by re-proposing
        it under our proposal number (Paxos.cc begin after collect)."""
        cu = self._collect_uncommitted
        self._collect_uncommitted = None
        if cu is None:
            return
        if cu[3]:
            # our own demoted topology proposal mutated the working map
            # in place; revert to the committed history first — if the
            # value still wins, the commit below re-applies it cleanly
            self._rebuild_from_incrementals()
        if cu[1] == self.osdmap.epoch + 1:
            from ..osdmap.encoding import incremental_from_dict
            inc = incremental_from_dict(cu[2])
            self._propose(inc, topology=False)

    # ---- proposal machinery (leader) --------------------------------------
    def _propose(self, inc: Incremental, topology: bool) -> None:
        self._pending_proposals.append({"inc": inc,
                                        "topology": topology})
        self._try_begin()

    def _try_begin(self) -> None:
        from ..osdmap.encoding import incremental_to_dict
        if self._inflight is not None or not self._pending_proposals:
            return
        p = self._pending_proposals.pop(0)
        epoch = self.osdmap.epoch + 1
        p["inc"].epoch = epoch
        if self._pending_log:
            # queued clog entries ride whatever epoch commits next
            # (LogMonitor batching onto the shared paxos round)
            p["inc"].service_log = list(p["inc"].service_log) + \
                self._pending_log
            self._pending_log = []
        d = incremental_to_dict(p["inc"])
        self._inflight = {"pn": self.election_epoch, "epoch": epoch,
                          "inc": p["inc"], "value": d,
                          "topology": p["topology"],
                          "accepts": {self.rank}}
        for r in self.quorum - {self.rank}:
            name = self._peer_name(r)
            if name:
                self.messenger.send_message(MMonPaxos(
                    op=MMonPaxos.OP_BEGIN, rank=self.rank,
                    pn=self.election_epoch,
                    last_committed=self.osdmap.epoch, values=[d]), name)
        self._maybe_commit()   # a self-quorum commits immediately

    def _maybe_commit(self) -> None:
        from ..osdmap.encoding import incremental_to_dict
        fl = self._inflight
        if fl is None or len(fl["accepts"]) < self._majority():
            return
        self._inflight = None
        inc = fl["inc"]
        if fl["topology"]:
            # the working map already holds the topology state (mutated
            # in place by create_*): commitment = the epoch bump, plus
            # any up/weight delta that was folded into the snapshot
            # (applied field-wise — apply_incremental would alias the
            # snapshot's crush/pool objects into the working map)
            from ..osdmap.osdmap import CEPH_OSD_EXISTS, CEPH_OSD_UP
            m = self.osdmap
            m.epoch = fl["epoch"]
            for osd, up in inc.new_up.items():
                st = m.osd_state[osd] | CEPH_OSD_EXISTS
                m.osd_state[osd] = \
                    (st | CEPH_OSD_UP) if up else (st & ~CEPH_OSD_UP)
            for osd, w in inc.new_weight.items():
                m.osd_state[osd] |= CEPH_OSD_EXISTS
                m.osd_weight[osd] = w
            for osd, a in inc.new_primary_affinity.items():
                m.set_primary_affinity(osd, a)
            for pg, osds in inc.new_pg_temp.items():
                if osds:
                    m.pg_temp[pg] = list(osds)
                else:
                    m.pg_temp.pop(pg, None)
            for pg, p in inc.new_primary_temp.items():
                if p >= 0:
                    m.primary_temp[pg] = p
                else:
                    m.primary_temp.pop(pg, None)
            for pg in inc.old_pg_upmap:
                m.pg_upmap.pop(pg, None)
            for pg in inc.old_pg_upmap_items:
                m.pg_upmap_items.pop(pg, None)
            m.pg_upmap.update(inc.new_pg_upmap)
            m.pg_upmap_items.update(inc.new_pg_upmap_items)
        else:
            self.osdmap.apply_incremental(inc)
        self.incrementals.append(inc)
        self._apply_service(inc)
        for r in self.quorum - {self.rank}:
            name = self._peer_name(r)
            if name:
                self.messenger.send_message(MMonPaxos(
                    op=MMonPaxos.OP_COMMIT, rank=self.rank,
                    pn=fl["pn"], last_committed=self.osdmap.epoch,
                    values=[fl["value"]]), name)
        for sub in self.subscribers:
            self.messenger.send_message(
                MOSDMap(first=inc.epoch, last=inc.epoch,
                        incrementals=[inc]), sub)
        self._try_begin()

    # ---- liveness (elector keepalives) ------------------------------------
    def tick(self, now: float) -> None:
        # Starvation compensation (Monitor.cc's clock-jump sanity on
        # the same check): when OUR OWN tick cadence stalled — an
        # oversubscribed host descheduled the process, a long pump —
        # the silence since the last tick measures local scheduling,
        # not peer death.  Comparing a grace window against it starts
        # spurious elections that churn quorum exactly when the box is
        # loaded (the two loadflaky vstart tests' election-timing
        # sensitivity; ROADMAP residual debt 2).  Credit every
        # liveness stamp with the stall so grace windows restart from
        # a tick cadence we actually sustained; a genuinely dead peer
        # still times out, one grace period of real ticks later.
        stall = (now - self._last_tick
                 if self._last_tick is not None else 0.0)
        self._last_tick = now
        if stall > MON_PING_GRACE / 2.0:
            # BOUNDED per silent stretch: at most two consecutive
            # stalls are compensated before the peer must actually
            # speak (any real ping/victory resets its ledger).  A
            # single long deschedule restarts the grace window in
            # full — no spurious election on wake — while a HOST that
            # stays slow against a genuinely dead peer stops earning
            # credit after two stalls, so failover is delayed by a
            # bounded amount, never postponed indefinitely.
            for r in self._last_peer_seen:
                n_stalls = self._grace_credit.get(r, 0)
                if n_stalls < 2:
                    self._grace_credit[r] = n_stalls + 1
                    self._last_peer_seen[r] = min(
                        now, self._last_peer_seen[r] + stall)
        if stall > MDS_BEACON_GRACE / 2.0:
            # same class of false positive, gated on ITS OWN grace
            # (mds_grace is configured independently of mon_grace): a
            # starved leader must not fail over a live MDS whose
            # beacons it never drained
            beacons = getattr(self, "_mds_last_beacon", {})
            for n in beacons:
                n_stalls = self._mds_grace_credit.get(n, 0)
                if n_stalls < 2:
                    self._mds_grace_credit[n] = n_stalls + 1
                    beacons[n] = min(now, beacons[n] + stall)
        self.now = now
        if self.is_leader() or not self.peers:
            # down->out eviction (OSDMonitor::tick down_pending_out)
            for osd, t0 in list(self._down_stamps.items()):
                if self.osdmap.is_up(osd) or \
                        self.osdmap.osd_weight[osd] == 0:
                    del self._down_stamps[osd]   # revived, or already out
                elif now - t0 >= self.down_out_interval:
                    del self._down_stamps[osd]
                    # remember the pre-out weight IN THE MAP so a later
                    # boot restores it on any leader, across failovers
                    # (osd_xinfo_t::old_weight, OSDMonitor::tick)
                    inc = Incremental()
                    inc.new_old_weight[osd] = self.osdmap.osd_weight[osd]
                    inc.new_weight[osd] = 0
                    self.log_entry("mon", "WRN",
                                   f"osd.{osd} marked out after "
                                   f"{self.down_out_interval:.0f}s down")
                    self.publish(inc)
            # clog entries with no epoch to ride commit on their own
            self.flush_log()
            self._check_mds_failover(now)
        if not self.peers:
            return
        for p in self.peers:
            self.messenger.send_message(MMonPing(
                op=MMonPing.PING, rank=self.rank, stamp=now), p)
        if self.leader_rank >= 0 and not self.is_leader():
            last = self._last_peer_seen.get(self.leader_rank, now)
            self._last_peer_seen.setdefault(self.leader_rank, now)
            if now - last > MON_PING_GRACE:
                self.start_election()
        elif self.is_leader() and len(self.quorum) > 1:
            # a leader losing quorum peons must re-elect (lease timeout,
            # Paxos::lease_timeout): a stale quorum would let a minority
            # keep committing
            for r in self.quorum - {self.rank}:
                last = self._last_peer_seen.get(r, now)
                self._last_peer_seen.setdefault(r, now)
                if now - last > MON_PING_GRACE:
                    self.start_election()
                    break
        elif self.leader_rank < 0:
            # election stalled (e.g. proposed to dead peers): retry —
            # but not while we just deferred to a lower rank whose
            # round is still in flight
            if now >= getattr(self, "_election_defer_until", 0.0):
                self.start_election()

    def _handle_mon_ping(self, msg: MMonPing) -> None:
        self._peer_ranks[msg.src] = msg.rank
        if msg.op == MMonPing.PING:
            self.messenger.send_message(MMonPing(
                op=MMonPing.REPLY, rank=self.rank, stamp=msg.stamp),
                msg.src)
        self._last_peer_seen[msg.rank] = self.now
        self._grace_credit.pop(msg.rank, None)
        # a LIVE mon pinging us while outside our quorum must be
        # brought back in (its election ack straggled past the window):
        # without this it never sees another BEGIN/COMMIT and its
        # committed history freezes (Monitor.cc quorum expand on
        # probe).  Damped: one rejoin election per grace period.
        if self.is_leader() and len(self.quorum) < self.n_mons() and \
                msg.rank not in self.quorum:
            last = getattr(self, "_last_rejoin_election", -1e9)
            if self.now - last > MON_PING_GRACE:
                self._last_rejoin_election = self.now
                self.start_election()

    # ---- cluster bootstrap -------------------------------------------------
    def bootstrap(self, n_osds: int, osds_per_host: int = 1) -> None:
        """Build the initial map: straw2 host tree, all osds up+in."""
        m = self.osdmap
        m.set_max_osd(n_osds)
        cw = m.crush
        cw.set_type_name(1, "host")
        cw.set_type_name(10, "root")
        hosts = []
        n_hosts = (n_osds + osds_per_host - 1) // osds_per_host
        for h in range(n_hosts):
            osds = list(range(h * osds_per_host,
                              min((h + 1) * osds_per_host, n_osds)))
            hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                                [0x10000] * len(osds), id=-(h + 2))
            hosts.append((hid, len(osds)))
        cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default",
                      [h for h, _ in hosts],
                      [0x10000 * n for _, n in hosts], id=-1)
        for i in range(n_osds):
            m.set_osd(i, up=True, weight=CEPH_OSD_IN)
            cw.set_item_name(i, f"osd.{i}")
        self._topology_dirty = True

    def subscribe(self, name: str) -> None:
        if name not in self.subscribers:
            self.subscribers.append(name)

    def quorum_status(self) -> dict:
        """This mon's view of the election ('ceph quorum_status',
        mon/MonCommands.h): rank, election epoch (odd = electing, even
        = decided), leader rank (-1 mid-election) and quorum set."""
        return {"rank": self.rank,
                "election_epoch": self.election_epoch,
                "leader_rank": self.leader_rank,
                "is_leader": self.is_leader(),
                "quorum": sorted(self.quorum)}

    # ---- cluster log (LogMonitor, src/mon/LogMonitor.cc) -------------------
    def log_entry(self, who: str, level: str, message: str) -> None:
        """Queue a cluster-log entry; it commits with the next epoch
        (immediately if the log is the only pending state — see tick)."""
        self._pending_log.append((self.now, who, level, message))

    def flush_log(self) -> None:
        """Commit queued log entries on their own no-op epoch."""
        if self._pending_log and (not self.peers or
                                  (self.is_leader() and
                                   len(self.quorum) >= self._majority())):
            self.publish(Incremental())

    def log_last(self, n: int = 20, level: Optional[str] = None
                 ) -> List[Tuple[float, str, str, str]]:
        ents = self.cluster_log
        if level is not None:
            ents = [e for e in ents if e[2] == level]
        return ents[-n:]

    # ---- config-key store (ConfigKeyService, mon/ConfigKeyService.cc) ------
    def config_key_set(self, key: str, value: str) -> None:
        """Replicate a key-value pair through paxos (ceph config-key
        set).  Leader-only, like every other mutation."""
        inc = Incremental()
        inc.service_config_kv[key] = value
        self.publish(inc)

    def config_key_rm(self, key: str) -> None:
        inc = Incremental()
        inc.service_config_kv[key] = None
        self.publish(inc)

    def config_key_get(self, key: str) -> Optional[str]:
        return self.config_kv.get(key)

    def config_key_dump(self) -> Dict[str, str]:
        return dict(self.config_kv)

    # ---- fsmap (MDSMonitor role, src/mon/MDSMonitor.cc at lite scale) ------
    #
    # The map of MDS daemons and their states rides the replicated
    # config-key store (one paxos service reused, like LogMonitor): the
    # FIRST daemon to beacon becomes active, later ones stand by, and
    # an active whose beacons go stale is failed over to the
    # longest-waiting live standby.  Beacon liveness itself is
    # leader-local RAM — a new leader re-learns it from the next
    # beacons, restarting the grace window.
    def _fsmap(self) -> Dict:
        import json as _json
        raw = self.config_key_get("fsmap")
        fsmap = _json.loads(raw) if raw else {"mds": {}}
        fsmap.setdefault("max_mds", 1)
        # rank back-fill for maps persisted before multi-active: a
        # rankless active is rank 0
        for e in fsmap["mds"].values():
            if e.get("state") == "active":
                e.setdefault("rank", 0)
        return fsmap

    def _save_fsmap(self, fsmap: Dict) -> None:
        import json as _json
        self.config_key_set("fsmap", _json.dumps(fsmap,
                                                 sort_keys=True))

    @staticmethod
    def _fsmap_ranks(fsmap: Dict) -> Dict[int, str]:
        """rank -> holder name, actives only."""
        return {int(e["rank"]): n for n, e in fsmap["mds"].items()
                if e.get("state") == "active"
                and e.get("rank") is not None}

    def fs_status(self) -> Dict:
        """Read-only fsmap view ('ceph mds stat' / 'ceph fs status'):
        answerable by any mon — the fsmap is paxos-replicated.
        ``active`` is ordered by RANK (active[0] == rank 0, which is
        what pre-multi-active clients expect)."""
        fsmap = self._fsmap()
        ranks = self._fsmap_ranks(fsmap)
        active = [ranks[r] for r in sorted(ranks)]
        standby = sorted(n for n, e in fsmap["mds"].items()
                         if e["state"] == "standby")
        return {"mds": fsmap["mds"], "active": active,
                "standby": standby, "max_mds": fsmap["max_mds"],
                "ranks": {str(r): n for r, n in sorted(ranks.items())}}

    def fs_set_max_mds(self, n: int) -> Dict:
        """'ceph fs set <fs> max_mds <n>' (MDSMonitor::filesystem_set):
        grow the active-rank count; live standbys are promoted into
        the new ranks immediately.  Shrinking deactivates the excess
        ranks (their daemons see the fsmap and respawn as standby)."""
        n = int(n)
        if n < 1:
            raise ValueError("max_mds must be >= 1")
        fsmap = self._fsmap()
        fsmap["max_mds"] = n
        for name, e in sorted(fsmap["mds"].items()):
            if e.get("state") == "active" and e.get("rank", 0) >= n:
                fsmap["mds"][name] = {"state": "standby",
                                      "rank": None}
                self.log_entry("mon", "INF",
                               f"mds {name} deactivated "
                               f"(max_mds={n})")
        self._fill_ranks(fsmap)
        self._save_fsmap(fsmap)
        return {"max_mds": n}

    def _fill_ranks(self, fsmap: Dict) -> None:
        """Promote LIVE standbys into unheld ranks < max_mds
        (MDSMonitor::maybe_promote_standby)."""
        beacons = getattr(self, "_mds_last_beacon", {})
        held = set(self._fsmap_ranks(fsmap))
        for rank in range(fsmap["max_mds"]):
            if rank in held:
                continue
            live = sorted(
                (n for n, e in fsmap["mds"].items()
                 if e["state"] == "standby"
                 and self.now - beacons.get(n, -1e18)
                 <= MDS_BEACON_GRACE))
            if not live:
                continue
            pick = live[0]
            fsmap["mds"][pick] = {"state": "active", "rank": rank}
            held.add(rank)
            self.log_entry("mon", "INF",
                           f"mds {pick} is now active rank {rank}")

    def _handle_mds_beacon(self, msg: MMDSBeacon) -> None:
        if self.peers and not self.is_leader():
            name = self._peer_name(self.leader_rank) \
                if self.leader_rank >= 0 else None
            if name:
                self.messenger.send_message(MMDSBeacon(
                    name=msg.name, state=msg.state, seq=msg.seq), name)
            return
        if not hasattr(self, "_mds_last_beacon"):
            self._mds_last_beacon = {}
        self._mds_last_beacon[msg.name] = self.now
        self._mds_grace_credit.pop(msg.name, None)
        fsmap = self._fsmap()
        cur = fsmap["mds"].get(msg.name)
        if cur is not None and cur["state"] == "standby":
            # a known standby beaconing while ranks sit unheld (e.g.
            # it was momentarily stale when fs_set_max_mds ran): seat
            # it now — without this, nothing would ever re-run the
            # promotion for an idle-but-healthy standby
            held = self._fsmap_ranks(fsmap)
            if len(held) < fsmap["max_mds"]:
                self._fill_ranks(fsmap)
                if fsmap["mds"][msg.name]["state"] != "standby" or \
                        self._fsmap_ranks(fsmap) != held:
                    self._save_fsmap(fsmap)
            return
        if cur is None or cur["state"] == "failed":
            # new daemon — or a FAILED one beaconing again (restarted
            # after the grace window): it rejoins as standby and takes
            # any unheld rank (MDSMonitor re-admitting a formerly-
            # laggy daemon)
            fsmap["mds"][msg.name] = {"state": "standby",
                                      "rank": None}
            self._fill_ranks(fsmap)
            st = fsmap["mds"][msg.name]
            joined = f"active rank {st['rank']}" \
                if st["state"] == "active" else "standby"
            self.log_entry("mon", "INF",
                           f"mds {msg.name} joined as {joined}")
            self._save_fsmap(fsmap)

    def _check_mds_failover(self, now: float) -> None:
        """Leader tick: fail a silent active and promote a LIVE
        standby into ITS rank (MDSMonitor::tick beacon grace).
        Failover is per-rank: other actives are untouched."""
        beacons = getattr(self, "_mds_last_beacon", None)
        if not beacons:
            return
        fsmap = self._fsmap()
        changed = False
        for name, e in sorted(fsmap["mds"].items()):
            if e["state"] != "active":
                continue
            last = beacons.get(name, now)
            beacons.setdefault(name, now)
            if now - last <= MDS_BEACON_GRACE:
                continue
            rank = e.get("rank", 0)
            fsmap["mds"][name] = {"state": "failed", "rank": None}
            changed = True
            self.log_entry("mon", "WRN",
                           f"mds {name} (rank {rank}) failed")
        if changed:
            self._fill_ranks(fsmap)
            self._save_fsmap(fsmap)

    # ---- pools -------------------------------------------------------------
    def create_replicated_pool(self, name: str, size: int = 3,
                               pg_num: int = 32) -> int:
        rno = self.osdmap.crush.get_rule_id("replicated_rule")
        if rno < 0:
            rno = self.osdmap.crush.add_simple_rule(
                "replicated_rule", "default", "host", mode="firstn")
        pool = pg_pool_t(type=TYPE_REPLICATED, size=size,
                         min_size=max(1, size - 1), crush_rule=rno,
                         pg_num=pg_num, pgp_num=pg_num)
        self._topology_dirty = True
        self.log_entry("mon", "INF",
                       f"pool '{name}' created (replicated size={size})")
        return self.osdmap.add_pool(name, pool)

    def set_pool_pg_num(self, name: str, pg_num: int) -> None:
        """Grow a pool's pg_num (PG splitting; OSDMonitor 'osd pool set
        pg_num').  pgp_num is left alone so children colocate with
        their parents (placement uses pgp_num) — raise pgp_num
        afterwards to actually spread them, like the reference."""
        pid = self.osdmap.lookup_pg_pool_name(name)
        if pid < 0:
            raise KeyError(f"no pool named {name!r}")
        pool = self.osdmap.pools[pid]
        if pg_num < pool.pg_num:
            raise ValueError("pg_num can only grow (no PG merging)")
        pool.set_pg_num(pg_num)
        self._topology_dirty = True

    def set_pool_pgp_num(self, name: str, pgp_num: int) -> None:
        """Spread split children to their own CRUSH positions
        (OSDMonitor 'osd pool set pgp_num'); bounded by pg_num.

        Placement changes are PRIMED (OSDMonitor::maybe_prime_pg_temp):
        every PG whose acting set would move to different OSDs gets
        pg_temp pinned to its OLD acting, so the data-bearing members
        keep serving while the realign machinery copies shards to the
        new CRUSH positions and then drops the pin — without this, a
        PG remapped to entirely fresh OSDs has no acting member holding
        its data and reads go EIO forever."""
        from ..crush.constants import CRUSH_ITEM_NONE
        from ..osdmap import pg_t as _pg_t
        pid = self.osdmap.lookup_pg_pool_name(name)
        if pid < 0:
            raise KeyError(f"no pool named {name!r}")
        pool = self.osdmap.pools[pid]
        if pgp_num > pool.pg_num:
            raise ValueError("pgp_num cannot exceed pg_num")
        old_acting = {}
        for ps in range(pool.pg_num):
            pg = _pg_t(pid, ps)
            if pg not in self.osdmap.pg_temp:   # existing pins win
                old_acting[ps] = list(
                    self.osdmap.pg_to_up_acting_osds(pg)[2])
        pool.set_pgp_num(pgp_num)
        for ps, olda in old_acting.items():
            pg = _pg_t(pid, ps)
            newa = list(self.osdmap.pg_to_up_acting_osds(pg)[2])
            if newa != olda and \
                    any(o != CRUSH_ITEM_NONE for o in olda):
                self.osdmap.pg_temp[pg] = [int(o) for o in olda]
                self._primed_pg_temp[pg] = [int(o) for o in olda]
        self._topology_dirty = True

    def create_ec_profile(self, name: str, profile: Dict[str, str]) -> None:
        # instantiating validates the profile (OSDMonitor get_erasure_code)
        create_erasure_code(dict(profile))
        self.osdmap.erasure_code_profiles[name] = dict(profile)

    def create_ec_pool(self, name: str, profile_name: str,
                       pg_num: int = 32,
                       ec_overwrites: bool = True) -> int:
        profile = self.osdmap.erasure_code_profiles[profile_name]
        ec = create_erasure_code(dict(profile))
        rule_name = f"{name}_rule"
        rno = ec.create_rule(rule_name, self.osdmap.crush)
        if rno < 0:
            raise RuntimeError(f"create_rule failed: {rno}")
        k = ec.get_data_chunk_count()
        stripe_unit = int(profile.get("stripe_unit", DEFAULT_STRIPE_UNIT))
        stripe_width = k * stripe_unit
        psw = getattr(ec, "preferred_stripe_width", None)
        if psw is not None:
            # codec-geometry pools (regenerating codes): the plugin
            # dictates the stripe width (one message matrix per stripe)
            stripe_width = psw()
        from ..osdmap.types import FLAG_EC_OVERWRITES, FLAG_HASHPSPOOL
        flags = FLAG_HASHPSPOOL | (FLAG_EC_OVERWRITES if ec_overwrites
                                   else 0)
        pool = pg_pool_t(type=TYPE_ERASURE, size=ec.get_chunk_count(),
                         min_size=k + 1, crush_rule=rno,
                         pg_num=pg_num, pgp_num=pg_num,
                         erasure_code_profile=profile_name,
                         stripe_width=stripe_width, flags=flags)
        self._topology_dirty = True
        self.log_entry("mon", "INF",
                       f"pool '{name}' created (erasure "
                       f"profile={profile_name})")
        return self.osdmap.add_pool(name, pool)

    # ---- cache tiering (OSDMonitor "osd tier add/cache-mode") --------------
    def add_cache_tier(self, base_name: str, cache_name: str,
                       mode: str = "writeback",
                       hit_set_period: float = 60.0,
                       hit_set_count: int = 4,
                       target_max_objects: int = 0) -> None:
        """Overlay *cache_name* (replicated) on *base_name*: clients
        redirect to the cache; the cache PGs promote/flush/evict
        (OSDMonitor::prepare_command 'osd tier add' + 'cache-mode' +
        'set-overlay')."""
        base_id = self.osdmap.lookup_pg_pool_name(base_name)
        cache_id = self.osdmap.lookup_pg_pool_name(cache_name)
        if base_id < 0 or cache_id < 0:
            raise KeyError("unknown pool")
        cache = self.osdmap.pools[cache_id]
        if cache.type != TYPE_REPLICATED:
            raise ValueError("cache tier pool must be replicated")
        if mode != "writeback":
            raise ValueError("only writeback cache-mode is implemented")
        cache.tier_of = base_id
        cache.cache_mode = mode
        cache.hit_set_period = hit_set_period
        cache.hit_set_count = hit_set_count
        cache.target_max_objects = target_max_objects
        base = self.osdmap.pools[base_id]
        base.read_tier = cache_id
        base.write_tier = cache_id
        self._topology_dirty = True

    def remove_cache_tier(self, base_name: str) -> None:
        base_id = self.osdmap.lookup_pg_pool_name(base_name)
        base = self.osdmap.pools[base_id]
        if base.read_tier >= 0:
            cache = self.osdmap.pools.get(base.read_tier)
            if cache is not None:
                cache.tier_of = -1
                cache.cache_mode = ""
        base.read_tier = -1
        base.write_tier = -1
        self._topology_dirty = True

    # ---- pool snapshots (OSDMonitor pool mksnap/rmsnap) --------------------
    def pool_snap_create(self, pool_name: str, snap_name: str) -> int:
        """Allocate the next snap id on the pool; publish via the next
        epoch (pg_pool_t::add_snap role)."""
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        if pool.selfmanaged:
            raise ValueError(
                f"pool {pool_name!r} is in selfmanaged snap mode")
        if snap_name in pool.snaps.values():
            raise ValueError(f"snap {snap_name!r} exists")
        sid = pool.snap_seq + 1
        pool.snaps[sid] = snap_name
        pool.snap_seq = sid
        self._topology_dirty = True
        return sid

    def pool_snap_rm(self, pool_name: str, snap_name: str) -> int:
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        for sid, n in list(pool.snaps.items()):
            if n == snap_name:
                del pool.snaps[sid]
                pool.removed_snaps.append(sid)
                self._topology_dirty = True
                return sid
        raise KeyError(f"no snap {snap_name!r} on {pool_name!r}")

    # ---- self-managed snaps (OSDMonitor "osd pool mksnap" unmanaged twin:
    # librados selfmanaged_snap_create/remove -> mon snapid allocation;
    # pg_pool_t::add_unmanaged_snap, src/osd/osd_types.cc) ------------------
    def selfmanaged_snap_create(self, pool_name: str) -> int:
        """Allocate the next snap id; the snapshot itself lives only in
        the client's SnapContext.  Commits the pool to selfmanaged mode."""
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        if pool.snaps:
            raise ValueError(
                f"pool {pool_name!r} already has pool snapshots")
        pool.selfmanaged = True
        sid = pool.snap_seq + 1
        pool.snap_seq = sid
        self._topology_dirty = True
        return sid

    def selfmanaged_snap_remove(self, pool_name: str, snapid: int) -> None:
        """Mark an allocated id removed so PGs trim its clones."""
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        if not pool.selfmanaged:
            # retiring a live pool-mode snapshot id here would corrupt
            # it (the reference returns EINVAL unless the pool is in
            # unmanaged snaps mode, pg_pool_t::remove_unmanaged_snap)
            raise ValueError(
                f"pool {pool_name!r} is not in selfmanaged snap mode")
        if not (0 < snapid <= pool.snap_seq):
            raise KeyError(f"snap id {snapid} never allocated")
        if snapid not in pool.removed_snaps:
            pool.removed_snaps.append(snapid)
            self._topology_dirty = True

    # ---- pool deletion (OSDMonitor "osd pool delete") ---------------------
    def delete_pool(self, pool_name: str) -> int:
        """Remove a pool from the map; OSDs purge its PGs and data on
        consuming the epoch (OSD 'PG removed' / PG::on_removal).  A
        pool participating in a cache tier must be detached first,
        like the reference refuses (EBUSY)."""
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        if pool.tier_of >= 0 or pool.read_tier >= 0:
            raise ValueError(
                f"pool {pool_name!r} is part of a cache tier")
        del self.osdmap.pools[pid]
        del self.osdmap.pool_name[pid]
        if not hasattr(self, "_pending_pool_deletes"):
            self._pending_pool_deletes = []
        self._pending_pool_deletes.append(pid)
        self._topology_dirty = True
        return pid

    # ---- pool quotas + full flags (OSDMonitor "osd pool set-quota",
    # "osd set full"; flag values from osd_types.h:1148-1158) --------------
    def set_pool_quota(self, pool_name: str, max_objects: int = 0,
                       max_bytes: int = 0) -> None:
        pid = self.osdmap.lookup_pg_pool_name(pool_name)
        if pid < 0:
            raise KeyError(f"no pool {pool_name!r}")
        pool = self.osdmap.pools[pid]
        pool.quota_max_objects = int(max_objects)
        pool.quota_max_bytes = int(max_bytes)
        self._topology_dirty = True

    def set_pool_flags(self, pool_id: int, set_mask: int = 0,
                       clear_mask: int = 0) -> bool:
        """Set/clear pg_pool_t flags (the mgr drives FULL_QUOTA from
        usage); returns whether anything changed."""
        pool = self.osdmap.pools.get(pool_id)
        if pool is None:
            return False
        new = (pool.flags | set_mask) & ~clear_mask
        if new == pool.flags:
            return False
        pool.flags = new
        self._topology_dirty = True
        return True

    def set_cluster_flags(self, set_mask: int = 0,
                          clear_mask: int = 0) -> bool:
        """Cluster-wide CEPH_OSDMAP_* flags (full/nearfull/pausewr)."""
        new = (self.osdmap.flags | set_mask) & ~clear_mask
        if new == self.osdmap.flags:
            return False
        self.osdmap.flags = new
        self._topology_dirty = True
        return True

    def _maybe_remove_pg_upmaps(self) -> None:
        """Drop upmap entries that reference deleted pools or
        nonexistent OSDs (OSDMonitor::maybe_remove_pg_upmaps) — stale
        entries would silently distort placement forever."""
        m = self.osdmap

        def stale(pg, osds) -> bool:
            if pg.pool not in m.pools or pg.ps >= m.pools[pg.pool].pg_num:
                return True
            return any(o >= m.max_osd or not m.exists(o) for o in osds)

        for pg in [pg for pg, v in m.pg_upmap.items() if stale(pg, v)]:
            del m.pg_upmap[pg]
            self._topology_dirty = True
        for pg in [pg for pg, v in m.pg_upmap_items.items()
                   if stale(pg, [o for pair in v for o in pair])]:
            del m.pg_upmap_items[pg]
            self._topology_dirty = True
        for store in (m.pg_temp, m.primary_temp):
            for pg in [pg for pg in store if pg.pool not in m.pools]:
                del store[pg]
                self._topology_dirty = True

    # ---- wire commands (MMonCommand -> handle_command, the
    # 'ceph tell mon' / librados mon_command surface) ----------------------
    def _handle_command(self, msg) -> None:
        from ..msg.messages import MMonCommand, MMonCommandAck
        # ack cache: a lossy client link may replay the same command
        # tid after a dropped ack — non-idempotent commands (snap id
        # allocation!) must not run twice (the reference's mon session
        # dedups by (client, tid) the same way).  Keyed by the ORIGIN
        # client, which for a peon-relayed command is reply_to, so a
        # replay arriving by a different route still dedups.
        from collections import OrderedDict
        cache = getattr(self, "_cmd_ack_cache", None)
        if cache is None:
            cache = self._cmd_ack_cache = OrderedDict()
        origin = msg.reply_to or msg.src
        key = (origin, msg.tid)
        hit = cache.get(key)
        if hit is not None:
            cache.move_to_end(key)
            self.messenger.send_message(MMonCommandAck(
                tid=hit.tid, result=hit.result, data=hit.data,
                reply_to=msg.reply_to), msg.src)
            return

        def reply(result: int, data: dict, cacheable: bool) -> None:
            ack = MMonCommandAck(tid=msg.tid, result=result, data=data,
                                 reply_to=msg.reply_to)
            if cacheable:
                # bounded LRU: evict the coldest single entries instead
                # of a wholesale clear (which would discard live acks
                # and let a delayed replay re-run a non-idempotent
                # command).  LRU also ages out an entry whose (client,
                # tid) could collide after a client restart resets tids.
                cache[key] = ack
                cache.move_to_end(key)
                while len(cache) > 1024:
                    cache.popitem(last=False)
            self.messenger.send_message(ack, msg.src)

        # read-only commands: no mutation, no publish, answerable on
        # ANY mon from replicated state — handled before the leader
        # relay so a client bound to a peon gets its answer even
        # mid-election
        if msg.cmd == "fs_status":
            reply(0, {"value": self.fs_status()}, cacheable=False)
            return
        if msg.cmd == "quorum_status":
            # election/quorum introspection ('ceph quorum_status'):
            # answerable mid-election on any mon, never relayed — the
            # vstart tests poll it to wait for a NEW leader after a
            # SIGKILL instead of guessing with fixed pump counts
            reply(0, {"value": self.quorum_status()}, cacheable=False)
            return

        # peons never mutate: relay to the leader (Monitor::
        # forward_request_leader, src/mon/Monitor.cc) and let the ack
        # route back through us.  A mutation here would diverge this
        # mon's working map from quorum AND publish() would refuse.
        if self.peers and not self.is_leader():
            leader = (self._peer_name(self.leader_rank)
                      if self.leader_rank >= 0 else None)
            if leader is None or msg.reply_to:
                # electing, or a stale forward that landed on a non-
                # leader: transient — tell the client to retry (-EAGAIN,
                # never cached so the retry re-resolves the leader)
                reply(-11, {"error": "mon not quorum leader"},
                      cacheable=False)
                return
            self.messenger.send_message(MMonCommand(
                tid=msg.tid, cmd=msg.cmd, args=dict(msg.args),
                reply_to=origin), leader)
            return

        allowed = {"pool_snap_create", "pool_snap_rm",
                   "selfmanaged_snap_create", "selfmanaged_snap_remove",
                   "set_pool_quota", "create_replicated_pool",
                   "create_ec_profile", "create_ec_pool",
                   "delete_pool", "fs_set_max_mds"}
        if msg.cmd not in allowed:
            reply(-22, {"error": f"unknown command {msg.cmd!r}"},
                  cacheable=True)
            return
        try:
            value = getattr(self, msg.cmd)(**msg.args)
        except (KeyError, ValueError, TypeError, RuntimeError) as e:
            # the command's own failure is permanent: cache it so a
            # replay gets the same answer instead of re-executing
            reply(-22, {"error": str(e)}, cacheable=True)
            return
        try:
            self.publish()
        except RuntimeError as e:
            # lost leadership between the check above and publish():
            # the local mutation will be rebuilt from committed history
            # on the next election; the client must retry at the new
            # leader.  Not cached — the retry must re-execute there.
            # (Scoped to publish() alone: a RuntimeError raised by the
            # command itself is a real error, not a leadership signal.)
            reply(-11, {"error": f"leadership lost: {e}"},
                  cacheable=False)
            return
        reply(0, {"value": value}, cacheable=True)

    def _relay_command_ack(self, msg) -> None:
        """Ack for a command this peon forwarded to the leader: route it
        to the waiting client (Monitor::route_message role)."""
        from ..msg.messages import MMonCommandAck
        if msg.reply_to:
            self.messenger.send_message(MMonCommandAck(
                tid=msg.tid, result=msg.result, data=msg.data),
                msg.reply_to)

    # ---- epoch publication -------------------------------------------------
    def _snapshot_inc(self) -> Incremental:
        """Full-state Incremental (crush/pools/osd states deep-copied so
        later mon mutations can't leak into published epochs)."""
        m = self.osdmap
        inc = Incremental()
        inc.new_flags = m.flags
        # full-state incs only REPLACE listed pools on consumers;
        # deletions must travel explicitly.  Filter against the WORKING
        # map: a paxos demotion can rebuild it from committed history
        # and resurrect a pool whose delete never got quorum — shipping
        # that stale pid would purge a live pool's data on every OSD
        # (pids are never reused, so absence == genuinely deleted)
        inc.old_pools = [pid for pid in
                         getattr(self, "_pending_pool_deletes", [])
                         if pid not in m.pools]
        self._pending_pool_deletes = []
        inc.crush = copy.deepcopy(m.crush)
        inc.new_pools = copy.deepcopy(m.pools)
        inc.new_pool_names = dict(m.pool_name)
        inc.new_max_osd = m.max_osd
        for o in range(m.max_osd):
            inc.new_up[o] = m.is_up(o)
            inc.new_weight[o] = m.osd_weight[o]
            inc.new_old_weight[o] = m.osd_old_weight.get(o, 0)
        inc.new_erasure_code_profiles = copy.deepcopy(
            m.erasure_code_profiles)
        return inc

    def publish(self, inc: Optional[Incremental] = None) -> None:
        """Commit a new epoch and broadcast it (mon → MOSDMap).

        Topology changes (crush/pools) publish as a full-state snapshot
        Incremental; osd up/weight deltas publish as true diffs which the
        mon also applies to its own map.

        Multi-mon: only the quorum leader may commit — a partitioned
        minority mutating its private map would diverge from the quorum
        (real paxos makes this impossible; we make it loud).
        """
        if self.peers and (not self.is_leader()
                           or len(self.quorum) < self._majority()):
            raise RuntimeError(
                f"{self.name}: not the quorum leader "
                f"(leader_rank={self.leader_rank}, quorum={self.quorum})")
        if self._topology_dirty:
            self._maybe_remove_pg_upmaps()
            delta = inc
            inc = self._snapshot_inc()
            # the snapshot reads the WORKING map, which does not yet
            # reflect deferred (in-flight/queued) delta proposals that
            # will commit before this epoch — fold their overrides in,
            # or the snapshot would silently revert them at commit
            deferred = ([self._inflight["inc"]] if self._inflight
                        else []) + \
                [p["inc"] for p in self._pending_proposals]
            for src in deferred + ([delta] if delta is not None else []):
                inc.new_up.update(src.new_up)
                inc.new_weight.update(src.new_weight)
                inc.new_old_weight.update(src.new_old_weight)
                inc.new_primary_affinity.update(src.new_primary_affinity)
                inc.new_pg_temp.update(src.new_pg_temp)
                inc.new_primary_temp.update(src.new_primary_temp)
                inc.new_pg_upmap.update(src.new_pg_upmap)
                inc.new_pg_upmap_items.update(src.new_pg_upmap_items)
                inc.old_pg_upmap.extend(src.old_pg_upmap)
                inc.old_pg_upmap_items.extend(src.old_pg_upmap_items)
            if self._primed_pg_temp:
                inc.new_pg_temp.update(self._primed_pg_temp)
                self._primed_pg_temp = {}
            if delta is not None:
                # service payloads fold from the DIRECT delta only:
                # deferred proposals commit on their own, and unlike
                # the idempotent map-field folding above, log entries
                # and kv mutations must apply exactly once
                inc.service_log.extend(delta.service_log)
                inc.service_config_kv.update(delta.service_config_kv)
            self._topology_dirty = False
            topology = True
        else:
            inc = inc if inc is not None else Incremental()
            topology = False
        # commitment is deferred to the accept quorum: a single mon (its
        # own majority) commits inline, a multi-mon cluster commits when
        # the peon ACCEPTs drain (the next network pump)
        self._propose(inc, topology)

    def send_full_map(self, dst: str) -> None:
        self.messenger.send_message(
            MOSDMap(first=1, last=self.osdmap.epoch,
                    incrementals=list(self.incrementals)), dst)

    # ---- osd state changes -------------------------------------------------
    def mark_osd_down(self, osd: int) -> None:
        inc = Incremental()
        inc.new_up[osd] = False
        # a down osd's past failure reports no longer count
        reporter = f"osd.{osd}"
        for reps in self._failure_reports.values():
            reps.discard(reporter)
        self._down_stamps.setdefault(osd, self.now)
        self.log_entry("mon", "WRN", f"osd.{osd} marked down")
        g_journal.emit(self.name, "osd_down", osd=osd)
        self.publish(inc)

    def mark_osd_up(self, osd: int) -> None:
        inc = Incremental()
        inc.new_up[osd] = True
        # a boot reverses an AUTOMATIC out (operator outs stay out):
        # mon_osd_auto_mark_auto_out_in, OSDMonitor::prepare_boot.
        # The memo rides the replicated map, so any leader can restore
        old_w = self.osdmap.osd_old_weight.get(osd)
        if old_w:
            if self.osdmap.osd_weight[osd] == 0:
                inc.new_weight[osd] = old_w
            inc.new_old_weight[osd] = 0
        # recovery voids any partial reports against this osd
        self._failure_reports.pop(osd, None)
        self._down_stamps.pop(osd, None)
        self.log_entry("mon", "INF", f"osd.{osd} boot")
        g_journal.emit(self.name, "osd_up", osd=osd)
        self.publish(inc)

    def mark_osd_out(self, osd: int) -> None:
        inc = Incremental()
        inc.new_weight[osd] = 0
        cur = self.osdmap.osd_weight[osd] \
            if osd < len(self.osdmap.osd_weight) else 0
        if 0 < cur < CEPH_OSD_IN:
            # memo a reweight override so a later 'in' restores it
            # (osd_xinfo_t::old_weight, OSDMonitor operator out/in)
            inc.new_old_weight[osd] = cur
        g_journal.emit(self.name, "osd_out", osd=osd)
        self.publish(inc)

    def handle_pg_temp(self, msg: MOSDPGTemp) -> None:
        """OSDMonitor pg_temp handling: pin/clear the PG's acting set
        (OSDMonitor::preprocess_pgtemp role)."""
        from ..osdmap import pg_t as _pg_t
        pg = _pg_t(msg.pgid[0], msg.pgid[1])
        want = [int(o) for o in msg.temp]
        cur = self.osdmap.pg_temp.get(pg, [])
        if want == list(cur):
            return
        inc = Incremental()
        inc.new_pg_temp[pg] = want      # [] clears the pin
        self.publish(inc)

    def mark_osd_in(self, osd: int) -> None:
        inc = Incremental()
        old = self.osdmap.osd_old_weight.get(osd, 0)
        inc.new_weight[osd] = old if old > 0 else CEPH_OSD_IN
        if old:
            inc.new_old_weight[osd] = 0      # memo consumed
        g_journal.emit(self.name, "osd_in", osd=osd)
        self.publish(inc)

    # ---- durability (mon store, src/mon/MonitorDBStore.h role) -------------
    def save(self, path: str) -> None:
        """Persist the authoritative map + full epoch history to a JSON
        file (the mon store: resume = load + replay)."""
        import json
        import os as _os
        state = mon_store_state(self.osdmap, self.incrementals,
                                self.monmap)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
        _os.replace(tmp, path)

    def load(self, path: str) -> None:
        import json
        from ..osdmap.encoding import incremental_from_dict, \
            osdmap_from_dict
        with open(path) as f:
            state = json.load(f)
        self.osdmap = osdmap_from_dict(state["osdmap"])
        self.incrementals = [incremental_from_dict(i)
                             for i in state["incrementals"]]
        if "monmap" in state:
            from .monmap import MonMap
            self.monmap = MonMap.from_bytes(
                state["monmap"].encode("latin1"))
        self.cluster_log = []
        self.config_kv = {}
        for inc in self.incrementals:
            self._apply_service(inc)
        self._topology_dirty = False

    # ---- dispatch ----------------------------------------------------------
    def min_down_reporters(self) -> int:
        n_up = sum(1 for o in range(self.osdmap.max_osd)
                   if self.osdmap.is_up(o))
        return 2 if n_up > 2 else 1

    def ms_fast_dispatch(self, msg: Message) -> None:
        from ..msg.messages import MMonCommand, MMonCommandAck
        if isinstance(msg, MMonSubscribe):
            # cross-process clients/daemons subscribe over the wire
            # (the in-process ones call subscribe() directly)
            self.subscribe(msg.src)
            self.send_full_map(msg.src)
        elif isinstance(msg, MMonCommand):
            self._handle_command(msg)
        elif isinstance(msg, MMonCommandAck):
            self._relay_command_ack(msg)
        elif isinstance(msg, MMonElection):
            self._handle_election(msg)
        elif isinstance(msg, MMonPaxos):
            self._handle_paxos(msg)
        elif isinstance(msg, MMonPing):
            self._handle_mon_ping(msg)
        elif isinstance(msg, MMDSBeacon):
            self._handle_mds_beacon(msg)
        elif isinstance(msg, MOSDPGTemp):
            if self.is_leader() or not self.peers:
                self.handle_pg_temp(msg)
            elif self.is_peon():
                name = self._peer_name(self.leader_rank)
                if name:
                    self.messenger.send_message(MOSDPGTemp(
                        pgid=msg.pgid, epoch=msg.epoch,
                        temp=list(msg.temp)), name)
        elif isinstance(msg, MOSDBoot):
            # a live osd the map calls down asks back in
            # (OSDMonitor::preprocess_boot/prepare_boot)
            if self.is_leader() or not self.peers:
                if 0 <= msg.osd < self.osdmap.max_osd and \
                        not self.osdmap.is_up(msg.osd):
                    self.mark_osd_up(msg.osd)
            elif self.is_peon():
                name = self._peer_name(self.leader_rank)
                if name:
                    self.messenger.send_message(MOSDBoot(
                        osd=msg.osd, epoch=msg.epoch), name)
        elif isinstance(msg, MLog):
            # daemons' clog entries: the leader queues (committed with
            # the next epoch / tick flush); peons forward.  Daemons
            # broadcast to every mon so the entry survives any single
            # mon death — the leader therefore sees the same entry
            # several times (direct + forwarded) and dedups by its
            # (stamp, who, level, message) identity
            if self.is_leader() or not self.peers:
                stamp = msg.stamp if msg.stamp >= 0 else self.now
                ent = (stamp, msg.who or msg.src,
                       msg.level, msg.message)
                if ent not in self._recent_log_keys:
                    if len(self._recent_log_keys) > 512:
                        # rolling reset — but keep the entry being
                        # admitted, or its own in-flight forwarded
                        # duplicates would slip past the dedup
                        self._recent_log_keys.clear()
                    self._recent_log_keys.add(ent)
                    self._pending_log.append(ent)
            elif self.is_peon():
                name = self._peer_name(self.leader_rank)
                if name:
                    self.messenger.send_message(MLog(
                        who=msg.who or msg.src, level=msg.level,
                        message=msg.message, stamp=msg.stamp), name)
        elif isinstance(msg, MOSDFailure):
            if not self.is_leader():
                # peons forward to the leader (Monitor::forward_request);
                # a mon mid-election drops the report — OSDs re-send
                # every tick, so the eventual leader still hears it
                if self.is_peon():
                    name = self._peer_name(self.leader_rank)
                    if name:
                        self.messenger.send_message(MOSDFailure(
                            target_osd=msg.target_osd,
                            failed_since=msg.failed_since,
                            epoch=msg.epoch,
                            reporter=msg.reporter or msg.src), name)
                return
            # OSDMonitor::check_failure quorum: distinct reporters must
            # agree before the mark (mon_osd_min_down_reporters)
            if not self.osdmap.is_up(msg.target_osd):
                return
            reporters = self._failure_reports.setdefault(
                msg.target_osd, set())
            reporters.add(msg.reporter or msg.src)
            if len(reporters) >= self.min_down_reporters():
                del self._failure_reports[msg.target_osd]
                self.mark_osd_down(msg.target_osd)


def mon_store_state(osdmap, incrementals, monmap) -> dict:
    """The mon store's on-disk shape — ONE writer definition shared by
    Monitor.save and the DR rebuild (tools/rebuild_mondb.py), so the
    two can never drift; Monitor.load is the reader."""
    from ..osdmap.encoding import incremental_to_dict, osdmap_to_dict
    return {
        "osdmap": osdmap_to_dict(osdmap),
        "incrementals": [incremental_to_dict(i) for i in incrementals],
        "monmap": monmap.to_bytes().decode("latin1"),
    }
