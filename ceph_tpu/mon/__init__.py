from .monitor import Monitor

__all__ = ["Monitor"]
