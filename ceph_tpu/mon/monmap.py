"""MonMap — the epoched monitor roster as a first-class map
(src/mon/MonMap.h role at lite scale).

Holds epoch, fsid, creation/change stamps, the name -> address
roster with ranks calculated by ADDRESS ORDER (MonMap::calc_ranks
sorts the addr map), and the persistent/optional feature sets
(mon/mon_types.h mon_feature_t).  Serialized as a magic-tagged JSON
blob — our own container format; the reference's wire encoding is a
non-goal, the TOOL surface (monmaptool) is pinned byte-exact against
src/test/cli/monmaptool instead.
"""
from __future__ import annotations

import json
import time
import uuid as _uuid
from typing import Dict, List, Optional, Tuple

MAGIC = b"CEPHTPU_MONMAP\x01"

# ceph::features::mon (mon/mon_types.h): the vintage's named persistent
# feature bits
FEATURE_NAMES = {1: "kraken", 2: "luminous", 4: "mimic"}
FEATURE_VALUES = {v: k for k, v in FEATURE_NAMES.items()}
SUPPORTED = 1 | 2 | 4
PERSISTENT = 1 | 2 | 4


def _stamp(t: float) -> str:
    lt = time.localtime(t)
    frac = int((t % 1) * 1_000_000)
    return time.strftime("%Y-%m-%d %H:%M:%S", lt) + f".{frac:06d}"


class MonMap:
    def __init__(self, fsid: Optional[str] = None):
        self.epoch = 0
        self.fsid = fsid or str(_uuid.uuid4())
        # cosmetic map-birth stamp in dumps; never compared
        # against fabric time
        self.created = time.time()  # lint: allow[no-wall-clock]
        self.last_changed = self.created
        self.mons: Dict[str, str] = {}       # name -> "ip:port/nonce"
        self.persistent_features = 0
        self.optional_features = 0

    # ---- roster ------------------------------------------------------------
    @staticmethod
    def _addr_key(addr: str) -> Tuple:
        hostport = addr.split("/", 1)[0]
        host, sep, port = hostport.rpartition(":")
        if not sep:                  # port-less address
            host, port = hostport, "0"
        try:
            ip = (0, tuple(int(x) for x in host.split(".")))
        except ValueError:
            ip = (1, (host,))        # hostnames sort after numerics
        return (ip, int(port) if port.isdigit() else 0)

    def add(self, name: str, addr: str) -> None:
        if name in self.mons:
            raise KeyError(name)
        if "/" not in addr:
            addr += "/0"
        self.mons[name] = addr

    def remove(self, name: str) -> None:
        del self.mons[name]

    def contains(self, name: str) -> bool:
        return name in self.mons

    def ranks(self) -> List[Tuple[str, str]]:
        """[(name, addr)] in rank order — by address, like
        MonMap::calc_ranks."""
        return sorted(self.mons.items(),
                      key=lambda kv: self._addr_key(kv[1]))

    # ---- io ----------------------------------------------------------------
    def to_bytes(self) -> bytes:
        return MAGIC + json.dumps({
            "epoch": self.epoch, "fsid": self.fsid,
            "created": self.created,
            "last_changed": self.last_changed, "mons": self.mons,
            "persistent_features": self.persistent_features,
            "optional_features": self.optional_features,
        }, sort_keys=True).encode()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "MonMap":
        if not raw.startswith(MAGIC):
            raise ValueError("not a monmap")
        d = json.loads(raw[len(MAGIC):])
        m = cls(fsid=d["fsid"])
        m.epoch = d["epoch"]
        m.created = d["created"]
        m.last_changed = d["last_changed"]
        m.mons = dict(d["mons"])
        m.persistent_features = d.get("persistent_features", 0)
        m.optional_features = d.get("optional_features", 0)
        return m

    # ---- print (MonMap::print, pinned by monmaptool cram) ------------------
    def print_lines(self) -> List[str]:
        out = [f"epoch {self.epoch}",
               f"fsid {self.fsid}",
               f"last_changed {_stamp(self.last_changed)}",
               f"created {_stamp(self.created)}"]
        for rank, (name, addr) in enumerate(self.ranks()):
            out.append(f"{rank}: {addr} mon.{name}")
        return out
