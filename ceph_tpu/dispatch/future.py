"""DispatchFuture — the handle a submitter holds while its request
waits in a batch.

The serving-stack analog: dynamic batching decouples *submission* from
*execution*, so every submit returns a future that resolves when the
flush containing the request lands.  Two consumption styles:

- ``result()``: the synchronous OSD paths (ec_backend's encode funnel)
  call it immediately — if the request is still queued this forces the
  owning queue to flush, so correctness NEVER depends on a timer or on
  other traffic arriving.  Coalescing happens when it can (concurrent
  submitters, batch_max triggers), never at the price of a stall.
- ``add_done_callback()``: async consumers (bench drivers, the EC
  write pipeline's continuation fan-out) get called on the flusher's
  thread.  Callback execution context: whichever thread resolves the
  future runs the callbacks inline — the submitter itself when the
  request executed inline or a backpressure ``force()``/``flush()``
  ran there, the OSD tick thread when the collection window expired,
  or another submitter whose demand flushed the shared queue.
  Consumers that touch shared state must therefore take their own
  locks (ec_backend's pipeline window does) and re-anchor their trace
  context (``g_tracer.activate``) — the thread-current span at
  callback time belongs to whoever flushed, not to the submitter.
- ``force()``: flush-on-demand WITHOUT blocking — runs the owning
  queue's flush inline (resolving this future and its batchmates via
  their callbacks) but never waits on another thread.  The write
  pipeline's backpressure forces its oldest pending future (falling
  back to the scheduler-wide ``flush()`` for mixed-signature
  windows): a full window empties by running the work, not by
  parking the submitter.

Error isolation contract: a future carries ITS request's exception
only.  One malformed or undecodable request in a batch must resolve
its own future with the error and leave every batchmate's bytes
untouched (scheduler._execute falls back to per-request execution when
a batched call throws).
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock
from typing import Any, Callable, List, Optional


class DispatchFuture:
    """Resolves exactly once with a value or an exception."""

    __slots__ = ("_event", "_value", "_exc", "_callbacks", "_lock",
                 "_flush_fn")

    def __init__(self, flush_fn: Optional[Callable[[], None]] = None):
        self._event = threading.Event()
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List[Callable[["DispatchFuture"], None]] = []
        self._lock = DebugLock("DispatchFuture::lock")
        # bound by the scheduler: forces the owning queue's flush so a
        # lone synchronous submitter can never deadlock on its own batch
        self._flush_fn = flush_fn

    # ---- producer side (scheduler) ----------------------------------------
    def set_result(self, value: Any) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._value = value
            self._event.set()
            cbs = self._drop_producer_refs()
        self._run_callbacks(cbs)

    def set_exception(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._event.set()
            cbs = self._drop_producer_refs()
        self._run_callbacks(cbs)

    def _drop_producer_refs(self) -> List:
        # the flush closure captures the Request (payload/chunk buffers,
        # codec) and the request points back here — clear both so a
        # consumer holding resolved futures doesn't pin dead payloads
        # until cyclic GC
        cbs = self._callbacks
        self._callbacks = []
        self._flush_fn = None
        return cbs

    def _run_callbacks(self, cbs) -> None:
        # concurrent.futures semantics: a raising consumer callback is
        # the consumer's bug, never the batch's — it must not abort the
        # resolution of batchmates or masquerade as a device failure
        for cb in cbs:
            try:
                cb(self)
            except Exception:               # noqa: BLE001
                import logging
                logging.getLogger(__name__).exception(
                    "dispatch future callback raised")

    # ---- consumer side -----------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def force(self) -> None:
        """Flush-on-demand: execute the owning queue's flush inline if
        this request is still queued.  Unlike ``result()`` this never
        waits — when the request is already executing on another
        thread the call returns immediately and completion arrives via
        ``add_done_callback``."""
        if not self._event.is_set() and self._flush_fn is not None:
            self._flush_fn()

    def result(self, timeout: Optional[float] = None) -> Any:
        """The request's own outcome; forces a flush when still queued."""
        if not self._event.is_set() and self._flush_fn is not None:
            self._flush_fn()
        if not self._event.wait(timeout):
            raise TimeoutError("dispatch request did not complete")
        if self._exc is not None:
            raise self._exc
        return self._value

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.is_set() and self._flush_fn is not None:
            self._flush_fn()
        if not self._event.wait(timeout):
            raise TimeoutError("dispatch request did not complete")
        return self._exc

    def add_done_callback(self,
                          cb: Callable[["DispatchFuture"], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        self._run_callbacks([cb])
