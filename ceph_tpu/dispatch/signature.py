"""Codec signatures and chunk-size bucketing — the batching key.

Two requests may share one device call only when the math guarantees
the coalesced output is byte-identical to running them alone:

1. Same *codec signature*: ``(family, k, m, technique, w, packetsize,
   chunk_mapping)``.  Codec instances are deterministic functions of
   this tuple (the encode matrix is derived from it), so requests from
   different pools — and even different plugin instances, e.g. the tpu
   and isa plugins which share matrix semantics by construction — can
   ride one call.  Codecs that don't opt in (``codec_signature``
   returning an identity-unique tuple) never group.
2. Same *chunk-size bucket*: chunk sizes are rounded up to the next
   power of two and requests padded with zero columns to the bucket
   width, so the jit compile cache holds O(log C) shapes per signature
   instead of one per distinct pool chunk size.  Zero-padding is
   output-preserving because the codes are columnwise independent:
   pointwise byte codes (RS/cauchy matrices) treat every byte column
   separately, and block-structured codes (jerasure bitmatrix packets)
   treat every ``stripe_block`` of columns separately — so the pad is
   only legal when it is a whole number of blocks (checked here; a
   misaligned codec falls back to uncoalesced execution, which is
   always correct).

Decode requests additionally key on (available chunk ids, wanted
chunk ids): the recovery matrix is a function of the survivor set, so
mixed erasure patterns cannot share a matmul.
"""
from __future__ import annotations

from typing import Tuple

# kinds of work the scheduler understands
KIND_ENCODE = "encode"
KIND_DECODE = "decode"           # reconstruct specific shards (recovery)
KIND_DECODE_CONCAT = "decode_concat"  # rebuild the logical payload (reads)


def codec_signature(ec_impl) -> Tuple:
    """The impl's grouping signature; falls back to an identity-unique
    tuple for codecs that don't declare one (never grouped, always
    executed alone — correct by construction)."""
    sig = getattr(ec_impl, "codec_signature", None)
    if sig is not None:
        return sig()
    return (type(ec_impl).__name__, id(ec_impl))


def stripe_block_of(ec_impl) -> int:
    """Columnwise-independence granularity (1 = pointwise byte codes;
    jerasure packet/word layouts override ``_stripe_block``)."""
    fn = getattr(ec_impl, "_stripe_block", None)
    try:
        return int(fn()) if fn is not None else 1
    except Exception:
        return 1


def next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def bucket_chunk_size(chunk_size: int, block: int = 1) -> int:
    """Power-of-two bucket for a chunk size, rounded up to a whole
    number of code blocks so the zero-pad never splits a block."""
    b = next_pow2(max(chunk_size, 1))
    if block > 1 and b % block:
        b += block - (b % block)
    return b


def batchable(ec_impl, chunk_size: int, kind: str) -> bool:
    """May requests of this (impl, chunk size, kind) coalesce with
    signature-mates?  False routes the request through the exact
    per-request path inside its flush — always correct, never faster."""
    if not getattr(ec_impl, "dispatch_batchable", False):
        return False
    if kind == KIND_ENCODE:
        if not hasattr(ec_impl, "encode_batch"):
            return False
        # mapped layouts (lrc-style) take the encode_batch_full /
        # per-stripe route in ecutil.encode; keep them uncoalesced
        if ec_impl.get_chunk_mapping():
            return False
    elif not hasattr(ec_impl, "decode_batch"):
        return False
    elif getattr(ec_impl, "dispatch_full_output", False):
        # full-output codecs' below-d decode interprets sub-chunk
        # positions, which bucket padding would shift — decode kinds
        # run uncoalesced (still through the dispatcher accounting,
        # still fault-guarded inside the codec)
        return False
    # the pad from chunk_size to its bucket must be whole blocks:
    # chunk_size % block == 0 here plus bucket_chunk_size rounding the
    # bucket up to a block multiple together guarantee it
    return chunk_size % stripe_block_of(ec_impl) == 0
