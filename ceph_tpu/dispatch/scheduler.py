"""DeviceDispatcher — the cross-PG dynamic-batching device scheduler.

The paper's >=10x encode claim comes from batching all stripes of ONE
op into a single MXU call; under heavy traffic an OSD process sees many
*concurrent* small EC ops across PGs, each paying a full device
dispatch — the per-call overhead regime of the batched-XOR literature
(arxiv 2108.02692), one level above the reference's per-stripe loop
(osd/ECUtil.cc:120-159).  This scheduler coalesces those ops: requests
queue per codec signature + chunk-size bucket (signature.py), flush on
a size trigger (``ec_dispatch_batch_max``), an age trigger
(``ec_dispatch_batch_window_us``), an explicit ``flush()``, or a
submitter demanding its result — the window is a collection
opportunity, never a latency floor, so ``window=0`` (the default) is an
exact passthrough to the uncoalesced path and any synchronous caller
gets today's behavior byte-for-byte.

Backpressure: a bounded total queue (``ec_dispatch_queue_max``)
force-flushes everything when full, so memory is bounded by config and
a stalled consumer cannot pile up unresolved futures.

Error isolation: a batched call that throws falls back to per-request
execution; each request's future then carries its own result or its
own error (one poisoned request never fails its batchmates).

Observability (the PR 2 machinery): a ``batch_dispatch`` span whose
children are the coalesced requests, a batch-occupancy PerfHistogram,
``dispatch dump`` on the admin socket, ``dispatch`` perf counters on
the mgr's Prometheus surface.  All host-side: with tracing disabled
the dispatcher adds ZERO device syncs per op (fence-count enforced).
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock, DebugRLock
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.config import g_conf
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..fault import g_faults
from ..trace import (g_devprof, g_oplat, g_perf_histograms, g_tracer,
                     occupancy_axes)
from ..trace.oplat import OpLedger
from .batch import Request, run_group, run_one
from .future import DispatchFuture
from .signature import (KIND_DECODE, KIND_DECODE_CONCAT, KIND_ENCODE,
                        batchable, bucket_chunk_size, codec_signature,
                        stripe_block_of)

# ---- perf counters ---------------------------------------------------------
DISPATCH_FIRST = 91000
l_dispatch_submitted = 91001      # requests submitted
l_dispatch_passthrough = 91002    # executed inline (window 0 / unbatchable)
l_dispatch_batches = 91003        # coalesced device flushes
l_dispatch_batched_reqs = 91004   # requests through coalesced flushes
l_dispatch_coalesced = 91005      # requests that shared a flush with >=1 mate
l_dispatch_fallbacks = 91006      # batched calls that fell back per-request
l_dispatch_errors = 91007         # requests that resolved with an error
l_dispatch_backpressure = 91008   # forced flushes from a full queue
l_dispatch_stripes = 91009        # stripes through the dispatcher
l_dispatch_bytes = 91010          # payload bytes through the dispatcher
l_dispatch_flush_time = 91011     # time inside flush execution
l_dispatch_fallback_reqs = 91012  # requests re-run alone after a
                                  # batched call fell back
DISPATCH_LAST = 91020

_dispatch_pc: Optional[PerfCounters] = None
_dispatch_pc_lock = DebugLock("dispatch_pc::init")


def dispatch_perf_counters() -> PerfCounters:
    """The dispatcher's counter logger (perf dump / Prometheus)."""
    global _dispatch_pc
    if _dispatch_pc is not None:
        return _dispatch_pc
    with _dispatch_pc_lock:
        if _dispatch_pc is None:
            b = PerfCountersBuilder("dispatch", DISPATCH_FIRST,
                                    DISPATCH_LAST)
            b.add_u64_counter(l_dispatch_submitted, "submitted",
                              "codec requests submitted")
            b.add_u64_counter(l_dispatch_passthrough, "passthrough",
                              "requests executed inline (window 0 or "
                              "unbatchable codec)")
            b.add_u64_counter(l_dispatch_batches, "batches",
                              "coalesced device flushes")
            b.add_u64_counter(l_dispatch_batched_reqs, "batched_reqs",
                              "requests through coalesced flushes")
            b.add_u64_counter(l_dispatch_coalesced, "coalesced_reqs",
                              "requests that shared a flush with a "
                              "batchmate")
            b.add_u64_counter(l_dispatch_fallbacks, "batch_fallbacks",
                              "batched calls that fell back to "
                              "per-request")
            b.add_u64_counter(l_dispatch_errors, "request_errors",
                              "requests resolved with an error")
            b.add_u64_counter(l_dispatch_backpressure,
                              "backpressure_flushes",
                              "forced flushes from a full queue")
            b.add_u64_counter(l_dispatch_stripes, "stripes",
                              "stripes through the dispatcher")
            b.add_u64_counter(l_dispatch_bytes, "bytes",
                              "payload bytes through the dispatcher")
            b.add_u64_counter(l_dispatch_fallback_reqs,
                              "dispatch_fallback",
                              "requests re-executed alone after their "
                              "batched call fell back")
            b.add_time_avg(l_dispatch_flush_time, "flush",
                           "time inside flush execution")
            _dispatch_pc = b.create_perf_counters()
    return _dispatch_pc


class _Queue:
    """Pending requests of one (kind, signature, bucket[, erasure])."""

    __slots__ = ("key", "reqs", "deadline", "bucket_c")

    def __init__(self, key, bucket_c: int, deadline: float):
        self.key = key
        self.reqs: List[Request] = []
        self.deadline = deadline
        self.bucket_c = bucket_c


class DeviceDispatcher:
    def __init__(self):
        self._lock = DebugRLock("DeviceDispatcher::lock")
        self._queues: "OrderedDict[Tuple, _Queue]" = OrderedDict()
        self._pending = 0

    # ---- options (read live so `config set` applies without restart) ------
    @staticmethod
    def _opts() -> Tuple[int, int, int]:
        return (int(g_conf.get_val("ec_dispatch_batch_max")),
                int(g_conf.get_val("ec_dispatch_batch_window_us")),
                int(g_conf.get_val("ec_dispatch_queue_max")))

    @property
    def _hist(self):
        return g_perf_histograms.get(
            "dispatch", "dispatch_batch_occupancy_histogram",
            occupancy_axes)

    # ---- synchronous entry points (the ec_backend funnel) ------------------
    # On the default window=0 these skip future/lambda construction
    # entirely: the hot write path pays one Request object and the same
    # ecutil call it always made, nothing else.
    def encode(self, sinfo, ec_impl, data, want) -> Dict[int, np.ndarray]:
        req = Request(KIND_ENCODE, sinfo, ec_impl, payload=data,
                      want=want)
        if not self._queueable(req):
            return self._run_inline(req)
        return self._submit(req).result()

    def decode_concat(self, sinfo, ec_impl, chunks) -> np.ndarray:
        req = Request(KIND_DECODE_CONCAT, sinfo, ec_impl,
                      chunks=dict(chunks))
        if not self._queueable(req):
            return self._run_inline(req)
        return self._submit(req).result()

    def decode(self, sinfo, ec_impl, chunks, need) -> Dict[int, np.ndarray]:
        req = Request(KIND_DECODE, sinfo, ec_impl, chunks=dict(chunks),
                      need=need)
        if not self._queueable(req):
            return self._run_inline(req)
        return self._submit(req).result()

    # ---- async entry points ------------------------------------------------
    def submit_encode(self, sinfo, ec_impl, data, want) -> DispatchFuture:
        return self._submit(Request(KIND_ENCODE, sinfo, ec_impl,
                                    payload=data, want=want))

    def submit_decode_concat(self, sinfo, ec_impl,
                             chunks) -> DispatchFuture:
        return self._submit(Request(KIND_DECODE_CONCAT, sinfo, ec_impl,
                                    chunks=dict(chunks)))

    def submit_decode(self, sinfo, ec_impl, chunks,
                      need) -> DispatchFuture:
        return self._submit(Request(KIND_DECODE, sinfo, ec_impl,
                                    chunks=dict(chunks), need=need))

    # ---- core --------------------------------------------------------------
    def _queueable(self, req: Request) -> bool:
        _batch_max, window_us, _queue_max = self._opts()
        return (window_us > 0 and req.n_stripes > 0
                and batchable(req.ec_impl, req.chunk_size, req.kind))

    def _account(self, req: Request) -> PerfCounters:
        pc = dispatch_perf_counters()
        pc.inc(l_dispatch_submitted)
        pc.inc(l_dispatch_bytes, req.nbytes)
        pc.inc(l_dispatch_stripes, req.n_stripes)
        return pc

    @staticmethod
    def _req_ledger(req: Request) -> OpLedger:
        """The stage ledger this request's device stages land on: the
        submitting op's (contextvar, like the span capture) or a fresh
        one homed on the ``dispatch`` daemon for op-less submitters
        (bench drivers) — device stages are accounted either way.  An
        op ledger also gets its ``op_service`` boundary stamped here:
        the codec submit ends the op-thread service interval."""
        led = g_oplat.current()
        if led is None:
            led = OpLedger("dispatch")
        else:
            led.mark("op_service")
        req.ledger = led
        return led

    def _run_inline(self, req: Request):
        """Exact passthrough: today's call, inline, no extra spans, no
        future machinery; errors propagate to the caller unchanged."""
        pc = self._account(req)
        pc.inc(l_dispatch_passthrough)
        self._hist.inc(1)
        led = self._req_ledger(req)
        try:
            # no collection window on the passthrough path, so no
            # batch_window stage; ecutil stamps device_call when the
            # codec returns, the d2h mark below closes the fetch
            with g_oplat.activate(led):
                out = run_one(req)
            led.mark("d2h")
            return out
        except Exception:
            pc.inc(l_dispatch_errors)
            raise

    def _submit(self, req: Request) -> DispatchFuture:
        batch_max, window_us, queue_max = self._opts()
        fut = DispatchFuture(flush_fn=lambda: self._force(req))
        req.future = fut
        req.parent_span = g_tracer.current() if g_tracer.enabled else None
        req.trace_id = g_tracer.current_trace_id() if g_tracer.enabled \
            else 0
        pc = self._account(req)
        led = self._req_ledger(req)
        if not self._queueable(req):
            pc.inc(l_dispatch_passthrough)
            self._hist.inc(1)
            try:
                with g_oplat.activate(led):
                    out = run_one(req)
                led.mark("d2h")
                fut.set_result(out)
            except Exception as e:
                pc.inc(l_dispatch_errors)
                fut.set_exception(e)
            return fut
        req.batchable = True
        block = stripe_block_of(req.ec_impl)
        bucket_c = bucket_chunk_size(req.chunk_size, block)
        extra: Tuple = ()
        if req.kind != KIND_ENCODE:
            # the recovery matrix is a function of (survivors, wanted):
            # mixed erasure patterns must not share a matmul
            extra = (tuple(sorted(req.chunks)), tuple(req.need))
        # keyed by the BUCKET, not the exact chunk size: pools whose
        # chunk sizes share a power-of-two bucket coalesce (each request
        # is padded to the bucket width and sliced back to its own)
        req.key = (req.kind, codec_signature(req.ec_impl),
                   bucket_c) + extra
        now = time.monotonic()
        ready: Optional[_Queue] = None
        overflow: List[_Queue] = []
        with self._lock:
            if self._pending >= queue_max:
                pc.inc(l_dispatch_backpressure)
                overflow = list(self._queues.values())
                self._queues.clear()
                self._pending = 0
            q = self._queues.get(req.key)
            if q is None:
                q = _Queue(req.key, bucket_c, now + window_us / 1e6)
                self._queues[req.key] = q
            q.reqs.append(req)
            req.enq_t = now
            self._pending += 1
            if len(q.reqs) >= batch_max:
                ready = self._queues.pop(req.key)
                self._pending -= len(ready.reqs)
        for oq in overflow:
            self._execute(oq.reqs, oq.bucket_c)
        if ready is not None:
            self._execute(ready.reqs, ready.bucket_c)
        else:
            self.poll(now)
        return fut

    def _force(self, req: Request) -> None:
        """A submitter demands its result: flush the owning queue NOW
        (correctness never depends on a timer or on other traffic)."""
        with self._lock:
            q = self._queues.get(req.key) if req.key is not None else None
            if q is None or not any(r is req for r in q.reqs):
                return      # in flight on another thread, or done
            self._queues.pop(req.key)
            self._pending -= len(q.reqs)
        self._execute(q.reqs, q.bucket_c)

    def poll(self, now: Optional[float] = None) -> int:
        """Flush queues whose collection window expired (driven from the
        OSD tick and opportunistically from submit)."""
        if now is None:
            now = time.monotonic()
        expired: List[_Queue] = []
        with self._lock:
            for key in [k for k, q in self._queues.items()
                        if q.deadline <= now]:
                q = self._queues.pop(key)
                self._pending -= len(q.reqs)
                expired.append(q)
        n = 0
        for q in expired:
            n += len(q.reqs)
            self._execute(q.reqs, q.bucket_c)
        return n

    def pending_count(self) -> int:
        """Requests currently queued (cheap probe for idle kickers:
        the mini-cluster fabric flushes on quiescence so pipelined
        submitters never depend on a wall-clock window)."""
        return self._pending

    def flush(self) -> int:
        """Flush everything pending regardless of deadline; returns the
        number of requests executed."""
        with self._lock:
            qs = list(self._queues.values())
            self._queues.clear()
            self._pending = 0
        n = 0
        for q in qs:
            n += len(q.reqs)
            self._execute(q.reqs, q.bucket_c)
        return n

    def _execute(self, reqs: List[Request], bucket_c: int) -> None:
        """Run one coalesced group and resolve every future exactly
        once.  Runs OUTSIDE the queue lock so new submitters keep
        accumulating into fresh queues while the device call is in
        flight — that overlap is where coalescing comes from."""
        if not reqs:
            return
        pc = dispatch_perf_counters()
        t0 = time.perf_counter()
        span = g_tracer.begin("batch_dispatch", daemon="dispatch") \
            if g_tracer.enabled else None
        children = []
        if span is not None:
            span.tags["occupancy"] = len(reqs)
            span.tags["bucket_chunk"] = bucket_c
            for r in reqs:
                ch = g_tracer.begin(
                    f"batched_req:{r.kind}", daemon="dispatch",
                    trace_id=r.trace_id or span.trace_id,
                    parent_id=span.span_id)
                if ch is not None:
                    ch.tags["bytes"] = r.nbytes
                children.append(ch)
        # stage ledger: one flush boundary ends every batched request's
        # batch-window wait (each op in the batch accrues the full
        # window it spent collecting — per-op attribution, docstring of
        # oplat.breakdown_since)
        t_launch = time.perf_counter()
        for r in reqs:
            if r.ledger is not None:
                r.ledger.mark("batch_window", t_launch)
        outcomes: List = []
        with g_tracer.activate(span), g_devprof.stage("dispatch.batch"):
            try:
                if g_faults.site_armed("dispatch.batch"):
                    g_faults.check("dispatch.batch",
                                   ctx=str(reqs[0].key or reqs[0].kind))
                # single-request groups execute via run_one -> ecutil,
                # which stamps device_call on the CURRENT ledger;
                # multi-request groups are stamped inside run_group
                with g_oplat.activate(
                        reqs[0].ledger if len(reqs) == 1 else None):
                    outcomes = [(True, res)
                                for res in run_group(reqs, bucket_c)]
            except Exception as batch_err:   # noqa: BLE001 — isolated
                # fail-fast isolation: re-run each request alone so one
                # bad request cannot poison its batchmates
                pc.inc(l_dispatch_fallbacks)
                if span is not None:
                    span.event("batch_fallback", error=repr(batch_err))
                for r in reqs:
                    pc.inc(l_dispatch_fallback_reqs)
                    if r.parent_span is not None:
                        # surface the degradation on the SUBMITTER's op
                        # span, where slow-op forensics will look
                        r.parent_span.event("dispatch_fallback",
                                            kind=r.kind,
                                            error=repr(batch_err))
                    try:
                        with g_oplat.activate(r.ledger):
                            outcomes.append((True, run_one(r)))
                    except Exception as e:   # noqa: BLE001 — per-req
                        pc.inc(l_dispatch_errors)
                        outcomes.append((False, e))
        t_done = time.perf_counter()
        for r in reqs:
            if r.ledger is not None:
                # outputs are host-materialized by the run: the d2h
                # stage closes each request's device round trip
                r.ledger.mark("d2h", t_done)
        for ch in children:
            g_tracer.finish(ch)
        g_tracer.finish(span)
        # resolve OUTSIDE the execution try: a raising consumer
        # callback must never be mistaken for a device failure and
        # trigger a re-execution of the whole batch
        for r, (ok, val) in zip(reqs, outcomes):
            if ok:
                r.future.set_result(val)
            else:
                r.future.set_exception(val)
        self._hist.inc(len(reqs))
        pc.inc(l_dispatch_batches)
        pc.inc(l_dispatch_batched_reqs, len(reqs))
        if len(reqs) > 1:
            pc.inc(l_dispatch_coalesced, len(reqs))
        pc.tinc(l_dispatch_flush_time, time.perf_counter() - t0)

    # ---- introspection (admin socket `dispatch dump`) ----------------------
    def dump(self) -> Dict:
        batch_max, window_us, queue_max = self._opts()
        now = time.monotonic()
        with self._lock:
            queues = [{
                "kind": q.key[0],
                "signature": list(map(str, q.key[1])),
                "bucket_chunk_size": q.bucket_c,
                "pending": len(q.reqs),
                "age_us": round(max(
                    (now - q.reqs[0].enq_t) * 1e6, 0.0), 1)
                if q.reqs else 0.0,
            } for q in self._queues.values()]
            pending = self._pending
        return {
            "options": {"ec_dispatch_batch_max": batch_max,
                        "ec_dispatch_batch_window_us": window_us,
                        "ec_dispatch_queue_max": queue_max},
            "pending": pending,
            "queues": queues,
            "counters": dispatch_perf_counters().dump(),
            "occupancy_histogram": self._hist.dump(),
            "mesh": self._mesh_dump(),
        }

    @staticmethod
    def _mesh_dump() -> Dict:
        """The mesh runtime's state rides `dispatch dump`: the mesh is
        the flush path's device back end, so operators read one pane."""
        from ..mesh import g_mesh
        return g_mesh.dump()


# process-wide scheduler: one accelerator per process, like g_tracer
# (each reference OSD is its own process; the mini-cluster's daemons
# share one, so one dispatcher coalesces across them the way one chip
# serves them)
g_dispatcher = DeviceDispatcher()
