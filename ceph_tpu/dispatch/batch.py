"""Batch assembly: many queued codec requests -> ONE padded device call.

The same shape the paper exploits *within* one op (all stripes of one
object in a single MXU call) applied one level up: all stripes of all
queued ops of one codec signature.  Assembly is pure numpy reshaping;
the single device call goes through the codec's own batched entry
points (``encode_batch`` / ``decode_batch``), so the kernel-timer and
backend-selection behavior of the uncoalesced path is preserved.

Correctness contract (property-tested): for every request in a group,
slicing its rows/columns back out of the coalesced result is
byte-identical to running the request alone.  This holds because (a)
stripes are independent — concatenating along S changes nothing, and
(b) the zero-pad from C to the bucket width is whole code blocks, and
blocks are columnwise independent (signature.batchable enforces it).

Requests are executed via the exact ecutil entry points when alone
(``run_one`` IS the passthrough path — not a reimplementation of it),
so window=0 behavior is today's behavior by construction.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..trace.devprof import g_devprof
from .signature import (KIND_DECODE, KIND_DECODE_CONCAT, KIND_ENCODE,
                        next_pow2)


class Request:
    """One queued codec work item (encode / decode / reconstruct)."""

    __slots__ = ("kind", "sinfo", "ec_impl", "payload", "chunks", "need",
                 "want", "future", "parent_span", "trace_id", "nbytes",
                 "n_stripes", "chunk_size", "enq_t", "batchable", "key",
                 "ledger")

    def __init__(self, kind: str, sinfo, ec_impl, *, payload=None,
                 chunks=None, need=None, want=None):
        self.kind = kind
        self.sinfo = sinfo
        self.ec_impl = ec_impl
        self.payload = payload            # np.uint8 1D (encode)
        self.chunks = chunks              # {chunk id: np.uint8 1D}
        self.need = tuple(need) if need is not None else ()
        self.want = set(want) if want is not None else set()
        self.future = None                # bound by the scheduler
        self.parent_span = None
        self.trace_id = 0
        self.chunk_size = sinfo.get_chunk_size()
        if kind == KIND_ENCODE:
            self.nbytes = len(payload)
            self.n_stripes = (len(payload)
                              // max(sinfo.get_stripe_width(), 1))
        else:
            total = len(next(iter(chunks.values()))) if chunks else 0
            self.nbytes = sum(len(b) for b in chunks.values())
            self.n_stripes = total // max(self.chunk_size, 1)
        self.enq_t = 0.0
        self.batchable = False
        self.key = None
        self.ledger = None       # stage-latency ledger (trace/oplat)


def _ecutil():
    # deferred: osd.ecutil is dependency-free, but importing it through
    # the osd package at module-load time would cycle with ec_backend
    from ..osd import ecutil
    return ecutil


def _mark_device_call(reqs: List["Request"]) -> None:
    """One batched codec call just returned: stamp every batchmate's
    stage ledger (each op waited on the SAME call — per-op attribution,
    like the batch_window stamp in scheduler._execute)."""
    t = time.perf_counter()
    for r in reqs:
        if r.ledger is not None:
            r.ledger.mark("device_call", t)


def run_one(req: Request):
    """Exact per-request execution — the window=0 passthrough path and
    the fallback when a batched call throws.  Calls the SAME ecutil
    entry points ec_backend always called, so outputs are identical to
    the pre-dispatcher code by construction."""
    eu = _ecutil()
    if req.kind == KIND_ENCODE:
        return eu.encode(req.sinfo, req.ec_impl, req.payload, req.want)
    arrays = {i: np.asarray(b, dtype=np.uint8)
              for i, b in req.chunks.items()}
    if req.kind == KIND_DECODE_CONCAT:
        return eu.decode_concat(req.sinfo, req.ec_impl, arrays)
    return eu.decode(req.sinfo, req.ec_impl, arrays, list(req.need))


def _pad_cols(a: np.ndarray, cb: int) -> np.ndarray:
    """Zero-pad the last (byte-column) axis to the bucket width.  A
    real pad is a whole-buffer host copy — accounted on the device-flow
    profiler so the copy ledger shows what bucket padding costs."""
    c = a.shape[-1]
    if c == cb:
        return a
    width = [(0, 0)] * (a.ndim - 1) + [(0, cb - c)]
    out = np.pad(a, width)
    g_devprof.account_host_copy("dispatch.pad_cols", out.nbytes)
    return out


def _pad_stripes(big: np.ndarray, use_device: bool) -> np.ndarray:
    """Pad the stripe axis to a power of two on the device path so the
    jit cache sees O(log S) batch shapes, not one per occupancy mix.
    Zero stripes encode/decode independently and are sliced off."""
    s = big.shape[0]
    if not use_device:
        return big
    st = next_pow2(s)
    if st == s:
        return big
    width = [(0, st - s)] + [(0, 0)] * (big.ndim - 1)
    out = np.pad(big, width)
    g_devprof.account_host_copy("dispatch.pad_stripes", out.nbytes)
    return out


def run_group(reqs: List[Request], bucket_c: int) -> List:
    """One coalesced device call for a signature/bucket group; returns
    per-request results aligned with *reqs*.  Any failure propagates to
    the caller, which re-runs each request alone so one bad request
    cannot poison its batchmates.

    With a mesh up (ceph_tpu/mesh, ``ec_mesh_chips``) encode groups —
    including single-request flushes, whose stripes alone can span the
    chips — execute through the mesh runtime instead of one device;
    mesh off (the default) or size 1 is the existing path by
    construction.  Decode/reconstruct groups ride the mesh the same
    way, but one level down: every path here funnels into the codec's
    ``decode_batch``, whose mesh hook (matrix_plugin.py /
    regenerating.py -> ``decode_stacked``) shards the survivor stack
    across chips — so singles, coalesced groups, recovery reads and
    repair solves all inherit the meshed decode without this module
    dispatching them specially."""
    leader = reqs[0].ec_impl
    kind = reqs[0].kind
    use_device = bool(getattr(leader, "_use_device", lambda: False)())
    if kind == KIND_ENCODE:
        if len(reqs) > 1 or (use_device and _mesh_active()):
            return _run_group_encode(reqs, bucket_c, leader, use_device)
        return [run_one(reqs[0])]
    if len(reqs) == 1:
        return [run_one(reqs[0])]
    return _run_group_decode(reqs, bucket_c, leader, use_device, kind)


def _mesh_active() -> bool:
    from ..mesh import g_mesh
    return g_mesh.active()


def _run_group_encode(reqs, bucket_c, leader, use_device):
    # requests may carry different pool chunk sizes within one bucket:
    # each is zero-padded to the bucket width and sliced back to its own
    # width (columnwise independence makes the pad invisible)
    k = leader.get_data_chunk_count()
    # full-output codecs (product-matrix regenerating): the payload
    # assembles into message matrices via the codec's own hook, and
    # encode_batch yields EVERY shard row — the post-matmul slice takes
    # all rows from the coalesced result, none from the input
    prepare = getattr(leader, "regen_prepare_batch", None)
    full_out = bool(getattr(leader, "dispatch_full_output", False))
    raw, offsets, s0 = [], [], 0
    for r in reqs:
        if prepare is not None:
            stripes = prepare(r.payload, r.n_stripes)
        else:
            stripes = np.frombuffer(bytes(r.payload), dtype=np.uint8) \
                if not isinstance(r.payload, np.ndarray) else r.payload
            stripes = stripes.reshape(r.n_stripes, k, r.chunk_size)
        raw.append(stripes)
        offsets.append((s0, stripes))
        s0 += r.n_stripes
    coding = None
    if use_device:
        # mesh path: the runtime assembles straight into its pooled
        # padded staging buffer and shards the batch axis across the
        # chips; None means mesh down / codec not row-shardable /
        # guarded call exhausted — the single-device path below is the
        # degradation, exactly as before the mesh existed
        from ..mesh import g_mesh
        coding = g_mesh.encode_stacked(leader, raw, bucket_c)
    if coding is None:
        stacks = [_pad_cols(st, bucket_c) for st in raw]
        if len(stacks) == 1:
            # a single-request flush only reaches here when the mesh
            # declined it mid-flight: run the exact per-request path
            return [run_one(reqs[0])]
        stacked = np.ascontiguousarray(np.concatenate(stacks))
        g_devprof.account_host_copy("dispatch.stack", stacked.nbytes)
        big = _pad_stripes(stacked, use_device)
        coding = leader.encode_batch(big)      # (S_total[, pad], m, Cb)
    _mark_device_call(reqs)
    coding = np.asarray(coding)
    out: List[Dict[int, np.ndarray]] = []
    for r, (off, stripes) in zip(reqs, offsets):
        # one contiguous pack per request, shard outputs as row views
        # (the fan-out sends memoryviews of these rows — same idiom as
        # ecutil._pack_rows)
        want_l = sorted(r.want)
        pack = np.empty((len(want_l), r.n_stripes * r.chunk_size),
                        dtype=np.uint8)
        for j, i in enumerate(want_l):
            dst = pack[j].reshape(r.n_stripes, r.chunk_size)
            if full_out:
                dst[:] = coding[off:off + r.n_stripes, i, :r.chunk_size]
            elif i < k:
                dst[:] = stripes[:, i, :]
            else:
                dst[:] = coding[off:off + r.n_stripes, i - k,
                                :r.chunk_size]
        g_devprof.account_host_copy("dispatch.pack_shards", pack.nbytes)
        out.append({i: pack[j] for j, i in enumerate(want_l)})
    return out


def _run_group_decode(reqs, bucket_c, leader, use_device, kind):
    k = leader.get_data_chunk_count()
    ids = sorted(reqs[0].chunks)
    stacked: Dict[int, np.ndarray] = {}
    for cid in ids:
        parts = [_pad_cols(np.asarray(r.chunks[cid], dtype=np.uint8)
                           .reshape(r.n_stripes, r.chunk_size), bucket_c)
                 for r in reqs]
        joined = np.ascontiguousarray(np.concatenate(parts))
        g_devprof.account_host_copy("dispatch.stack", joined.nbytes)
        stacked[cid] = _pad_stripes(joined, use_device)
    if kind == KIND_DECODE_CONCAT:
        want_phys = [leader.chunk_index(i) for i in range(k)]
    else:
        want_phys = list(reqs[0].need)
    got = leader.decode_batch(stacked, want_phys)
    _mark_device_call(reqs)
    got = {i: np.asarray(b) for i, b in got.items()}
    out: List = []
    s0 = 0
    for r in reqs:
        s1, c = s0 + r.n_stripes, r.chunk_size
        if kind == KIND_DECODE_CONCAT:
            data = np.stack([got[want_phys[i]][s0:s1, :c]
                             for i in range(k)], axis=1)   # (S, k, C)
            out.append(np.ascontiguousarray(data).reshape(-1))
        else:
            out.append({i: np.ascontiguousarray(
                got[i][s0:s1, :c]).reshape(-1) for i in r.need})
        s0 = s1
    return out
