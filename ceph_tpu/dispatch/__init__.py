"""Cross-PG dynamic-batching device scheduler for EC codec work.

The serving-stack pattern (dynamic batching) landed behind Ceph's
plugin boundary: concurrent encode/decode/reconstruct requests from
every PG on an OSD coalesce into one padded batched device call per
flush.  See docs/DISPATCH.md for the queueing model, bucketing rules,
tuning knobs, and the window=0 exact-passthrough contract.
"""
from .batch import Request, run_group, run_one
from .future import DispatchFuture
from .scheduler import (DeviceDispatcher, dispatch_perf_counters,
                        g_dispatcher)
from .signature import (KIND_DECODE, KIND_DECODE_CONCAT, KIND_ENCODE,
                        batchable, bucket_chunk_size, codec_signature)

__all__ = [
    "Request", "run_group", "run_one",
    "DispatchFuture",
    "DeviceDispatcher", "dispatch_perf_counters", "g_dispatcher",
    "KIND_DECODE", "KIND_DECODE_CONCAT", "KIND_ENCODE",
    "batchable", "bucket_chunk_size", "codec_signature",
]
