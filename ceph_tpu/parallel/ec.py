"""Sharded GF(2^8) erasure coding over a device mesh.

The encode matmul ``bits(S, C, k*8) @ B(k*8, m*8)`` shards S over the
``stripe`` axis and the m*8 output columns over the ``shard`` axis — a pure
SPMD layout needing zero collectives on the forward path (the contraction
dimension stays replicated), so throughput scales linearly with chips the
way Ceph scales EC across OSDs.  Decode reuses the identical matmul with the
host-inverted survivor matrix.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.gf_matmul import gf_bit_matmul, DeviceRSBackend
from .mesh import STRIPE_AXIS, SHARD_AXIS


class ShardedRS:
    """Mesh-wide executor for one (k+m, k) systematic code.

    Wraps :class:`~ceph_tpu.ops.gf_matmul.DeviceRSBackend` with explicit
    shardings; falls back to single-device semantics when the mesh has one
    device, so callers never branch.
    """

    def __init__(self, encode_matrix: np.ndarray, mesh: Mesh):
        self.mesh = mesh
        self.backend = DeviceRSBackend(encode_matrix)
        self.k = self.backend.k
        self.m = self.backend.m
        # data (S, k, C): shard stripes; chunk + byte dims replicated
        self.data_sharding = NamedSharding(mesh, P(STRIPE_AXIS, None, None))
        # bit matrix (k*8, m*8): shard output columns over the shard axis
        self.mat_sharding = NamedSharding(mesh, P(None, SHARD_AXIS))
        self.out_sharding = NamedSharding(mesh, P(STRIPE_AXIS, None, None))
        self._enc_bits = jax.device_put(
            self.backend._enc_bits, self.mat_sharding)
        # one wrapper serves encode and decode: jit caches per shape
        self._matmul_jit = jax.jit(
            gf_bit_matmul, out_shardings=self.out_sharding)
        # sharded decode bit-matrices keyed like the backend's host cache
        self._dev_decode_bits: dict = {}

    # -- encode -------------------------------------------------------------
    def encode_device(self, data: jnp.ndarray) -> jnp.ndarray:
        """(S, k, C) uint8 -> (S, m, C); the stripe-axis size must divide
        S (each device takes S/stripe_axis stripes)."""
        data = jax.device_put(data, self.data_sharding)
        return self._matmul_jit(data, self._enc_bits)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self.encode_device(jnp.asarray(data)))

    # -- decode -------------------------------------------------------------
    def decode_bits(self, srcs: Tuple[int, ...],
                    want_rows: Tuple[int, ...]) -> jnp.ndarray:
        key = (tuple(srcs), tuple(want_rows))
        hit = self._dev_decode_bits.get(key)
        if hit is not None:
            return hit
        bits = self.backend._decode_bits_for(*key)
        out = jax.device_put(bits, NamedSharding(self.mesh, P(None, None)))
        self._dev_decode_bits[key] = out
        return out

    def decode_data(self, survivors: np.ndarray, srcs: Sequence[int],
                    want_rows: Sequence[int]) -> np.ndarray:
        bits = self.decode_bits(tuple(srcs), tuple(want_rows))
        sv = jax.device_put(jnp.asarray(survivors), self.data_sharding)
        return np.asarray(self._matmul_jit(sv, bits))
