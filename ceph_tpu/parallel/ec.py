"""Sharded GF(2^8) erasure coding over a device mesh.

The encode matmul ``bits(S, C, k*8) @ B(k*8, m*8)`` shards S over the
``stripe`` axis and the m*8 output columns over the ``shard`` axis — a pure
SPMD layout needing zero collectives on the forward path (the contraction
dimension stays replicated), so throughput scales linearly with chips the
way Ceph scales EC across OSDs.  Decode reuses the identical matmul with the
host-inverted survivor matrix.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.gf_matmul import gf_bit_matmul, DeviceRSBackend
from .mesh import STRIPE_AXIS, SHARD_AXIS


class ShardedRS:
    """Mesh-wide executor for one (k+m, k) systematic code.

    Wraps :class:`~ceph_tpu.ops.gf_matmul.DeviceRSBackend` with explicit
    shardings; falls back to single-device semantics when the mesh has one
    device, so callers never branch.
    """

    def __init__(self, encode_matrix: np.ndarray, mesh: Mesh):
        self.mesh = mesh
        self.backend = DeviceRSBackend(encode_matrix)
        self.k = self.backend.k
        self.m = self.backend.m
        # data (S, k, C): shard stripes; chunk + byte dims replicated
        self.data_sharding = NamedSharding(mesh, P(STRIPE_AXIS, None, None))
        # bit matrix (k*8, m*8): shard output columns over the shard axis
        self.mat_sharding = NamedSharding(mesh, P(None, SHARD_AXIS))
        # output (S, m, C): keep the chunk dim on the shard axis when it
        # divides evenly — the matmul's column sharding then lands in place
        # with zero collectives; otherwise replicate (forces a gather)
        shard_size = mesh.shape[SHARD_AXIS]
        out_chunk_axis = SHARD_AXIS if self.m % shard_size == 0 else None
        self.out_sharding = NamedSharding(
            mesh, P(STRIPE_AXIS, out_chunk_axis, None))
        self._enc_bits = jax.device_put(
            self.backend._enc_bits, self.mat_sharding)
        self._matmul_jit = jax.jit(
            gf_bit_matmul, out_shardings=self.out_sharding)
        # decode output width is len(want_rows), not m: replicate it
        self._decode_jit = jax.jit(
            gf_bit_matmul,
            out_shardings=NamedSharding(mesh, P(STRIPE_AXIS, None, None)))
        # sharded decode bit-matrices: bounded LRU mirroring the backend's
        # host-side cache so device memory cannot grow without bound
        self._dev_decode_bits: OrderedDict = OrderedDict()
        self._dev_decode_cap = 2516

    # -- encode -------------------------------------------------------------
    def encode_device(self, data: jnp.ndarray) -> jnp.ndarray:
        """(S, k, C) uint8 -> (S, m, C); the stripe-axis size must divide
        S (each device takes S/stripe_axis stripes)."""
        data = jax.device_put(data, self.data_sharding)
        return self._matmul_jit(data, self._enc_bits)

    def encode(self, data: np.ndarray) -> np.ndarray:
        return np.asarray(self.encode_device(jnp.asarray(data)))

    # -- decode -------------------------------------------------------------
    def decode_bits(self, srcs: Tuple[int, ...],
                    want_rows: Tuple[int, ...]) -> jnp.ndarray:
        key = (tuple(srcs), tuple(want_rows))
        hit = self._dev_decode_bits.get(key)
        if hit is not None:
            self._dev_decode_bits.move_to_end(key)
            return hit
        bits = self.backend._decode_bits_for(*key)
        out = jax.device_put(bits, NamedSharding(self.mesh, P(None, None)))
        self._dev_decode_bits[key] = out
        if len(self._dev_decode_bits) > self._dev_decode_cap:
            self._dev_decode_bits.popitem(last=False)
        return out

    def decode_data(self, survivors: np.ndarray, srcs: Sequence[int],
                    want_rows: Sequence[int]) -> np.ndarray:
        bits = self.decode_bits(tuple(srcs), tuple(want_rows))
        sv = jax.device_put(jnp.asarray(survivors), self.data_sharding)
        return np.asarray(self._decode_jit(sv, bits))
