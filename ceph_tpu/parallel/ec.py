"""Sharded GF(2^8) erasure coding over a device mesh.

The encode matmul ``bits(S, C, k*8) @ B(k*8, m*8)`` shards S over the
``stripe`` axis and the m*8 output columns over the ``shard`` axis — a pure
SPMD layout needing zero collectives on the forward path (the contraction
dimension stays replicated), so throughput scales linearly with chips the
way Ceph scales EC across OSDs.  Decode reuses the identical matmul with the
host-inverted survivor matrix.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.gf_matmul import gf_bit_matmul, DeviceRSBackend
from ..trace.devprof import g_devprof
from .mesh import STRIPE_AXIS, SHARD_AXIS

try:
    from jax import shard_map                    # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def drain_sharded(out) -> int:
    """Completion fence for a MESH-SHARDED output: fetch one element
    from EVERY addressable shard of *out*; returns the number of shards
    drained.

    The single-device drain (bench/fence.py) fetches one element of the
    last output — enough there because PJRT executes per device in
    submission order.  A sharded output extends that contract per
    device: device d's dispatches are only proven complete by a
    readback from a buffer ON d, so the mesh fence touches each shard
    once (one element each, never a full fetch — a large device->host
    transfer flips a tunnelled transport into sync-dispatch mode and
    poisons later measurements).  Unsharded / host values fall back to
    the single drain.
    """
    bur = getattr(out, "block_until_ready", None)
    if bur is not None:
        bur()
    shards = getattr(out, "addressable_shards", None)
    if not shards:
        from ..bench.fence import drain
        drain(out)
        return 1
    n = 0
    for sh in shards:
        piece = sh.data
        try:
            one = piece.ravel()[:1]
        except Exception:
            one = piece
        np.asarray(one)   # THE fence: the device->host readback
        n += 1
    # deliberately NOT accounted on the devflow ledger: n one-element
    # fetches are sub-byte calibration noise, and per-shard accounting
    # would put copies_per_op = n/n_steps over the copy-budget gate's
    # noise floor at a value that moves with step calibration — a
    # flaky gate, not a copy chain.  The dispatch-path mesh flush
    # accounts its REAL boundary crossings at mesh.assemble /
    # mesh.encode (ceph_tpu/mesh/runtime.py).
    return n


def mesh_roofline(gibs: float, workload, mesh: Mesh,
                  platform: str = "", device_kind: str = ""):
    """Roofline verdict for a mesh-wide throughput reading: the chip
    peaks scale by mesh size (N devices = N chips of headroom), so a
    sharded reading is flagged suspect only above the MESH's physics,
    not a single chip's."""
    from ..bench.roofline import validate_reading
    dev = np.asarray(mesh.devices).ravel()[0]
    return validate_reading(
        gibs, workload,
        platform or getattr(dev, "platform", "unknown"),
        device_kind or getattr(dev, "device_kind", ""),
        n_devices=mesh.size)


class ShardedRS:
    """Mesh-wide executor for one (k+m, k) systematic code.

    Wraps :class:`~ceph_tpu.ops.gf_matmul.DeviceRSBackend` with explicit
    shardings; falls back to single-device semantics when the mesh has one
    device, so callers never branch.
    """

    def __init__(self, encode_matrix: np.ndarray, mesh: Mesh):
        self.mesh = mesh
        self.backend = DeviceRSBackend(encode_matrix)
        self.k = self.backend.k
        self.m = self.backend.m
        # data (S, k, C): shard stripes; chunk + byte dims replicated
        self.data_sharding = NamedSharding(mesh, P(STRIPE_AXIS, None, None))
        # bit matrix (k*8, m*8): shard output columns over the shard axis
        self.mat_sharding = NamedSharding(mesh, P(None, SHARD_AXIS))
        # output (S, m, C): keep the chunk dim on the shard axis when it
        # divides evenly — the matmul's column sharding then lands in place
        # with zero collectives; otherwise replicate (forces a gather)
        shard_size = mesh.shape[SHARD_AXIS]
        out_chunk_axis = SHARD_AXIS if self.m % shard_size == 0 else None
        self.out_sharding = NamedSharding(
            mesh, P(STRIPE_AXIS, out_chunk_axis, None))
        self._enc_bits = jax.device_put(
            self.backend._enc_bits, self.mat_sharding)
        self._matmul_jit = jax.jit(
            gf_bit_matmul, out_shardings=self.out_sharding)
        # decode output width is len(want_rows), not m: replicate it
        self._decode_jit = jax.jit(
            gf_bit_matmul,
            out_shardings=NamedSharding(mesh, P(STRIPE_AXIS, None, None)))
        # sharded decode bit-matrices: bounded LRU mirroring the backend's
        # host-side cache so device memory cannot grow without bound
        self._dev_decode_bits: OrderedDict = OrderedDict()
        self._dev_decode_cap = 2516

    # -- completion fence (the multichip ROADMAP item) -----------------------
    def drain(self, out) -> int:
        """Prove *out* complete on EVERY device of the mesh (one-element
        fetch per shard); returns the shard count drained.  Fenced
        mesh measurements must stop the clock here, not at
        block_until_ready (see drain_sharded)."""
        return drain_sharded(out)

    def roofline(self, gibs: float, workload):
        """Physics verdict for a mesh-wide reading, peaks scaled by
        this mesh's device count."""
        return mesh_roofline(gibs, workload, self.mesh)

    # -- encode -------------------------------------------------------------
    def encode_device(self, data: jnp.ndarray) -> jnp.ndarray:
        """(S, k, C) uint8 -> (S, m, C); the stripe-axis size must divide
        S (each device takes S/stripe_axis stripes)."""
        data = jax.device_put(data, self.data_sharding)
        return self._matmul_jit(data, self._enc_bits)

    def encode(self, data: np.ndarray) -> np.ndarray:
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("parallel.encode", data.nbytes)
        with g_devprof.stage("parallel.encode"):
            out = np.asarray(self.encode_device(jnp.asarray(data)))
        g_devprof.account_d2h("parallel.encode", out.nbytes)
        return out

    # -- decode -------------------------------------------------------------
    def decode_bits(self, srcs: Tuple[int, ...],
                    want_rows: Tuple[int, ...]) -> jnp.ndarray:
        key = (tuple(srcs), tuple(want_rows))
        hit = self._dev_decode_bits.get(key)
        if hit is not None:
            self._dev_decode_bits.move_to_end(key)
            return hit
        # no devprof h2d here: _decode_bits_for already accounted the
        # real host->device crossing; this device_put is a device-to-
        # device reshard onto the mesh, not a boundary copy
        bits = self.backend._decode_bits_for(*key)
        out = jax.device_put(bits, NamedSharding(self.mesh, P(None, None)))
        self._dev_decode_bits[key] = out
        if len(self._dev_decode_bits) > self._dev_decode_cap:
            self._dev_decode_bits.popitem(last=False)
        return out

    def decode_data(self, survivors: np.ndarray, srcs: Sequence[int],
                    want_rows: Sequence[int]) -> np.ndarray:
        bits = self.decode_bits(tuple(srcs), tuple(want_rows))
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("parallel.decode", survivors.nbytes)
        with g_devprof.stage("parallel.decode"):
            sv = jax.device_put(jnp.asarray(survivors),
                                self.data_sharding)
            out = np.asarray(self._decode_jit(sv, bits))
        g_devprof.account_d2h("parallel.decode", out.nbytes)
        return out

    # -- contraction-sharded decode -----------------------------------------
    def decode_data_survivor_sharded(self, survivors: np.ndarray,
                                     srcs: Sequence[int],
                                     want_rows: Sequence[int]
                                     ) -> np.ndarray:
        """Decode with the SURVIVORS sharded across the ``shard`` axis.

        The degraded-read case where no single chip holds all k
        survivor shards (each device fetched its own subset from its
        OSDs — the sequence/context-parallel layout of this
        framework).  GF(2) makes the contraction reduction a psum:
        every device computes the int32 bit-accumulator over its local
        k-slice, one ``lax.psum`` rides the ICI mesh, and only THEN is
        accumulator parity taken — XOR-allreduce expressed as the
        compiler-native collective (the NCCL-allreduce role in the
        reference's recovery fan-in, osd/ECBackend.cc:1141-1281, where
        shard reads converge on the primary).
        """
        nshard = self.mesh.shape[SHARD_AXIS]
        k = survivors.shape[1]
        if k % nshard:
            raise ValueError(f"k={k} not divisible by shard axis "
                             f"size {nshard}")
        bits = self.decode_bits(tuple(srcs), tuple(want_rows))
        g_devprof.install_compile_listener()
        g_devprof.account_h2d("parallel.decode_sharded",
                              survivors.nbytes)
        with g_devprof.stage("parallel.decode_sharded"):
            sv = jax.device_put(
                jnp.asarray(survivors),
                NamedSharding(self.mesh,
                              P(STRIPE_AXIS, SHARD_AXIS, None)))
            bd = jax.device_put(
                bits, NamedSharding(self.mesh, P(SHARD_AXIS, None)))
            out = np.asarray(self._collective_decode_jit()(sv, bd))
        g_devprof.account_d2h("parallel.decode_sharded", out.nbytes)
        return out

    # -- layout conversion (all-to-all) -------------------------------------
    def reshard_stripes_to_chunks(self, chunks: jnp.ndarray
                                  ) -> jnp.ndarray:
        """(S, k+m, C) stripe-sharded -> chunk-sharded, on-mesh.

        Encode produces stripe-parallel output (each device holds ALL
        chunks of ITS stripes); distribution to OSD shards wants
        chunk-parallel layout (each device holds ONE chunk slice of
        ALL stripes — the k+m fan-out, ECBackend.cc:1942+).  The
        switch is a single ``lax.all_to_all`` over the stripe axis —
        the storage analog of the sequence<->head resharding in
        all-to-all context parallelism, riding ICI instead of a
        device->host->device bounce."""
        nstripe = self.mesh.shape[STRIPE_AXIS]
        s, r, _c = chunks.shape
        if r % nstripe or s % nstripe:
            raise ValueError(f"shape ({s}, {r}, ...) not divisible "
                             f"by stripe axis size {nstripe}")
        fn = getattr(self, "_reshard_fn", None)
        if fn is None:
            def swap(local):
                # local (S/n, r, C) -> all_to_all splits r, concats S
                return jax.lax.all_to_all(local, STRIPE_AXIS,
                                          split_axis=1, concat_axis=0,
                                          tiled=True)

            fn = self._reshard_fn = jax.jit(shard_map(
                swap, mesh=self.mesh,
                in_specs=P(STRIPE_AXIS, None, None),
                out_specs=P(None, STRIPE_AXIS, None)))
        src = jax.device_put(chunks, NamedSharding(
            self.mesh, P(STRIPE_AXIS, None, None)))
        return fn(src)

    def _collective_decode_jit(self):
        """The shard_map-wrapped kernel, built once per instance so
        repeat degraded reads hit jit's cache instead of retracing."""
        fn = getattr(self, "_collective_fn", None)
        if fn is not None:
            return fn
        from ..ops.gf_matmul import _pack_bits, _unpack_bits

        def local_partial(sv_local, bits_local):
            # sv_local (S/dp, k/tp, C); bits_local (k*8/tp, r*8)
            d = jnp.transpose(sv_local, (0, 2, 1))
            planes = _unpack_bits(d).astype(jnp.int8)
            acc = jax.lax.dot_general(
                planes, bits_local,
                dimension_numbers=(((2,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            acc = jax.lax.psum(acc, SHARD_AXIS)
            parity = (acc & 1).astype(jnp.uint8)
            return jnp.transpose(_pack_bits(parity), (0, 2, 1))

        fn = jax.jit(shard_map(
            local_partial, mesh=self.mesh,
            in_specs=(P(STRIPE_AXIS, SHARD_AXIS, None),
                      P(SHARD_AXIS, None)),
            out_specs=P(STRIPE_AXIS, None, None)))
        self._collective_fn = fn
        return fn
