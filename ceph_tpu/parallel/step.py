"""The fused device step: one write batch, end to end on the mesh.

This is the TPU-native analog of ECBackend's write pipeline
(reference src/osd/ECBackend.cc:1459-2101): for a batch of S stripes it
produces every shard chunk (data pass-through + GF coding matmul) and the
per-shard digest the shards use for HashInfo-style integrity
(src/osd/ECUtil.cc:161-207 keeps cumulative crc32c per shard; on device we
fold a cheap fingerprint and reduce it across the stripe axis, the
byte-exact crc32c belongs to the host C++ path).

Everything is one jitted function over the (stripe, shard) mesh: stripes
sharded, coding columns sharded, the digest reduction is the only
collective.  ``dryrun_multichip`` in ``__graft_entry__.py`` compiles exactly
this over an N-device mesh.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.gf_matmul import gf_bit_matmul
from .mesh import STRIPE_AXIS, SHARD_AXIS


def pipeline_step(data: jnp.ndarray, enc_bits: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """data (S, k, C) uint8, enc_bits (k*8, m*8) int8 ->
    (chunks (S, k+m, C) uint8, shard_digests (k+m,) uint32).

    chunks = [data | coding] exactly as they would fan out to k+m OSDs;
    shard_digests = per-shard fingerprint folded over all stripes (the
    cross-device reduction).
    """
    c = data.shape[2]
    coding = gf_bit_matmul(data, enc_bits)                   # (S, m, C)
    chunks = jnp.concatenate([data, coding], axis=1)         # (S, k+m, C)
    # FNV-ish device fingerprint per shard, reduced over stripes+bytes
    w = (jnp.arange(c, dtype=jnp.uint32) * jnp.uint32(0x01000193)
         + jnp.uint32(0x811C9DC5))
    digests = jnp.sum(chunks.astype(jnp.uint32) * w[None, None, :],
                      axis=(0, 2), dtype=jnp.uint32)         # (k+m,)
    return chunks, digests


def example_pipeline_args(mesh: Mesh, s: int = 8, k: int = 8, m: int = 4,
                          c: int = 256):
    """Tiny sharded example inputs for compile checks."""
    from ..gf.matrices import gf_gen_rs_matrix
    from ..gf.tables import expand_to_bitmatrix
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(s, k, c), dtype=np.uint8)
    mat = gf_gen_rs_matrix(k + m, k)
    bits = expand_to_bitmatrix(mat[k:]).astype(np.int8)
    data_sh = NamedSharding(mesh, P(STRIPE_AXIS, None, None))
    mat_sh = NamedSharding(mesh, P(None, SHARD_AXIS))
    return (jax.device_put(jnp.asarray(data), data_sh),
            jax.device_put(jnp.asarray(bits), mat_sh))
