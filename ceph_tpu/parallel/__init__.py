"""Device-mesh parallelism for the TPU-native data path.

Ceph's distribution axes (SURVEY.md §2.10) map onto a 2-D
``jax.sharding.Mesh``:

- ``stripe`` — the data-parallel axis: batches of EC stripes (and batches
  of PGs in the placement kernel) shard across devices, the analog of
  object→PG sharding / ParallelPGMapper's thread fan-out
  (reference src/osd/OSDMapMapping.h:17).
- ``shard`` — the model-parallel axis: the k+m output-chunk dimension of the
  GF coding matmul shards its columns across devices, the analog of one
  stripe's chunks fanning out to k+m OSDs (src/osd/ECBackend.cc:1942).

Collectives ride ICI: encode needs none (the contraction dim is replicated);
cluster-wide reductions (chunk checksums, placement histograms) are psums.
"""
from .mesh import make_mesh, mesh_shape_for
from .ec import ShardedRS, drain_sharded, mesh_roofline
from .step import pipeline_step, example_pipeline_args
from .crush import ShardedFastRule, sharded_fast_rule

__all__ = [
    "make_mesh", "mesh_shape_for", "ShardedRS", "drain_sharded",
    "mesh_roofline",
    "pipeline_step", "ShardedFastRule", "sharded_fast_rule", "example_pipeline_args",
]
