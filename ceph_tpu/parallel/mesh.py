"""Mesh construction helpers.

A 2-D ``(stripe, shard)`` mesh over however many devices exist.  The shard
axis is kept small (it shards the m*8 coding-bit columns of the GF matmul),
the stripe axis takes the rest — stripes are the abundant dimension in a
storage workload, exactly like PGs are for placement.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

STRIPE_AXIS = "stripe"
SHARD_AXIS = "shard"


def mesh_shape_for(n: int, max_shard: int = 2) -> Tuple[int, int]:
    """Factor n devices into (stripe, shard) with shard | n and small."""
    shard = 1
    for cand in range(min(max_shard, n), 0, -1):
        if n % cand == 0:
            shard = cand
            break
    return n // shard, shard


def make_mesh(n_devices: Optional[int] = None,
              devices: Optional[Sequence] = None,
              max_shard: int = 2) -> Mesh:
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # single real chip but a bigger mesh requested: the virtual host
            # platform carries --xla_force_host_platform_device_count devices
            cpus = jax.devices("cpu")
            if len(cpus) < n_devices:
                try:
                    # works when the cpu backend is not initialized yet
                    jax.config.update("jax_num_cpu_devices", n_devices)
                    cpus = jax.devices("cpu")
                except Exception:
                    pass
            devices = cpus
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}; for a "
                "virtual mesh start the process with XLA_FLAGS="
                f"--xla_force_host_platform_device_count={n_devices}")
        devices = devices[:n_devices]
    dp, tp = mesh_shape_for(len(devices), max_shard)
    arr = np.array(devices).reshape(dp, tp)
    return Mesh(arr, (STRIPE_AXIS, SHARD_AXIS))
