"""Multi-chip CRUSH: the whole-cluster remap sharded over a device mesh.

The single-chip fast path (ops/crush_fast.py) resolves every PG in one
kernel call.  At larger scale (millions of PGs, whole-map remaps every
epoch) the PG axis shards across chips exactly like stripes do for EC:
candidate tables are computed and cached per device slice, each epoch's
resolve runs fully parallel with NO cross-chip traffic — placement is
embarrassingly parallel per PG, the ideal ICI workload is the one that
never uses ICI — and only the compacted (X, result_max+1) output
gathers back.  This is OSDMapMapping's ParallelPGMapper
(osd/OSDMapMapping.h:17) with chips in place of CPU worker threads.

GSPMD does the partitioning: the xs / weight inputs carry NamedShardings
and XLA propagates them through the candidate and resolve kernels, so
the very same jitted programs serve one chip or a whole mesh.
"""
from __future__ import annotations

import hashlib
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crush.types import CrushMap
from ..ops.crush_fast import FastRule, compile_fast_rule
from .mesh import SHARD_AXIS, STRIPE_AXIS


class ShardedFastRule:
    """A FastRule whose PG axis is sharded over every device of *mesh*."""

    def __init__(self, m: CrushMap, ruleno: int, result_max: int,
                 mesh: Mesh, **kw):
        self.fr: FastRule = compile_fast_rule(m, ruleno, result_max, **kw)
        self.mesh = mesh
        self.n_devices = int(np.prod(mesh.devices.shape))
        self._xs_sharding = NamedSharding(mesh, P((STRIPE_AXIS, SHARD_AXIS)))
        self._rep_sharding = NamedSharding(mesh, P())
        self._cand = None
        self._cand_x = None
        self._cand_key: Optional[bytes] = None

    @property
    def result_max(self) -> int:
        return self.fr.result_max

    @property
    def residual_fraction(self) -> float:
        return self.fr.residual_fraction

    def prepare_candidates(self, xs_padded: np.ndarray) -> None:
        key = hashlib.sha1(xs_padded.tobytes()).digest()
        if self._cand_key == key:
            return
        xd = jax.device_put(xs_padded, self._xs_sharding)
        # _run_candidates, NOT _cand_jit: the exact64 draw needs its
        # enable_x64 trace scope — a direct _cand_jit call would
        # silently truncate the u64 tables to 32 bits
        self._cand = jax.block_until_ready(
            self.fr._run_candidates(xd))
        self._cand_x = xd
        self._cand_key = key

    def resolve_device(self, weight) -> jnp.ndarray:
        """Sharded packed resolve (see FastRule._resolve_packed); the
        per-epoch device call — weights replicate, PGs stay put."""
        if self._cand is None:
            raise RuntimeError("no candidate tables; call "
                               "prepare_candidates(xs) first")
        wd = jax.device_put(np.asarray(weight, dtype=np.uint32),
                            self._rep_sharding)
        return self.fr._packed_jit(*self._cand, self._cand_x, wd)

    def map_batch(self, xs: np.ndarray, weight: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """Exact whole-map mapping, PGs sharded across the mesh."""
        xs = np.asarray(xs, dtype=np.uint32)
        X = xs.shape[0]
        pad = (-X) % self.n_devices
        xs_p = np.concatenate([xs, np.repeat(xs[:1], pad)]) if pad else xs
        self.prepare_candidates(xs_p)
        R = self.fr.result_max
        packed = self.resolve_device(weight)
        full = np.asarray(packed)[:X]
        out = full[:, :R].copy()
        counts = (full[:, R] & 0xFFFF).astype(np.int32)
        residual = (full[:, R] >> 16) != 0
        self.fr._residual_frac = float(residual.mean())
        self.fr._replay_exact(np.nonzero(residual)[0], xs,
                              np.asarray(weight, dtype=np.uint32),
                              out, counts)
        return out, counts


def sharded_fast_rule(m: CrushMap, ruleno: int, result_max: int,
                      mesh: Mesh, **kw) -> ShardedFastRule:
    return ShardedFastRule(m, ruleno, result_max, mesh, **kw)
