"""``python -m ceph_tpu.analysis`` — the invariant analyzer runner.

Exit status 0 = clean, 1 = violations, 2 = usage error.  The tier-1
suite runs the full-tree pass (tests/test_static_analysis.py);
``scripts/lint.sh`` is the local entry point; ``--changed`` scopes to
the git working-tree diff for fast pre-commit rounds.
"""
from __future__ import annotations

import argparse
import json
import sys

from .core import PKG_ROOT, changed_files, run_analysis
from .rules import ALL_RULES, rule_by_id


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ceph_tpu.analysis",
        description="repo-wide AST invariant analyzer")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: ceph_tpu/)")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="RULE-ID",
                    help="run only this rule (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable violation list on stdout")
    ap.add_argument("--changed", action="store_true",
                    help="git-diff-scoped: only working-tree-changed "
                         "ceph_tpu/*.py files")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--update-wire-manifest", action="store_true",
                    help="regenerate analysis/wire_manifest.json from "
                         "msg/messages.py (requires corpus "
                         "re-validation — see docs/ANALYSIS.md)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id:24s} {cls.doc}")
        return 0

    if args.update_wire_manifest:
        import os

        from .core import AnalysisContext
        from .rules import WIRE_MANIFEST_PATH, collect_wire_fields
        ctx = AnalysisContext(os.path.join(PKG_ROOT, "msg",
                                           "messages.py"))
        fields = collect_wire_fields(ctx.tree)
        with open(WIRE_MANIFEST_PATH, "w", encoding="utf-8") as f:
            json.dump(fields, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wire manifest: {len(fields)} message classes -> "
              f"{WIRE_MANIFEST_PATH}")
        return 0

    rules = None
    if args.rules:
        try:
            rules = [rule_by_id(r) for r in args.rules]
        except KeyError as e:
            print(e.args[0], file=sys.stderr)
            return 2

    paths = args.paths or None
    if args.changed:
        paths = changed_files()
        if not paths:
            print("analysis: no changed ceph_tpu/*.py files")
            return 0

    violations = run_analysis(paths, rules)
    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=1))
    else:
        for v in violations:
            print(v)
        n_rules = len(rules) if rules else len(ALL_RULES)
        print(f"analysis: {len(violations)} violation(s), "
              f"{n_rules} rule(s)", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
