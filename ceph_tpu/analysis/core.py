"""Analyzer core: AST walk, rule protocol, pragma suppression.

Design mirrors the reference's lint layering (``src/script/``'s checks
run over the whole tree, per-file, with explicit suppressions): a
:class:`Rule` sees one parsed module at a time through an
:class:`AnalysisContext` and yields :class:`Violation`\\ s; the driver
walks ``ceph_tpu/``, applies every requested rule, and drops any
violation whose source line (or the line above it) carries a
``# lint: allow[rule-id]`` pragma.  Pragmas are the *audited
exception* mechanism — each one marks a site a human justified in
place; module-scope exceptions live in the rules' own allowlists
(ceph_tpu/analysis/rules.py) so they are reviewed like code.
"""
from __future__ import annotations

import ast
import os
import re
import subprocess
from dataclasses import dataclass
from typing import Iterable, Iterator, List, Optional, Sequence

# repo layout anchors: .../ceph_tpu/analysis/core.py
PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PKG_ROOT)

_PRAGMA_RE = re.compile(r"#\s*lint:\s*allow\[([^\]]*)\]")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative, e.g. "ceph_tpu/dispatch/batch.py"
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class AnalysisContext:
    """One module under analysis: parsed tree + source + identity."""

    def __init__(self, abspath: str, source: Optional[str] = None,
                 relpath: Optional[str] = None):
        self.abspath = abspath
        self.path = os.path.relpath(abspath, REPO_ROOT)
        # rules match on the ceph_tpu-relative path so fixture trees
        # analyzed from tmp dirs hit the same allowlists; tests pass
        # an explicit relpath to place a snippet anywhere in the tree
        self.relpath = relpath if relpath is not None else self.path
        if self.relpath.startswith("ceph_tpu" + os.sep):
            self.relpath = self.relpath[len("ceph_tpu" + os.sep):]
        if source is None:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=abspath)
        self._imports: Optional[set] = None
        self._aliases: Optional[dict] = None

    def imported_modules(self) -> set:
        """Top-of-dotted-path module names imported anywhere in the
        file (function-local imports included — device-facing modules
        routinely defer ``import jax``)."""
        if self._imports is None:
            mods = set()
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        mods.add(a.name.split(".")[0])
                        mods.add(a.name)
                elif isinstance(node, ast.ImportFrom) and node.module:
                    mods.add(node.module.split(".")[0])
                    mods.add(node.module)
            self._imports = mods
        return self._imports

    def import_aliases(self) -> dict:
        """Local binding -> canonical dotted origin, so rules cannot
        be evaded by ``from threading import Lock`` or ``import
        threading as th``: {"Lock": "threading.Lock", "th":
        "threading"}."""
        if self._aliases is None:
            al = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        al[a.asname or a.name.split(".")[0]] = \
                            a.name if a.asname else a.name.split(".")[0]
                elif isinstance(node, ast.ImportFrom) and node.module \
                        and node.level == 0:
                    for a in node.names:
                        if a.name != "*":
                            al[a.asname or a.name] = \
                                f"{node.module}.{a.name}"
            self._aliases = al
        return self._aliases

    def resolve_call(self, node: ast.AST) -> str:
        """Canonical dotted name of a called expression with local
        import aliases expanded (``Lock()`` -> ``threading.Lock``)."""
        parts = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return ""
        root = self.import_aliases().get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        """True when the line (or the one above, for pragmas that
        would overflow the line) allows ``rule_id``."""
        for ln in (lineno, lineno - 1):
            m = _PRAGMA_RE.search(self.line_text(ln))
            if m:
                allowed = {s.strip() for s in m.group(1).split(",")}
                if rule_id in allowed or "*" in allowed:
                    return True
        return False


class Rule:
    """A named invariant checked per module.

    Subclasses set ``id``/``doc`` and implement :meth:`check`.  A rule
    that only concerns specific files should early-return on others —
    the driver calls every rule on every module.
    """

    id: str = ""
    doc: str = ""

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        raise NotImplementedError

    def run(self, ctx: AnalysisContext) -> List[Violation]:
        return [v for v in self.check(ctx)
                if not ctx.suppressed(self.id, v.line)]


def iter_tree(root: Optional[str] = None) -> Iterator[str]:
    """All analyzable .py files under ``root`` (default: the
    ``ceph_tpu`` package), sorted for stable output."""
    root = root or PKG_ROOT
    if os.path.isfile(root):
        yield root
        return
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    yield from out


def changed_files() -> List[str]:
    """git-diff-scoped file set for ``--changed``: working-tree +
    staged modifications plus untracked files, filtered to package
    sources."""
    paths = set()
    for args in (["git", "diff", "--name-only", "HEAD"],
                 ["git", "ls-files", "-o", "--exclude-standard"]):
        try:
            res = subprocess.run(args, cwd=REPO_ROOT, capture_output=True,
                                 text=True, timeout=30)
        except Exception:
            continue
        if res.returncode == 0:
            paths.update(p for p in res.stdout.splitlines() if p)
    out = (os.path.join(REPO_ROOT, p) for p in paths
           if p.endswith(".py") and p.startswith("ceph_tpu/"))
    # a deleted file still shows in the diff; there is nothing to parse
    return sorted(p for p in out if os.path.isfile(p))


def run_analysis(paths: Optional[Sequence[str]] = None,
                 rules: Optional[Iterable[Rule]] = None,
                 ) -> List[Violation]:
    """Run ``rules`` (default: the full catalog) over ``paths``
    (default: the whole ``ceph_tpu`` tree); returns surviving
    violations sorted by location."""
    from .rules import ALL_RULES
    if rules is None:
        rules = [cls() for cls in ALL_RULES]
    files: List[str] = []
    for p in (paths or [PKG_ROOT]):
        files.extend(iter_tree(os.path.abspath(p)))
    out: List[Violation] = []
    for f in files:
        try:
            ctx = AnalysisContext(f)
        except (SyntaxError, UnicodeDecodeError) as e:
            out.append(Violation("parse-error",
                                 os.path.relpath(f, REPO_ROOT),
                                 getattr(e, "lineno", 0) or 0, str(e)))
            continue
        for rule in rules:
            out.extend(rule.run(ctx))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))
