"""Repo-wide invariant analyzer (the port's ``src/common/lockdep.cc`` +
clang-tidy role).

The reference ships race/correctness tooling as first-class
infrastructure: lockdep is wired into every qa/vstart run and the
sanitizers are CMake options.  Our port's cross-cutting contracts —
zero hidden device syncs, pinned wire format, bounded jit caches,
tick-driven fabric clocks, every lock witnessed — were until now
enforced only where some runtime test happened to sample them.  This
package checks them *statically over the whole tree* on every tier-1
round:

- :mod:`.core` — AST walk + rule registry + ``# lint: allow[...]``
  pragma mechanism;
- :mod:`.rules` — the rule catalog (no-bare-lock, no-untracked-sync,
  no-wall-clock, no-wire-drift, jit-cache-hygiene,
  options-doc-coverage) and the one-time allowlists;
- ``python -m ceph_tpu.analysis`` — the runner (``--rule``,
  ``--json``, ``--changed``, path filters).

See docs/ANALYSIS.md for the catalog and the allowlist/pragma policy.
"""
from .core import AnalysisContext, Rule, Violation, iter_tree, run_analysis
from .rules import ALL_RULES, rule_by_id

__all__ = [
    "ALL_RULES", "AnalysisContext", "Rule", "Violation",
    "iter_tree", "rule_by_id", "run_analysis",
]
