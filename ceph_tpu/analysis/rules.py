"""The invariant rule catalog.

Every rule here encodes a contract an earlier PR established by
convention and until now enforced only by whichever runtime test
happened to sample it:

- **no-bare-lock** — every lock in the tree must be a *named*
  ``common.lockdep`` lock so the lock-order witness is structurally
  universal (the reference builds every Mutex through
  ``ceph::make_mutex`` for exactly this reason).
- **no-untracked-sync** — the "zero added device syncs" invariant the
  fence-count test samples (tests/test_observability.py) becomes a
  whole-tree guarantee: sync primitives only in the audited
  fence/drain/devprof call-site modules.
- **no-wall-clock** — deterministic-fabric modules (cluster, msg,
  mon, osd) take time as a tick parameter; stray wall reads are how
  election timing went load-sensitive (ROADMAP residual debt 2).
- **no-wire-drift** — the wire format is pinned by the 69-blob
  corpus; this rule pins the *field lists* of every Message subclass
  against a checked-in manifest so drift fails lint before it can
  fail (or silently skew) the corpus.
- **jit-cache-hygiene** — ``jax.jit``/``shard_map`` call sites must
  be build-once (module level, ``__init__``, a recognized plan
  builder, or a memoized self-attribute assign), preventing the
  hot-path retrace leaks the dispatch plan caches were built to
  avoid.
- **options-doc-coverage** — every option registered in
  ``common/config.py`` is documented under ``docs/``; the allowlist
  below is one-time and closed (new options cannot join it).

Module-scope exceptions live in the ``*_ALLOWED`` constants here;
line-scope exceptions use ``# lint: allow[rule-id]`` pragmas at the
site.  Both are audited-in-review mechanisms, not escape hatches —
see docs/ANALYSIS.md for the policy.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import REPO_ROOT, AnalysisContext, Rule, Violation

WIRE_MANIFEST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "wire_manifest.json")


def _call_name(node: ast.Call) -> str:
    """Trailing name of the called thing: ``jax.jit`` -> ``jit``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name for Name/Attribute chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def _path_allowed(relpath: str, allowed: Tuple[str, ...]) -> bool:
    rp = relpath.replace(os.sep, "/")
    for a in allowed:
        if a.endswith("/"):
            if rp.startswith(a):
                return True
        elif rp == a:
            return True
    return False


# ---------------------------------------------------------------------------
# no-bare-lock
# ---------------------------------------------------------------------------

# the witness's own internals are the only place a raw primitive may
# live (lockdep cannot witness itself without recursing)
BARE_LOCK_ALLOWED = ("common/lockdep.py",)


class NoBareLockRule(Rule):
    id = "no-bare-lock"
    doc = ("threading.Lock()/RLock() must be a named common.lockdep "
           "DebugLock/DebugRLock so the lock-order witness covers it")

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if _path_allowed(ctx.relpath, BARE_LOCK_ALLOWED):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = ctx.resolve_call(node.func)
            if dn in ("threading.Lock", "threading.RLock"):
                kind = dn.split(".")[1]
                repl = "DebugLock" if kind == "Lock" else "DebugRLock"
                yield Violation(
                    self.id, ctx.path, node.lineno,
                    f"bare threading.{kind}() — use a named "
                    f"common.lockdep.{repl} so the lock-order witness "
                    f"sees it")
            elif dn == "threading.Condition" and not node.args:
                yield Violation(
                    self.id, ctx.path, node.lineno,
                    "zero-arg threading.Condition() creates a hidden "
                    "bare RLock — pass a named DebugLock")


# ---------------------------------------------------------------------------
# no-untracked-sync
# ---------------------------------------------------------------------------

# the audited fence/drain/devprof call-site modules: every
# host<->device boundary in these is (or routes through) a named
# devprof call site, so a sync here is *tracked* by construction
SYNC_ALLOWED = (
    "ops/",                    # device kernels: the accounted boundary
    "parallel/",               # sharded kernels (mesh collectives)
    "mesh/",                   # mesh runtime: devprof-accounted flush
    "bench/",                  # fence harness: drains are its job
    "dispatch/batch.py",       # batch assembly: accounted pad/stack/d2h
    "trace/devprof.py",        # the profiler itself
    "common/kernel_trace.py",  # opt-in timing fence (sync is the point)
    "arch.py",                 # one-shot capability probe
    "ec/shec.py",              # SHEC device decode call site
    "osdmap/mapping.py",       # CRUSH device mapper d2h boundary
    "os_store/device_shard.py",  # DeviceShard materialize: accounted
                                 # d2h at memstore.fetch_shard
)

_SYNC_PRIMITIVES = ("block_until_ready", "device_get")
_HOST_FETCH = ("asarray", "array")


class NoUntrackedSyncRule(Rule):
    id = "no-untracked-sync"
    doc = ("device syncs (block_until_ready / jax.device_get / "
           "np.asarray on device values) only inside the allowlisted "
           "fence/drain/devprof call-site modules")

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if _path_allowed(ctx.relpath, SYNC_ALLOWED):
            return
        device_facing = bool({"jax", "jax.numpy"}
                             & ctx.imported_modules())
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name in _SYNC_PRIMITIVES:
                yield Violation(
                    self.id, ctx.path, node.lineno,
                    f"{name}() is a device sync — route it through an "
                    f"allowlisted fence/drain/devprof call-site module")
            elif device_facing and name in _HOST_FETCH:
                dn = ctx.resolve_call(node.func)
                if dn.startswith("numpy."):
                    yield Violation(
                        self.id, ctx.path, node.lineno,
                        f"{dn}() in a jax-importing module is a "
                        f"potential hidden d2h sync — move the fetch "
                        f"to an accounted call-site module")


# ---------------------------------------------------------------------------
# no-wall-clock
# ---------------------------------------------------------------------------

# the deterministic fabric: these modules take time as a tick/now
# parameter; reading the wall directly makes behavior depend on host
# scheduling (the loadflaky election-timing lesson)
WALL_CLOCK_SCOPE = ("cluster.py", "msg/", "mon/", "osd/")
# real-socket transport: kernel select/connect timeouts are wall-bound
# by nature — the ONLY fabric module allowed to read the wall wholesale
WALL_CLOCK_ALLOWED = ("msg/tcp.py",)

_WALL_READS = ("time.time", "time.monotonic", "datetime.now",
               "datetime.utcnow", "datetime.datetime.now",
               "datetime.datetime.utcnow")


class NoWallClockRule(Rule):
    id = "no-wall-clock"
    doc = ("deterministic-fabric modules (cluster, msg, mon, osd) "
           "must take time as a tick parameter, not read the wall")

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if not _path_allowed(ctx.relpath, WALL_CLOCK_SCOPE):
            return
        if _path_allowed(ctx.relpath, WALL_CLOCK_ALLOWED):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = ctx.resolve_call(node.func)
            if dn in _WALL_READS:
                yield Violation(
                    self.id, ctx.path, node.lineno,
                    f"{dn}() is a wall read inside the deterministic "
                    f"fabric — take `now` from the tick, or pragma the "
                    f"site with its justification")


# ---------------------------------------------------------------------------
# no-wire-drift
# ---------------------------------------------------------------------------

WIRE_MODULE = "msg/messages.py"


def collect_wire_fields(tree: ast.Module) -> Dict[str, List[str]]:
    """Per Message-subclass sorted field list, from the dataclass
    class bodies (AnnAssign + plain class-level Assign)."""
    bases: Dict[str, List[str]] = {}
    class_nodes: Dict[str, ast.ClassDef] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            class_nodes[node.name] = node
            bases[node.name] = [_dotted(b) for b in node.bases]

    def is_message(name: str, seen: Optional[Set[str]] = None) -> bool:
        if name == "Message":
            return True
        seen = seen or set()
        if name in seen or name not in bases:
            return False
        seen.add(name)
        return any(is_message(b, seen) for b in bases[name])

    out: Dict[str, List[str]] = {}
    for name, node in class_nodes.items():
        if not is_message(name):
            continue
        fields = []
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                fields.append(stmt.target.id)
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name) and not \
                            t.id.isupper():  # class constants aren't wire
                        fields.append(t.id)
        out[name] = sorted(fields)
    return out


def load_wire_manifest() -> Dict[str, List[str]]:
    with open(WIRE_MANIFEST_PATH, "r", encoding="utf-8") as f:
        return json.load(f)


class NoWireDriftRule(Rule):
    id = "no-wire-drift"
    doc = ("Message subclass field lists are pinned against "
           "analysis/wire_manifest.json — a new/renamed wire field "
           "fails lint before it can drift the pinned corpus")

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if ctx.relpath.replace(os.sep, "/") != WIRE_MODULE:
            return
        try:
            manifest = load_wire_manifest()
        except FileNotFoundError:
            yield Violation(self.id, ctx.path, 1,
                            f"wire manifest missing: {WIRE_MANIFEST_PATH}"
                            " (run --update-wire-manifest once)")
            return
        current = collect_wire_fields(ctx.tree)
        lineno = {n.name: n.lineno for n in ctx.tree.body
                  if isinstance(n, ast.ClassDef)}
        for cls, fields in sorted(current.items()):
            if cls not in manifest:
                yield Violation(
                    self.id, ctx.path, lineno.get(cls, 1),
                    f"new wire message {cls!r} not in the pinned "
                    f"manifest — extend the encoding corpus, then "
                    f"`python -m ceph_tpu.analysis "
                    f"--update-wire-manifest`")
                continue
            added = sorted(set(fields) - set(manifest[cls]))
            removed = sorted(set(manifest[cls]) - set(fields))
            for f in added:
                yield Violation(
                    self.id, ctx.path, lineno.get(cls, 1),
                    f"wire field {cls}.{f} is not in the pinned "
                    f"manifest — wire drift; re-validate the corpus "
                    f"and update the manifest deliberately")
            for f in removed:
                yield Violation(
                    self.id, ctx.path, lineno.get(cls, 1),
                    f"pinned wire field {cls}.{f} disappeared — "
                    f"removing a wire field breaks the pinned corpus")
        for cls in sorted(set(manifest) - set(current)):
            yield Violation(
                self.id, ctx.path, 1,
                f"pinned wire message {cls!r} disappeared from "
                f"msg/messages.py")


# ---------------------------------------------------------------------------
# jit-cache-hygiene
# ---------------------------------------------------------------------------

_JIT_NAMES = ("jit", "shard_map")
# function names recognized as build-once plan builders
_BUILDER_RE = re.compile(r"(__init__|_jit\b|_jit$|build|plan|cached)")


class JitCacheHygieneRule(Rule):
    id = "jit-cache-hygiene"
    doc = ("jax.jit/shard_map call sites must be module-level or "
           "inside recognized cached-plan builders (no hot-path "
           "retrace leaks)")

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if not ({"jax", "jax.numpy"} & ctx.imported_modules()):
            return
        viol: List[Violation] = []

        def fn_allowed(stack: List[str]) -> bool:
            # module/class level, or EVERY enclosing fn a builder
            funcs = [s for s in stack if s]
            return not funcs or any(_BUILDER_RE.search(f) for f in funcs)

        class V(ast.NodeVisitor):
            def __init__(self):
                self.fstack: List[str] = []
                self.memo_depth = 0

            def _visit_fn(self, node):
                for d in node.decorator_list:
                    self._check_decorator(d, node)
                self.fstack.append(node.name)
                for child in (node.body
                              + getattr(node.args, "defaults", [])):
                    self.visit(child)
                self.fstack.pop()

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

            def visit_ClassDef(self, node):
                for d in node.decorator_list:
                    self.visit(d)
                self.fstack.append("")          # class scope marker
                for child in node.body:
                    self.visit(child)
                self.fstack.pop()

            def _check_decorator(self, dec, fn_node):
                # @jax.jit / @jit / @functools.partial(jax.jit, ...)
                names = {_dotted(dec)}
                if isinstance(dec, ast.Call):
                    names.add(_dotted(dec.func))
                    names.update(_dotted(a) for a in dec.args)
                if any(n.split(".")[-1] in _JIT_NAMES
                       for n in names if n) and \
                        not fn_allowed(self.fstack):
                    viol.append(Violation(
                        JitCacheHygieneRule.id, ctx.path, dec.lineno,
                        f"@jit-family decorator on {fn_node.name!r} "
                        f"inside a non-builder function retraces per "
                        f"call — hoist it or memoize the built fn"))

            def visit_Assign(self, node):
                # memoized-plan idiom: `self._fn = jax.jit(...)` (or
                # `fn = self._fn = ...`) is build-once by construction
                memo = any(isinstance(t, ast.Attribute)
                           and isinstance(t.value, ast.Name)
                           and t.value.id == "self"
                           for t in node.targets)
                if memo:
                    self.memo_depth += 1
                self.generic_visit(node)
                if memo:
                    self.memo_depth -= 1

            def visit_Call(self, node):
                name = _call_name(node)
                if name in _JIT_NAMES and not fn_allowed(self.fstack) \
                        and not self.memo_depth:
                    viol.append(Violation(
                        JitCacheHygieneRule.id, ctx.path, node.lineno,
                        f"{name}() inside "
                        f"{'.'.join(s for s in self.fstack if s)}() "
                        f"is not a recognized cached-plan builder — "
                        f"each call retraces; hoist to __init__/module "
                        f"level or memoize on self"))
                self.generic_visit(node)

        V().visit(ctx.tree)
        yield from viol


# ---------------------------------------------------------------------------
# options-doc-coverage
# ---------------------------------------------------------------------------

# ONE-TIME allowlist of options that predate this lint and are not yet
# documented under docs/.  This list is CLOSED: entries may only be
# removed (by documenting the option) — a new option landing here
# instead of in docs/ is a lint failure by design.
OPTIONS_DOC_ALLOW: Set[str] = set()


class OptionsDocCoverageRule(Rule):
    id = "options-doc-coverage"
    doc = ("every option registered in common/config.py must be "
           "documented under docs/ (one-time closed allowlist for "
           "pre-existing gaps)")

    def _docs_text(self) -> str:
        docs_dir = os.path.join(REPO_ROOT, "docs")
        chunks = []
        if os.path.isdir(docs_dir):
            for f in sorted(os.listdir(docs_dir)):
                if f.endswith(".md"):
                    with open(os.path.join(docs_dir, f),
                              encoding="utf-8") as fh:
                        chunks.append(fh.read())
        return "\n".join(chunks)

    def check(self, ctx: AnalysisContext) -> Iterator[Violation]:
        if ctx.relpath.replace(os.sep, "/") != "common/config.py":
            return
        docs = self._docs_text()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _call_name(node) == "Option" and node.args):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue  # f-string families (debug_<subsys>) are
                # documented as a family; runtime coverage is in tests
            name = arg.value
            if name in OPTIONS_DOC_ALLOW:
                continue
            if name not in docs:
                yield Violation(
                    self.id, ctx.path, node.lineno,
                    f"option {name!r} is not documented anywhere "
                    f"under docs/ — an option an operator cannot "
                    f"discover is an option nobody sets")


ALL_RULES = [NoBareLockRule, NoUntrackedSyncRule, NoWallClockRule,
             NoWireDriftRule, JitCacheHygieneRule,
             OptionsDocCoverageRule]


def rule_by_id(rule_id: str) -> Rule:
    for cls in ALL_RULES:
        if cls.id == rule_id:
            return cls()
    raise KeyError(f"unknown rule {rule_id!r}; known: "
                   f"{[c.id for c in ALL_RULES]}")
