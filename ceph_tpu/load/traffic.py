"""Traffic generator — N synthetic clients over the real client stack.

The "heavy traffic" half of the north star (ROADMAP): nothing else in
the repo generates sustained concurrent multi-client load — tests drive
a handful of ops and every bench workload uses one submitter.  This
module drives a MiniCluster with N :class:`SyntheticClient`\\ s, each a
real ``RadosClient`` (own messenger endpoint, own Objecter-style tid
space, own map subscription) submitting ops WITHOUT blocking on each
reply, so one fabric pump carries a genuine burst of concurrent client
traffic into the OSDs' sharded op queues — exactly the case the
per-client dmClock tier and overload admission control exist for
(docs/QOS.md).

Determinism: the fabric is single-threaded; a run is a sequence of
*rounds*.  Each round every client issues ops per its arrival process
(interleaved round-robin across clients so arrival order is fair), then
one ``network.pump()`` delivers the burst; with
``osd_op_queue_batch_intake`` the OSDs accumulate the whole burst and
drain it through the mClock tiers at quiescence.  Completion *rounds*
are therefore deterministic (seeded RNGs, no wall time in any decision
path); wall-clock latencies feed the per-client PerfHistograms the
percentiles are read from.

Workload shape knobs (:class:`TrafficSpec`): arrival process (closed
loop with a per-client in-flight window, or open loop with a Poisson
per-round rate and per-client rate multipliers — the abusive-client
dial), read/write mix, object-size distribution, and Zipfian hot-key
skew over each client's key space.  Clients own disjoint key spaces and
serialize per key, so every read is verifiable byte-exact against the
client's last committed payload — "every op completes byte-exact" is an
assertable property, not a hope.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..client.rados import RadosClient
from ..common.config import g_conf
from ..common.work_queue import (
    l_qos_admission_rejections, l_qos_queue_depth, l_qos_throttle_events,
    qos_perf_counters,
)
from ..msg.messages import (
    CEPH_OSD_OP_READ, CEPH_OSD_OP_WRITEFULL, MOSDOp, MOSDOpReply,
    new_trace_id,
)
from ..trace import g_perf_histograms, latency_axes
from ..trace.oplat import stamp_client

# retryable resend caps: an op survives this many peering/silent-primary
# rounds (throttle resends are budgeted separately — backpressure is
# not an error and under saturation legitimately recurs for a while)
MAX_OP_ATTEMPTS = 64
MAX_THROTTLE_RESENDS = 4096


@dataclass
class TrafficSpec:
    """One workload's shape (see module docstring)."""
    pool: str = "load"
    n_clients: int = 8
    ops_per_client: int = 64
    read_fraction: float = 0.5
    # (size_bytes, weight) choices for write payloads
    object_sizes: Tuple[Tuple[int, float], ...] = (
        (512, 0.50), (4096, 0.35), (16384, 0.15))
    keys_per_client: int = 16
    zipf_theta: float = 0.99      # hot-key skew; 0 = uniform
    mode: str = "closed"          # "closed" | "open"
    window: int = 4               # closed loop: ops in flight per client
    rate: float = 8.0             # open loop: mean issues per round
    # per-client multiplier on ``rate`` (pad with 1.0); (10, 1, 1, ...)
    # is the abusive-client saturation shape
    rate_multipliers: Tuple[float, ...] = ()
    seed: int = 20260803
    max_rounds: int = 100000
    tick_every: int = 32          # cluster.tick cadence (retry sweeps)
    keep_completions: bool = True  # False for soaks: aggregate only
    # first-class cluster events scheduled mid-run (the recovery-storm
    # shape, docs/RECOVERY.md): (round, action, arg) with action in
    # osd_kill | osd_down | osd_out | osd_revive | osd_in (arg = osd
    # id) or mesh_chip_add | mesh_chip_retire (arg = CHIP COUNT delta
    # applied to the live ec_mesh_chips target; docs/CHAOS.md) — fired
    # at the START of that round, so the remaining traffic runs
    # against the changed topology.  "osd_kill" is the full storm
    # trigger (network down + mon mark-down); pair it with "osd_out"
    # to start backfill to a spare while clients keep running.
    events: Tuple[Tuple[int, str, int], ...] = ()
    # scheduled callables (round, fn) fired at the START of that round
    # with the cluster as the only argument, same passed-round
    # semantics as ``events`` — the chaos composer compiles fault
    # arm/clear legs into these (the declarative ScenarioSpec stays
    # the unit of determinism; ceph_tpu/chaos/engine.py compiles it)
    hooks: Tuple[Tuple[int, Callable], ...] = ()


@dataclass
class PendingOp:
    kind: str                     # "write" | "read"
    oid: str
    payload: bytes                # write body / expected read body
    expect_absent: bool = False
    t0: float = 0.0               # perf_counter at FIRST issue
    round0: int = 0               # round of first issue
    attempts: int = 0
    throttle_resends: int = 0


class SyntheticClient(RadosClient):
    """A RadosClient that submits without blocking on replies.

    ``step(round)`` (re)sends per the arrival process; replies are
    consumed in ``ms_fast_dispatch`` during the pump, where wall
    latency lands in this client's PerfHistogram
    (``client_op_latency_histogram``, logger = client name) and
    completion rounds in the deterministic round-latency tally.
    """

    def __init__(self, network, mon, name: str, spec: TrafficSpec,
                 index: int):
        super().__init__(network, mon, name)
        self.spec = spec
        self.index = index
        self.rng = np.random.default_rng(spec.seed * 1009 + index)
        self.pool_id = self.lookup_pool(spec.pool)
        self.issued = 0
        self.completed = 0
        self.throttled = 0
        self.errors: List[str] = []
        self.completions: List[Tuple[str, int, int, float]] = []
        self.round_latency_max = 0
        self.pending: Dict[int, PendingOp] = {}
        self._resend: List[PendingOp] = []
        self._inflight_oids: set = set()
        self._committed: Dict[str, bytes] = {}
        self._gen: Dict[str, int] = {}
        self.hist = g_perf_histograms.get(
            name, "client_op_latency_histogram", latency_axes)
        # the registry is process-global and a later run may reuse this
        # entity name: this run's percentiles must be THIS run's
        # distribution, not the session's
        self.hist.reset()
        self.bytes_moved = 0
        # zipf CDF over the client's key space: p(k) ~ 1/(k+1)^theta
        w = np.arange(1, spec.keys_per_client + 1,
                      dtype=np.float64) ** -max(spec.zipf_theta, 0.0)
        self._zipf_cdf = np.cumsum(w / w.sum())
        sizes = np.asarray([s for s, _w in spec.object_sizes])
        sw = np.asarray([w for _s, w in spec.object_sizes],
                        dtype=np.float64)
        self._sizes, self._size_cdf = sizes, np.cumsum(sw / sw.sum())

    # ---- arrival process ---------------------------------------------------
    def done(self) -> bool:
        return (self.issued >= self.spec.ops_per_client
                and not self.pending and not self._resend)

    def ops_to_issue(self) -> int:
        """How many NEW ops this round's arrival process asks for."""
        sp = self.spec
        budget = sp.ops_per_client - self.issued
        if budget <= 0:
            return 0
        if sp.mode == "open":
            mult = sp.rate_multipliers[self.index] \
                if self.index < len(sp.rate_multipliers) else 1.0
            n = int(self.rng.poisson(max(sp.rate * mult, 0.0)))
        else:
            n = sp.window - len(self.pending) - len(self._resend)
        return max(0, min(n, budget))

    def _pick_key(self) -> str:
        k = int(np.searchsorted(self._zipf_cdf, self.rng.random()))
        return f"{self.name}-k{k}"

    def _pick_size(self) -> int:
        return int(self._sizes[int(np.searchsorted(
            self._size_cdf, self.rng.random()))])

    def make_op(self) -> Optional[PendingOp]:
        """Draw the next op: a read hits a committed key byte-exactly
        (falling back to a write while nothing is committed yet); a
        client never races itself on one oid, so "expected bytes" stays
        well-defined under concurrency."""
        want_read = (self.rng.random() < self.spec.read_fraction
                     and bool(self._committed))
        if want_read:
            oid = self._pick_key()     # zipf skew first...
            if oid not in self._committed or oid in self._inflight_oids:
                ks = [k for k in self._committed
                      if k not in self._inflight_oids]
                if not ks:
                    want_read = False  # every committed key is busy
                else:                  # ...uniform over committed else
                    oid = ks[int(self.rng.integers(len(ks)))]
            if want_read:
                # reserve at DRAW time: a later draw this same round
                # must not put a write on this oid, or the expected
                # bytes go stale if the ops retry in throttle cycles
                self._inflight_oids.add(oid)
                return PendingOp("read", oid, self._committed[oid])
        for _try in range(8):
            oid = self._pick_key()
            if oid in self._inflight_oids:
                continue
            gen = self._gen.get(oid, 0) + 1
            self._gen[oid] = gen
            body = np.random.default_rng(
                (hash(oid) & 0xFFFFFFFF) * 131 + gen).integers(
                    0, 256, self._pick_size(), dtype=np.uint8).tobytes()
            self._inflight_oids.add(oid)
            return PendingOp("write", oid, body)
        return None

    # ---- send / resend -----------------------------------------------------
    def _send(self, op: PendingOp, round_no: int) -> None:
        pgid, primary = self._calc_target(self.pool_id, op.oid)
        self._tid += 1
        tid = self._tid
        if op.attempts == 0 and op.throttle_resends == 0:
            op.t0 = time.perf_counter()
            op.round0 = round_no
        self.pending[tid] = op
        self._inflight_oids.add(op.oid)
        if primary < 0:
            # no primary yet (peering): park for the next round, under
            # the same attempt cap as reply-path retries — a PG that
            # never elects a primary must fail fast as "retries
            # exhausted", not spin the run to max_rounds
            del self.pending[tid]
            op.attempts += 1
            if op.attempts > MAX_OP_ATTEMPTS:
                self._inflight_oids.discard(op.oid)
                self.errors.append(
                    f"{op.kind} {op.oid}: retries exhausted (no primary)")
                return
            self.mon.send_full_map(self.name)
            self._resend.append(op)
            return
        msg = MOSDOp(
            tid=tid, pool=pgid[0], oid=op.oid, pgid=pgid,
            op=CEPH_OSD_OP_WRITEFULL if op.kind == "write"
            else CEPH_OSD_OP_READ,
            data=op.payload if op.kind == "write" else b"",
            epoch=self.osdmap.epoch,
            trace_id=new_trace_id())
        # stage-latency ledger submit stamp (trace/oplat.py): harness
        # traffic decomposes like any client's — the OSD-side
        # client_flight stage shows pump-cycle transit under load
        stamp_client(msg, self.name)
        self.messenger.send_message(msg, f"osd.{primary}")

    def collect_sends(self, round_no: int) -> List[PendingOp]:
        """This round's sends, IN ORDER (resends first — throttled /
        peering replays — then new ops per the arrival process) but not
        yet sent: the generator interleaves the per-client batches
        round-robin so one client's burst cannot monopolize arrival
        order (independent clients' packets interleave on a real
        network; without this the abusive client would always win the
        admission race simply by being enumerated first)."""
        self._round = round_no
        # window accounting BEFORE the resend swap: throttled/parked
        # ops already left self.pending, so the closed-loop window
        # must count them via _resend or a throttled client would
        # stack a full window of NEW ops on top of its replays
        n_new = self.ops_to_issue()
        out, self._resend = self._resend, []
        # NOTE: resend ops keep their _inflight_oids reservation — a
        # throttled read must not race a new write to its oid, or the
        # expected bytes become ambiguous (per-oid serialization is
        # what makes byte-exact verification sound)
        for _ in range(n_new):
            op = self.make_op()
            if op is None:
                break
            self.issued += 1
            out.append(op)
        return out

    # ---- completion --------------------------------------------------------
    def ms_fast_dispatch(self, msg) -> None:
        if isinstance(msg, MOSDOpReply) and msg.tid in self.pending:
            self._complete(msg.tid, msg)
            return
        super().ms_fast_dispatch(msg)

    def _complete(self, tid: int, reply: MOSDOpReply) -> None:
        op = self.pending.pop(tid)
        if reply.result == -11:
            # retryable: the op stays logically in flight — its
            # _inflight_oids reservation is NOT released, so no new op
            # can race it on the same oid while it waits to resend
            if getattr(reply, "retry_after", 0.0) > 0:
                # admission throttle: resend next round (the pump in
                # between is what drains the OSD's queue)
                self.throttled += 1
                op.throttle_resends += 1
                if op.throttle_resends <= MAX_THROTTLE_RESENDS:
                    self._resend.append(op)
                    return
            else:
                op.attempts += 1
                if op.attempts <= MAX_OP_ATTEMPTS:
                    # peering/misroute: refresh the map, retry
                    self.mon.send_full_map(self.name)
                    self._resend.append(op)
                    return
            self._inflight_oids.discard(op.oid)
            self.errors.append(f"{op.kind} {op.oid}: retries exhausted")
            return
        self._inflight_oids.discard(op.oid)
        round_no = getattr(self, "_round", op.round0)
        if op.kind == "write":
            if reply.result != 0:
                self.errors.append(
                    f"write {op.oid}: {reply.result}")
                return
            self._committed[op.oid] = op.payload
        else:
            if op.expect_absent:
                if reply.result != -2:
                    self.errors.append(
                        f"read {op.oid}: expected ENOENT, "
                        f"got {reply.result}")
                    return
            elif reply.result != 0:
                self.errors.append(f"read {op.oid}: {reply.result}")
                return
            elif bytes(reply.data) != op.payload:
                self.errors.append(f"read {op.oid}: BYTES DIVERGED "
                                   f"({len(reply.data)} vs "
                                   f"{len(op.payload)})")
                return
        self.completed += 1
        self.bytes_moved += len(op.payload)
        lat_us = (time.perf_counter() - op.t0) * 1e6
        self.hist.inc(lat_us)
        rl = round_no - op.round0
        self.round_latency_max = max(self.round_latency_max, rl)
        if self.spec.keep_completions:
            self.completions.append((op.kind, op.round0, round_no,
                                     lat_us))


# ---- percentiles out of the PerfHistogram machinery ------------------------
# ONE percentile implementation for every consumer: the quantile rule
# lives in trace.histogram (hist_percentiles / merged_percentiles,
# shared with `latency dump`, the bench stage_breakdown deltas and the
# mgr telemetry rollup's cluster merge) and is re-exported here for the
# harness's historical import path.
from ..trace.histogram import hist_percentiles, merged_percentiles  # noqa: E402


def _apply_event(cluster, action: str, osd_id: int) -> None:
    """One scheduled topology event (TrafficSpec.events)."""
    if action == "osd_kill":
        cluster.kill_osd(osd_id)
        cluster.mark_osd_down(osd_id)
    elif action == "osd_down":
        cluster.mark_osd_down(osd_id)
    elif action == "osd_out":
        cluster.mark_osd_out(osd_id)
    elif action == "osd_revive":
        cluster.revive_osd(osd_id)
    elif action == "osd_in":
        cluster.mark_osd_in(osd_id)
    elif action in ("mesh_chip_add", "mesh_chip_retire"):
        # elastic membership as a first-class storyline step: the arg
        # is a CHIP COUNT delta (not an osd id) applied to the live
        # ec_mesh_chips target.  set_checked fires the MeshRuntime
        # observer, so the drain-on-old-mesh + plan-cache rebuild run
        # right here, between rounds, under open traffic.
        from ..mesh import g_mesh
        cur = int(g_conf.get_val("ec_mesh_chips"))
        if cur < 0:         # -1 = all devices: resolve to the live size
            mesh = g_mesh.topology()
            cur = 0 if mesh is None else mesh.size
        delta = int(osd_id)
        if action == "mesh_chip_retire":
            delta = -delta
        g_conf.set_checked("ec_mesh_chips", max(cur + delta, 1))
    else:
        raise ValueError(f"unknown traffic event action '{action}'")


@dataclass
class TrafficResult:
    spec: TrafficSpec
    rounds: int = 0
    elapsed_s: float = 0.0
    total_ops: int = 0
    completed: int = 0
    bytes_moved: int = 0          # payload bytes of completed ops
    errors: List[str] = field(default_factory=list)
    byte_exact: bool = False
    throttled_total: int = 0
    admission_rejections: int = 0
    throttle_events: int = 0
    max_intake_depth: int = 0
    per_client: Dict[str, Dict] = field(default_factory=dict)
    aggregate: Dict[str, float] = field(default_factory=dict)

    @property
    def ops_per_sec(self) -> float:
        # client-observed completions, never issue rate: an op that
        # exhausted retries must not inflate a fenced throughput figure
        return self.completed / self.elapsed_s if self.elapsed_s else 0.0


def run_traffic(cluster, spec: TrafficSpec,
                progress=None) -> TrafficResult:
    """Drive *cluster* (pool ``spec.pool`` must exist) with the traffic
    shape in *spec*; returns per-client + aggregate stats.  Batch
    intake is enabled for the run (and restored after) so each round's
    burst sees real mClock arbitration."""
    qos = qos_perf_counters()
    rej0 = qos.get(l_qos_admission_rejections)
    thr0 = qos.get(l_qos_throttle_events)
    # the depth gauge is only written at admission-checked intakes: a
    # previous run's high-water must not leak into this run's report
    qos.set(l_qos_queue_depth, 0)
    saved = g_conf.values.get("osd_op_queue_batch_intake")
    g_conf.set_val("osd_op_queue_batch_intake", True)
    res = TrafficResult(spec=spec)
    t_start = time.perf_counter()
    try:
        clients = [SyntheticClient(cluster.network, cluster.mon,
                                   f"client.{spec.pool}.{i}", spec, i)
                   for i in range(spec.n_clients)]
        rnd = 0
        fired: set = set()
        hooks_fired: set = set()
        while rnd < spec.max_rounds:
            for i, (r_ev, action, osd_id) in enumerate(spec.events):
                # events fire when their round arrives (or is passed —
                # a run can complete rounds faster than scheduled)
                if i not in fired and rnd >= r_ev:
                    fired.add(i)
                    _apply_event(cluster, action, osd_id)
            for i, (r_hk, fn) in enumerate(spec.hooks):
                if i not in hooks_fired and rnd >= r_hk:
                    hooks_fired.add(i)
                    fn(cluster)
            if all(cl.done() for cl in clients) and \
                    len(fired) == len(spec.events) and \
                    len(hooks_fired) == len(spec.hooks):
                break
            batches = [cl.collect_sends(rnd) for cl in clients]
            sent = sum(len(b) for b in batches)
            # fair arrival order: round-robin one op per client until
            # every batch drains (per-client order preserved)
            while any(batches):
                for cl, batch in zip(clients, batches):
                    if batch:
                        cl._send(batch.pop(0), rnd)
            cluster.network.pump()
            res.max_intake_depth = max(res.max_intake_depth,
                                       qos.get(l_qos_queue_depth))
            if spec.tick_every and rnd % spec.tick_every == \
                    spec.tick_every - 1:
                # drive retry sweeps / heartbeats like a live cluster
                cluster.tick(dt=0.5)
            if progress is not None and rnd % 256 == 255:
                progress(rnd, sum(cl.completed for cl in clients))
            if sent == 0 and not any(cl.pending or cl._resend
                                     for cl in clients) and \
                    len(fired) == len(spec.events) and \
                    len(hooks_fired) == len(spec.hooks) and \
                    all(cl.issued >= spec.ops_per_client
                        for cl in clients):
                # truly drained: budgets spent AND nothing in flight.
                # An all-zero Poisson round with budget remaining must
                # NOT end the run — later rounds draw again.
                break
            rnd += 1
        res.rounds = rnd
    finally:
        if saved is None:
            g_conf.rm_val("osd_op_queue_batch_intake")
        else:
            g_conf.set_val("osd_op_queue_batch_intake", saved)
    res.elapsed_s = time.perf_counter() - t_start
    res.total_ops = sum(cl.issued for cl in clients)
    res.completed = sum(cl.completed for cl in clients)
    res.bytes_moved = sum(cl.bytes_moved for cl in clients)
    res.throttled_total = sum(cl.throttled for cl in clients)
    res.admission_rejections = \
        qos.get(l_qos_admission_rejections) - rej0
    res.throttle_events = qos.get(l_qos_throttle_events) - thr0
    for cl in clients:
        res.errors.extend(f"{cl.name}: {e}" for e in cl.errors)
        res.per_client[cl.name] = {
            "issued": cl.issued,
            "completed": cl.completed,
            "throttled": cl.throttled,
            "round_latency_max": cl.round_latency_max,
            **hist_percentiles(cl.hist),
        }
    res.byte_exact = (not res.errors
                      and res.completed == res.total_ops
                      and res.total_ops
                      == spec.n_clients * spec.ops_per_client)
    # aggregate percentiles over the union of the per-client
    # distributions — the telemetry rollup's merge core (same edges,
    # so the cluster tail is exact, not an approximation)
    res.aggregate = merged_percentiles([cl.hist for cl in clients])
    return res
