"""ceph_tpu.load — million-op traffic harness (docs/QOS.md).

Open/closed-loop multi-client workload generation over the real
messenger/client stack, with per-client latency percentiles out of the
PerfHistogram machinery.  The load every QoS / perf PR is measured
under; exposed to ``python -m ceph_tpu.bench`` via
``bench.workloads.measure_traffic``.
"""
from .traffic import (
    MAX_OP_ATTEMPTS, MAX_THROTTLE_RESENDS, PendingOp, SyntheticClient,
    TrafficResult, TrafficSpec, hist_percentiles, run_traffic,
)

__all__ = [
    "MAX_OP_ATTEMPTS", "MAX_THROTTLE_RESENDS", "PendingOp",
    "SyntheticClient", "TrafficResult", "TrafficSpec",
    "hist_percentiles", "run_traffic",
]
