"""cls_lock: advisory object locks (src/cls/lock/cls_lock.cc).

The reference's generic lock class — librbd exclusive-lock, rgw
coordination, and rados_lock_exclusive/shared all build on it.  Lock
state lives in an object xattr (``lock.<name>`` here, as in the
reference), so it works on EC pools too (no omap needed), and every
operation is a class method running atomically on the object's PG.

Semantics (cls_lock_types.h / cls_lock.cc):
- a lock has a type (EXCLUSIVE or SHARED), a tag, and a set of lockers
  identified by (entity, cookie) with per-locker expiration;
- lock: EXCLUSIVE conflicts with any other locker; SHARED coexists
  with other SHARED holders of the same tag; re-locking your own
  (entity, cookie) renews the expiration; expired lockers are pruned
  on every operation;
- unlock: removes exactly your (entity, cookie); -ENOENT otherwise;
- break_lock: removes a NAMED other locker (operator intervention);
- get_info: lockers + type + tag; assert_locked: vector guard.
"""
from __future__ import annotations

import json

from .cls import CLS_METHOD_WR, ClsContext, register_cls_method

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2

_PREFIX = "lock."


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _parse(inp: bytes):
    try:
        return json.loads(inp.decode()) if inp else {}
    except ValueError:
        return {}


def _load(ctx: ClsContext, name: str):
    try:
        st = json.loads(ctx.getxattr(_PREFIX + name))
    except Exception:
        return None
    # prune expired lockers on every access (cls_lock does the same)
    live = [lk for lk in st["lockers"]
            if not lk["expiration"] or lk["expiration"] > ctx.now]
    if len(live) != len(st["lockers"]):
        st["lockers"] = live
    return st


def _store(ctx: ClsContext, name: str, st) -> None:
    if st["lockers"]:
        ctx.setxattr(_PREFIX + name, _j(st))
    else:
        ctx.rmxattr(_PREFIX + name)


@register_cls_method("lock", "lock", CLS_METHOD_WR)
def _lock(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    name = str(req["name"])
    ltype = int(req["type"])
    cookie = str(req.get("cookie", ""))
    tag = str(req.get("tag", ""))
    duration = float(req.get("duration", 0))
    entity = ctx.entity
    if ltype not in (LOCK_EXCLUSIVE, LOCK_SHARED):
        return -22, b""
    st = _load(ctx, name) or {"type": ltype, "tag": tag, "lockers": []}
    mine = [lk for lk in st["lockers"]
            if lk["entity"] == entity and lk["cookie"] == cookie]
    others = [lk for lk in st["lockers"] if lk not in mine]
    if others:
        if ltype == LOCK_EXCLUSIVE or st["type"] == LOCK_EXCLUSIVE:
            return -16, b""                           # EBUSY
        if st["tag"] != tag:
            return -16, b""       # shared lockers must agree on tag
    else:
        # no OTHER lockers: the caller (re)defines type + tag, incl. a
        # sole holder downgrading exclusive->shared (cls_lock.cc resets
        # lock_type whenever only the caller's own entry remains)
        st["type"], st["tag"] = ltype, tag
    expiration = ctx.now + duration if duration else 0
    st["lockers"] = others + [{
        "entity": entity, "cookie": cookie, "expiration": expiration,
        "description": str(req.get("description", ""))}]
    _store(ctx, name, st)
    return 0, b""


@register_cls_method("lock", "unlock", CLS_METHOD_WR)
def _unlock(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    st = _load(ctx, str(req["name"]))
    if st is None:
        return -2, b""
    entity, cookie = ctx.entity, str(req.get("cookie", ""))
    keep = [lk for lk in st["lockers"]
            if not (lk["entity"] == entity and lk["cookie"] == cookie)]
    if len(keep) == len(st["lockers"]):
        return -2, b""                                # not a holder
    st["lockers"] = keep
    _store(ctx, str(req["name"]), st)
    return 0, b""


@register_cls_method("lock", "break_lock", CLS_METHOD_WR)
def _break_lock(ctx: ClsContext, inp: bytes):
    """Forcibly remove ANOTHER entity's lock (operator tooling:
    rados lock break / rbd lock rm)."""
    req = _parse(inp)
    st = _load(ctx, str(req["name"]))
    if st is None:
        return -2, b""
    target, cookie = str(req["entity"]), str(req.get("cookie", ""))
    keep = [lk for lk in st["lockers"]
            if not (lk["entity"] == target and lk["cookie"] == cookie)]
    if len(keep) == len(st["lockers"]):
        return -2, b""
    st["lockers"] = keep
    _store(ctx, str(req["name"]), st)
    return 0, b""


@register_cls_method("lock", "get_info")
def _get_info(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    st = _load(ctx, str(req["name"]))
    if st is None or not st["lockers"]:
        return 0, _j({"type": 0, "tag": "", "lockers": []})
    return 0, _j(st)


@register_cls_method("lock", "list_locks")
def _list_locks(ctx: ClsContext, inp: bytes):
    names = []
    for k in ctx.attr_names():
        if k.startswith(_PREFIX):
            st = _load(ctx, k[len(_PREFIX):])
            if st is not None and st["lockers"]:
                names.append(k[len(_PREFIX):])
    return 0, _j(sorted(names))


@register_cls_method("lock", "assert_locked")
def _assert_locked(ctx: ClsContext, inp: bytes):
    """Vector guard: abort unless the CALLER holds the lock as
    specified (cls_lock assert_locked — librbd uses this to fence
    writes behind the exclusive lock)."""
    req = _parse(inp)
    st = _load(ctx, str(req["name"]))
    if st is None:
        return -16, b""                               # EBUSY
    entity, cookie = ctx.entity, str(req.get("cookie", ""))
    for lk in st["lockers"]:
        if lk["entity"] == entity and lk["cookie"] == cookie:
            if "type" in req and st["type"] != int(req["type"]):
                return -16, b""
            return 0, b""
    return -16, b""
