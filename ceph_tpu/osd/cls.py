"""Object classes — server-side op plugins (src/cls, 30k LoC in the
reference; dispatched by PrimaryLogPG do_osd_ops CEPH_OSD_OP_CALL).

The reference loads ``libcls_<name>.so`` plugins that register named
methods; clients invoke them with ``rados_exec``/``ObjectOperation::
exec`` and the method runs ON the OSD inside the op transaction, with
read/write access to the target object.  Same shape here: a registry of
``(class, method) -> fn(ctx, input) -> (ret, output)`` where ctx wraps
the vector interpreter's in-memory object state, so a method's
mutations commit atomically with the rest of the op vector.

Built-ins mirror reference fixtures: ``hello`` (cls_hello.cc) and
``numops`` (cls_numops.cc: string-encoded arithmetic on the object
body).
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

_METHODS: Dict[Tuple[str, str], Callable] = {}

# method flags (cls_method_handle_t CLS_METHOD_RD/WR)
CLS_METHOD_RD = 1
CLS_METHOD_WR = 2


class ClsError(Exception):
    """Typed method failure: carries the errno the call returns
    (cls_cxx_* negative returns)."""

    def __init__(self, ret: int):
        super().__init__(f"cls error {ret}")
        self.ret = ret


class ClsContext:
    """The method's view of the object (cls_method_context_t role):
    reads and writes go through the SAME staged state the rest of the
    op vector sees, so everything commits (or aborts) together."""

    def __init__(self, st: Dict):
        self._st = st

    @property
    def exists(self) -> bool:
        return self._st["exists"]

    @property
    def now(self) -> float:
        """OSD wall time (for cls_lock expirations)."""
        return self._st.get("now", 0.0)

    @property
    def entity(self) -> str:
        """The calling client's entity name (cls_cxx_get_origin)."""
        return self._st.get("entity", "")

    def read(self) -> bytes:
        return bytes(self._st["body"])

    def write_full(self, data: bytes) -> None:
        self._st["body"] = bytearray(data)
        self._st["exists"] = True
        self._st["_mutated"] = True

    def getxattr(self, name: str) -> bytes:
        try:
            return self._st["attrs"][name]
        except KeyError:
            raise ClsError(-61)       # ENODATA (cls_cxx_getxattr)

    def setxattr(self, name: str, value: bytes) -> None:
        self._st["attrs"][name] = bytes(value)
        self._st["exists"] = True
        self._st["_meta"] = True

    def rmxattr(self, name: str) -> None:
        self._st["attrs"].pop(name, None)
        self._st["_meta"] = True

    def attr_names(self):
        return sorted(self._st["attrs"])

    def _check_omap(self) -> None:
        if not self._st.get("omap_ok", True):
            raise ClsError(-95)      # EOPNOTSUPP: no omap on EC pools

    def omap_get(self) -> Dict[str, bytes]:
        self._check_omap()
        return dict(self._st["omap"])

    def omap_set(self, kv: Dict[str, bytes]) -> None:
        self._check_omap()
        self._st["omap"].update(
            {k: v if isinstance(v, bytes) else str(v).encode()
             for k, v in kv.items()})
        self._st["exists"] = True
        self._st["_meta"] = True

    def omap_rm_keys(self, keys) -> None:
        self._check_omap()
        for k in keys:
            self._st["omap"].pop(k, None)
        self._st["_meta"] = True


def register_cls_method(cls: str, method: str, flags: int = CLS_METHOD_RD
                        ) -> Callable:
    """Decorator: register fn(ctx, input: bytes) -> (ret, out: bytes)
    (cls_register_cxx_method)."""

    def wrap(fn: Callable) -> Callable:
        _METHODS[(cls, method)] = (fn, flags)
        return fn
    return wrap


def lookup(cls: str, method: str):
    return _METHODS.get((cls, method))


# ---- built-in classes ------------------------------------------------------

@register_cls_method("hello", "say_hello")
def _say_hello(ctx: ClsContext, inp: bytes):
    who = inp.decode() if inp else "world"
    return 0, f"Hello, {who}!".encode()


@register_cls_method("hello", "record_hello", CLS_METHOD_WR)
def _record_hello(ctx: ClsContext, inp: bytes):
    who = inp.decode() if inp else "world"
    ctx.write_full(f"Hello, {who}!".encode())
    ctx.setxattr("hello", b"1")
    return 0, b""


@register_cls_method("numops", "add", CLS_METHOD_WR)
def _numops_add(ctx: ClsContext, inp: bytes):
    """cls_numops: the object body holds a string-encoded number; add
    the input to it (cls_numops.cc add)."""
    try:
        delta = float(inp.decode())
        cur = float(ctx.read().decode()) if ctx.exists and ctx.read() \
            else 0.0
    except ValueError:
        return -22, b""                      # EINVAL, like the reference
    out = cur + delta
    enc = ("%d" % out if out == int(out) else repr(out)).encode()
    ctx.write_full(enc)
    return 0, b""


@register_cls_method("numops", "mul", CLS_METHOD_WR)
def _numops_mul(ctx: ClsContext, inp: bytes):
    try:
        factor = float(inp.decode())
        cur = float(ctx.read().decode()) if ctx.exists and ctx.read() \
            else 0.0
    except ValueError:
        return -22, b""
    out = cur * factor
    enc = ("%d" % out if out == int(out) else repr(out)).encode()
    ctx.write_full(enc)
    return 0, b""


# generic lock class registers with the same registry (src/cls/lock)
from . import cls_lock  # noqa: E402,F401


def load_builtin_classes() -> None:
    """Import every in-tree object class (osd_class_load_list='*'):
    the reference OSD dlopens all cls plugins at start, so a client's
    call works whether or not ITS process imported the owning package
    — essential for cross-process clusters, where the OSD daemon never
    imports ceph_tpu.rbd/cephfs/rgw on its own."""
    import importlib
    for mod in ("ceph_tpu.rbd.cls_rbd", "ceph_tpu.cephfs.cls_fs",
                "ceph_tpu.rgw.cls_rgw", "ceph_tpu.journal.cls_journal"):
        importlib.import_module(mod)
