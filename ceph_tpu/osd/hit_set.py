"""HitSet — per-PG access tracking for the cache-tier agent.

The reference records object accesses in periodically-rotated bloom
filters (src/osd/HitSet.h BloomHitSet; hit_set_setup / hit_set_persist
in PrimaryLogPG.cc): the agent asks "was this object touched in the
last N periods?" to decide flush/evict temperature.  Same design here:
a fixed-width bloom with rjenkins-derived probes, a deque of sealed
sets, and a combined containment query.
"""
from __future__ import annotations

from collections import deque
from typing import Deque, Iterable

from ..utils.str_hash import ceph_str_hash_rjenkins

_BITS = 1 << 12            # 4096-bit filter: ample for test-scale PGs
_PROBES = 4


class BloomHitSet:
    def __init__(self):
        self.bits = 0
        self.inserts = 0

    def _probes(self, name: str) -> Iterable[int]:
        h1 = ceph_str_hash_rjenkins(name)
        h2 = ceph_str_hash_rjenkins(name + "\x01")
        for i in range(_PROBES):
            yield (h1 + i * h2) % _BITS

    def insert(self, name: str) -> None:
        for p in self._probes(name):
            self.bits |= 1 << p
        self.inserts += 1

    def contains(self, name: str) -> bool:
        return all(self.bits >> p & 1 for p in self._probes(name))

    def encode(self) -> bytes:
        return self.bits.to_bytes(_BITS // 8, "little")

    @classmethod
    def decode(cls, blob: bytes) -> "BloomHitSet":
        hs = cls()
        hs.bits = int.from_bytes(blob, "little")
        return hs


class HitSetHistory:
    """Current open set + up to *count* sealed predecessors."""

    def __init__(self, count: int = 4):
        self.count = count
        self.current = BloomHitSet()
        self.sealed: Deque[BloomHitSet] = deque(maxlen=max(count, 1))
        self.last_rotate = 0.0

    def record(self, name: str) -> None:
        self.current.insert(name)

    def rotate(self, now: float) -> None:
        """Seal the open set (hit_set_persist role)."""
        self.sealed.append(self.current)
        self.current = BloomHitSet()
        self.last_rotate = now

    def maybe_rotate(self, now: float, period: float) -> None:
        if now - self.last_rotate >= period:
            self.rotate(now)

    def contains(self, name: str) -> bool:
        if self.current.contains(name):
            return True
        return any(hs.contains(name) for hs in self.sealed)
