"""PG — placement group with log-based peering and recovery.

The reference drives each PG through a boost::statechart RecoveryMachine
(src/osd/PG.h:1879: Initial/Peering(GetInfo/GetLog/GetMissing)/Active
(Activating/Recovering/Backfilling)); here the same lifecycle is an
explicit state machine driven entirely by messages over the fabric:

- AdvMap: on every epoch the PG recomputes up/acting; a changed acting set
  puts the primary into PEERING and fans MOSDPGQuery to every acting
  shard (GetInfo).
- GetLog: if a peer reports a newer last_update, the primary fetches the
  authoritative log suffix and merges it (PGLog.merge_authoritative).
- GetMissing: each peer's missing set is computed from the log suffix
  past its reported last_update (log-bounded delta recovery, PGLog.h
  role); peers beyond the log tail go through backfill (MOSDPGScan
  listing diff).
- Activation: the primary ships each peer the log suffix it lacks
  (MOSDPGInfo activate=True) and goes ACTIVE; ops flow while recovery
  pushes reconstructed chunks in the background (ECBackend.cc:535-743).

Client ops on degraded objects are gated: reads exclude shards missing
the object; rmw writes recover the object first (PrimaryLogPG's
wait_for_missing_object semantics).
"""
from __future__ import annotations

import copy
import struct
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..crush.constants import CRUSH_ITEM_NONE
from ..msg import (
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE, CEPH_OSD_OP_READ,
    CEPH_OSD_OP_STAT, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
    MOSDOp, MOSDOpReply, MOSDPGInfo, MOSDPGQuery, MOSDPGScan,
    MOSDPGScanReply, MOSDRepScrub, MOSDRepScrubMap, Message,
)
from ..msg.messages import (
    CEPH_OSD_CMPXATTR_OP_EQ, CEPH_OSD_CMPXATTR_OP_GT,
    CEPH_OSD_CMPXATTR_OP_GTE, CEPH_OSD_CMPXATTR_OP_LT,
    CEPH_OSD_CMPXATTR_OP_LTE, CEPH_OSD_CMPXATTR_OP_NE,
    CEPH_OSD_OP_ASSERT_VER, CEPH_OSD_OP_CALL,
    CEPH_OSD_OP_CMPXATTR, CEPH_OSD_OP_COPY_FROM, CEPH_OSD_OP_CREATE,
    CEPH_OSD_OP_FLAG_EXCL,
    CEPH_OSD_OP_GETXATTR, CEPH_OSD_OP_GETXATTRS, CEPH_OSD_OP_OMAPGETVALS,
    CEPH_OSD_OP_OMAPRMKEYS, CEPH_OSD_OP_OMAPSETKEYS, CEPH_OSD_OP_RMXATTR,
    CEPH_OSD_OP_SETXATTR, CEPH_OSD_OP_TRUNCATE, CEPH_OSD_OP_ZERO, OSDOp,
)
from ..msg.kv import pack_kv, unpack_keys, unpack_kv
from ..common.dout import dlog
from ..trace import g_oplat
from ..os_store import Transaction, hobject_t
from .ec_backend import ECBackend, SIZE_ATTR
from .pg_log import (
    LogEntry, OP_DELETE, OP_MODIFY, PGLog, PG_META_OID, SNAP_CLONE,
    SNAP_TRIMMED, SNAP_WHITEOUT, VERSION_ATTR, encode_snapset,
    load_snapsets, stage_snapset,
)

PG_NUM_ATTR = "_pg_num"          # pg_num this PG's store layout reflects


def stored_pg_num_of(store, pg_id: Tuple[int, int]) -> int:
    """Read a PG layout's recorded pg_num straight from the store (0 =
    never recorded) — usable before any PG object exists."""
    cid = f"{pg_id[0]}.{pg_id[1]}_meta"
    meta = hobject_t(PG_META_OID)
    if store.collection_exists(cid) and store.exists(cid, meta):
        b = store.getattrs(cid, meta).get(PG_NUM_ATTR)
        if b:
            return struct.unpack("<I", b)[0]
    return 0

STATE_INITIAL = "initial"
STATE_PEERING = "peering"
STATE_ACTIVE = "active"
STATE_ACTIVE_RECOVERING = "active+recovering"


class ReplicatedBackend:
    """Full-copy backend for replicated pools (osd/ReplicatedBackend —
    replication is host-side fan-out, not device compute)."""

    def __init__(self, pg):
        self.pg = pg

    def cid(self) -> str:
        return f"{self.pg.pgid[0]}.{self.pg.pgid[1]}"

    def write(self, oid: str, data: bytes, offset: Optional[int] = None,
              full: bool = False, version: int = 0,
              xattrs: Optional[Dict[str, bytes]] = None,
              omap: Optional[Dict[str, bytes]] = None,
              attr_only: bool = False,
              snapset_update: Optional[Tuple[str, bytes]] = None) -> None:
        from ..msg.messages import MOSDECSubOpWrite
        if attr_only:
            off, partial, new_size = 0, True, 0
        elif full:
            off, partial = 0, False
            new_size = len(data)
        else:
            old = self.read(oid)
            old_size = len(old) if old is not None else 0
            off = old_size if offset is None else offset
            partial = True
            new_size = max(old_size, off + len(data))
        for osd in self.pg.acting:
            if osd == CRUSH_ITEM_NONE:
                continue
            msg = MOSDECSubOpWrite(tid=0, pgid=self.pg.pgid, shard=-1,
                                   oid=oid, chunk=data, offset=off,
                                   partial=partial, at_version=new_size,
                                   version=version, xattrs=xattrs,
                                   omap=omap, attr_only=attr_only,
                                   snapset_update=snapset_update)
            self.pg.send_to_osd(osd, msg)
        # stage ledger: replicated fans are fire-and-forget, so the
        # fan_out boundary (covering interpret + message build here)
        # is the last stage before the reply mark (trace/oplat.py)
        g_oplat.checkpoint("fan_out")

    def apply_write(self, msg, store) -> None:
        from .ec_backend import ECBackend, USER_ATTR_PREFIX
        cid = self.cid()
        t = Transaction()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        ho = hobject_t(msg.oid)
        if msg.attr_only:
            t.touch(cid, ho)
            if not (store.collection_exists(cid)
                    and store.exists(cid, ho)):
                t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", 0))
        else:
            from .ec_backend import DIGEST_ATTR
            if not msg.partial:
                t.truncate(cid, ho, 0)
            t.write(cid, ho, msg.offset, msg.chunk)
            if not msg.partial:
                from ..utils.crc32c import crc32c
                t.setattr(cid, ho, DIGEST_ATTR,
                          struct.pack("<I", crc32c(bytes(msg.chunk))))
            else:
                # unaligned overwrite: the whole-object digest no
                # longer describes the bytes — invalidate, don't lie
                # (after t.write, so the object exists to rmattr on)
                t.rmattr(cid, ho, DIGEST_ATTR)
            t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", msg.at_version))
        ECBackend._apply_user_attrs(t, store, cid, ho, msg.xattrs)
        if msg.omap is not None:
            existing = store.omap_get(cid, ho) \
                if store.collection_exists(cid) and store.exists(cid, ho) \
                else {}
            if existing:
                t.omap_rmkeys(cid, ho, list(existing))
            if msg.omap:
                t.omap_setkeys(cid, ho, msg.omap)
        if msg.version:
            from .pg_log import VERSION_ATTR
            t.setattr(cid, ho, VERSION_ATTR, struct.pack("<Q", msg.version))
            if not msg.is_push:
                self.pg.append_log(
                    LogEntry(msg.version, msg.oid, OP_MODIFY), t)
        if msg.snapset_update is not None:
            self.pg.apply_snapset_update(tuple(msg.snapset_update), t)
        store.queue_transaction(t)
        if not msg.partial:
            self.pg.data_received(msg.oid)

    def read(self, oid: str) -> Optional[bytes]:
        store = self.pg.osd.store
        cid = self.cid()
        ho = hobject_t(oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return None
        return store.read(cid, ho)

    def object_state(self, oid: str):
        """(exists, data, user_attrs, omap) from the local replica."""
        from .ec_backend import user_attrs_of
        store = self.pg.osd.store
        cid = self.cid()
        ho = hobject_t(oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return False, b"", {}, {}
        return (True, store.read(cid, ho),
                user_attrs_of(store.getattrs(cid, ho)),
                dict(store.omap_get(cid, ho)))


class PG:
    def __init__(self, osd, pgid: Tuple[int, int], pool):
        self.osd = osd
        self.pgid = pgid
        self.pool = pool
        self.up: List[int] = []
        self.acting: List[int] = []
        self.up_primary = -1
        self.acting_primary = -1
        self.state = STATE_INITIAL
        self.last_epoch_started = 0
        self.last_scrub_stamp = 0.0
        self.last_deep_scrub_stamp = 0.0
        self.backend: Optional[ECBackend] = None
        self.rep_backend: Optional[ReplicatedBackend] = None
        if pool.is_erasure():
            ec_impl = osd.get_ec_impl(pool)
            self.backend = ECBackend(self, ec_impl, pool.stripe_width)
        else:
            self.rep_backend = ReplicatedBackend(self)
        # per-PG op lock (PG::lock; taken by threaded dequeue_op and
        # visible to lockdep)
        from ..common.lockdep import DebugLock
        self.op_lock = DebugLock(f"pg-{pgid[0]}.{pgid[1]}")
        # cache-tier machinery (replicated cache pools only)
        self.tier = None
        if pool.tier_of >= 0 and pool.cache_mode and \
                self.rep_backend is not None:
            from .tier import TierState
            self.tier = TierState(self)
        # log + versions (one per PG replica; persists in the meta coll)
        self.pg_log = PGLog()
        self.pg_log.load(osd.store, self.meta_cid())
        # pg_num this replica's layout reflects: from disk if recorded
        # (restart case — may lag the map, triggering a catch-up
        # split), else the pool's current value, persisted now so a
        # restart straddling a future split epoch can't miss it
        stored = self.stored_pg_num()
        if stored:
            self.known_pg_num = stored
        else:
            self.record_pg_num(pool.pg_num)
        self._version_alloc = self.pg_log.head
        # replica-side: objects whose log entries arrived (activation)
        # but whose data has not (pg_missing_t role) — rebuilt from
        # log-vs-store on mount so restarts don't forget
        self.local_missing: Dict[str, Tuple[int, str]] = {}
        # per-head snapset (clone bookkeeping) mirrored from the meta
        # object on this shard — every replica has it (SnapSet role)
        self.snapsets: Dict[str, List[Tuple[int, int]]] = \
            load_snapsets(osd.store, self.meta_cid())
        # snap -> heads index (SnapMapper role) + what was already
        # trimmed (pg_info_t.purged_snaps role, persisted so a primary
        # dying mid-trim is finished by its successor)
        from .snap_mapper import SnapMapper, load_purged
        self.purged_snaps: Set[int] = load_purged(osd.store,
                                                  self.meta_cid())
        self.snap_mapper = SnapMapper()
        self.snap_mapper.rebuild(self.snapsets, self._interesting_snaps())
        # watch/notify: primary-side in-memory state (Watch.cc role;
        # watchers re-register after a primary change, like clients do
        # on watch timeout in the reference)
        self.watchers: Dict[str, Dict[Tuple[str, int], float]] = {}
        self._notifies: Dict[int, Dict] = {}
        self._notify_seq = 0
        self._rebuild_local_missing()
        # primary-side peering/recovery state
        self.peer_last_update: Dict[int, int] = {}
        self.missing: Dict[int, Dict[str, Tuple[int, str]]] = {}
        self._peer_pending: Set[int] = set()
        self._peer_infos: Dict[int, MOSDPGInfo] = {}
        self._getlog_pending: Optional[int] = None
        self._rewind_requested = False
        self._rewind_horizon: Optional[int] = None
        # peering-round query retry state (retry_peering): the exact
        # queries sent this round, and the last (re)send stamp
        self._peering_queries: Dict[int, MOSDPGQuery] = {}
        self._peering_sent_at = -1e9
        self._backfill_pending: Set[int] = set()
        self._self_backfill_from: Optional[int] = None
        self._recovering: Set[str] = set()
        self._recovering_since: Dict[str, float] = {}
        self._waiting_for_recovery: Dict[str, List[Callable[[], None]]] = {}

    # ---- pg splitting (OSD::split_pgs / PG::split_into) -------------------
    def stored_pg_num(self) -> int:
        """pg_num this replica's on-disk layout reflects (0 = never
        recorded); lets a restarted OSD catch up on splits it missed."""
        return stored_pg_num_of(self.osd.store, self.pgid)

    def record_pg_num(self, n: int,
                      t: Optional[Transaction] = None) -> None:
        self.known_pg_num = n
        own = t is None
        if own:
            t = Transaction()
        cid = self.ensure_meta_collection(t)
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        t.setattr(cid, meta, PG_NUM_ATTR, struct.pack("<I", n))
        if own:
            self.osd.store.queue_transaction(t)

    @staticmethod
    def _head_of(oid: str) -> str:
        """Snap clones hash (and therefore split) with their head."""
        return oid.split("\x00snap\x00", 1)[0]

    def split_children(self) -> None:
        """Split this PG's local shard data into its children after a
        pg_num increase (ceph_stable_mod keeps parent ps stable, so
        only objects whose hash lands in a child ps move).  Runs
        identically on every replica; with pgp_num unchanged the
        children map to the SAME acting set as the parent
        (raw_pg_to_pps uses pgp_num), so the split is purely local —
        a later pgp_num increase migrates children through the normal
        peering/backfill machinery.  Mirrors OSD::split_pgs +
        PG::split_into + PGLog::split_into.
        """
        pool_id, ps = self.pgid
        pool = self.osd.osdmap.pools.get(pool_id)
        if pool is None or pool.pg_num <= self.known_pg_num:
            return
        # serialize against in-flight client writes: worker threads run
        # do_op under this lock, and a write landing between our read
        # and the parent-side delete would be lost
        self.op_lock.acquire()
        try:
            self._split_children_locked(pool)
        finally:
            self.op_lock.release()

    def _split_children_locked(self, pool) -> None:
        pool_id, ps = self.pgid
        store = self.osd.store
        new_num, new_mask = pool.pg_num, pool.pg_num_mask
        from ..osdmap import ceph_stable_mod

        def target_ps(oid: str) -> int:
            return ceph_stable_mod(pool.hash_key(self._head_of(oid)),
                                   new_num, new_mask)

        # data collections: replicated "{pool}.{ps}", EC shards
        # "{pool}.{ps}s{shard}" — children keep the shard suffix
        suffixes: List[str] = []
        base = f"{pool_id}.{ps}"
        if self.backend is not None:
            prefix = base + "s"
            suffixes = [cid[len(base):] for cid in
                        store.list_collections()
                        if cid.startswith(prefix)]
        elif store.collection_exists(base):
            suffixes = [""]
        t_parent = Transaction()
        child_ts: Dict[int, Transaction] = {}
        moved_oids: Dict[int, set] = {}

        def child_t(cps: int) -> Transaction:
            if cps not in child_ts:
                child_ts[cps] = Transaction()
                moved_oids[cps] = set()
            return child_ts[cps]

        for sfx in suffixes:
            pcid = base + sfx
            if not store.collection_exists(pcid):
                continue
            for ho in store.list_objects(pcid):
                if ho.oid == PG_META_OID:
                    continue
                tps = target_ps(ho.oid)
                if tps == ps:
                    continue
                tc = child_t(tps)
                ccid = f"{pool_id}.{tps}{sfx}"
                if not store.collection_exists(ccid):
                    tc.create_collection(ccid)   # MKCOLL is idempotent
                data = store.read(pcid, ho)
                tc.touch(ccid, ho)
                if data:
                    tc.write(ccid, ho, 0, data)
                for name, val in store.getattrs(pcid, ho).items():
                    tc.setattr(ccid, ho, name, val)
                omap = store.omap_get(pcid, ho)
                if omap:
                    tc.omap_setkeys(ccid, ho, dict(omap))
                t_parent.remove(pcid, ho)
                moved_oids[tps].add(ho.oid)
        # meta: pg_log entries, snapsets, rollback stashes — split by
        # oid ownership under the NEW pg_num (log entries can name
        # deleted objects, so ownership comes from the hash, not the
        # moved set)
        pcid_meta = self.ensure_meta_collection(t_parent)
        meta = hobject_t(PG_META_OID)
        meta_omap = store.omap_get(pcid_meta, meta) \
            if store.collection_exists(pcid_meta) and \
            store.exists(pcid_meta, meta) else {}
        children: List["PG"] = []
        all_child_oids: Dict[int, set] = {}
        for e in self.pg_log.entries:
            tps = target_ps(e.oid)
            if tps != ps:
                all_child_oids.setdefault(tps, set()).add(e.oid)
        for oid in list(self.snapsets):
            tps = target_ps(oid)
            if tps != ps:
                all_child_oids.setdefault(tps, set()).add(oid)
        for tps in set(all_child_oids) | set(moved_oids):
            child = self.osd.get_or_create_pg((pool_id, tps))
            children.append(child)
            tc = child_t(tps)
            ccid_meta = child.ensure_meta_collection(tc)
            oids = all_child_oids.get(tps, set()) | moved_oids[tps]
            self.pg_log.split_into(child.pg_log, oids, t_parent,
                                   pcid_meta, tc, ccid_meta)
            # snapset + rollback omap keys follow their oid
            from .pg_log import ROLLBACK_KEY_PREFIX, SNAPSET_KEY_PREFIX
            move_keys = {}
            for k, v in meta_omap.items():
                for pfx in (SNAPSET_KEY_PREFIX, ROLLBACK_KEY_PREFIX):
                    if k.startswith(pfx) and \
                            target_ps(k[len(pfx):]) == tps:
                        move_keys[k] = v
            if move_keys:
                tc.touch(ccid_meta, meta)
                tc.omap_setkeys(ccid_meta, meta, move_keys)
                t_parent.omap_rmkeys(pcid_meta, meta,
                                     list(move_keys))
            # the child inherits the parent's trim history FIRST (its
            # objects were governed by it until this instant) so the
            # index entries built below exclude already-purged snaps
            child._adopt_purged(sorted(self.purged_snaps))
            # in-memory state follows
            child_interesting = child._interesting_snaps()
            for oid in list(self.snapsets):
                if target_ps(oid) == tps:
                    child.snapsets[oid] = self.snapsets.pop(oid)
                    self.snap_mapper.update_oid(
                        oid, [], ())
                    child.snap_mapper.update_oid(
                        oid, child.snapsets[oid], child_interesting)
            for oid in list(self.local_missing):
                if target_ps(oid) == tps:
                    child.local_missing[oid] = \
                        self.local_missing.pop(oid)
            for oid in list(self.watchers):
                if target_ps(oid) == tps:
                    child.watchers[oid] = self.watchers.pop(oid)
            child._version_alloc = max(child._version_alloc,
                                       child.pg_log.head)
            child.record_pg_num(new_num, tc)
            child.state = STATE_INITIAL
        self.record_pg_num(new_num, t_parent)
        self._version_alloc = max(self._version_alloc,
                                  self.pg_log.head)
        # children first: if we crash between transactions, objects
        # exist in both collections and the recorded parent pg_num
        # triggers a re-split that converges (moves are idempotent)
        for tps, tc in child_ts.items():
            store.queue_transaction(tc)
        store.queue_transaction(t_parent)
        self.state = STATE_INITIAL
        dlog("pg", 3,
             f"pg {self.pgid} split into "
             f"{sorted(c.pgid for c in children)} at pg_num {new_num}",
             f"osd.{self.osd.osd_id}")

    def data_high_water(self) -> int:
        """Highest object version this replica can actually SERVE —
        max of the log head and stored VERSION_ATTRs (pushed data can
        be newer than the local log after a realign/backfill).

        Cached against the store's commit counter: a refused stray
        notify retries every few seconds forever, and an O(objects)
        attr walk per retry on an idle cluster is pure waste."""
        store = self.osd.store
        cache = getattr(self, "_dhw_cache", None)
        key = (store.committed_txns, self.pg_log.head)
        if cache is not None and cache[0] == key:
            return cache[1]
        hi = self.pg_log.head
        if self.backend is not None:
            prefix = f"{self.pgid[0]}.{self.pgid[1]}s"
            cids = [c for c in store.list_collections()
                    if c.startswith(prefix)]
        else:
            cids = [f"{self.pgid[0]}.{self.pgid[1]}"]
        for cid in cids:
            if not store.collection_exists(cid):
                continue
            for ho in store.list_objects(cid):
                vb = store.getattrs(cid, ho).get(VERSION_ATTR)
                if vb:
                    hi = max(hi, struct.unpack("<Q", vb)[0])
        self._dhw_cache = (key, hi)
        return hi

    # ---- identity ---------------------------------------------------------
    def meta_cid(self) -> str:
        """Per-PG-replica meta collection (log + superblock attrs); named
        independently of the acting shard position, which changes on
        remap."""
        return f"{self.pgid[0]}.{self.pgid[1]}_meta"

    def is_primary(self) -> bool:
        return self.acting_primary == self.osd.osd_id

    def my_shard(self) -> int:
        for i, o in enumerate(self.acting):
            if o == self.osd.osd_id:
                return i
        return -1

    def acting_shards(self) -> Dict[int, int]:
        """shard index -> osd id, skipping NONE holes."""
        return {i: o for i, o in enumerate(self.acting)
                if o != CRUSH_ITEM_NONE}

    def send_to_osd(self, osd_id: int, msg: Message) -> None:
        self.osd.messenger.send_message(msg, f"osd.{osd_id}")

    def next_version(self) -> int:
        self._version_alloc = max(self._version_alloc,
                                  self.pg_log.head) + 1
        return self._version_alloc

    def ensure_meta_collection(self, t: Transaction) -> str:
        """Make sure *t* creates the meta collection if absent (spliced
        at the front so later ops in *t* can target it); returns its
        cid."""
        cid = self.meta_cid()
        if not self.osd.store.collection_exists(cid):
            pre = Transaction()
            pre.create_collection(cid)
            t.ops[0:0] = pre.ops      # mkcoll is idempotent in the store
        return cid

    def append_log(self, entry: LogEntry, t: Transaction) -> None:
        """Stage a log append into *t* (the data-write transaction)."""
        cid = self.ensure_meta_collection(t)
        if entry.version > self.pg_log.head:
            self.pg_log.append(entry, t, cid)

    def _rebuild_local_missing(self) -> None:
        """Mount-time: any logged modify whose object is absent — or
        present at an older version — is data this replica never
        received."""
        latest: Dict[str, Tuple[int, str]] = {}
        for e in self.pg_log.entries:
            latest[e.oid] = (e.version, e.op)
        if not latest:
            return
        snap = self._object_versions_snapshot()
        for oid, (v, op) in latest.items():
            if op == OP_DELETE:
                continue
            if snap.get(oid, -1) < v:
                self.local_missing[oid] = (v, op)

    def _object_versions_snapshot(self) -> Dict[str, int]:
        """One pass over this replica's collections: oid -> stored
        version (0 = pre-log object).  Batch form of _object_version so
        mount/activation stay linear, not quadratic."""
        from .pg_log import VERSION_ATTR
        store = self.osd.store
        if self.backend is not None:
            prefix = f"{self.pgid[0]}.{self.pgid[1]}s"
            cids = [cid for cid in store.list_collections()
                    if cid.startswith(prefix)]
        else:
            cids = [f"{self.pgid[0]}.{self.pgid[1]}"]
        out: Dict[str, int] = {}
        for cid in cids:
            if not store.collection_exists(cid):
                continue
            for ho in store.list_objects(cid):
                if ho.oid == PG_META_OID:
                    continue
                try:
                    v = struct.unpack(
                        "<Q", store.getattr(cid, ho, VERSION_ATTR))[0]
                except KeyError:
                    v = 0
                out[ho.oid] = max(out.get(ho.oid, -1), v)
        return out

    def _object_version(self, oid: str) -> int:
        """Stored pg_log version of this replica's copy (-1 = absent,
        0 = pre-log object)."""
        return self._object_versions_snapshot().get(oid, -1)

    def _have_version(self, oid: str, version: int) -> bool:
        return self._object_version(oid) >= version

    def _have_object(self, oid: str) -> bool:
        return self._object_version(oid) >= 0

    def data_received(self, oid: str) -> None:
        """A full copy/chunk of *oid* landed on this replica."""
        self.local_missing.pop(oid, None)

    # ---- peering (GetInfo / GetLog / GetMissing / Activate) ----------------
    def advance_map(self, osdmap) -> None:
        from ..osdmap import pg_t
        newpool = osdmap.get_pg_pool(self.pgid[0])
        snaps_changed = False
        if newpool is not None:
            snaps_changed = (newpool.snap_seq != self.pool.snap_seq or
                             newpool.removed_snaps !=
                             self.pool.removed_snaps)
            self.pool = newpool
            if self.tier is None and newpool.tier_of >= 0 and \
                    newpool.cache_mode and self.rep_backend is not None:
                from .tier import TierState
                self.tier = TierState(self)
            elif self.tier is not None and newpool.tier_of < 0:
                # overlay removed: stop intercepting, drain every
                # dirty object down, then drop the state (the agent
                # clears self.tier once nothing is owed; replicas owe
                # nothing and drop immediately)
                if self.tier.dirty or self.tier._flushing:
                    self.tier.shutting_down = True
                else:
                    self.tier = None
        up, upp, acting, actp = osdmap.pg_to_up_acting_osds(
            pg_t(self.pgid[0], self.pgid[1]))
        changed = (acting != self.acting or actp != self.acting_primary)
        self.up, self.up_primary = up, upp
        self.acting, self.acting_primary = acting, actp
        if snaps_changed and not changed:
            # AFTER the acting update: trim must fan from the new
            # epoch's primary to the new acting set.  If the acting set
            # itself changed in this epoch, defer to the peering we are
            # about to start — _activate re-drives the trim once peer
            # snapsets/purged knowledge has been merged (a freshly
            # promoted primary trimming now could record purged off
            # near-empty knowledge)
            self._maybe_trim_snaps()
        if not (changed or self.state == STATE_INITIAL):
            return
        self.last_epoch_started = osdmap.epoch
        if not self.is_primary():
            # replicas serve sub-ops; the primary drives consistency
            self.state = STATE_ACTIVE
            return
        self.start_peering(osdmap.epoch)

    def start_peering(self, epoch: int) -> None:
        self.state = STATE_PEERING
        dlog("pg", 5, f"pg {self.pgid} -> peering, acting {self.acting}",
             f"osd.{self.osd.osd_id}")
        self.peering_epoch = epoch
        self._peer_infos.clear()
        self._getlog_pending = None
        self._rewind_requested = False
        self._backfill_pending.clear()
        self._self_backfill_from = None
        self.missing = {}
        self._recovering.clear()
        self._recovering_since.clear()
        self._waiting_for_recovery.clear()
        if self.backend is not None:
            self.backend.on_change()
        self._peer_pending = set(self.acting_shards())
        self._peering_queries = {}
        self._peering_sent_at = getattr(self.osd, "now", 0.0)
        self._rewind_horizon = None
        for shard, osd in self.acting_shards().items():
            self._send_peering_query(shard, MOSDPGQuery(
                pgid=self.pgid, shard=shard, epoch=epoch))

    def _send_peering_query(self, shard: int, msg: MOSDPGQuery) -> None:
        """Send one peering-round query, remembering it so the tick can
        resend the EXACT message (rewind_to/log_since included) while
        the shard stays pending — peering rides the same droppable
        fabric as data, and a lost query must not wedge the round."""
        self._peering_queries[shard] = msg
        osd = self.acting_shards().get(shard)
        if osd is not None:
            self.send_to_osd(osd, msg)

    def retry_peering(self) -> None:
        """Tick-driven resend of this peering round's outstanding
        queries (rate-limited).  Replies are idempotent: a replica
        re-answers info, an already-rewound shard's rewind is a no-op
        (pg_log.head <= to), a duplicate GetLog reply is dropped by
        the _getlog_pending check in handle_pg_info, and a late
        pre-rewind duplicate is rejected by the horizon gate there."""
        if not self.is_primary() or self.state != STATE_PEERING:
            return
        pending = set(self._peer_pending) \
            | ({self._getlog_pending}
               if self._getlog_pending is not None else set())
        if not pending:
            return
        now = self.osd.now
        if now - self._peering_sent_at < 2.0:
            return
        self._peering_sent_at = now
        acting = self.acting_shards()
        for shard in sorted(pending):
            msg = self._peering_queries.get(shard)
            if msg is not None and shard in acting:
                self.send_to_osd(acting[shard], msg)

    def handle_pg_query(self, msg: MOSDPGQuery) -> None:
        """Any replica (incl. the primary itself): report state; attach
        the log suffix when asked (GetLog)."""
        if msg.rewind_to >= 0 and msg.shard >= 0 and \
                msg.epoch >= self.last_epoch_started:
            # the epoch gate drops destructive rewinds from a superseded
            # primary (handle_pg_info filters its replies the same way)
            self._rewind_divergent(msg.rewind_to, msg.shard)
        entries: List[bytes] = []
        if msg.log_since >= 0:
            suffix = self.pg_log.entries_after(msg.log_since)
            if suffix:
                entries = [e.encode() for e in suffix]
        self.osd.messenger.send_message(MOSDPGInfo(
            pgid=self.pgid, shard=msg.shard, epoch=msg.epoch,
            last_update=self.pg_log.head, log_tail=self.pg_log.tail,
            log_entries=entries,
            missing_oids=[(o, v) for o, (v, _op)
                          in self.local_missing.items()],
            snapsets=self._encoded_snapsets(),
            purged_snaps=sorted(self.purged_snaps),
            held_shards=self.held_shards()), msg.src)

    def held_shards(self) -> List[int]:
        """EC shard positions whose collection holds data on THIS osd
        (spg_t identity stand-in: the data, not the log, names the
        shard)."""
        if self.backend is None:
            return []
        store = self.osd.store
        out = []
        for shard in range(self.pool.size):
            cid = f"{self.pgid[0]}.{self.pgid[1]}s{shard}"
            if store.collection_exists(cid) and store.list_objects(cid):
                out.append(shard)
        return out

    def _choose_acting(self) -> bool:
        """EC choose_acting (PG::choose_acting + queue_want_pg_temp):
        when CRUSH's remap put surviving shard data at the wrong
        positions, ask the mon to pin pg_temp so every data-bearing OSD
        serves the shard it actually holds; freed positions go to the
        remaining acting members, which then backfill.  Returns True if
        a pin was requested (activation waits for the new epoch)."""
        if self.backend is None:
            return False
        # shard -> ALL acting osds holding a copy (stale realign/split
        # leftovers mean several members can hold the same shard; a
        # first-writer-wins map here oscillated pg_temp forever)
        holders: Dict[int, Set[int]] = {}
        for slot, info in sorted(self._peer_infos.items()):
            osd = self.acting_shards().get(slot)
            if osd is None:
                continue
            for h in info.held_shards:
                holders.setdefault(h, set()).add(osd)
        acting_osds = [o for o in self.acting if o != CRUSH_ITEM_NONE]

        def placed(assignment: List[int]) -> int:
            return sum(1 for s, o in enumerate(assignment)
                       if o != CRUSH_ITEM_NONE
                       and o in holders.get(s, ()))

        current_good = placed(self.acting)
        # deterministic proposal: keep correctly-placed members, then
        # give each uncovered slot the lowest-id unused holder
        used: Set[int] = set()
        temp: List[int] = [CRUSH_ITEM_NONE] * len(self.acting)
        for s, o in enumerate(self.acting):
            if o != CRUSH_ITEM_NONE and o in holders.get(s, ()):
                temp[s] = o
                used.add(o)
        for s in range(len(temp)):
            if temp[s] != CRUSH_ITEM_NONE:
                continue
            cands = sorted(o for o in holders.get(s, ())
                           if o in acting_osds and o not in used)
            if cands:
                temp[s] = cands[0]
                used.add(cands[0])
        spare = [o for o in acting_osds if o not in used]
        spare += [o for o in self.up
                  if o != CRUSH_ITEM_NONE and o not in used
                  and o not in spare and o not in acting_osds]
        for s in range(len(temp)):
            if temp[s] == CRUSH_ITEM_NONE and spare:
                temp[s] = spare.pop(0)
        # pin only when the permutation STRICTLY beats the current
        # placement — equal-coverage alternatives would flip-flop, and
        # slots no permutation can cover belong to recovery/backfill
        if temp == self.acting or placed(temp) <= current_good:
            return False
        dlog("pg", 3, f"pg {self.pgid} choose_acting: data holders "
             f"{holders} vs acting {self.acting} -> pg_temp {temp}",
             f"osd.{self.osd.osd_id}")
        self._request_pg_temp(temp)
        return True

    def _request_pg_temp(self, temp: List[int]) -> None:
        """Send (and keep re-sending from the tick until an epoch
        carrying it arrives — the request can be dropped or hit a mon
        mid-election) the pg_temp pin/clear."""
        from ..msg.messages import MOSDPGTemp
        self._pending_pg_temp = list(temp)
        for mon in self.osd.mon_names:
            self.osd.messenger.send_message(MOSDPGTemp(
                pgid=self.pgid, epoch=self.last_epoch_started,
                temp=list(temp)), mon)

    def retry_pending_pg_temp(self) -> None:
        want = getattr(self, "_pending_pg_temp", None)
        if want is None:
            return
        if not self.is_primary():
            # demoted: a pin chosen under our old map must not override
            # the new primary's placement
            self._pending_pg_temp = None
            return
        from ..osdmap import pg_t
        cur = self.osd.osdmap.pg_temp.get(
            pg_t(self.pgid[0], self.pgid[1]), [])
        if list(cur) == want or (not want and not cur):
            self._pending_pg_temp = None
            return
        self._request_pg_temp(want)

    def maybe_realign(self) -> None:
        """Clean + pinned: move each shard to its CRUSH-up position
        (decode + push to the up member), then clear the pin — the
        reference's backfill-to-up that lets pg_temp be temporary."""
        if not self.is_primary():
            return
        if self.state != STATE_ACTIVE or self._has_missing() \
                or self._backfill_pending:
            return
        from ..osdmap import pg_t
        if pg_t(self.pgid[0], self.pgid[1]) not in self.osd.osdmap.pg_temp:
            return
        if getattr(self, "_realigning", False):
            # an ack/reply chain lost mid-flight must not wedge the
            # pin forever: reset after a grace and retry
            if self.osd.now - getattr(self, "_realign_started",
                                      self.osd.now) > 15.0:
                self._realigning = False
                self._rep_realign_ack = None
            return
        if self.backend is None:
            self._realign_replicated()
            return
        # quiesce: no in-flight writes may interleave with the shard
        # copies (clients see EAGAIN while realigning and resend) —
        # including pipelined encodes still queued in the dispatcher
        if self.backend._oid_queues or self.backend.inflight_writes \
                or self.backend.pipeline_inflight:
            return
        moves = [s for s in range(len(self.up))
                 if s < len(self.acting)
                 and self.up[s] != CRUSH_ITEM_NONE
                 and self.up[s] != self.acting[s]]
        objects = sorted(self._authoritative_objects())
        if not moves or not objects:
            self._request_pg_temp([])
            return
        self._realigning = True
        self._realign_started = self.osd.now
        start_head = self.pg_log.head
        dlog("pg", 3, f"pg {self.pgid} realign to up {self.up} "
             f"(moves {moves}, {len(objects)} objects)",
             f"osd.{self.osd.osd_id}")
        state = {"left": len(objects), "failed": False}

        def done_obj(ok: bool) -> None:
            state["left"] -= 1
            state["failed"] |= not ok
            if state["left"] == 0:
                self._realigning = False
                if not state["failed"] and \
                        self.pg_log.head == start_head:
                    # nothing wrote while the copies were in flight:
                    # the pushed shards are current -> drop the pin
                    self._request_pg_temp([])   # next epoch: acting = up

        from ..msg.messages import MOSDECSubOpWrite
        be = self.backend

        def start_obj(oid: str) -> None:
            def on_chunks(res, chunks, size, attrs):
                if res != 0:
                    done_obj(False)
                    return
                rec = be.recover_object(oid, set(moves), chunks, size)
                # stamp the object's version on the pushed shards —
                # receivers compare store VERSION_ATTR against their
                # log to build local_missing, and a mismatch leaves
                # the object "missing" forever on the new members
                ver = 0
                mine = self.my_shard()
                if mine >= 0:
                    scid = be.shard_cid(mine)
                    sho = be.shard_oid(oid, mine)
                    store = self.osd.store
                    if store.collection_exists(scid) and \
                            store.exists(scid, sho):
                        vb = store.getattrs(scid, sho).get(VERSION_ATTR)
                        if vb:
                            ver = struct.unpack("<Q", vb)[0]
                # acked pushes: done_obj only fires once every target
                # APPLIED its shard — clearing the pin earlier lets the
                # next peering round see the new members as missing and
                # wedge recovery on a stale missing-map
                be.push_chunks(
                    oid, {s_: rec[s_] for s_ in moves}, size,
                    lambda: done_obj(True), version=ver, xattrs=attrs,
                    targets={s_: self.up[s_] for s_ in moves})
            be.read_chunks(oid, on_chunks)

        for oid in objects:
            start_obj(oid)

    def _realign_replicated(self) -> None:
        """Full-copy analog of the EC realign for replicated pools:
        push every object (data + user attrs + omap + snapset +
        version) to the up members that are not yet acting, then clear
        the pin (backfill-to-up).  Needed when a placement change
        (pgp_num growth, crush edit) moves a PG to OSDs that never
        held its data — the mon primes pg_temp to the old acting and
        this migrates the copies before the flip.

        Same invariants as the EC realign: concurrent client writes
        are excluded (op_lock — tick runs without it), every push is
        ACKED before the pin clears, and a log-head change while the
        copies were in flight aborts the clear so the next tick
        re-runs with current data."""
        to_add = [o for o in self.up
                  if o != CRUSH_ITEM_NONE and o not in self.acting]
        store = self.osd.store
        be = self.rep_backend
        cid = be.cid()
        oids = [ho.oid for ho in store.list_objects(cid)] \
            if store.collection_exists(cid) else []
        if not to_add or not oids:
            self._request_pg_temp([])
            return
        if not self.op_lock.acquire(blocking=False):
            return                       # a write holds the PG; retry
        try:
            self._realigning = True
            self._realign_started = self.osd.now
            start_head = self.pg_log.head
            pending: Set[int] = set()
            state = {"armed": False}

            def on_ack(tid: int) -> None:
                pending.discard(tid)
                if state["armed"] and not pending:
                    self._realigning = False
                    self._rep_realign_ack = None
                    if self.pg_log.head == start_head:
                        self._request_pg_temp([])
            self._rep_realign_ack = on_ack
            from ..msg.messages import MOSDECSubOpWrite
            for oid in sorted(oids):
                exists, data, uattrs, omap = be.object_state(oid)
                ho = hobject_t(oid)
                vb = store.getattrs(cid, ho).get(VERSION_ATTR)
                ver = struct.unpack("<Q", vb)[0] if vb else 0
                ss = self.snapsets.get(oid)
                ssu = (oid, encode_snapset(ss)) if ss else None
                for tgt in to_add:
                    tid = self.osd.next_pull_tid()
                    pending.add(tid)
                    self.send_to_osd(tgt, MOSDECSubOpWrite(
                        tid=tid, pgid=self.pgid, shard=-1, oid=oid,
                        chunk=data, offset=0, partial=False,
                        at_version=len(data), version=ver,
                        is_push=True, xattrs=uattrs or None,
                        omap=omap or None, snapset_update=ssu))
            dlog("pg", 3, f"pg {self.pgid} replicated realign: pushed "
                 f"{len(oids)} objects to {to_add}",
                 f"osd.{self.osd.osd_id}")
            state["armed"] = True
            if not pending:              # acks raced the sends
                on_ack(-1)
        finally:
            self.op_lock.release()

    def handle_pg_info(self, msg: MOSDPGInfo) -> None:
        if not self.is_primary():
            self._apply_activation(msg)
            return
        if msg.epoch != getattr(self, "peering_epoch", msg.epoch):
            return  # reply from a superseded peering round
        if self._getlog_pending is not None and \
                msg.shard == self._getlog_pending:
            if msg.log_entries:
                self._merge_auth_log(msg)
            else:
                # authority's log is trimmed past our head: our log can't
                # catch up — adopt the authoritative head and backfill
                # ourselves from the authority's listing
                self._adopt_head_and_self_backfill(msg)
            return
        if self.state != STATE_PEERING:
            return
        if msg.shard not in self._peer_pending:
            # duplicate info (the tick's query resend raced the
            # original reply): refresh the record but never re-enter
            # _peering_all_infos — the round already advanced past
            # this shard (a GetLog may be outstanding)
            self._peer_infos[msg.shard] = msg
            return
        if self._rewind_horizon is not None and \
                msg.last_update > self._rewind_horizon:
            # the shard is being asked to rewind to the horizon, so the
            # reply that settles it must show last_update <= horizon; a
            # head beyond it is a late duplicate of the PRE-rewind info
            # (the retry resend raced the original reply) — consuming
            # it would activate on entries the shard just rolled back
            return
        self._peer_infos[msg.shard] = msg
        self._peer_pending.discard(msg.shard)
        if not self._peer_pending:
            self._peering_all_infos()

    def _rewind_divergent(self, to: int, shard: int) -> None:
        """Rewind this replica's log past *to* and roll every touched
        object back to its stashed pre-write state (the
        rewind_divergent_log + rollback step of src/osd/PGLog.cc
        merge_log, using the append-only/rollback design of
        doc/dev/osd_internals/erasure_coding/ecbackend.rst:1-27).
        *shard* is the acting position the requesting primary holds us
        at.  Objects whose stash can't reach *to* are only destroyed if
        their on-disk version actually sits past the horizon; otherwise
        the (valid, old) local chunk is kept and at most re-reported
        missing so recovery can top it up."""
        if self.backend is None or self.pg_log.head <= to:
            return
        from .pg_log import clear_rollback, load_rollback
        store = self.osd.store
        t = Transaction()
        cid = self.meta_cid()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        dropped = self.pg_log.rewind_to(to, t, cid)
        dlog("pg", 3,
             f"pg {self.pgid} rewinding {len(dropped)} divergent "
             f"entries to v{to}", f"osd.{self.osd.osd_id}")
        scid = self.backend.shard_cid(shard)
        handled: Set[str] = set()
        for e in sorted(dropped, key=lambda e: e.version, reverse=True):
            if e.oid in handled:
                continue
            handled.add(e.oid)
            ho = hobject_t(e.oid, shard)
            have = (store.collection_exists(scid)
                    and store.exists(scid, ho))
            cur_v = 0
            if have:
                try:
                    cur_v = struct.unpack(
                        "<Q", store.getattr(scid, ho, VERSION_ATTR))[0]
                except KeyError:
                    pass
            stash = load_rollback(store, cid, e.oid)
            restorable = (stash is not None and stash[0] == e.version)
            if restorable and stash[1]:
                # the stash's own version must sit at/below the horizon,
                # else it is the residue of an EARLIER divergent write
                # and restoring it would still leave torn state
                pv = stash[3].get(VERSION_ATTR)
                if pv is not None and \
                        struct.unpack("<Q", pv)[0] > to:
                    restorable = False
            if restorable:
                _v, prev_exists, data, attrs = stash
                if prev_exists:
                    if not store.collection_exists(scid):
                        t.create_collection(scid)
                    t.touch(scid, ho)
                    t.truncate(scid, ho, 0)
                    if data:
                        t.write(scid, ho, 0, data)
                    cur = store.getattrs(scid, ho) if have else {}
                    for k in cur:
                        if k not in attrs:
                            t.rmattr(scid, ho, k)
                    for k, v in attrs.items():
                        t.setattr(scid, ho, k, v)
                elif have:
                    t.remove(scid, ho)
                clear_rollback(t, cid, e.oid)
                self.local_missing.pop(e.oid, None)
            elif have and cur_v <= to:
                # the divergent entry was merged into our log without
                # its data ever landing here (activation): the local
                # chunk predates the horizon and stays valid — keep it
                if stash is not None:
                    clear_rollback(t, cid, e.oid)
                if cur_v < to:
                    self.local_missing[e.oid] = (to, OP_MODIFY)
                else:
                    self.local_missing.pop(e.oid, None)
            else:
                # torn local write with no usable stash: drop the copy
                # and report it missing so recovery rebuilds by decode
                dlog("pg", 1,
                     f"pg {self.pgid} no rollback stash for {e.oid}"
                     f"@v{e.version}; marking missing",
                     f"osd.{self.osd.osd_id}")
                if have:
                    t.remove(scid, ho)
                if stash is not None:
                    clear_rollback(t, cid, e.oid)
                self.local_missing[e.oid] = (to, OP_MODIFY)
        store.queue_transaction(t)

    def _maybe_rewind_divergent(self) -> bool:
        """EC interrupted-write consistency: a log entry is recoverable
        only if at least k shards hold its data, so the roll-forward
        horizon is the k-th highest last_update among data-bearing
        acting shards.  Entries past the horizon were partial fan-outs
        the client never saw acked — tell every shard carrying them to
        roll back before the logs merge.  Returns True when rewind
        queries went out (peering resumes on their fresh infos)."""
        if self.backend is None or self._rewind_requested:
            return False
        # only LOG-bearing data shards vote: a backfilled/pushed shard
        # holds chunks but no history (last_update 0, like a reference
        # backfill target) — counting it would drag the horizon to 0
        # and destroy healthy peers' state
        lus = sorted((info.last_update
                      for shard, info in self._peer_infos.items()
                      if shard in info.held_shards
                      and info.last_update > 0),
                     reverse=True)
        k = self.backend.k
        if len(lus) < k:
            # fewer than k data-bearing shards: nothing is decodable
            # at ANY version — rolling back could only destroy state
            return False
        horizon = lus[k - 1]
        divergent = [shard for shard, info in self._peer_infos.items()
                     if info.last_update > horizon]
        if not divergent:
            return False
        self._rewind_requested = True
        self._rewind_horizon = horizon
        for shard in divergent:
            self._peer_pending.add(shard)
            self._send_peering_query(shard, MOSDPGQuery(
                pgid=self.pgid, shard=shard, epoch=self.peering_epoch,
                rewind_to=horizon))
        return True

    def _peering_all_infos(self) -> None:
        if self._choose_acting():
            # a pg_temp pin is on its way; the next epoch re-peers with
            # the data-aligned acting set
            return
        if self._maybe_rewind_divergent():
            # divergent shards report fresh infos after rewinding
            return
        infos = self._peer_infos
        auth_shard, auth_lu = None, self.pg_log.head
        for shard, info in infos.items():
            if info.last_update > auth_lu:
                auth_shard, auth_lu = shard, info.last_update
        if auth_shard is not None:
            # GetLog: pull the authoritative suffix before activating
            self._getlog_pending = auth_shard
            self._send_peering_query(auth_shard, MOSDPGQuery(
                pgid=self.pgid, shard=auth_shard,
                epoch=self.last_epoch_started,
                log_since=self.pg_log.head))
            return
        self._activate()

    def _merge_auth_log(self, msg: MOSDPGInfo) -> None:
        entries = [LogEntry.decode(b) for b in msg.log_entries]
        my_old_head = self.pg_log.head
        t = Transaction()
        cid = self.meta_cid()
        if not self.osd.store.collection_exists(cid):
            t.create_collection(cid)
        self.pg_log.merge_authoritative(entries, t, cid)
        self.osd.store.queue_transaction(t)
        self._version_alloc = max(self._version_alloc, self.pg_log.head)
        # everything merged is missing on our own shard
        mine = self.missing.setdefault(self.my_shard(), {})
        for e in entries:
            if e.version > my_old_head:
                mine[e.oid] = (e.version, e.op)
                if e.op != OP_DELETE:
                    self.local_missing[e.oid] = (e.version, e.op)
        self._getlog_pending = None
        self._activate()

    def _adopt_head_and_self_backfill(self, msg: MOSDPGInfo) -> None:
        """Primary beyond the authority's log tail: no entry replay is
        possible.  Adopt the authoritative head (so versions stay
        monotonic) and diff our store against the authority's listing."""
        import struct as _s
        from .pg_log import LAST_UPDATE_ATTR, LOG_TAIL_ATTR, PG_META_OID
        self.pg_log.head = max(self.pg_log.head, msg.last_update)
        self.pg_log.tail = self.pg_log.head
        self.pg_log.entries = []
        t = Transaction()
        cid = self.meta_cid()
        if not self.osd.store.collection_exists(cid):
            t.create_collection(cid)
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        t.setattr(cid, meta, LAST_UPDATE_ATTR,
                  _s.pack("<Q", self.pg_log.head))
        t.setattr(cid, meta, LOG_TAIL_ATTR, _s.pack("<Q", self.pg_log.tail))
        self.osd.store.queue_transaction(t)
        self._version_alloc = max(self._version_alloc, self.pg_log.head)
        auth = self._getlog_pending
        self._getlog_pending = None
        self._self_backfill_from = auth
        self.send_to_osd(self.acting_shards()[auth], MOSDPGScan(
            pgid=self.pgid, shard=auth, epoch=self.peering_epoch))
        self._activate()

    def _activate(self) -> None:
        """GetMissing + Activate: compute per-shard deltas from the
        (now authoritative) log plus each replica's own reported missing
        set; ship peers the suffix they lack."""
        my_shard = self.my_shard()
        for info in self._peer_infos.values():
            self.merge_snapsets(info.snapsets)
            self._adopt_purged(info.purged_snaps)
        for oid, (v, op) in self.local_missing.items():
            self.missing.setdefault(my_shard, {}).setdefault(oid, (v, op))
        for shard, info in self._peer_infos.items():
            self.peer_last_update[shard] = info.last_update
            if shard == my_shard:
                continue
            if self.backend is not None and \
                    shard not in info.held_shards and \
                    self.pg_log.head > 0:
                # the osd's log may be current (it held ANOTHER shard of
                # this pg before the remap) but it has no data for THIS
                # position: only a listing diff finds the debt
                self._backfill_pending.add(shard)
                self.send_to_osd(self.acting_shards()[shard], MOSDPGScan(
                    pgid=self.pgid, shard=shard,
                    epoch=self.peering_epoch))
                continue
            delta = self.pg_log.missing_after(info.last_update)
            if delta is None:
                # peer is beyond the log tail: backfill via listing diff
                self._backfill_pending.add(shard)
                self.send_to_osd(self.acting_shards()[shard], MOSDPGScan(
                    pgid=self.pgid, shard=shard,
                    epoch=self.peering_epoch))
            elif delta:
                self.missing[shard] = dict(delta)
            # plus whatever the replica itself knows it never received
            for oid, v in info.missing_oids:
                self.missing.setdefault(shard, {}).setdefault(
                    oid, (v, OP_MODIFY))
            # activation: ship the log suffix the peer lacks
            suffix = self.pg_log.entries_after(info.last_update) or []
            self.send_to_osd(self.acting_shards()[shard], MOSDPGInfo(
                pgid=self.pgid, shard=shard,
                epoch=self.peering_epoch,
                last_update=self.pg_log.head,
                log_tail=self.pg_log.tail,
                log_entries=[e.encode() for e in suffix],
                snapsets=self._encoded_snapsets(),
                purged_snaps=sorted(self.purged_snaps)))
        self.state = STATE_ACTIVE_RECOVERING if self._has_missing() \
            else STATE_ACTIVE
        if self.state == STATE_ACTIVE_RECOVERING or self._backfill_pending:
            self.osd.request_recovery(self)
        # a predecessor may have died between the snap-removal epoch and
        # its trim pass: removed_snaps - (unioned) purged_snaps is the
        # outstanding debt, and we are now the one who owes it
        self._maybe_trim_snaps()

    def send_backfill_complete(self, shard: int) -> None:
        """Primary: this shard now holds every object we tracked —
        ship our log wholesale so its info stops reading as
        missing-everything (the reference's last_backfill == MAX info
        update at backfill completion)."""
        osd = self.acting_shards().get(shard)
        if osd is None or osd == self.osd.osd_id:
            return
        self.send_to_osd(osd, MOSDPGInfo(
            pgid=self.pgid, shard=shard,
            epoch=self.last_epoch_started,
            last_update=self.pg_log.head, log_tail=self.pg_log.tail,
            log_entries=[e.encode() for e in self.pg_log.entries],
            snapsets=self._encoded_snapsets(),
            purged_snaps=sorted(self.purged_snaps), adopt_log=True))

    def _adopt_full_log(self, msg: MOSDPGInfo) -> None:
        """Backfill target: adopt the primary's log window (entries +
        head + tail) — our data is complete, our history was not."""
        from .pg_log import LAST_UPDATE_ATTR, LOG_TAIL_ATTR
        self.merge_snapsets(msg.snapsets)
        t = Transaction()
        cid = self.ensure_meta_collection(t)
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        entries = sorted((LogEntry.decode(b) for b in msg.log_entries),
                         key=lambda e: e.version)
        for e in entries:
            t.omap_setkeys(cid, meta,
                           {PGLog._key(e.version): e.encode()})
        t.setattr(cid, meta, LAST_UPDATE_ATTR,
                  struct.pack("<Q", msg.last_update))
        t.setattr(cid, meta, LOG_TAIL_ATTR,
                  struct.pack("<Q", msg.log_tail))
        self.osd.store.queue_transaction(t)
        self.pg_log.entries = entries
        self.pg_log.head = max(self.pg_log.head, msg.last_update)
        self.pg_log.tail = max(self.pg_log.tail, msg.log_tail)
        self._version_alloc = max(self._version_alloc, self.pg_log.head)
        dlog("pg", 4, f"pg {self.pgid} adopted log to "
             f"v{self.pg_log.head} (backfill complete)",
             f"osd.{self.osd.osd_id}")

    def _apply_activation(self, msg: MOSDPGInfo) -> None:
        """Replica side: adopt the authoritative log suffix.  Modify
        entries whose data has not arrived are recorded in local_missing
        (the head advances, the data debt does not vanish — pg_missing_t);
        delete entries apply immediately (reference merge_log)."""
        self._adopt_purged(msg.purged_snaps)
        if msg.adopt_log:
            self._adopt_full_log(msg)
            return
        self.merge_snapsets(msg.snapsets)
        entries = [LogEntry.decode(b) for b in msg.log_entries]
        if not entries:
            return
        my_old_head = self.pg_log.head
        t = Transaction()
        cid = self.meta_cid()
        if not self.osd.store.collection_exists(cid):
            t.create_collection(cid)
        self.pg_log.merge_authoritative(entries, t, cid)
        latest: Dict[str, Tuple[int, str]] = {}
        for e in entries:
            if e.version > my_old_head:
                latest[e.oid] = (e.version, e.op)
        snap = self._object_versions_snapshot() if latest else {}
        for oid, (v, op) in latest.items():
            if op == OP_DELETE:
                self.local_missing.pop(oid, None)
                self._stage_local_delete(oid, t)
            elif snap.get(oid, -1) < v:
                # absent OR present at an older version: data debt
                self.local_missing[oid] = (v, op)
        self.osd.store.queue_transaction(t)

    def _stage_local_delete(self, oid: str, t: Transaction) -> None:
        store = self.osd.store
        if self.backend is not None:
            prefix = f"{self.pgid[0]}.{self.pgid[1]}s"
            for cid in store.list_collections():
                if cid.startswith(prefix):
                    for ho in store.list_objects(cid):
                        if ho.oid == oid:
                            t.remove(cid, ho)
        else:
            cid = f"{self.pgid[0]}.{self.pgid[1]}"
            if store.collection_exists(cid) and \
                    store.exists(cid, hobject_t(oid)):
                t.remove(cid, hobject_t(oid))

    def handle_pg_scan(self, msg: MOSDPGScan) -> None:
        """Backfill scan: list (oid, version) on this replica's shard —
        the version attr lets the primary spot present-but-stale copies."""
        from .pg_log import VERSION_ATTR
        store = self.osd.store
        objects: List[Tuple[str, int]] = []
        cid = self._data_cid()
        if cid and store.collection_exists(cid):
            for ho in store.list_objects(cid):
                if ho.oid == PG_META_OID:
                    continue
                try:
                    v = struct.unpack(
                        "<Q", store.getattr(cid, ho, VERSION_ATTR))[0]
                except KeyError:
                    v = 0
                objects.append((ho.oid, v))
        self.osd.messenger.send_message(MOSDPGScanReply(
            pgid=self.pgid, shard=msg.shard, epoch=msg.epoch,
            objects=objects), msg.src)

    def _data_cid(self) -> Optional[str]:
        if self.backend is not None:
            s = self.my_shard()
            return self.backend.shard_cid(s) if s >= 0 else None
        return self.rep_backend.cid()

    def handle_pg_scan_reply(self, msg: MOSDPGScanReply) -> None:
        if not self.is_primary():
            return
        if msg.epoch != getattr(self, "peering_epoch", msg.epoch):
            return  # stale round
        if msg.shard == self._self_backfill_from:
            # our own backfill: whatever the authority lists at a newer
            # version than our copy is missing on us; our extras were
            # deleted while we were out
            self._self_backfill_from = None
            my = self.my_shard()
            auth_objects = {o: v for o, v in msg.objects}
            for oid, v in auth_objects.items():
                if not self._have_version(oid, v):
                    vv = max(v, 1)
                    self.local_missing[oid] = (vv, OP_MODIFY)
                    self.missing.setdefault(my, {}).setdefault(
                        oid, (vv, OP_MODIFY))
            mine = self._authoritative_objects()
            t = Transaction()
            for oid in set(mine) - set(auth_objects):
                self._stage_local_delete(oid, t)
            if not t.empty():
                self.osd.store.queue_transaction(t)
            if self._has_missing():
                self.state = STATE_ACTIVE_RECOVERING
                self.osd.request_recovery(self)
            return
        self._backfill_pending.discard(msg.shard)
        peer_objects = {o: v for o, v in msg.objects}
        auth = self._authoritative_objects()
        delta: Dict[str, Tuple[int, str]] = {}
        for oid, version in auth.items():
            # absent OR present at an older version than the authority
            if peer_objects.get(oid, -1) < version:
                delta[oid] = (max(version, 1), OP_MODIFY)
        for oid in set(peer_objects) - set(auth):
            delta[oid] = (self.pg_log.head, OP_DELETE)
        if delta:
            self.missing.setdefault(msg.shard, {}).update(delta)
            self.state = STATE_ACTIVE_RECOVERING
            self.osd.request_recovery(self)
        elif not self._has_missing() and not self._backfill_pending:
            self.state = STATE_ACTIVE

    def _authoritative_objects(self) -> Dict[str, int]:
        """oid -> version for every live object (primary's own store is
        authoritative once self-recovery has drained)."""
        from .pg_log import VERSION_ATTR
        store = self.osd.store
        out: Dict[str, int] = {}
        cid = self._data_cid()
        if cid and store.collection_exists(cid):
            for ho in store.list_objects(cid):
                if ho.oid == PG_META_OID:
                    continue
                try:
                    v = struct.unpack(
                        "<Q", store.getattr(cid, ho, VERSION_ATTR))[0]
                except KeyError:
                    v = 0
                out[ho.oid] = v
        # objects newer than the store view (log wins)
        for e in self.pg_log.entries:
            if e.op == OP_DELETE:
                out.pop(e.oid, None)
            else:
                out[e.oid] = max(out.get(e.oid, 0), e.version)
        return out

    # ---- scrub (PG.cc scrub path + ECUtil HashInfo, scrub-lite) ------------
    def start_scrub(self, deep: bool = False) -> bool:
        """Primary: collect scrub maps from every acting shard; compare
        when all arrive.  Background consistency checking — no client
        read involved (ScrubStore/PG scrub role).  Shallow scrubs
        compare metadata only (sizes + attr/omap digests, no object
        data is read); deep scrubs additionally checksum every byte —
        the reference's scrub vs deep-scrub split (PG::Scrubber::deep,
        src/osd/PG.cc chunky_scrub).  Returns whether a scrub round
        actually started (a peering/non-primary PG declines)."""
        if not self.is_primary() or self.state not in (
                STATE_ACTIVE, STATE_ACTIVE_RECOVERING):
            return False
        self.last_scrub_stamp = self.osd.now
        if deep:
            self.last_deep_scrub_stamp = self.osd.now
        dlog("scrub", 5,
             f"pg {self.pgid} {'deep-' if deep else ''}scrub start",
             f"osd.{self.osd.osd_id}")
        self._scrub_maps: Dict[int, MOSDRepScrubMap] = {}
        self._scrub_pending = set(self.acting_shards())
        self._scrub_deep = deep
        for shard, osd in self.acting_shards().items():
            self.send_to_osd(osd, MOSDRepScrub(
                pgid=self.pgid, shard=shard,
                epoch=self.last_epoch_started, deep=deep))
        return True

    def handle_rep_scrub(self, msg: MOSDRepScrub) -> None:
        """Replica: build this shard's scrub map.  Always: stored size
        plus attr/omap digests (metadata is cheap).  Deep only: read
        the data and checksum it, verifying against HashInfo
        (handle_sub_read's check, proactively).  Shallow still catches
        a shard whose stored size disagrees with its HashInfo total."""
        from ..utils.crc32c import crc32c
        from .ec_backend import DIGEST_ATTR, HINFO_ATTR
        store = self.osd.store
        objects: List[tuple] = []
        if self.backend is not None:
            s = self.my_shard()
            cids = [self.backend.shard_cid(s)] if s >= 0 else []
        else:
            cids = [f"{self.pgid[0]}.{self.pgid[1]}"]
        for cid in cids:
            if not store.collection_exists(cid):
                continue
            for ho in store.list_objects(cid):
                if ho.oid == PG_META_OID:
                    continue
                attrs = store.getattrs(cid, ho)
                # pack_kv's length-prefixed framing (values are
                # struct-packed binary, so separator framing would let
                # different k/v sets hash identically).  Integrity
                # metadata is excluded: per-shard hinfo differs by
                # construction, and the recorded data digest can
                # legitimately exist on a recovery-pushed copy while
                # its peers (post-partial-write) have none
                attrs_dg = crc32c(pack_kv(dict(
                    (k, v) for k, v in sorted(attrs.items())
                    if k not in (HINFO_ATTR, DIGEST_ATTR))))
                omap_dg = crc32c(pack_kv(dict(
                    sorted(store.omap_get(cid, ho).items()))))
                hv = attrs.get(HINFO_ATTR) \
                    if self.backend is not None else None
                validated = False
                if msg.deep:
                    data = store.read(cid, ho)
                    size = len(data)
                    digest = crc32c(data)
                    ok = True
                    if hv is not None:
                        total, expect = struct.unpack("<QI", hv)
                        ok = not (total == size and digest != expect)
                        validated = ok and total == size
                    elif self.backend is None:
                        # replicated: verify against the write-time
                        # recorded digest (object_info data_digest) —
                        # a self-inconsistent copy is known-bad on its
                        # own and gets no vote in _scrub_compare, even
                        # if identical rot hit a majority of copies
                        rec = attrs.get(DIGEST_ATTR)
                        if rec is not None and len(rec) == 4:
                            ok = struct.unpack("<I", rec)[0] == digest
                            validated = ok
                else:
                    size = store.stat(cid, ho)
                    digest = -1
                    ok = True
                    if hv is not None:
                        total, _expect = struct.unpack("<QI", hv)
                        ok = (total == size)
                objects.append((ho.oid, size, ok, digest,
                                attrs_dg, omap_dg, validated))
        self.osd.messenger.send_message(MOSDRepScrubMap(
            pgid=self.pgid, shard=msg.shard, epoch=msg.epoch,
            objects=objects, deep=msg.deep), msg.src)

    def handle_rep_scrub_map(self, msg: MOSDRepScrubMap) -> None:
        if not self.is_primary() or \
                not hasattr(self, "_scrub_pending"):
            return
        if msg.deep != getattr(self, "_scrub_deep", False) or \
                msg.epoch != self.last_epoch_started:
            # stale reply from a superseded scrub round (e.g. a shallow
            # map resent over a healed link after a deep round started):
            # its digests don't mean what this round's comparison needs
            return
        self._scrub_maps[msg.shard] = msg
        self._scrub_pending.discard(msg.shard)
        if self._scrub_pending:
            return
        self._scrub_compare()

    def _scrub_compare(self) -> None:
        """Compare shard scrub maps; inconsistent/absent copies become
        missing entries and the recovery machinery repairs them by
        decode/push (repair = recovery, like the reference).

        What compares depends on depth: metadata (replicated size,
        attr/omap digests) on every scrub; data digests only when the
        maps were built deep (shallow maps carry no data digest)."""
        maps = self._scrub_maps
        deep = getattr(self, "_scrub_deep", False)
        del self._scrub_maps, self._scrub_pending
        my_shard = self.my_shard()
        auth = self._authoritative_objects()
        by_shard: Dict[int, Dict[str, tuple]] = {
            s: {o: (sz, ok, dg, adg, odg, val)
                for o, sz, ok, dg, adg, odg, val in m.objects}
            for s, m in maps.items()}
        from collections import Counter
        found = 0
        shard_order = sorted(self.acting_shards(),
                             key=lambda s: (s != my_shard, s))

        def data_identity(e):
            return (e[0], e[2] if deep else None)

        def meta_identity(e):
            return (e[3], e[4])

        for oid, version in auth.items():
            ents = {s: by_shard.get(s, {}).get(oid)
                    for s in self.acting_shards()}
            # Authority selection (be_select_auth_object role), split
            # by what the write-time digest actually protects:
            #
            # DATA (size + data digest), precedence order: (1) majority
            # among DIGEST-VALIDATED copies — their bytes provably
            # match their recorded digest, so even identical rot on a
            # majority can't outvote them; (2) no validated copy
            # (partial-write history wiped the digests): the primary's
            # self-consistent copy — plain majority there would let
            # identical rot on two replicas overwrite a healthy
            # primary; (3) majority among self-consistent copies
            # (primary absent/bad).  Ties break toward the primary
            # (my_shard votes first in shard_order).
            #
            # METADATA (attr/omap digests): no recorded digest guards
            # it, so data-validation must not lend false authority —
            # the primary's self-consistent copy rules (the pre-digest
            # semantics), majority only when the primary can't vote.
            mine = ents.get(my_shard)
            if self.rep_backend is not None:
                val = [data_identity(ents[s]) for s in shard_order
                       if ents[s] is not None and ents[s][1]
                       and ents[s][5]]
                if val:
                    data_win = Counter(val).most_common(1)[0][0]
                elif mine is not None and mine[1]:
                    data_win = data_identity(mine)
                else:
                    votes = [data_identity(ents[s]) for s in shard_order
                             if ents[s] is not None and ents[s][1]]
                    data_win = Counter(votes).most_common(1)[0][0] \
                        if votes else None
            else:
                data_win = None     # EC chunks differ by construction
            if mine is not None and mine[1]:
                meta_win = meta_identity(mine) \
                    if self.rep_backend is not None else mine[3]
            else:
                if self.rep_backend is not None:
                    mvotes = [meta_identity(ents[s]) for s in shard_order
                              if ents[s] is not None and ents[s][1]]
                else:
                    mvotes = [ents[s][3] for s in shard_order
                              if ents[s] is not None and ents[s][1]]
                meta_win = Counter(mvotes).most_common(1)[0][0] \
                    if mvotes else None
            for shard in self.acting_shards():
                ent = ents[shard]
                bad = ent is None or not ent[1]
                if not bad and data_win is not None:
                    bad = data_identity(ent) != data_win
                if not bad and meta_win is not None:
                    if self.rep_backend is not None:
                        bad = meta_identity(ent) != meta_win
                    else:
                        bad = ent[3] != meta_win
                if bad:
                    v = version or self.pg_log.head
                    self.missing.setdefault(shard, {})[oid] = \
                        (v, OP_MODIFY)
                    if shard == my_shard:
                        self.local_missing[oid] = (v, OP_MODIFY)
                    found += 1       # this scrub's findings only —
                    # pre-existing missing entries are recovery debt,
                    # not scrub results
        if found:
            noun = "copy" if found == 1 else "copies"
            self.osd.clog(
                "ERR", f"pg {self.pgid[0]}.{self.pgid[1]} "
                f"{'deep-' if deep else ''}scrub: {found} inconsistent "
                f"object {noun}, repairing")
            self.state = STATE_ACTIVE_RECOVERING
            self.osd.request_recovery(self)

    # ---- degraded-object tracking -----------------------------------------
    def _has_missing(self) -> bool:
        return any(self.missing.values())

    def missing_shards_for(self, oid: str) -> Set[int]:
        return {s for s, mm in self.missing.items() if oid in mm}

    def clear_missing_for(self, oid: str) -> None:
        """A full-object write/delete rewrote every acting shard."""
        for mm in self.missing.values():
            mm.pop(oid, None)
        self._maybe_clean()

    def _maybe_clean(self) -> None:
        if self.state == STATE_ACTIVE_RECOVERING and \
                not self._has_missing() and not self._backfill_pending:
            self.state = STATE_ACTIVE

    # ---- op execution (PrimaryLogPG::do_op analog) ------------------------
    def do_op(self, msg: MOSDOp) -> None:
        if getattr(self, "_realigning", False):
            # shard copies are in flight; EAGAIN makes the client
            # resend after the realign epoch lands
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-11, epoch=self.osd.osdmap.epoch))
            return
        if not self.is_primary() or self.state not in (
                STATE_ACTIVE, STATE_ACTIVE_RECOVERING):
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-11,  # EAGAIN: wrong primary / not ready
                epoch=self.osd.osdmap.epoch))
            return
        from ..msg.messages import CEPH_OSD_OP_PGLS as _PGLS
        if msg.op == _PGLS and not msg.ops:
            # pg-targeted op: no object to misdirect-check
            self._do_pgls(msg)
            return
        cur_pool = self.osd.osdmap.pools.get(self.pgid[0])
        if cur_pool is not None:
            actual = cur_pool.raw_pg_to_pg(
                self.osd.osdmap.map_to_pg(self.pgid[0], msg.oid))
            if actual.ps != self.pgid[1]:
                # misdirected: the client targeted us from a pre-split
                # map (PrimaryLogPG::do_op "wrong node" handling) —
                # EAGAIN makes it refresh the map and resend to the
                # child PG
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-11,
                    epoch=self.osd.osdmap.epoch))
                return
        from ..msg.messages import (
            CEPH_OSD_OP_NOTIFY, CEPH_OSD_OP_UNWATCH, CEPH_OSD_OP_WATCH,
        )
        # min_size gate (PG::get_min_peer_features / is_degraded_below):
        # mutations need at least min_size live acting members, or a
        # single further failure could lose acked data — clients retry
        # until recovery/remap restores enough copies
        is_write = (any(self._op_mutates(o) for o in msg.ops)
                    if msg.ops else
                    msg.op in (CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
                               CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE))
        if is_write:
            alive = sum(1 for o in self.acting if o != CRUSH_ITEM_NONE)
            if alive < self.pool.min_size:
                dlog("pg", 5, f"pg {self.pgid} write blocked: "
                     f"{alive} acting < min_size {self.pool.min_size}",
                     f"osd.{self.osd.osd_id}")
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-11,
                    epoch=self.osd.osdmap.epoch))
                return
            # full gate (PrimaryLogPG.cc:7832-7842 check_full /
            # osd_is_full): a FULL pool or cluster refuses mutations —
            # EDQUOT when quota-driven, ENOSPC otherwise.  Deletes pass
            # so users can free space (the reference's may-free-space
            # carve-out).
            deletes_only = (
                all(o.op == CEPH_OSD_OP_DELETE for o in msg.ops)
                if msg.ops else msg.op == CEPH_OSD_OP_DELETE)
            if not deletes_only:
                from ..osdmap.osdmap import CEPH_OSDMAP_FULL
                from ..osdmap.types import FLAG_FULL, FLAG_FULL_QUOTA
                if self.pool.has_flag(FLAG_FULL) or \
                        (self.osd.osdmap.flags & CEPH_OSDMAP_FULL):
                    res = -122 if self.pool.has_flag(FLAG_FULL_QUOTA) \
                        else -28
                    self.osd.send_op_reply(msg.src, MOSDOpReply(
                        tid=msg.tid, result=res,
                        epoch=self.osd.osdmap.epoch))
                    return
        if msg.op == CEPH_OSD_OP_WATCH and not msg.ops:
            self._do_watch(msg)
            return
        elif msg.op == CEPH_OSD_OP_UNWATCH and not msg.ops:
            self._do_unwatch(msg)
            return
        elif msg.op == CEPH_OSD_OP_NOTIFY and not msg.ops:
            self._do_notify(msg)
            return
        # a client SnapContext is only meaningful on selfmanaged-snap
        # pools; honoring one on a pool-snapshot pool would replace the
        # pool snapc and corrupt its snapshots (the reference rejects
        # this with EINVAL, PrimaryLogPG do_op snapc checks)
        if getattr(msg, "snapc_seq", 0) > 0 and not self.pool.selfmanaged:
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-22, epoch=self.osd.osdmap.epoch))
            return
        # FLAG_EC_OVERWRITES gate — BEFORE any clone/side effect, and
        # covering both message shapes (a partial update is a partial
        # update whether it rides a single op or a vector)
        if self.backend is not None and \
                not self.pool.allows_ecoverwrites() and \
                self._is_partial_update(msg):
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-95, epoch=self.osd.osdmap.epoch))
            return
        if self.tier is not None and self.tier.intercept(msg):
            return      # parked behind a promote; re-dispatched after
        if msg.ops and any(o.op == CEPH_OSD_OP_COPY_FROM
                           for o in msg.ops):
            # async source fetch: cannot run inside the synchronous
            # vector interpreter (PrimaryLogPG starts a CopyOp the
            # same way, do_copy_from)
            if len(msg.ops) != 1:
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-95,
                    epoch=self.osd.osdmap.epoch))
                return
            self.with_clone(msg.oid, lambda: self._do_copy_from(msg),
                            snapc=self._msg_snapc(msg))
            return
        if msg.ops:
            self._do_op_vector(msg)
        elif msg.op == CEPH_OSD_OP_WRITEFULL:
            self.with_clone(msg.oid, lambda: self._do_write(msg),
                            snapc=self._msg_snapc(msg))
        elif msg.op in (CEPH_OSD_OP_WRITE, CEPH_OSD_OP_APPEND):
            self.with_clone(msg.oid,
                            lambda: self._do_partial_write(msg),
                            snapc=self._msg_snapc(msg))
        elif msg.op == CEPH_OSD_OP_READ:
            self._do_read(msg)
        elif msg.op == CEPH_OSD_OP_STAT:
            self._do_stat(msg)
        elif msg.op == CEPH_OSD_OP_DELETE:
            self.with_clone(msg.oid, lambda: self._do_delete(msg),
                            snapc=self._msg_snapc(msg))
        else:
            self.osd.send_op_reply(msg.src,
                                   MOSDOpReply(tid=msg.tid, result=-95))

    # ---- watch / notify (Watch.cc + do_osd_op_effects, scoped) -------------
    def _do_watch(self, msg: MOSDOp) -> None:
        """Register (client, cookie) as a watcher of the object; the
        cookie rides msg.offset (librados rados_watch)."""
        self.watchers.setdefault(msg.oid, {})[(msg.src, msg.offset)] = \
            self.osd.now
        dlog("osd", 10, f"watch {msg.oid} by {msg.src} "
             f"cookie {msg.offset}", f"osd.{self.osd.osd_id}")
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

    def _do_unwatch(self, msg: MOSDOp) -> None:
        ws = self.watchers.get(msg.oid, {})
        ws.pop((msg.src, msg.offset), None)
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

    def _do_notify(self, msg: MOSDOp) -> None:
        """Broadcast to every live watcher; complete the notifier when
        all acks arrive (or the timeout sweep gives up on the dead)."""
        from ..msg.messages import MWatchNotify
        self._notify_seq += 1
        nid = self._notify_seq
        live = {}
        down = self.osd.network.down
        for (client, cookie), since in self.watchers.get(msg.oid,
                                                         {}).items():
            if client not in down and client != msg.src:
                live[(client, cookie)] = since
            elif client == msg.src:
                # the notifier's own watch acks implicitly (librados
                # does not deliver a notify to its own handle)
                pass
        st = {"src": msg.src, "tid": msg.tid, "oid": msg.oid,
              "pending": set(live), "replies": {},
              "deadline": self.osd.now + (msg.length or 30)}
        if not live:
            self._notify_complete(nid, st)
            return
        self._notifies[nid] = st
        for (client, cookie) in live:
            self.osd.messenger.send_message(MWatchNotify(
                op=MWatchNotify.NOTIFY, pgid=self.pgid, oid=msg.oid,
                cookie=cookie, notify_id=nid, payload=msg.data), client)

    def handle_notify_ack(self, msg) -> None:
        st = self._notifies.get(msg.notify_id)
        if st is None:
            return
        st["pending"].discard((msg.src, msg.cookie))
        st["replies"][f"{msg.src}:{msg.cookie}"] = msg.payload
        if not st["pending"]:
            self._notify_complete(msg.notify_id, st)

    def _notify_complete(self, nid: int, st: Dict,
                         result: int = 0) -> None:
        self._notifies.pop(nid, None)
        self.osd.send_op_reply(st["src"], MOSDOpReply(
            tid=st["tid"], result=result, data=pack_kv(st["replies"]),
            epoch=self.osd.osdmap.epoch))

    def sweep_notifies(self) -> None:
        """Tick-driven timeout: notifies whose remaining watchers went
        silent complete with ETIMEDOUT + the partial replies (the
        reference reports the timed-out watchers, never fake success)."""
        for nid, st in list(self._notifies.items()):
            if self.osd.now >= st["deadline"]:
                dlog("osd", 5, f"notify {nid} timed out waiting for "
                     f"{st['pending']}", f"osd.{self.osd.osd_id}")
                self._notify_complete(nid, st, result=-110)

    # ---- snapshots (PrimaryLogPG snapset/clone model, pool snaps) ----------
    #
    # Pool snaps only (rados mksnap).  On the first write after the
    # pool's snap_seq advances, the primary clones the head's current
    # state into an ordinary PG object named _clone_oid(oid, seq) (so
    # recovery/scrub/backfill/durability cover clones for free) — or
    # records a whiteout when the head did not exist.  The per-head
    # snapset (sorted [(seq, kind)]) rides the shard write transactions
    # into every replica's PG meta object.  A read at snap s resolves to
    # the earliest entry with seq >= s (whiteout -> ENOENT; none -> head).

    def _adopt_purged(self, snaps: List[int]) -> None:
        """Union a peer's purged_snaps into ours (peering exchange —
        trim-is-done knowledge must survive any single death)."""
        extra = set(snaps) - self.purged_snaps
        if not extra:
            return
        from .snap_mapper import stage_purged
        self.purged_snaps |= extra
        t = Transaction()
        self.ensure_meta_collection(t)
        stage_purged(t, self.meta_cid(), self.purged_snaps)
        self.osd.store.queue_transaction(t)

    def _interesting_snaps(self) -> Set[int]:
        """Snap ids the SnapMapper indexes: live plus removed ones —
        deliberately NOT minus purged_snaps.  The index must stay a
        truthful "who still references this snap" so the trimmer can
        detect a purged marker whose trim never actually landed (a
        primary killed between staging purged and the fan-out being
        delivered) and redo it; purged_snaps is a fast-path hint, not
        ground truth."""
        return self.pool.live_snaps() | set(self.pool.removed_snaps)

    @staticmethod
    def _clone_oid(oid: str, seq: int) -> str:
        return f"{oid}\x00snap\x00{seq}"

    @staticmethod
    def is_clone_oid(oid: str) -> bool:
        return "\x00snap\x00" in oid

    def _snapset_max(self, oid: str) -> int:
        ents = self.snapsets.get(oid)
        return ents[-1][0] if ents else 0

    def _msg_snapc(self, msg) -> Optional[Tuple[int, Tuple[int, ...]]]:
        """Client-supplied write SnapContext (selfmanaged-snap pools);
        None means clone against the pool snapc as before."""
        if getattr(msg, "snapc_seq", 0) > 0:
            return (msg.snapc_seq, tuple(msg.snapc_snaps))
        return None

    def _clone_needed(self, oid: str, snapc=None) -> bool:
        if snapc is None:
            seq, snaps = self.pool.snap_seq, self.pool.snaps
        else:
            seq, snaps = snapc
        if seq == 0 or self.is_clone_oid(oid):
            return False
        m = self._snapset_max(oid)
        if m >= seq:
            return False
        # a clone is only worth taking if a LIVE snap falls in the
        # window it would cover — after every snap is removed, writes
        # must not keep manufacturing instant garbage.  A client snapc
        # may lag the mon's removals, so filter those out too.
        removed = set(self.pool.removed_snaps)
        return any(m < sid <= seq and sid not in removed for sid in snaps)

    def with_clone(self, oid: str, proceed: Callable[[], None],
                   snapc=None) -> None:
        """Run *proceed* after ensuring the pre-write state is cloned
        (make_writeable's clone step, PrimaryLogPG.cc)."""
        if not self._clone_needed(oid, snapc):
            proceed()
            return
        if self.backend is not None:
            self.backend.object_state(
                oid, lambda res, data, _size, attrs:
                self._clone_have_state(oid, res, data, attrs, {}, proceed,
                                       snapc))
        else:
            exists, data, attrs, omap = self.rep_backend.object_state(oid)
            self._clone_have_state(oid, 0 if exists else -2, data, attrs,
                                   omap, proceed, snapc)

    def _clone_have_state(self, oid: str, res: int, data: bytes,
                          attrs: Dict[str, bytes],
                          omap: Dict[str, bytes],
                          proceed: Callable[[], None],
                          snapc=None) -> None:
        if res not in (0, -2):
            # can't read the head (EIO): write anyway, skip the clone —
            # losing a snapshot beats failing every write
            dlog("pg", 1, f"snap clone of {oid} failed: {res}",
                 f"osd.{self.osd.osd_id}")
            proceed()
            return
        seq = snapc[0] if snapc is not None else self.pool.snap_seq
        if self._snapset_max(oid) >= seq:   # raced with ourselves
            proceed()
            return
        entries = list(self.snapsets.get(oid, []))
        kind = SNAP_CLONE if res == 0 else SNAP_WHITEOUT
        entries.append((seq, kind))
        blob = encode_snapset(entries)
        self.snapsets[oid] = entries
        self.snap_mapper.update_oid(oid, entries,
                                    self._interesting_snaps())
        dlog("pg", 5, f"cloning {oid} @ seq {seq} "
             f"({'clone' if kind else 'whiteout'})",
             f"osd.{self.osd.osd_id}")
        if kind == SNAP_CLONE:
            cl = self._clone_oid(oid, seq)
            if self.backend is not None:
                self.backend.submit_transaction(
                    cl, data, lambda _r: None, xattrs=attrs,
                    snapset_update=(oid, blob))
            else:
                self.rep_backend.write(cl, data, full=True,
                                       version=self.next_version(),
                                       xattrs=attrs, omap=omap,
                                       snapset_update=(oid, blob))
        else:
            self._fan_snapset(oid, blob)
        proceed()

    def _fan_snapset(self, oid: str, blob: bytes) -> None:
        """Pure snapset-metadata fan-out (no object touched).  On EC
        pools the fan is acked and retried like sub-op writes (an
        InflightWrite swept by the OSD tick / idle kick); replicated
        pools keep the rep backend's fire-and-forget shape."""
        from ..msg.messages import MOSDECSubOpWrite
        if self.backend is not None:
            self._fan_acked(
                oid, lambda shard, tid: MOSDECSubOpWrite(
                    tid=tid, pgid=self.pgid, shard=shard, oid=oid,
                    snapset_only=True, snapset_update=(oid, blob)))
            return
        for shard, osd in self.acting_shards().items():
            self.send_to_osd(osd, MOSDECSubOpWrite(
                tid=0, pgid=self.pgid, shard=-1,
                oid=oid, snapset_only=True, snapset_update=(oid, blob)))

    def _fan_acked(self, oid: str, make_msg) -> int:
        """Fan ``make_msg(shard, tid)`` to every acting shard through
        the EC backend's InflightWrite machinery: acked per shard,
        unacked sends resent by sweep_inflight (tick + idle kick) —
        the retry contract sub-op writes already have
        (docs/ROBUSTNESS.md).  Returns the fan's tid."""
        from .ec_backend import InflightWrite
        be = self.backend
        tid = be.next_tid()
        wr = InflightWrite(tid=tid, oid=oid,
                           client_reply=lambda _r: None)
        for shard, osd in self.acting_shards().items():
            msg = make_msg(shard, tid)
            wr.pending_shards.add(shard)
            wr.sent_msgs[shard] = (osd, msg)
            self.send_to_osd(osd, msg)
        if wr.pending_shards:
            wr.last_send = self.osd.now
            be.inflight_writes[tid] = wr
        return tid

    def _encoded_snapsets(self) -> List[Tuple[str, bytes]]:
        return [(oid, encode_snapset(ents))
                for oid, ents in self.snapsets.items()]

    def merge_snapsets(self, pairs: List[Tuple[str, bytes]]) -> None:
        """Adopt peer snapsets that are ahead of ours (higher max clone
        seq wins — seqs only grow, so the longer history is newer)."""
        from .pg_log import decode_snapset
        if not pairs:
            return
        t = Transaction()
        changed = False
        interesting = self._interesting_snaps()

        def rank(entries):
            # trimmed beats clone/whiteout at the same seq, so a trim
            # tombstone always propagates over the entries it killed;
            # ties on max seq break on the highest trimmed seq anywhere
            # in the history (a tombstone below a surviving live clone
            # must still dominate the pre-trim history it replaced)
            return (entries[-1][0],
                    1 if entries[-1][1] == SNAP_TRIMMED else 0,
                    max((s for s, k in entries if k == SNAP_TRIMMED),
                        default=0))

        for oid, blob in pairs:
            ents = decode_snapset(blob)
            if not ents:
                continue
            mine = self.snapsets.get(oid, [])
            if not mine or rank(ents) > rank(mine):
                if not self.osd.store.collection_exists(self.meta_cid()):
                    t.create_collection(self.meta_cid())
                stage_snapset(t, self.meta_cid(), oid, blob)
                self.snapsets[oid] = ents
                self.snap_mapper.update_oid(oid, ents, interesting)
                changed = True
        if changed:
            self.osd.store.queue_transaction(t)

    def apply_snapset_update(self, upd: Tuple[str, bytes],
                             t: Transaction) -> None:
        """Shard-side: stage the snapset into the meta object and
        mirror it in memory (every replica tracks snapsets)."""
        from .pg_log import decode_snapset
        oid, blob = upd
        if not self.osd.store.collection_exists(self.meta_cid()):
            t.create_collection(self.meta_cid())
        stage_snapset(t, self.meta_cid(), oid, blob)
        if blob:
            self.snapsets[oid] = decode_snapset(blob)
        else:
            self.snapsets.pop(oid, None)
        self.snap_mapper.update_oid(oid, self.snapsets.get(oid, []),
                                    self._interesting_snaps())

    def resolve_snap(self, oid: str, snapid: int):
        """-> (target_oid | None for ENOENT).  Earliest snapset entry
        with seq >= snapid wins; none means the head is unchanged since
        the snap and serves it."""
        for seq, kind in self.snapsets.get(oid, []):
            if seq >= snapid:
                if kind == SNAP_TRIMMED:
                    continue        # the covering state is gone
                if kind == SNAP_WHITEOUT:
                    return None
                return self._clone_oid(oid, seq)
        return oid

    def _maybe_trim_snaps(self) -> None:
        """Drop clones covering only removed snaps (snap trimmer role).
        Entry (S, kind) covers pool snaps s with prev_S < s <= S; when no
        live snap falls in that window the clone is garbage.

        The candidates come from the SnapMapper index (snap -> heads),
        not a scan of every snapset, and the snaps to handle come from
        ``removed_snaps - purged_snaps`` rather than "did this epoch
        change them" — so a primary that died before trimming is
        finished by its successor at the next activation (the
        reference's purged_snaps catch-up, src/osd/PrimaryLogPG.cc
        AwaitAsyncWork + pg_info_t.purged_snaps)."""
        if not self.is_primary():
            return
        if self.state not in (STATE_ACTIVE, STATE_ACTIVE_RECOVERING):
            # mid-peering our snapsets/purged knowledge is incomplete —
            # recording purged now would mark debt paid that was never
            # collected; _activate re-calls us once the merge is done
            return
        # unpurged removed snaps, PLUS purged ones the index says are
        # still referenced — a purged marker can outlive a crash that
        # swallowed the trim's fan-out, and only the index knows
        to_purge = {s for s in self.pool.removed_snaps
                    if s not in self.purged_snaps
                    or self.snap_mapper.lookup(s)}
        if not to_purge:
            return
        candidates: Set[str] = set()
        for sid in to_purge:
            candidates |= self.snap_mapper.lookup(sid)
        live = self.pool.live_snaps()
        interesting = self._interesting_snaps()
        for oid in sorted(candidates):
            entries = self.snapsets.get(oid)
            if not entries:
                continue
            keep = []
            prev = 0
            changed = False
            trimmed_max = 0
            for seq, kind in entries:
                if kind == SNAP_TRIMMED:
                    trimmed_max = max(trimmed_max, seq)
                    changed = True      # re-emitted (possibly merged) below
                elif any(prev < sid <= seq for sid in live):
                    keep.append((seq, kind))
                else:
                    changed = True
                    trimmed_max = max(trimmed_max, seq)
                    if kind == SNAP_CLONE:
                        dlog("pg", 5, f"trimming clone {oid}@{seq}",
                             f"osd.{self.osd.osd_id}")
                        self._fan_delete(self._clone_oid(oid, seq))
                prev = seq
            if changed:
                # one tombstone at the max trimmed seq keeps a stale
                # rejoining peer from resurrecting the dead entries
                if trimmed_max:
                    keep = sorted(keep + [(trimmed_max, SNAP_TRIMMED)])
                self.snapsets[oid] = keep
                self.snap_mapper.update_oid(oid, keep, interesting)
                self._fan_snapset(oid, encode_snapset(keep))
        # record completion so no successor (or later epoch) redoes it
        self._adopt_purged(sorted(to_purge))

    # ---- multi-op vector interpreter (do_osd_ops) --------------------------

    # ops whose execution needs the object's current bytes; vectors with
    # none of these run off a one-shard attrs-only probe on EC pools
    _BODY_OPS = frozenset([
        CEPH_OSD_OP_READ, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_APPEND,
        CEPH_OSD_OP_TRUNCATE, CEPH_OSD_OP_ZERO, CEPH_OSD_OP_STAT,
        CEPH_OSD_OP_WRITEFULL,
        CEPH_OSD_OP_CALL,       # class methods may read/write the body
    ])

    _READONLY_OPS = frozenset([
        CEPH_OSD_OP_READ, CEPH_OSD_OP_STAT, CEPH_OSD_OP_GETXATTR,
        CEPH_OSD_OP_GETXATTRS, CEPH_OSD_OP_OMAPGETVALS,
        CEPH_OSD_OP_CMPXATTR, CEPH_OSD_OP_ASSERT_VER,
    ])

    def _op_mutates(self, o: OSDOp) -> bool:
        """Write-ness of one vector op; class calls consult their
        registered RD/WR flags (the reference's cls method flags) so a
        pure-read exec is not gated or cloned like a write."""
        if o.op in self._READONLY_OPS:
            return False
        if o.op == CEPH_OSD_OP_CALL:
            from .cls import CLS_METHOD_WR, lookup
            cls_name, _, method = o.name.partition(".")
            ent = lookup(cls_name, method)
            return bool(ent and (ent[1] & CLS_METHOD_WR))
        return True

    def _stored_user_version(self, oid: str) -> int:
        """Current pg_log version stamped on the object's VERSION_ATTR
        (0 when absent) — the reply user_version analog that assert_ver
        guards compare against.  Distinct from _object_version, the
        recovery-path helper whose absent sentinel is -1."""
        store = self.osd.store
        if self.backend is not None:
            shard = self.my_shard()
            cid = self.backend.shard_cid(shard)
            ho = hobject_t(oid, shard)
        else:
            cid = self.rep_backend.cid()
            ho = hobject_t(oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return 0
        try:
            return struct.unpack("<Q",
                                 store.getattr(cid, ho, VERSION_ATTR))[0]
        except KeyError:
            return 0

    def _do_op_vector(self, msg: MOSDOp) -> None:
        """Atomic multi-op execution (PrimaryLogPG::do_osd_ops,
        PrimaryLogPG.cc:7796 via prepare_transaction): fetch the object's
        state once, run every op of the vector in order against it, and
        commit all mutations as ONE backend transaction — which on EC
        pools means one batched device encode for the whole vector.  The
        first failing op aborts the vector with nothing committed (the
        reference aborts the ctx on the first negative rval).  Vectors
        ride the backend's per-object queue, so concurrent vectors and
        single-op writes on one object serialize (start_rmw's
        guarantee)."""
        oid = msg.oid
        if msg.snapid:
            # snap-targeted vectors are read-only views of the clone
            if any(self._op_mutates(o) for o in msg.ops):
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-30,     # EROFS
                    epoch=self.osd.osdmap.epoch))
                return
            target = self.resolve_snap(oid, msg.snapid)
            if target is None:
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-2,
                    epoch=self.osd.osdmap.epoch))
                return
            oid = target

        def start() -> None:
            if self.backend is not None:
                meta_only = all(o.op not in self._BODY_OPS
                                for o in msg.ops)
                self.backend.submit_vector(
                    oid,
                    lambda res, body, _size, attrs:
                    self._run_op_vector(msg, res, body, attrs, {}),
                    meta_only=meta_only)
            else:
                exists, data, attrs, omap = \
                    self.rep_backend.object_state(oid)
                spec = self._run_op_vector(
                    msg, 0 if exists else -2, data, attrs, omap)
                self._commit_rep_vector(msg.oid, spec)

        def gated() -> None:
            mutates = any(self._op_mutates(o) for o in msg.ops)
            if mutates:
                self.with_clone(oid, start,
                                snapc=self._msg_snapc(msg))
            else:
                start()

        degraded = (self.missing_shards_for(oid) if self.backend is not None
                    else (oid in self.local_missing))
        if degraded:
            self.wait_for_recovery(oid, gated)
        else:
            gated()

    def _run_op_vector(self, msg: MOSDOp, res: int, data: bytes,
                       attrs: Dict[str, bytes], omap: Dict[str, bytes]):
        """Execute the ops; send the reply for no-commit outcomes; return
        the commit spec (see ec_backend.VectorOp) otherwise."""
        if res not in (0, -2):
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=res, epoch=self.osd.osdmap.epoch))
            return None
        st = {"exists": res == 0, "body": bytearray(data),
              "attrs": dict(attrs), "omap": dict(omap),
              # EC stores have no omap; class methods touching it must
              # fail loudly (EOPNOTSUPP) instead of staging silently
              # dropped keys (reference: cls_cxx_map_* on EC pools)
              "omap_ok": self.backend is None,
              # cls_lock needs wall time (expirations) and the caller
              # identity (cls_cxx_get_origin / ceph_cls_current_*)
              "now": self.osd.now, "entity": msg.src}
        if any(o.op == CEPH_OSD_OP_ASSERT_VER for o in msg.ops):
            st["cur_version"] = self._stored_user_version(msg.oid)
        existed = st["exists"]
        mutated = meta_mutated = False
        results: List[Tuple[int, bytes]] = []
        error = 0
        for op in msg.ops:
            r, out = self._exec_one_op(op, st)
            mutated |= st.pop("_mutated", False)
            meta_mutated |= st.pop("_meta", False)
            results.append((r, out))
            if r < 0:
                error = r
                break
        reply = MOSDOpReply(tid=msg.tid, result=error,
                            epoch=self.osd.osdmap.epoch,
                            op_results=results)
        if error or not (mutated or meta_mutated):
            # read-only vector or aborted mutation: nothing to commit
            if results and not error:
                reply.data = next((d for r, d in reversed(results) if d),
                                  b"")
            self.osd.send_op_reply(msg.src, reply)
            return None
        src = msg.src

        def on_commit(result: int) -> None:
            reply.result = result
            self.osd.send_op_reply(src, reply)

        if not st["exists"]:
            # the vector's NET effect is removal (a later create/write
            # in the same vector would have set exists back — the final
            # state decides, like the reference's ctx->delta_stats)
            if not existed:
                # never existed and still doesn't: nothing to fan
                self.osd.send_op_reply(src, reply)
                return None
            self.clear_missing_for(msg.oid)
            return ("delete", lambda: self._fan_delete(msg.oid), on_commit)
        if mutated:
            def committed(result: int) -> None:
                if result == 0:
                    self.clear_missing_for(msg.oid)
                on_commit(result)
            return ("write", bytes(st["body"]), dict(st["attrs"]),
                    committed, dict(st["omap"]))
        return ("attrs", dict(st["attrs"]), on_commit, dict(st["omap"]))

    def _commit_rep_vector(self, oid: str, spec) -> None:
        """Apply a commit spec synchronously on the replicated backend
        (the in-process fabric serializes rep ops; no queue needed)."""
        if spec is None:
            return
        kind = spec[0]
        if kind == "delete":
            _, fan_fn, on_commit = spec
            fan_fn()
            on_commit(0)
            return
        if kind == "write":
            _, body, attrs, on_commit, omap = spec
            self.rep_backend.write(oid, body, full=True,
                                   version=self.next_version(),
                                   xattrs=attrs, omap=omap)
            on_commit(0)
            return
        _, attrs, on_commit, omap = spec
        self.rep_backend.write(oid, b"", version=self.next_version(),
                               xattrs=attrs, omap=omap, attr_only=True)
        on_commit(0)

    def _exec_one_op(self, op: OSDOp, st: Dict) -> Tuple[int, bytes]:
        """Run one op against the in-memory object state; mutations are
        recorded in st via _mutated/_meta/_deleted flags."""
        exists, body = st["exists"], st["body"]
        attrs, omap = st["attrs"], st["omap"]
        o = op.op
        if o == CEPH_OSD_OP_CREATE:
            if exists and (op.flags & CEPH_OSD_OP_FLAG_EXCL):
                return -17, b""                     # EEXIST
            if not exists:
                st["exists"], st["_mutated"] = True, True
            return 0, b""
        if o == CEPH_OSD_OP_WRITEFULL:
            st["body"] = bytearray(op.data)
            st["exists"], st["_mutated"] = True, True
            return 0, b""
        if o == CEPH_OSD_OP_WRITE:
            end = op.offset + len(op.data)
            if end > len(body):
                body.extend(b"\0" * (end - len(body)))
            body[op.offset:end] = op.data
            st["exists"], st["_mutated"] = True, True
            return 0, b""
        if o == CEPH_OSD_OP_APPEND:
            body.extend(op.data)
            st["exists"], st["_mutated"] = True, True
            return 0, b""
        if o == CEPH_OSD_OP_TRUNCATE:
            if not exists:
                return -2, b""                      # ENOENT
            if op.offset <= len(body):
                del body[op.offset:]
            else:
                body.extend(b"\0" * (op.offset - len(body)))
            st["_mutated"] = True
            return 0, b""
        if o == CEPH_OSD_OP_ZERO:
            if not exists:
                return -2, b""
            end = min(op.offset + op.length, len(body))
            if end > op.offset:
                body[op.offset:end] = b"\0" * (end - op.offset)
                st["_mutated"] = True
            return 0, b""
        if o == CEPH_OSD_OP_DELETE:
            if not exists:
                return -2, b""
            st["exists"], st["_mutated"] = False, True
            st["body"] = bytearray()
            attrs.clear()
            omap.clear()
            return 0, b""
        if o == CEPH_OSD_OP_READ:
            if not exists:
                return -2, b""
            end = op.offset + op.length if op.length else len(body)
            return 0, bytes(body[op.offset:end])
        if o == CEPH_OSD_OP_STAT:
            if not exists:
                return -2, b""
            return 0, struct.pack("<Q", len(body))
        if o == CEPH_OSD_OP_SETXATTR:
            attrs[op.name] = bytes(op.data)
            st["exists"], st["_meta"] = True, True
            return 0, b""
        if o == CEPH_OSD_OP_RMXATTR:
            if op.name not in attrs:
                return -61, b""                     # ENODATA
            del attrs[op.name]
            st["_meta"] = True
            return 0, b""
        if o == CEPH_OSD_OP_GETXATTR:
            if not exists:
                return -2, b""                      # ENOENT
            v = attrs.get(op.name)
            if v is None:
                return -61, b""
            return 0, v
        if o == CEPH_OSD_OP_GETXATTRS:
            if not exists:
                return -2, b""
            return 0, pack_kv({k: attrs[k] for k in sorted(attrs)})
        if o == CEPH_OSD_OP_CMPXATTR:
            v = attrs.get(op.name)
            if v is None:
                return -61, b""
            cmp = (v > op.data) - (v < op.data)
            ok = {CEPH_OSD_CMPXATTR_OP_EQ: cmp == 0,
                  CEPH_OSD_CMPXATTR_OP_NE: cmp != 0,
                  CEPH_OSD_CMPXATTR_OP_GT: cmp > 0,
                  CEPH_OSD_CMPXATTR_OP_GTE: cmp >= 0,
                  CEPH_OSD_CMPXATTR_OP_LT: cmp < 0,
                  CEPH_OSD_CMPXATTR_OP_LTE: cmp <= 0}.get(op.flags)
            if ok is None:
                return -22, b""                     # EINVAL
            return (1, b"") if ok else (-125, b"")  # ECANCELED on mismatch
        if o == CEPH_OSD_OP_ASSERT_VER:
            # expected version rides op.offset; mismatch aborts the
            # vector with ERANGE (PrimaryLogPG.cc do_osd_ops)
            return (0, b"") if op.offset == st["cur_version"] \
                else (-34, b"")
        if o == CEPH_OSD_OP_CALL:
            # object-class method (do_osd_ops CEPH_OSD_OP_CALL ->
            # ClassHandler): runs against the staged state so its
            # mutations commit with the rest of the vector
            from .cls import ClsContext, ClsError, lookup
            cls_name, _, method = op.name.partition(".")
            ent = lookup(cls_name, method)
            if ent is None:
                return -95, b""             # EOPNOTSUPP: no such method
            fn, _flags = ent
            try:
                ret, out = fn(ClsContext(st), bytes(op.data))
            except ClsError as e:
                return e.ret, b""
            except Exception:
                return -22, b""
            return ret, out
        if o in (CEPH_OSD_OP_OMAPSETKEYS, CEPH_OSD_OP_OMAPRMKEYS,
                 CEPH_OSD_OP_OMAPGETVALS):
            if self.backend is not None:
                return -95, b""   # EOPNOTSUPP: no omap on EC pools
            if o == CEPH_OSD_OP_OMAPSETKEYS:
                omap.update(unpack_kv(op.data))
                st["exists"], st["_meta"] = True, True
                return 0, b""
            if o == CEPH_OSD_OP_OMAPRMKEYS:
                for k in unpack_keys(op.data):
                    omap.pop(k, None)
                st["_meta"] = True
                return 0, b""
            if not exists:
                return -2, b""
            return 0, pack_kv({k: omap[k] for k in sorted(omap)})
        return -95, b""                             # EOPNOTSUPP

    def _fan_delete(self, oid: str) -> None:
        """Fan a versioned delete to every acting shard/replica.  EC
        deletes are acked + retried like sub-op writes (tid assigned,
        resent from the OSD tick/idle kick, shard replay deduped
        against the pg log) — the last unacked write-path class
        (docs/ROBUSTNESS.md); replicated deletes stay fire-and-forget
        like every other rep-backend fan."""
        from ..msg.messages import MOSDECSubOpWrite
        version = self.next_version()
        if self.backend is not None:
            self._fan_acked(
                oid, lambda shard, tid: MOSDECSubOpWrite(
                    tid=tid, pgid=self.pgid, shard=shard, oid=oid,
                    chunk=b"", at_version=-1, version=version))
        else:
            for osd in self.acting:
                if osd == CRUSH_ITEM_NONE:
                    continue
                self.send_to_osd(osd, MOSDECSubOpWrite(
                    tid=0, pgid=self.pgid, shard=-1, oid=oid,
                    chunk=b"", at_version=-1, version=version))

    _PARTIAL_OPS = frozenset([
        CEPH_OSD_OP_WRITE, CEPH_OSD_OP_APPEND, CEPH_OSD_OP_TRUNCATE,
        CEPH_OSD_OP_ZERO,
    ])

    def _is_partial_update(self, msg: MOSDOp) -> bool:
        if msg.ops:
            return any(o.op in self._PARTIAL_OPS for o in msg.ops)
        return msg.op in (CEPH_OSD_OP_WRITE, CEPH_OSD_OP_APPEND)

    def _do_write(self, msg: MOSDOp) -> None:
        if self.backend is not None:
            src = msg.src
            oid = msg.oid

            def on_commit(result: int) -> None:
                if result == 0:
                    self.clear_missing_for(oid)
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result,
                    epoch=self.osd.osdmap.epoch))

            self.backend.submit_transaction(msg.oid, msg.data, on_commit)
        else:
            self.rep_backend.write(msg.oid, msg.data, full=True,
                                   version=self.next_version())
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

    def _do_partial_write(self, msg: MOSDOp) -> None:
        """Offset write / append: rmw on EC pools, splice on replicated
        (PrimaryLogPG do_osd_ops CEPH_OSD_OP_WRITE/APPEND).  Degraded
        objects are recovered before the rmw touches shard state."""
        offset = None if msg.op == CEPH_OSD_OP_APPEND else msg.offset
        if self.backend is not None:
            src = msg.src

            def on_commit(result: int) -> None:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result,
                    epoch=self.osd.osdmap.epoch))

            def submit() -> None:
                self.backend.submit_write(msg.oid, msg.data, offset,
                                          on_commit)

            if self.missing_shards_for(msg.oid):
                self.wait_for_recovery(msg.oid, submit)
            else:
                submit()
        else:
            def rep_submit() -> None:
                self.rep_backend.write(msg.oid, msg.data, offset=offset,
                                       version=self.next_version())
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

            if msg.oid in self.local_missing:
                # our own copy is stale/absent: the splice offset would
                # be wrong — recover first (wait_for_missing_object)
                self.wait_for_recovery(msg.oid, rep_submit)
            else:
                rep_submit()

    def wait_for_recovery(self, oid: str, then: Callable[[], None]) -> None:
        """Queue *then* until the object is fully recovered
        (wait_for_missing_object semantics)."""
        self._waiting_for_recovery.setdefault(oid, []).append(then)
        self.osd.recover_oid(self, oid)

    def recovery_done_for(self, oid: str) -> None:
        self._recovering.discard(oid)
        self._recovering_since.pop(oid, None)
        self._maybe_clean()
        for cb in self._waiting_for_recovery.pop(oid, []):
            cb()

    def _snap_redirect(self, msg: MOSDOp) -> Optional[MOSDOp]:
        """Resolve msg.snapid to the object serving that snap view;
        returns the (possibly cloned-and-redirected) msg, or None after
        replying ENOENT for whiteouts/absent-at-snap."""
        if not msg.snapid:
            return msg
        target = self.resolve_snap(msg.oid, msg.snapid)
        if target is None:
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-2,
                epoch=self.osd.osdmap.epoch))
            return None
        if target != msg.oid:
            msg = copy.copy(msg)
            msg.oid = target
        return msg

    def data_cids(self) -> List[str]:
        """The store collections holding this PG's objects on THIS OSD
        (one shard cid on EC pools, the replica cid otherwise) — shared
        by listing and stats reporting."""
        if self.backend is not None:
            shard = self.my_shard()
            return [self.backend.shard_cid(shard)] if shard >= 0 else []
        return [self.rep_backend.cid()]

    def _do_pgls(self, msg: MOSDOp) -> None:
        """List this PG's head objects (PrimaryLogPG do_pg_op
        CEPH_OSD_OP_PGNLS): cursor = last name already returned
        (msg.data), page size = msg.length (0 = everything).  Clones
        and PG-internal metadata never appear; objects the primary
        knows about but has not recovered yet DO (the reference merges
        the missing set the same way, so a listing taken mid-recovery
        is complete).  The page ships as JSON (names may contain any
        byte); result carries 1 when more remain."""
        import heapq
        import json as _json
        store = self.osd.store
        cursor = msg.data.decode() if msg.data else ""
        names = set()
        for cid in self.data_cids():
            if not store.collection_exists(cid):
                continue
            for ho in store.list_objects(cid):
                if ho.oid == PG_META_OID or self.is_clone_oid(ho.oid) \
                        or ho.oid <= cursor:
                    continue
                names.add(ho.oid)
        # merge known-but-unrecovered objects (do_pgnls missing merge)
        if self.backend is not None:
            for per_shard in self.missing.values():
                for oid in per_shard:
                    if not self.is_clone_oid(oid) and oid > cursor:
                        names.add(oid)
        else:
            for oid in self.local_missing:
                if not self.is_clone_oid(oid) and oid > cursor:
                    names.add(oid)
        if msg.length:
            page = heapq.nsmallest(msg.length + 1, names)
            more = 1 if len(page) > msg.length else 0
            page = page[:msg.length]
        else:
            page, more = sorted(names), 0
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=more, epoch=self.osd.osdmap.epoch,
            data=_json.dumps(page).encode()))

    def _do_read(self, msg: MOSDOp) -> None:
        msg = self._snap_redirect(msg)
        if msg is None:
            return
        if self.backend is not None:
            src = msg.src

            def on_complete(result: int, data: bytes) -> None:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result, data=data,
                    epoch=self.osd.osdmap.epoch))

            self.backend.objects_read_and_reconstruct(
                msg.oid, on_complete, offset=msg.offset, length=msg.length)
        else:
            def rep_read() -> None:
                data = self.rep_backend.read(msg.oid)
                if data is None:
                    self.osd.send_op_reply(
                        msg.src, MOSDOpReply(tid=msg.tid, result=-2))
                else:
                    body = data
                    if msg.length:
                        body = data[msg.offset:msg.offset + msg.length]
                    elif msg.offset:
                        body = data[msg.offset:]
                    self.osd.send_op_reply(msg.src, MOSDOpReply(
                        tid=msg.tid, result=0, data=body,
                        epoch=self.osd.osdmap.epoch))

            if msg.oid in self.local_missing:
                # serving the stale local copy would return old bytes
                self.wait_for_recovery(msg.oid, rep_read)
            else:
                rep_read()

    def _do_stat(self, msg: MOSDOp) -> None:
        msg = self._snap_redirect(msg)
        if msg is None:
            return
        store = self.osd.store
        if self.backend is not None:
            shard = self.my_shard()
            cid = self.backend.shard_cid(shard)
            ho = hobject_t(msg.oid, shard)
        else:
            cid = self.rep_backend.cid()
            ho = hobject_t(msg.oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            self.osd.send_op_reply(msg.src,
                                   MOSDOpReply(tid=msg.tid, result=-2))
            return
        try:
            size = struct.unpack("<Q", store.getattr(cid, ho, SIZE_ATTR))[0]
        except KeyError:
            size = store.stat(cid, ho)
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, data=struct.pack("<Q", size),
            epoch=self.osd.osdmap.epoch,
            version=self._stored_user_version(msg.oid)))

    def _do_copy_from(self, msg: MOSDOp) -> None:
        """Server-side object copy (PrimaryLogPG do_copy_from /
        process_copy_chunk): the primary fetches the SOURCE — possibly
        from another pool — through its own client path, then commits
        the bytes + user attrs locally as one full write."""
        from ..msg.messages import (
            CEPH_OSD_OP_GETXATTRS as _GX, CEPH_OSD_OP_OMAPGETVALS as _OG,
            CEPH_OSD_OP_READ as _RD,
        )
        op = msg.ops[0]
        src_oid = op.name
        # pool ids start at 0: -1 is the same-pool sentinel
        src_pool = op.offset if op.offset >= 0 else msg.pool
        src = msg.src
        # omap rides along only when the SOURCE pool can hold it (an
        # OMAPGETVALS in the fetch vector would abort on an EC source)
        spool = self.osd.osdmap.get_pg_pool(src_pool)
        fetch = [OSDOp(op=_RD), OSDOp(op=_GX)]
        src_has_omap = spool is not None and not spool.is_erasure()
        if src_has_omap:
            fetch.append(OSDOp(op=_OG))

        def on_fetch(reply) -> None:
            if reply.result != 0 or not reply.op_results:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=reply.result or -5,
                    epoch=self.osd.osdmap.epoch))
                return
            data = reply.op_results[0][1]
            attrs = {}
            if len(reply.op_results) > 1 and reply.op_results[1][0] >= 0:
                attrs = unpack_kv(reply.op_results[1][1])
            omap = {}
            if src_has_omap and len(reply.op_results) > 2 and \
                    reply.op_results[2][0] >= 0:
                omap = unpack_kv(reply.op_results[2][1])

            def on_commit(result: int) -> None:
                if result == 0:
                    self.clear_missing_for(msg.oid)
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result,
                    epoch=self.osd.osdmap.epoch))

            if self.backend is not None:
                # EC destinations cannot hold omap; body + attrs copy
                self.backend.submit_transaction(msg.oid, data, on_commit,
                                                xattrs=attrs)
            else:
                # full replacement INCLUDING omap (reference copy-from
                # replaces the whole object; {} clears stale dst keys)
                self.rep_backend.write(msg.oid, data, full=True,
                                       version=self.next_version(),
                                       xattrs=attrs, omap=omap)
                on_commit(0)

        self.osd.tier_submit(src_pool, src_oid, fetch, on_fetch)

    def _do_delete(self, msg: MOSDOp) -> None:
        self._fan_delete(msg.oid)
        self.clear_missing_for(msg.oid)
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))
