"""PG — placement group with a peering-lite state machine.

The reference drives each PG through a boost::statechart RecoveryMachine
(src/osd/PG.h:1879: Initial/Peering/Active/...); here the same lifecycle is
a small explicit state machine: on every map epoch the PG recomputes
up/acting (AdvMap), re-peers when membership changed, and schedules
shard recovery for acting members that lack data (the ECBackend recovery
flow, src/osd/ECBackend.cc:535-743).  Ops only execute in the Active state
on the primary (PrimaryLogPG::do_op gating).
"""
from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..crush.constants import CRUSH_ITEM_NONE
from ..msg import (
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE, CEPH_OSD_OP_READ,
    CEPH_OSD_OP_STAT, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
    MOSDOp, MOSDOpReply, Message,
)
from ..os_store import Transaction, hobject_t
from .ec_backend import ECBackend, SIZE_ATTR

STATE_INITIAL = "initial"
STATE_PEERING = "peering"
STATE_ACTIVE = "active"
STATE_ACTIVE_RECOVERING = "active+recovering"


class ReplicatedBackend:
    """Full-copy backend for replicated pools (osd/ReplicatedBackend —
    replication is host-side fan-out, not device compute)."""

    def __init__(self, pg):
        self.pg = pg

    def cid(self) -> str:
        return f"{self.pg.pgid[0]}.{self.pg.pgid[1]}"

    def write(self, oid: str, data: bytes, offset: Optional[int] = None,
              full: bool = False) -> None:
        """full=True replaces the object; otherwise an offset write
        (offset=None appends at the current size, read from the primary's
        own full copy)."""
        from ..msg.messages import MOSDECSubOpWrite
        if full:
            off, partial = 0, False
            new_size = len(data)
        else:
            old = self.read(oid)
            old_size = len(old) if old is not None else 0
            off = old_size if offset is None else offset
            partial = True
            new_size = max(old_size, off + len(data))
        for osd in self.pg.acting:
            if osd == CRUSH_ITEM_NONE:
                continue
            msg = MOSDECSubOpWrite(tid=0, pgid=self.pg.pgid, shard=-1,
                                   oid=oid, chunk=data, offset=off,
                                   partial=partial, at_version=new_size)
            self.pg.send_to_osd(osd, msg)

    def apply_write(self, msg, store) -> None:
        cid = self.cid()
        t = Transaction()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        ho = hobject_t(msg.oid)
        if not msg.partial:
            t.truncate(cid, ho, 0)
        t.write(cid, ho, msg.offset, msg.chunk)
        t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", msg.at_version))
        store.queue_transaction(t)

    def read(self, oid: str) -> Optional[bytes]:
        store = self.pg.osd.store
        cid = self.cid()
        ho = hobject_t(oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return None
        return store.read(cid, ho)


class PG:
    def __init__(self, osd, pgid: Tuple[int, int], pool):
        self.osd = osd
        self.pgid = pgid
        self.pool = pool
        self.up: List[int] = []
        self.acting: List[int] = []
        self.up_primary = -1
        self.acting_primary = -1
        self.state = STATE_INITIAL
        self.last_epoch_started = 0
        self.backend: Optional[ECBackend] = None
        self.rep_backend: Optional[ReplicatedBackend] = None
        if pool.is_erasure():
            ec_impl = osd.get_ec_impl(pool)
            self.backend = ECBackend(self, ec_impl, pool.stripe_width)
        else:
            self.rep_backend = ReplicatedBackend(self)

    # ---- topology ---------------------------------------------------------
    def is_primary(self) -> bool:
        return self.acting_primary == self.osd.osd_id

    def my_shard(self) -> int:
        for i, o in enumerate(self.acting):
            if o == self.osd.osd_id:
                return i
        return -1

    def acting_shards(self) -> Dict[int, int]:
        """shard index -> osd id, skipping NONE holes."""
        return {i: o for i, o in enumerate(self.acting)
                if o != CRUSH_ITEM_NONE}

    def send_to_osd(self, osd_id: int, msg: Message) -> None:
        self.osd.messenger.send_message(msg, f"osd.{osd_id}")

    # ---- peering-lite (AdvMap/ActMap events) ------------------------------
    def advance_map(self, osdmap) -> None:
        from ..osdmap import pg_t
        up, upp, acting, actp = osdmap.pg_to_up_acting_osds(
            pg_t(self.pgid[0], self.pgid[1]))
        changed = (acting != self.acting or actp != self.acting_primary)
        self.up, self.up_primary = up, upp
        self.acting, self.acting_primary = acting, actp
        if changed or self.state == STATE_INITIAL:
            self.state = STATE_PEERING
            # peering-lite: membership is authoritative from the map; data
            # completeness is restored by recovery below
            self.last_epoch_started = osdmap.epoch
            if self.is_primary():
                self.state = STATE_ACTIVE
                self.osd.request_recovery(self)
            else:
                self.state = STATE_ACTIVE

    # ---- op execution (PrimaryLogPG::do_op analog) ------------------------
    def do_op(self, msg: MOSDOp) -> None:
        if not self.is_primary() or self.state not in (
                STATE_ACTIVE, STATE_ACTIVE_RECOVERING):
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-11,  # EAGAIN: wrong primary / not ready
                epoch=self.osd.osdmap.epoch))
            return
        if msg.op == CEPH_OSD_OP_WRITEFULL:
            self._do_write(msg)
        elif msg.op in (CEPH_OSD_OP_WRITE, CEPH_OSD_OP_APPEND):
            self._do_partial_write(msg)
        elif msg.op == CEPH_OSD_OP_READ:
            self._do_read(msg)
        elif msg.op == CEPH_OSD_OP_STAT:
            self._do_stat(msg)
        elif msg.op == CEPH_OSD_OP_DELETE:
            self._do_delete(msg)
        else:
            self.osd.send_op_reply(msg.src,
                                   MOSDOpReply(tid=msg.tid, result=-95))

    def _do_write(self, msg: MOSDOp) -> None:
        if self.backend is not None:
            src = msg.src

            def on_commit(result: int) -> None:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result,
                    epoch=self.osd.osdmap.epoch))

            self.backend.submit_transaction(msg.oid, msg.data, on_commit)
        else:
            self.rep_backend.write(msg.oid, msg.data, full=True)
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

    def _do_partial_write(self, msg: MOSDOp) -> None:
        """Offset write / append: rmw on EC pools, splice on replicated
        (PrimaryLogPG do_osd_ops CEPH_OSD_OP_WRITE/APPEND)."""
        offset = None if msg.op == CEPH_OSD_OP_APPEND else msg.offset
        if self.backend is not None:
            src = msg.src

            def on_commit(result: int) -> None:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result,
                    epoch=self.osd.osdmap.epoch))

            self.backend.submit_write(msg.oid, msg.data, offset, on_commit)
        else:
            self.rep_backend.write(msg.oid, msg.data, offset=offset)
            self.osd.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))

    def _do_read(self, msg: MOSDOp) -> None:
        if self.backend is not None:
            src = msg.src

            def on_complete(result: int, data: bytes) -> None:
                self.osd.send_op_reply(src, MOSDOpReply(
                    tid=msg.tid, result=result, data=data,
                    epoch=self.osd.osdmap.epoch))

            self.backend.objects_read_and_reconstruct(
                msg.oid, on_complete, offset=msg.offset, length=msg.length)
        else:
            data = self.rep_backend.read(msg.oid)
            if data is None:
                self.osd.send_op_reply(msg.src,
                                       MOSDOpReply(tid=msg.tid, result=-2))
            else:
                if msg.length:
                    data = data[msg.offset:msg.offset + msg.length]
                elif msg.offset:
                    data = data[msg.offset:]
                self.osd.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=0, data=data,
                    epoch=self.osd.osdmap.epoch))

    def _do_stat(self, msg: MOSDOp) -> None:
        store = self.osd.store
        if self.backend is not None:
            shard = self.my_shard()
            cid = self.backend.shard_cid(shard)
            ho = hobject_t(msg.oid, shard)
        else:
            cid = self.rep_backend.cid()
            ho = hobject_t(msg.oid)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            self.osd.send_op_reply(msg.src,
                                   MOSDOpReply(tid=msg.tid, result=-2))
            return
        size = struct.unpack("<Q", store.getattr(cid, ho, SIZE_ATTR))[0]
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, data=struct.pack("<Q", size),
            epoch=self.osd.osdmap.epoch))

    def _do_delete(self, msg: MOSDOp) -> None:
        from ..msg.messages import MOSDECSubOpWrite
        if self.backend is not None:
            for shard, osd in self.acting_shards().items():
                m = MOSDECSubOpWrite(tid=-msg.tid, pgid=self.pgid,
                                     shard=shard, oid=msg.oid, chunk=b"",
                                     at_version=-1)
                self.send_to_osd(osd, m)
        else:
            for osd in self.acting:
                if osd == CRUSH_ITEM_NONE:
                    continue
                m = MOSDECSubOpWrite(tid=-msg.tid, pgid=self.pgid,
                                     shard=-1, oid=msg.oid, chunk=b"",
                                     at_version=-1)
                self.send_to_osd(osd, m)
        self.osd.send_op_reply(msg.src, MOSDOpReply(
            tid=msg.tid, result=0, epoch=self.osd.osdmap.epoch))
