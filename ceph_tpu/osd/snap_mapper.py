"""Snap -> object index driving the snap trimmer (SnapMapper role).

The reference maintains a persistent omap index from snap id to the
objects whose clones contain that snap (``src/osd/SnapMapper.h``: the
``MAP_`` / ``OBJ_`` key families), so `get_next_objects_to_trim`
(`src/osd/SnapMapper.cc`) hands the trimmer exactly the objects that
matter instead of scanning the whole PG.  It pairs the index with
``pg_info_t.purged_snaps`` so a primary that dies mid-trim is resumed
by its successor: at activation the new primary compares the pool's
``removed_snaps`` against what was actually purged and finishes the
difference.

This module is the TPU-framework analog.  Differences from the
reference, on purpose:

- The index is **derived, not persisted**.  Every replica already
  persists the per-head snapsets in its PG meta object; a clone entry
  ``(seq, CLONE)`` with predecessor ``prev`` covers exactly the snap
  ids in ``(prev, seq]``.  Rebuilding the index at PG load is one pass
  over the loaded snapsets — so there is nothing extra to keep
  consistent on disk, and a mapper bug can never strand on-disk state.
- ``purged_snaps`` IS persisted (one omap key in the PG meta object)
  and rides peering (`MOSDPGInfo.purged_snaps`) so the
  primary-died-before-trimming case converges: the reference keeps it
  in ``pg_info_t`` for the same reason (`src/osd/osd_types.h`).

Live AND removed snaps are indexed — deliberately including purged
ones: the index is a truthful "who still references this snap", which
lets the trimmer detect (and redo) a purge whose marker survived a
crash that swallowed the actual trim work.  Snap ids only grow, so a
new snap can never fall inside an existing clone's window — the index
never needs reindexing on map change, only on snapset change.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterable, List, Set, Tuple

from .pg_log import SNAP_TRIMMED

# omap key in the PG meta object holding the packed purged-snap ids
PURGED_SNAPS_KEY = "purged_snaps"


class SnapMapper:
    """In-memory two-way index: snap id <-> head oids with a clone (or
    whiteout) whose window covers that snap."""

    def __init__(self) -> None:
        self.by_snap: Dict[int, Set[str]] = {}
        self.by_oid: Dict[str, Set[int]] = {}

    # ---- queries -----------------------------------------------------------
    def lookup(self, snap: int) -> Set[str]:
        """Head oids whose snapset still references *snap* (the
        get_next_objects_to_trim role, without the paging)."""
        return set(self.by_snap.get(snap, ()))

    @staticmethod
    def covered_snaps(entries: List[Tuple[int, int]],
                      interesting: Iterable[int]) -> Set[int]:
        """Snap ids from *interesting* covered by any non-tombstone
        entry's window (prev_seq, seq]."""
        out: Set[int] = set()
        if not entries:
            return out
        snaps = sorted(interesting)
        prev = 0
        for seq, kind in entries:
            if kind != SNAP_TRIMMED:
                for sid in snaps:
                    if prev < sid <= seq:
                        out.add(sid)
            prev = seq
        return out

    # ---- maintenance -------------------------------------------------------
    def update_oid(self, oid: str, entries: List[Tuple[int, int]],
                   interesting: Iterable[int]) -> None:
        """Recompute *oid*'s memberships after its snapset changed
        (clone taken, trim applied, peer snapset adopted, delete)."""
        new = self.covered_snaps(entries, interesting)
        old = self.by_oid.get(oid, set())
        for sid in old - new:
            objs = self.by_snap.get(sid)
            if objs is not None:
                objs.discard(oid)
                if not objs:
                    del self.by_snap[sid]
        for sid in new - old:
            self.by_snap.setdefault(sid, set()).add(oid)
        if new:
            self.by_oid[oid] = new
        else:
            self.by_oid.pop(oid, None)

    def rebuild(self, snapsets: Dict[str, List[Tuple[int, int]]],
                interesting: Iterable[int]) -> None:
        """One pass over the loaded snapsets (PG mount)."""
        self.by_snap.clear()
        self.by_oid.clear()
        snaps = set(interesting)
        for oid, entries in snapsets.items():
            self.update_oid(oid, entries, snaps)


# ---- purged_snaps persistence (pg_info_t.purged_snaps role) ----------------

def encode_purged(purged: Set[int]) -> bytes:
    return b"".join(struct.pack("<Q", s) for s in sorted(purged))


def decode_purged(blob: bytes) -> Set[int]:
    return {struct.unpack_from("<Q", blob, off)[0]
            for off in range(0, len(blob), 8)}


def stage_purged(t, cid: str, purged: Set[int]) -> None:
    """Stage the purged-snap set into the PG meta object (same
    transaction as the trim it records)."""
    from .pg_log import PG_META_OID
    from ..os_store import hobject_t
    meta = hobject_t(PG_META_OID)
    t.touch(cid, meta)
    t.omap_setkeys(cid, meta, {PURGED_SNAPS_KEY: encode_purged(purged)})


def load_purged(store, cid: str) -> Set[int]:
    from .pg_log import PG_META_OID
    from ..os_store import hobject_t
    meta = hobject_t(PG_META_OID)
    if not store.collection_exists(cid) or not store.exists(cid, meta):
        return set()
    blob = store.omap_get(cid, meta).get(PURGED_SNAPS_KEY)
    return decode_purged(blob) if blob else set()
