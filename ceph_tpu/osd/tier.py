"""Cache tiering — writeback promote/flush/evict over a 2-pool tier.

The reference layers a replicated CACHE pool over a BASE pool
(osd_types.h pg_pool_t tier fields; PrimaryLogPG.cc hit_set_setup,
promote_object, agent_work; HitSet.h): clients are redirected to the
cache by read_tier/write_tier, a miss promotes the object from the
base, writes dirty the cache copy, and a background agent flushes
cold dirty objects down and evicts cold clean ones.  This module is
that machinery for the cache PG's primary:

- ``intercept(msg)``: record the access in the PG's hit sets; on a
  miss that needs the object's bytes, start a promote (an OSD-side
  Objecter-lite op to the base pool) and requeue the op behind it.
- ``agent_work(now)``: rotate hit sets each hit_set_period; flush
  dirty objects that fell out of every hit set (write_full to the
  base); evict cold clean objects while the cache sits over
  target_max_objects.

Dirty markers persist in the PG meta omap (``dt\\x00<oid>``) so a
restarted cache OSD still knows what it owes the base pool.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Set

from ..common.dout import dlog
from ..msg.messages import (
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_CMPXATTR, CEPH_OSD_OP_DELETE,
    CEPH_OSD_OP_GETXATTR, CEPH_OSD_OP_GETXATTRS,
    CEPH_OSD_OP_OMAPGETVALS, CEPH_OSD_OP_OMAPRMKEYS,
    CEPH_OSD_OP_OMAPSETKEYS, CEPH_OSD_OP_READ, CEPH_OSD_OP_RMXATTR,
    CEPH_OSD_OP_SETXATTR, CEPH_OSD_OP_STAT, CEPH_OSD_OP_TRUNCATE,
    CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL, CEPH_OSD_OP_ZERO,
    MOSDOp, OSDOp,
)
from ..msg.kv import unpack_kv
from ..os_store import Transaction, hobject_t
from .hit_set import HitSetHistory
from .pg_log import PG_META_OID

DIRTY_KEY_PREFIX = "dt\x00"      # meta omap namespace for dirty markers

# ops that need the object's existing state: a cache miss on these
# must promote before executing (WRITEFULL replaces wholesale; xattr
# and omap ops read-modify the promoted copy's metadata)
_NEED_BODY = frozenset([
    CEPH_OSD_OP_READ, CEPH_OSD_OP_STAT, CEPH_OSD_OP_WRITE,
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_TRUNCATE, CEPH_OSD_OP_ZERO,
    CEPH_OSD_OP_GETXATTR, CEPH_OSD_OP_GETXATTRS, CEPH_OSD_OP_CMPXATTR,
    CEPH_OSD_OP_SETXATTR, CEPH_OSD_OP_RMXATTR,
    CEPH_OSD_OP_OMAPGETVALS, CEPH_OSD_OP_OMAPSETKEYS,
    CEPH_OSD_OP_OMAPRMKEYS,
])
_MUTATES = frozenset([
    CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL, CEPH_OSD_OP_APPEND,
    CEPH_OSD_OP_TRUNCATE, CEPH_OSD_OP_ZERO, CEPH_OSD_OP_DELETE,
    CEPH_OSD_OP_SETXATTR, CEPH_OSD_OP_RMXATTR,
    CEPH_OSD_OP_OMAPSETKEYS, CEPH_OSD_OP_OMAPRMKEYS,
])


class TierState:
    """Per-cache-PG tiering state, owned by the PG (primary-driven)."""

    def __init__(self, pg):
        self.pg = pg
        self.base_pool = pg.pool.tier_of     # survives tier removal
        self.hit_sets = HitSetHistory(pg.pool.hit_set_count)
        # oid -> mutation seq: a flush only clears the marker if no
        # NEWER write landed while it was in flight
        self.dirty: Dict[str, int] = {}
        self._promoting: Dict[str, List[Callable[[], None]]] = {}
        self._promote_miss: Set[str] = set()
        self._flushing: Set[str] = set()
        # tier removed: drain every dirty object to the base, then the
        # PG drops this state (reference: flush/evict-all before
        # tearing the overlay down)
        self.shutting_down = False
        self._load_dirty()

    # ---- persistence -------------------------------------------------------
    def _meta(self):
        return self.pg.meta_cid(), hobject_t(PG_META_OID)

    def _load_dirty(self) -> None:
        store = self.pg.osd.store
        cid, meta = self._meta()
        if not store.collection_exists(cid) or \
                not store.exists(cid, meta):
            return
        for k in store.omap_get(cid, meta):
            if k.startswith(DIRTY_KEY_PREFIX):
                self.dirty[k[len(DIRTY_KEY_PREFIX):]] = 1

    def _mark_dirty(self, oid: str, dirty: bool) -> None:
        if dirty:
            was = oid in self.dirty
            # ALWAYS bump the seq: an in-flight flush must not clear a
            # marker that a newer write re-dirtied
            self.dirty[oid] = self.dirty.get(oid, 0) + 1
            if was:
                return          # marker already persisted
        else:
            if oid not in self.dirty:
                return
            del self.dirty[oid]
        t = Transaction()
        cid = self.pg.ensure_meta_collection(t)
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        if dirty:
            t.omap_setkeys(cid, meta, {DIRTY_KEY_PREFIX + oid: b"1"})
        else:
            t.omap_rmkeys(cid, meta, [DIRTY_KEY_PREFIX + oid])
        self.pg.osd.store.queue_transaction(t)

    # ---- op interception ---------------------------------------------------
    def _have(self, oid: str) -> bool:
        exists, *_ = self.pg.rep_backend.object_state(oid)
        return exists

    def intercept(self, msg: MOSDOp) -> bool:
        """Returns True when the op was parked behind a promote; the
        op re-dispatches once the base copy lands."""
        pg = self.pg
        oid = msg.oid
        self.hit_sets.record(oid)
        ops = msg.ops or [OSDOp(op=msg.op)]
        mutates = any(o.op in _MUTATES for o in ops)
        needs_body = any(o.op in _NEED_BODY for o in ops)
        if any(o.op == CEPH_OSD_OP_DELETE for o in ops):
            # deletes write through: a promote must never resurrect a
            # deleted object from the base (the reference's whiteout
            # role, collapsed to synchronous base deletion)
            pg.osd.tier_submit(self.base_pool, oid,
                               [OSDOp(op=CEPH_OSD_OP_DELETE)],
                               lambda _r: None)
            self._mark_dirty(oid, False)
        elif mutates:
            self._mark_dirty(oid, True)
        # NOTE: intercept stays FULLY active while a removed tier
        # drains (shutting_down): skipping the promote for a needs-body
        # mutation would execute it against a missing cache copy and
        # the drain would then flush that partial body over the intact
        # base object — the promote path is still safe (the base pool
        # is still there to read from).
        if oid in self._promoting:
            self._promoting[oid].append(lambda: pg.do_op(msg))
            return True
        if needs_body and not self._have(oid) and \
                oid not in self._promote_miss:
            self._promote(oid, lambda: pg.do_op(msg))
            return True
        return False

    def _promote(self, oid: str, then: Callable[[], None]) -> None:
        """Fetch body + user xattrs from the base pool, materialize the
        object in the cache CLEAN, then run the parked ops
        (PrimaryLogPG::promote_object)."""
        pg = self.pg
        self._promoting[oid] = [then]
        dlog("pg", 5, f"tier promote {oid} from pool {pg.pool.tier_of}",
             f"osd.{pg.osd.osd_id}")

        def on_reply(reply) -> None:
            if reply.result == 0 and reply.op_results:
                data = reply.op_results[0][1]
                attrs = {}
                if len(reply.op_results) > 1 and \
                        reply.op_results[1][0] >= 0:
                    attrs = unpack_kv(reply.op_results[1][1])
                pg.rep_backend.write(oid, data, full=True,
                                     version=pg.next_version(),
                                     xattrs=attrs)
            elif reply.result == -2:
                # base ENOENT: remember the miss while the parked ops
                # re-dispatch, or they would re-promote forever; the
                # ops then answer for the absent object themselves
                self._promote_miss.add(oid)
            # any other result is transient (timeout, primary down):
            # neither materialize nor mark — the re-dispatch below
            # starts a fresh promote
            cbs = self._promoting.pop(oid, [])
            try:
                for cb in cbs:
                    cb()
            finally:
                self._promote_miss.discard(oid)

        pg.osd.tier_submit(
            self.base_pool, oid,
            [OSDOp(op=CEPH_OSD_OP_READ),
             OSDOp(op=CEPH_OSD_OP_GETXATTRS)], on_reply)

    # ---- the agent ---------------------------------------------------------
    def agent_work(self, now: float) -> None:
        """Flush cold dirty objects; evict cold clean ones over target
        (PrimaryLogPG::agent_work).  In shutdown (tier removed) every
        dirty object flushes regardless of temperature, and the PG
        drops the tier state once drained."""
        pg = self.pg
        self.hit_sets.maybe_rotate(now, pg.pool.hit_set_period)
        for oid in sorted(self.dirty):
            if oid in self._flushing or \
                    (not self.shutting_down
                     and self.hit_sets.contains(oid)):
                continue
            self._flush(oid)
        if self.shutting_down:
            if not self.dirty and not self._flushing:
                pg.tier = None      # drained: the overlay is gone
            return
        target = pg.pool.target_max_objects
        if not target:
            return
        # pool-wide target split across PGs (agent_choose_mode's
        # per-PG divide of target_max_objects)
        target = max(1, target // max(pg.pool.pg_num, 1))
        objs = sorted(o.oid for o in pg.osd.store.list_objects(
            pg.rep_backend.cid())
            if not o.oid.startswith("_"))
        over = len(objs) - target
        for oid in objs:
            if over <= 0:
                break
            if oid in self.dirty or oid in self._flushing or \
                    self.hit_sets.contains(oid):
                continue
            dlog("pg", 5, f"tier evict {oid}", f"osd.{pg.osd.osd_id}")
            pg._fan_delete(oid)
            over -= 1

    def _flush(self, oid: str) -> None:
        pg = self.pg
        exists, data, xattrs, _omap = pg.rep_backend.object_state(oid)
        if not exists:
            self._mark_dirty(oid, False)
            return
        self._flushing.add(oid)
        dlog("pg", 5, f"tier flush {oid} -> pool {pg.pool.tier_of}",
             f"osd.{pg.osd.osd_id}")
        ops = [OSDOp(op=CEPH_OSD_OP_WRITEFULL, data=bytes(data))]
        for k, v in xattrs.items():
            ops.append(OSDOp(op=CEPH_OSD_OP_SETXATTR, name=k,
                             data=bytes(v)))
        if _omap:
            # EC base pools reject omap (-95): the flush then fails loud
            # and the object stays dirty, rather than dropping the keys
            from ..msg.kv import pack_kv
            ops.append(OSDOp(op=CEPH_OSD_OP_OMAPSETKEYS,
                             data=pack_kv(_omap)))
        seq = self.dirty.get(oid, 0)

        def on_reply(reply) -> None:
            self._flushing.discard(oid)
            if reply.result == 0 and self.dirty.get(oid) == seq:
                # only clear if no NEWER write landed mid-flight
                self._mark_dirty(oid, False)
            # otherwise stay dirty and retry on the next agent pass

        pg.osd.tier_submit(self.base_pool, oid, ops, on_reply)
