"""ECUtil — stripe bookkeeping between the object store and the EC codec.

The reference loops stripes one at a time through the plugin
(src/osd/ECUtil.cc:120-159 encode, :9-45 decode) because its codecs are
CPU-SIMD calls.  Here the whole multi-stripe payload is reshaped into one
(S, k, C) uint8 tensor and handed to the codec's batched device entry
points when it has them (ErasureCodeTpu.encode_batch), falling back to the
reference's per-stripe loop for host-only codecs — results are identical
either way, per-shard buffers are the stripe-concatenated chunks.

HashInfo mirrors osd/ECUtil.cc:161-207: cumulative per-shard crc32c seeded
with -1, appended as shards grow.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..trace.devprof import g_devprof
from ..trace.oplat import g_oplat
from ..utils.crc32c import crc32c

CHUNK_ALIGNMENT = 64
CHUNK_INFO = 8
CHUNK_PADDING = 8
CHUNK_OVERHEAD = 16


class stripe_info_t:
    """(stripe_size=k, stripe_width=k*chunk_size) (ECUtil.h:31-76)."""

    def __init__(self, stripe_size: int, stripe_width: int):
        assert stripe_width % stripe_size == 0
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def get_stripe_width(self) -> int:
        return self.stripe_width

    def get_chunk_size(self) -> int:
        return self.chunk_size

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return ((offset + self.stripe_width - 1)
                // self.stripe_width) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset if not rem else offset - rem + self.stripe_width

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def offset_len_to_stripe_bounds(self, offset: int, length: int):
        start = self.logical_to_prev_stripe_offset(offset)
        end = self.logical_to_next_stripe_offset(offset + length)
        return start, end - start


def _pack_rows(want_l, rows) -> Dict[int, np.ndarray]:
    """ONE materialized pack: every wanted shard's body lands in a
    single contiguous (n_want, S*C) buffer and the per-shard outputs
    are row VIEWS of it.  Downstream fan-out sends zero-copy
    memoryviews of these rows, replacing the old per-shard
    ``ecutil.shard_slice`` materialization + ``ec.subop_messages``
    re-materialization pair with one accounted copy."""
    rows = list(rows)
    S, C = rows[0].shape
    pack = np.empty((len(want_l), S * C), dtype=np.uint8)
    for j, src in enumerate(rows):
        pack[j].reshape(S, C)[:] = src
    g_devprof.account_host_copy("ecutil.pack_shards", pack.nbytes)
    return {i: pack[j] for j, i in enumerate(want_l)}


def encode(sinfo: stripe_info_t, ec_impl, data,
           want: Set[int]) -> Dict[int, np.ndarray]:
    """Erasure-code a stripe-aligned payload; returns shard id -> buffer.

    Batched: all S stripes go through the codec in one call when it
    provides encode_batch (the device path); otherwise the reference's
    stripe loop runs (identical output).
    """
    buf = np.frombuffer(bytes(data), dtype=np.uint8) \
        if not isinstance(data, np.ndarray) else data
    logical_size = len(buf)
    assert logical_size % sinfo.get_stripe_width() == 0
    if logical_size == 0:
        return {}
    S = logical_size // sinfo.get_stripe_width()
    k = ec_impl.get_data_chunk_count()
    n = ec_impl.get_chunk_count()
    C = sinfo.get_chunk_size()

    prepare = getattr(ec_impl, "regen_prepare_batch", None)
    if prepare is not None and hasattr(ec_impl, "encode_batch"):
        # product-matrix regenerating codes (ec/regenerating.py): the
        # payload assembles into batched message matrices first, and
        # ONE Ψ projection yields every shard row (full-output codec —
        # there is no systematic passthrough set)
        allc = ec_impl.encode_batch(prepare(buf, S))     # (S, n, C)
        g_oplat.checkpoint("device_call")
        want_l = sorted(want)
        return _pack_rows(want_l, (allc[:, i, :] for i in want_l))
    if hasattr(ec_impl, "encode_batch_full"):
        # mapped layered codes (lrc): one batched call yields every
        # physical chunk directly
        stripes = buf.reshape(S, k, C)
        allc = ec_impl.encode_batch_full(stripes)     # (S, n, C)
        # stage ledger: the codec call returned; the submitting op's
        # d2h stage (stamped by the dispatcher) covers the pack below
        g_oplat.checkpoint("device_call")
        want_l = sorted(want)
        return _pack_rows(want_l, (allc[:, i, :] for i in want_l))
    if hasattr(ec_impl, "encode_batch") and not ec_impl.get_chunk_mapping():
        stripes = buf.reshape(S, k, C)
        coding = ec_impl.encode_batch(stripes)        # (S, m, C)
        g_oplat.checkpoint("device_call")
        want_l = sorted(want)
        return _pack_rows(want_l,
                          (stripes[:, i, :] if i < k
                           else coding[:, i - k, :] for i in want_l))

    out_parts: Dict[int, List[np.ndarray]] = {i: [] for i in want}
    w = sinfo.get_stripe_width()
    for s in range(S):
        encoded = ec_impl.encode(want, buf[s * w:(s + 1) * w])
        for i, chunk in encoded.items():
            assert len(chunk) == C
            out_parts[i].append(chunk)
    # host-only codec loop: the "device_call" stage is the codec call
    # by definition, wherever it executes
    g_oplat.checkpoint("device_call")
    want_l = sorted(want)
    pack = np.empty((len(want_l), S * C), dtype=np.uint8)
    for j, i in enumerate(want_l):
        row = pack[j].reshape(S, C)
        for s, chunk in enumerate(out_parts[i]):
            row[s] = chunk
    g_devprof.account_host_copy("ecutil.pack_shards", pack.nbytes)
    return {i: pack[j] for j, i in enumerate(want_l)}


def decode_concat(sinfo: stripe_info_t, ec_impl,
                  to_decode: Dict[int, np.ndarray]) -> np.ndarray:
    """Rebuild the full logical payload from whole-object shards
    (ECUtil.cc:9-45)."""
    assert to_decode
    total = len(next(iter(to_decode.values())))
    C = sinfo.get_chunk_size()
    assert total % C == 0
    for b in to_decode.values():
        assert len(b) == total
    if total == 0:
        return np.zeros(0, dtype=np.uint8)
    S = total // C
    k = ec_impl.get_data_chunk_count()
    chunks2d = {i: np.asarray(b, dtype=np.uint8).reshape(S, C)
                for i, b in to_decode.items()}
    if hasattr(ec_impl, "decode_payload_batch"):
        # non-systematic regenerating codes: no shard holds raw data
        # rows — the codec reconstructs the logical payload directly
        # from any k shard chunks (structured product-matrix decode)
        data = ec_impl.decode_payload_batch(chunks2d)    # (S, width)
        g_oplat.checkpoint("device_call")
        return np.ascontiguousarray(data).reshape(-1)
    if hasattr(ec_impl, "decode_batch"):
        # decode_batch is keyed by *physical* chunk ids; logical data row
        # i lives at chunk_index(i) for mapped codes (lrc)
        want_phys = [ec_impl.chunk_index(i) for i in range(k)]
        got = ec_impl.decode_batch(chunks2d, want_phys)
        g_oplat.checkpoint("device_call")
        data = np.stack([got[want_phys[i]] for i in range(k)],
                        axis=1)  # (S, k, C)
        return data.reshape(-1)
    outs = []
    for s in range(S):
        chunks = {i: b[s] for i, b in chunks2d.items()}
        outs.append(np.frombuffer(
            ec_impl.decode_concat(chunks), dtype=np.uint8))
    g_oplat.checkpoint("device_call")
    return np.concatenate(outs)


def decode(sinfo: stripe_info_t, ec_impl,
           to_decode: Dict[int, np.ndarray],
           need: Sequence[int]) -> Dict[int, np.ndarray]:
    """Reconstruct specific shards across all stripes (ECUtil.cc:47-118),
    e.g. recovery of a failed OSD's chunk for a whole object."""
    assert to_decode
    total = len(next(iter(to_decode.values())))
    C = sinfo.get_chunk_size()
    if total == 0:
        return {i: np.zeros(0, dtype=np.uint8) for i in need}
    S = total // C
    chunks2d = {i: np.asarray(b, dtype=np.uint8).reshape(S, C)
                for i, b in to_decode.items()}
    if hasattr(ec_impl, "decode_batch"):
        got = ec_impl.decode_batch(chunks2d, list(need))
        g_oplat.checkpoint("device_call")
        return {i: np.ascontiguousarray(got[i]).reshape(-1) for i in need}
    out_parts: Dict[int, List[np.ndarray]] = {i: [] for i in need}
    for s in range(S):
        chunks = {i: b[s] for i, b in chunks2d.items()}
        decoded = ec_impl.decode(set(need), chunks)
        for i in need:
            out_parts[i].append(decoded[i])
    g_oplat.checkpoint("device_call")
    return {i: np.concatenate(parts) for i, parts in out_parts.items()}


class HashInfo:
    """Cumulative per-shard crc32c (ECUtil.cc:161-207)."""

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def append(self, old_size: int,
               to_append: Dict[int, np.ndarray]) -> None:
        assert old_size == self.total_chunk_size
        size = len(next(iter(to_append.values())))
        if self.has_chunk_hash():
            assert len(to_append) == len(self.cumulative_shard_hashes)
            for i, buf in to_append.items():
                assert len(buf) == size
                self.cumulative_shard_hashes[i] = crc32c(
                    buf, self.cumulative_shard_hashes[i])
        self.total_chunk_size += size

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    def dump(self) -> dict:
        return {
            "total_chunk_size": self.total_chunk_size,
            "cumulative_shard_hashes": [
                {"shard": i, "hash": h}
                for i, h in enumerate(self.cumulative_shard_hashes)],
        }
