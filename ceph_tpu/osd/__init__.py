from .ecutil import HashInfo, stripe_info_t, encode as ecutil_encode, \
    decode as ecutil_decode, decode_concat as ecutil_decode_concat

__all__ = ["HashInfo", "stripe_info_t", "ecutil_encode", "ecutil_decode",
           "ecutil_decode_concat"]
