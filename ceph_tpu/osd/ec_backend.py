"""ECBackend — the erasure-coded PG backend (write fan-out, reads, recovery).

Mirrors the reference pipeline shapes (src/osd/ECBackend.{h,cc}):

- writes: submit_transaction → encode all stripes in ONE batched device
  call (ECUtil/encode over (S, k, C), replacing the per-stripe CPU loop at
  ECUtil.cc:136-148) → MOSDECSubOpWrite to every shard → all_commit ack
  (ECBackend.cc:1459,1793-2101).
- reads: objects_read_and_reconstruct consults the plugin's
  minimum_to_decode, fans MOSDECSubOpRead to the cheapest shard set, and
  reconstructs via the batched decode (ECBackend.cc:1580-1669,986,1141).
- recovery: RecoveryOp reads k available shards, decodes the missing
  shard's chunks, and pushes them to the replacement OSD
  (ECBackend.cc:535-743).

Chunk placement is positional: acting[i] holds shard i (chunk_mapping
applies inside the codec).  HashInfo crc32c guards every shard read
(ECUtil.cc:161-207; checked like handle_sub_read's crc path,
ECBackend.cc:1022-1066).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..msg import (
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
)
from ..os_store import MemStore, Transaction, hobject_t
from ..utils.crc32c import crc32c
from .ecutil import HashInfo, decode as ec_decode, \
    decode_concat as ec_decode_concat, encode as ec_encode, stripe_info_t

SIZE_ATTR = "_size"          # logical object size (un-padded)
HINFO_ATTR = "hinfo_key"     # reference's hinfo xattr name


@dataclass
class InflightWrite:
    tid: int
    oid: str
    client_reply: Callable[[int], None]
    pending_shards: Set[int] = field(default_factory=set)


@dataclass
class InflightRead:
    tid: int
    oid: str
    want: List[int]
    on_complete: Callable[[int, bytes], None]
    length: int = 0
    chunks: Dict[int, bytes] = field(default_factory=dict)
    pending: Set[int] = field(default_factory=set)
    failed: Set[int] = field(default_factory=set)


class ECBackend:
    """One per EC PG on its primary; shard handlers run on every OSD."""

    def __init__(self, pg, ec_impl, stripe_width: int):
        self.pg = pg                      # owning PG (provides osd/messenger)
        self.ec_impl = ec_impl
        k = ec_impl.get_data_chunk_count()
        self.sinfo = stripe_info_t(k, stripe_width)
        self.k = k
        self.n = ec_impl.get_chunk_count()
        self.inflight_writes: Dict[int, InflightWrite] = {}
        self.inflight_reads: Dict[int, InflightRead] = {}
        self._tid = 0

    # ---- helpers ----------------------------------------------------------
    def next_tid(self) -> int:
        self._tid += 1
        return self._tid

    def shard_cid(self, shard: int) -> str:
        return f"{self.pg.pgid[0]}.{self.pg.pgid[1]}s{shard}"

    def shard_oid(self, oid: str, shard: int) -> hobject_t:
        return hobject_t(oid, shard)

    def _pad(self, data: bytes) -> bytes:
        w = self.sinfo.get_stripe_width()
        rem = len(data) % w
        return data if not rem else data + b"\0" * (w - rem)

    # ---- write path (primary) --------------------------------------------
    def submit_transaction(self, oid: str, data: bytes,
                           on_commit: Callable[[int], None]) -> int:
        """Full-object EC write: one batched encode, fan out shards."""
        tid = self.next_tid()
        padded = self._pad(data)
        shards = ec_encode(self.sinfo, self.ec_impl, padded,
                           set(range(self.n)))
        op = InflightWrite(tid=tid, oid=oid, client_reply=on_commit)
        acting = self.pg.acting_shards()
        for shard, osd in acting.items():
            chunk = shards[shard].tobytes() if shard in shards else b""
            msg = MOSDECSubOpWrite(
                tid=tid, pgid=self.pg.pgid, shard=shard, oid=oid,
                chunk=chunk, offset=0, at_version=len(data))
            op.pending_shards.add(shard)
            self.pg.send_to_osd(osd, msg)
        self.inflight_writes[tid] = op
        return tid

    def handle_sub_write(self, msg: MOSDECSubOpWrite, store: MemStore
                         ) -> MOSDECSubOpWriteReply:
        """Shard-side apply (ECBackend.cc:921-983): one transaction with
        chunk data, size attr, and the updated HashInfo."""
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
        t = Transaction()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        ho = hobject_t(msg.oid, msg.shard)
        t.truncate(cid, ho, 0)
        t.write(cid, ho, msg.offset, msg.chunk)
        t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", msg.at_version))
        hi = HashInfo(1)
        hi.append(0, {0: np.frombuffer(msg.chunk, dtype=np.uint8)})
        t.setattr(cid, ho, HINFO_ATTR,
                  struct.pack("<QI", hi.total_chunk_size,
                              hi.get_chunk_hash(0)))
        store.queue_transaction(t)
        return MOSDECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                     shard=msg.shard, committed=True)

    def handle_sub_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        op = self.inflight_writes.get(msg.tid)
        if op is None:
            return
        op.pending_shards.discard(msg.shard)
        if not op.pending_shards:
            del self.inflight_writes[msg.tid]
            op.client_reply(0)

    # ---- read path (primary) ---------------------------------------------
    def objects_read_and_reconstruct(
            self, oid: str, on_complete: Callable[[int, bytes], None]
    ) -> int:
        """Route the cheapest shard set through minimum_to_decode and fan
        out reads (ECBackend.cc:1580-1669)."""
        tid = self.next_tid()
        acting = self.pg.acting_shards()
        avail = set(acting)
        # want the *physical* positions of the data chunks (chunk_mapping
        # remaps logical->physical for lrc/shec layouts)
        want = {self.ec_impl.chunk_index(i) for i in range(self.k)}
        try:
            minimum = self.ec_impl.minimum_to_decode(want, avail)
        except IOError:
            on_complete(-5, b"")  # EIO: not enough shards
            return tid
        rd = InflightRead(tid=tid, oid=oid, want=sorted(want),
                          on_complete=on_complete)
        for shard in minimum:
            msg = MOSDECSubOpRead(tid=tid, pgid=self.pg.pgid, shard=shard,
                                  oid=oid, offset=0, length=0,
                                  subchunks=list(minimum[shard]))
            rd.pending.add(shard)
            self.pg.send_to_osd(acting[shard], msg)
        self.inflight_reads[tid] = rd
        return tid

    def handle_sub_read(self, msg: MOSDECSubOpRead, store: MemStore
                        ) -> MOSDECSubOpReadReply:
        """Shard-side read + crc check (ECBackend.cc:986-1066)."""
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
        ho = hobject_t(msg.oid, msg.shard)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                        shard=msg.shard, oid=msg.oid,
                                        result=-2)  # ENOENT
        data = store.read(cid, ho)
        attrs = store.getattrs(cid, ho)
        hv = attrs.get(HINFO_ATTR)
        if hv is not None:
            total, expect = struct.unpack("<QI", hv)
            if total == len(data) and crc32c(data) != expect:
                # bit rot: fail the shard read so the primary reconstructs
                return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                            shard=msg.shard, oid=msg.oid,
                                            result=-5)
        return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                    shard=msg.shard, oid=msg.oid,
                                    data=data, attrs=attrs, result=0)

    def handle_sub_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        """Collect shard replies; reconstruct on completion
        (ECBackend.cc:1141-1281)."""
        rd = self.inflight_reads.get(msg.tid)
        if rd is None:
            return
        rd.pending.discard(msg.shard)
        if msg.result == 0:
            rd.chunks[msg.shard] = msg.data
            sz = msg.attrs.get(SIZE_ATTR)
            if sz is not None:
                rd.length = struct.unpack("<Q", sz)[0]
        else:
            rd.failed.add(msg.shard)
            # retry with reconstruction from any other shards
            acting = self.pg.acting_shards()
            others = (set(acting) - set(rd.chunks) - rd.failed
                      - rd.pending)
            for shard in others:
                m2 = MOSDECSubOpRead(tid=rd.tid, pgid=self.pg.pgid,
                                     shard=shard, oid=rd.oid)
                rd.pending.add(shard)
                self.pg.send_to_osd(acting[shard], m2)
        if rd.pending:
            return
        del self.inflight_reads[msg.tid]
        if len(rd.chunks) < self.k:
            rd.on_complete(-5, b"")
            return
        arrays = {i: np.frombuffer(b, dtype=np.uint8)
                  for i, b in rd.chunks.items()}
        try:
            data = ec_decode_concat(self.sinfo, self.ec_impl, arrays)
        except IOError:
            rd.on_complete(-5, b"")
            return
        rd.on_complete(0, data.tobytes()[:rd.length])

    # ---- recovery (ECBackend.cc:535-743) ----------------------------------
    def recover_object(self, oid: str, missing_shards: Set[int],
                       source_chunks: Dict[int, bytes],
                       logical_size: int) -> Dict[int, bytes]:
        """Decode the missing shards' chunks from k sources."""
        arrays = {i: np.frombuffer(b, dtype=np.uint8)
                  for i, b in source_chunks.items()}
        rec = ec_decode(self.sinfo, self.ec_impl, arrays,
                        sorted(missing_shards))
        return {i: rec[i].tobytes() for i in rec}
