"""ECBackend — the erasure-coded PG backend (write fan-out, reads, recovery).

Mirrors the reference pipeline shapes (src/osd/ECBackend.{h,cc}):

- full writes: submit_transaction → encode all stripes in ONE batched
  device call (ECUtil/encode over (S, k, C), replacing the per-stripe CPU
  loop at ECUtil.cc:136-148) → MOSDECSubOpWrite to every shard →
  all_commit ack (ECBackend.cc:1459,1793-2101).
- partial writes (rmw): submit_write runs the read-modify-write pipeline
  (start_rmw → try_state_to_reads → try_reads_to_commit,
  ECBackend.cc:1793,1819,1894): read the affected stripe range from the
  cheapest shard set (reconstructing if degraded), splice the new bytes,
  re-encode the whole affected range in one batched device call, and fan
  chunk-granularity deltas to every shard.  Per-object ops are pipelined
  through an ExtentCache (ExtentCache.h:23) so queued overlapping writes
  read projected extents instead of re-fetching shards.
- reads: objects_read_and_reconstruct consults the plugin's
  minimum_to_decode, fans MOSDECSubOpRead to the cheapest shard set, and
  reconstructs via the batched decode (ECBackend.cc:1580-1669,986,1141).
  Ranged reads fetch only the covering chunk range.  With a mesh up the
  reconstruct's ``decode_batch`` call shards the survivor stack across
  the chips inside the codec (docs/DISPATCH.md "Mesh-sharded degraded
  reads") — this backend sees the identical bytes either way.
- recovery: RecoveryOp reads k available shards, decodes the missing
  shard's chunks, and pushes them to the replacement OSD
  (ECBackend.cc:535-743).

Chunk placement is positional: acting[i] holds shard i (chunk_mapping
applies inside the codec).  HashInfo crc32c guards every shard read
(ECUtil.cc:161-207; checked like handle_sub_read's crc path,
ECBackend.cc:1022-1066).

Async write pipeline (``ec_pipeline_depth`` > 1): the encode no longer
blocks the op thread on ``future.result()`` — submit enqueues the
encode into the dispatch scheduler and registers a continuation
(``add_done_callback``) that fans out the per-shard sub-op writes when
the batched device call completes, so a SINGLE submitter can keep up
to ``ec_pipeline_depth`` encodes in flight per PG and the scheduler
sees real batches (docs/DISPATCH.md "Async write pipeline").  Per-oid
ordering is untouched (the per-object queue still admits one op at a
time), depth 1 (the default) is exactly the old synchronous path, and
a full window backpressures by force-flushing the scheduler inline —
never by parking the submitter on a cross-thread wait.

Sub-op write retry: every in-flight write remembers its per-shard
messages; the OSD tick (and the deterministic fabric's idle kick)
resends unacked sub-writes after ``ec_subwrite_retry_timeout``, so a
messenger-level drop no longer wedges the per-oid pipeline until
peering.  Shard-side replay is idempotent — ``handle_sub_write``
short-circuits when the stored object version already covers the
message's version and just re-acks.
"""
from __future__ import annotations

import struct
import threading

from ..common.lockdep import DebugLock, DebugRLock
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from ..common.config import g_conf
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..dispatch import g_dispatcher
from ..fault import (fault_perf_counters, g_faults, l_fault_eio_injected,
                     l_fault_eio_reconstructs)
from ..msg import (
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
)
from ..trace import (g_devprof, g_oplat, g_perf_histograms, g_tracer,
                     latency_in_bytes_axes, pipeline_axes)
from ..os_store import MemStore, Transaction, hobject_t
from ..os_store.device_shard import (DeviceShard, l_msd_crc_device,
                                     l_msd_crc_host,
                                     memstore_device_perf_counters)
from ..utils.crc32c import crc32c
from .ecutil import HashInfo, stripe_info_t

SIZE_ATTR = "_size"          # logical object size (un-padded)
DIGEST_ATTR = "_data_digest"  # crc32c recorded at full-object write
# (object_info_t::data_digest role, src/osd/osd_types.h): lets scrub
# tell WHICH copy rotted instead of just that copies differ; partial
# overwrites invalidate it (rmattr), exactly like the reference
# clears FLAG_DATA_DIGEST on unaligned writes
HINFO_ATTR = "hinfo_key"     # reference's hinfo xattr name
USER_ATTR_PREFIX = "_u_"     # user xattr namespace in shard/replica attrs

# ---- pipeline perf counters (perf dump / Prometheus) -----------------------
PIPELINE_FIRST = 93000
l_pipeline_inflight = 93001       # gauge: encodes in flight (all PGs)
l_pipeline_submitted = 93002      # ops submitted through the async path
l_pipeline_backpressure = 93003   # full-window force-flushes
l_pipeline_stale_drops = 93004    # continuations dropped by an interval
                                  # change (peering raced the encode)
l_pipeline_errors = 93005         # ops whose encode future carried an
                                  # exception (client answered EIO)
l_pipeline_subwrite_resends = 93006  # unacked sub-op writes resent
PIPELINE_LAST = 93010

_pipeline_pc: Optional[PerfCounters] = None
_pipeline_pc_lock = DebugLock("pipeline_pc::init")


def pipeline_perf_counters() -> PerfCounters:
    """The EC write pipeline's counter logger (perf dump/Prometheus)."""
    global _pipeline_pc
    if _pipeline_pc is not None:
        return _pipeline_pc
    with _pipeline_pc_lock:
        if _pipeline_pc is None:
            b = PerfCountersBuilder("pipeline", PIPELINE_FIRST,
                                    PIPELINE_LAST)
            b.add_u64(l_pipeline_inflight, "pipeline_inflight",
                      "EC write encodes currently in flight in the "
                      "dispatch scheduler (all PGs)")
            b.add_u64_counter(l_pipeline_submitted, "submitted",
                              "EC writes submitted through the async "
                              "pipeline")
            b.add_u64_counter(l_pipeline_backpressure, "backpressure",
                              "full-window force-flushes")
            b.add_u64_counter(l_pipeline_stale_drops, "stale_drops",
                              "continuations dropped by an interval "
                              "change")
            b.add_u64_counter(l_pipeline_errors, "encode_errors",
                              "encode futures resolved with an error")
            b.add_u64_counter(l_pipeline_subwrite_resends,
                              "subwrite_resends",
                              "unacked EC sub-op writes resent")
            _pipeline_pc = b.create_perf_counters()
    return _pipeline_pc


def user_attrs_of(attrs: Dict[str, bytes]) -> Dict[str, bytes]:
    """The user-visible xattrs hiding in a shard's attr dict."""
    n = len(USER_ATTR_PREFIX)
    return {k[n:]: v for k, v in attrs.items()
            if k.startswith(USER_ATTR_PREFIX)}


def stash_pre_write_state(t: Transaction, store: MemStore, pg, oid: str,
                          cid: str, ho, version: int) -> None:
    """Stash the object's pre-write state (body + every attr) into the
    PG meta omap in the same transaction as the write, so peering can
    roll this write back if it proves divergent — the role of the
    reference's append-only writes + rollback info in the PG log
    (ECTransaction.h rollback extents, ecbackend.rst:1-27)."""
    from .pg_log import encode_rollback, load_rollback, stage_rollback
    prior = load_rollback(store, pg.meta_cid(), oid)
    if prior is not None and prior[0] >= version:
        # first-writer-wins per version: a replayed fan-out (resend whose
        # log entry was dropped as stale, so the log dedup can't see it)
        # would re-stash POST-apply state here and peering's rollback
        # would then restore the wrong bytes — keep the original stash
        return
    exists = store.collection_exists(cid) and store.exists(cid, ho)
    data = store.read(cid, ho) if exists else b""
    attrs = dict(store.getattrs(cid, ho)) if exists else {}
    mcid = pg.ensure_meta_collection(t)
    stage_rollback(t, mcid, oid,
                   encode_rollback(version, exists, data, attrs))


class ExtentCache:
    """Projected in-flight object extents (src/osd/ExtentCache.h:23).

    While a per-object write pipeline is non-empty, the logical bytes each
    op produced are cached here so the next queued op's rmw pre-read hits
    memory instead of re-fetching shards.  Extents are stripe-range bytes
    (already padded); the map is trimmed when the object's pipeline drains.
    """

    def __init__(self):
        self._extents: Dict[str, List[Tuple[int, bytearray]]] = {}
        self._sizes: Dict[str, int] = {}

    def projected_size(self, oid: str) -> Optional[int]:
        return self._sizes.get(oid)

    def write(self, oid: str, offset: int, data: bytes,
              new_size: int) -> None:
        """Merge [offset, offset+len) into the extent list (sorted,
        non-overlapping, coalesced)."""
        runs = self._extents.setdefault(oid, [])
        new = (offset, bytearray(data))
        merged: List[Tuple[int, bytearray]] = []
        for off, buf in runs:
            if off + len(buf) < new[0] or new[0] + len(new[1]) < off:
                merged.append((off, buf))
                continue
            # overlap/adjacent: splice the older run around the new bytes
            lo = min(off, new[0])
            hi = max(off + len(buf), new[0] + len(new[1]))
            combined = bytearray(hi - lo)
            combined[off - lo:off - lo + len(buf)] = buf
            combined[new[0] - lo:new[0] - lo + len(new[1])] = new[1]
            new = (lo, combined)
        merged.append(new)
        merged.sort(key=lambda r: r[0])
        self._extents[oid] = merged
        self._sizes[oid] = new_size

    def read(self, oid: str, offset: int, length: int) -> Optional[bytes]:
        """The cached bytes for [offset, offset+length) iff fully covered."""
        for off, buf in self._extents.get(oid, []):
            if off <= offset and offset + length <= off + len(buf):
                return bytes(buf[offset - off:offset - off + length])
        return None

    def replace(self, oid: str, data: bytes, size: int) -> None:
        """Whole-object overwrite: drop stale extents, cache the new body."""
        self._extents[oid] = [(0, bytearray(data))]
        self._sizes[oid] = size

    def clear(self, oid: str) -> None:
        self._extents.pop(oid, None)
        self._sizes.pop(oid, None)


@dataclass
class InflightWrite:
    tid: int
    oid: str
    client_reply: Callable[[int], None]
    pending_shards: Set[int] = field(default_factory=set)
    on_all_commit: Optional[Callable[[], None]] = None
    # sub-write retry state: the exact message sent to each shard (the
    # in-process fabric passes objects by reference, so resending the
    # same object is byte-identical), the destination osd, the cluster
    # clock at the last send, and how many resend rounds have run
    sent_msgs: Dict[int, Tuple[int, object]] = field(default_factory=dict)
    last_send: float = 0.0
    resends: int = 0
    # the submitting op's stage ledger (trace/oplat): the last shard
    # ack stamps its ack_gather boundary
    ledger: object = None


@dataclass
class InflightRead:
    """One fan-out read round over a chunk range.

    ``on_done(result, data, size, attrs)``: data = decoded logical bytes
    for the stripe range covering [chunk_off, chunk_off+chunk_len)
    (padded), size = the object's logical size from shard attrs (-1 if
    unknown), attrs = the object's user xattrs (replicated on every
    shard, so any healthy reply carries them).
    """
    tid: int
    oid: str
    on_done: Callable[[int, bytes, int, Dict[str, bytes]], None]
    chunk_off: int = 0
    chunk_len: int = 0            # 0 = to end of shard
    attrs_only: bool = False
    size: int = -1
    chunks: Dict[int, bytes] = field(default_factory=dict)
    pending: Set[int] = field(default_factory=set)
    failed: Set[int] = field(default_factory=set)
    seen: int = 0                 # shards that answered at all
    saw_eio: bool = False         # any non-ENOENT shard failure (crc etc.)
    raw: bool = False             # recovery mode: deliver raw shard chunks
    repair_for: int = -1          # >=0: sub-chunk repair round for this
                                  # shard — replies carry computed helper
                                  # contributions, and NO reconstruction
                                  # retry fans out (the recovery
                                  # orchestrator owns the fallback)
    user_attrs: Dict[str, bytes] = field(default_factory=dict)
    ledger: object = None         # see InflightWrite.ledger


@dataclass
class RMWOp:
    """One queued partial write (start_rmw state, ECBackend.h:467)."""
    tid: int
    oid: str
    data: bytes
    offset: Optional[int]         # None = append at current size
    on_commit: Callable[[int], None]
    old_size: int = -1
    # the submitting op's span, captured at ENQUEUE time: a queued op
    # starts from _op_done (the sub-write-reply dispatch context, no
    # span active), so reading the thread-current span at start time
    # would trace contended ops — the slow ones — as orphans
    parent_span: object = None
    # the op's stage ledger, captured at enqueue for the same reason
    ledger: object = None


@dataclass
class FullWriteOp:
    tid: int
    oid: str
    data: bytes
    on_commit: Callable[[int], None]
    xattrs: Optional[Dict[str, bytes]] = None   # full user-attr replacement
    snapset_update: Optional[Tuple[str, bytes]] = None
    parent_span: object = None    # see RMWOp.parent_span
    ledger: object = None         # see RMWOp.ledger


@dataclass
class VectorOp:
    """A queued atomic multi-op vector (the interpreter's rmw unit).

    ``run(result, body, size, attrs)`` executes the ops against the
    fetched state and returns a commit spec — None (read-only/aborted;
    reply already sent), ("write", body, attrs, on_commit, omap),
    ("attrs", attrs, on_commit, omap) or ("delete", fan_fn, on_commit).
    Riding the per-oid queue serializes whole vectors against each
    other and the single-op write pipelines (start_rmw's guarantee).
    """
    tid: int
    oid: str
    run: Callable
    meta_only: bool = False   # no body op: fetch attrs from one shard
    parent_span: object = None    # see RMWOp.parent_span
    ledger: object = None         # see RMWOp.ledger


class ECBackend:
    """One per EC PG on its primary; shard handlers run on every OSD."""

    def __init__(self, pg, ec_impl, stripe_width: int):
        self.pg = pg                      # owning PG (provides osd/messenger)
        self.ec_impl = ec_impl
        k = ec_impl.get_data_chunk_count()
        # codecs with their own chunk geometry (product-matrix
        # regenerating codes: stored chunk != stripe_width/k) supply a
        # stripe_info through the plugin hook; classic codes keep the
        # reference shape
        mk_sinfo = getattr(ec_impl, "make_stripe_info", None)
        self.sinfo = mk_sinfo(stripe_width) if mk_sinfo is not None \
            else stripe_info_t(k, stripe_width)
        self.k = k
        self.n = ec_impl.get_chunk_count()
        self.inflight_writes: Dict[int, InflightWrite] = {}
        self.inflight_reads: Dict[int, InflightRead] = {}
        self.extent_cache = ExtentCache()
        self._oid_queues: Dict[str, Deque] = {}
        self._tid = 0
        # async write pipeline (ec_pipeline_depth > 1): encodes this PG
        # currently has in flight in the dispatch scheduler, an RLock
        # because continuations run on whichever thread flushed (the
        # submitter itself under backpressure), and a generation stamp
        # so a continuation resolving AFTER an interval change drops
        # its fan-out instead of writing into a dead acting set
        self.pipeline_inflight = 0
        self._pipeline_futs: Deque = deque()   # oldest-first pending
        self._pipeline_lock = DebugRLock("ECBackend::pipeline_lock")
        self._interval_gen = 0
        # batched-codec latency x bytes distributions, per daemon
        # (dumped under `perf histogram dump` next to the op hists)
        name = pg.osd.name
        self.hist_encode = g_perf_histograms.get(
            name, "ec_encode_latency_in_bytes_histogram",
            latency_in_bytes_axes)
        self.hist_decode = g_perf_histograms.get(
            name, "ec_decode_latency_in_bytes_histogram",
            latency_in_bytes_axes)
        # write-pipeline occupancy at encode-submit time (linear,
        # dimensionless — the mgr renderer exports raw bucket edges
        # like the dispatcher's occupancy family)
        self.hist_pipeline = g_perf_histograms.get(
            name, "pipeline_inflight_histogram", pipeline_axes)
        # pipelined submit->resolve latency (queue wait INCLUDED) —
        # kept apart from hist_encode, whose samples are pure codec
        # calls the slow-op forensics compare against
        self.hist_encode_pipelined = g_perf_histograms.get(
            name, "ec_encode_pipelined_latency_in_bytes_histogram",
            latency_in_bytes_axes)

    # ---- helpers ----------------------------------------------------------
    def next_tid(self) -> int:
        self._tid += 1
        return self._tid

    def on_change(self) -> None:
        """Interval change (new acting set): drop all in-flight state —
        the reference's ECBackend::on_change; clients resend through the
        Objecter, so unanswered ops are safe to forget.  Pipelined
        encodes still queued in the dispatcher are NOT cancelled (their
        device work may be batched with live PGs'); bumping the
        generation makes their continuations complete as no-ops."""
        self.inflight_writes.clear()
        self.inflight_reads.clear()
        self._oid_queues.clear()
        self.extent_cache = ExtentCache()
        with self._pipeline_lock:
            self._interval_gen += 1

    def shard_cid(self, shard: int) -> str:
        return f"{self.pg.pgid[0]}.{self.pg.pgid[1]}s{shard}"

    def shard_oid(self, oid: str, shard: int) -> hobject_t:
        return hobject_t(oid, shard)

    def _pad(self, data: bytes) -> bytes:
        w = self.sinfo.get_stripe_width()
        rem = len(data) % w
        if not rem:
            return data
        # stripe-align pad: the first host-side copy of the write
        # path's ledger (bufferlist bytes -> padded stripe buffer)
        out = data + b"\0" * (w - rem)
        g_devprof.account_host_copy("ec.pad_stripe_align", len(out))
        return out

    # ---- instrumented codec entry points ----------------------------------
    def _encode(self, data: bytes) -> Dict[int, np.ndarray]:
        """The one batched-encode funnel: span (tracer on) + latency x
        bytes histogram (always).  Host-side wall clock only — the
        encode itself already materializes chunks for the wire, so no
        extra device sync is introduced.  Execution goes through the
        dynamic-batching device scheduler (ceph_tpu/dispatch), which is
        an exact passthrough at the default window=0 and coalesces
        signature-equal requests from other PGs otherwise."""
        t0 = time.perf_counter()
        want = set(range(self.n))
        if g_tracer.enabled:
            with g_tracer.span("ec_encode") as sp:
                if sp is not None:      # enable() can race the check
                    sp.tags["bytes"] = len(data)
                shards = g_dispatcher.encode(self.sinfo, self.ec_impl,
                                             data, want)
        else:
            shards = g_dispatcher.encode(self.sinfo, self.ec_impl, data,
                                         want)
        self.hist_encode.inc((time.perf_counter() - t0) * 1e6, len(data))
        return shards

    def _encode_resident(self, data: bytes) \
            -> Optional[Dict[int, DeviceShard]]:
        """The zero-copy encode: fused GF matmul + crc32c in one jitted
        call, shard bodies staying on device as ``DeviceShard`` handles
        (ops/resident).  None = residency off or the codec's layout
        rules the fused kernel out — callers fall back to the classic
        funnel, byte-identical by construction."""
        if int(g_conf.get_val("os_memstore_device_bytes_max")) <= 0:
            return None
        w = self.sinfo.get_stripe_width()
        if not data or len(data) % w:
            return None
        from ..ops.resident import encode_resident_shards
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data
        stripes = buf.reshape(len(buf) // w, self.k,
                              self.sinfo.get_chunk_size())
        t0 = time.perf_counter()
        try:
            if g_tracer.enabled:
                with g_tracer.span("ec_encode") as sp:
                    if sp is not None:
                        sp.tags["bytes"] = len(data)
                        sp.tags["resident"] = True
                    shards = encode_resident_shards(self.ec_impl,
                                                    stripes)
            else:
                shards = encode_resident_shards(self.ec_impl, stripes)
        except Exception:
            # any device-side surprise degrades to the classic path —
            # a residency failure must never cost the client op
            return None
        if shards is None:
            return None
        g_oplat.checkpoint("device_call")
        self.hist_encode.inc((time.perf_counter() - t0) * 1e6, len(data))
        return shards

    def _decode_timed(self, nbytes: int, fn, *args):
        """Shared decode instrumentation (concat + shard-recovery)."""
        t0 = time.perf_counter()
        if g_tracer.enabled:
            with g_tracer.span("ec_decode") as sp:
                if sp is not None:      # enable() can race the check
                    sp.tags["bytes"] = nbytes
                out = fn(*args)
        else:
            out = fn(*args)
        self.hist_decode.inc((time.perf_counter() - t0) * 1e6, nbytes)
        return out

    def _encode_pipelined(self, data: bytes, parent_span,
                          then: Callable[[Optional[Dict[int, np.ndarray]],
                                          Optional[BaseException]],
                                         None]) -> None:
        """The write path's encode in continuation-passing style:
        ``then(shards, None)`` on success, ``then(None, exc)`` on a
        (semantic) encode failure.

        Depth <= 1 (the default) is the old synchronous call by
        construction — same funnel, inline continuation.  Depth > 1
        submits the encode as a dispatch future and returns
        immediately; the continuation runs on whichever thread flushes
        the batch (window expiry from the OSD tick, batch_max, another
        submitter's demand, or this PG's own backpressure flush), with
        the submitting op's span re-anchored so the sub_write fan-out
        and the batch_dispatch children stay on the op's trace."""
        depth = int(g_conf.get_val("ec_pipeline_depth"))
        if depth <= 1:
            # device-resident first (os_memstore_device_bytes_max > 0):
            # the fused encode+crc keeps shard bodies in HBM and the
            # fan-out passes handles — zero body d2h on this path
            shards = self._encode_resident(data)
            if shards is not None:
                then(shards, None)
                return
            # today's synchronous path by construction: any encode
            # exception propagates to the submitter exactly as before
            then(self._encode(data), None)
            return
        pc = pipeline_perf_counters()
        # window reservation is atomic with the full-check (a plain
        # check-then-increment would let N concurrent op threads
        # overshoot the depth by N-1).  Backpressure drains the window
        # by EXECUTING pending work inline — force() flushes only the
        # OLDEST request's own queue (its signature-mates, i.e. this
        # PG's backlog) so other PGs' collection windows keep
        # accumulating; a mixed-signature window falls back to the
        # scheduler-wide flush.  A submitter whose window stays full
        # after two rounds (PG-mates mid-execution on ANOTHER thread)
        # proceeds rather than spinning: the overshoot is transient
        # and bounded by the op-thread count.
        rounds = 0
        while True:
            with self._pipeline_lock:
                if self.pipeline_inflight < depth or rounds >= 2:
                    self.pipeline_inflight += 1
                    inflight = self.pipeline_inflight
                    break
                oldest = self._pipeline_futs[0] \
                    if self._pipeline_futs else None
            pc.inc(l_pipeline_backpressure)
            if oldest is not None:
                oldest.force()
            else:
                g_dispatcher.flush()
            rounds += 1
        gen = self._interval_gen
        nbytes = len(data)
        led = g_oplat.current()      # the op's stage ledger, if any
        t0 = time.perf_counter()
        sp = g_tracer.begin("ec_encode") if g_tracer.enabled else None
        if sp is not None:
            sp.tags["bytes"] = nbytes
            sp.tags["pipelined"] = True
        # the gauge counts encodes in flight across ALL PGs, so it must
        # inc/dec — a set() of this PG's count would clobber others'
        pc.inc(l_pipeline_inflight)
        self.hist_pipeline.inc(inflight)
        pc.inc(l_pipeline_submitted)
        want = set(range(self.n))
        # activate the encode span around the submit so the scheduler
        # captures it as the request's parent — batch_dispatch children
        # then hang off the submitting op exactly like the sync path
        with g_tracer.activate(sp):
            fut = g_dispatcher.submit_encode(self.sinfo, self.ec_impl,
                                             data, want)
        with self._pipeline_lock:
            self._pipeline_futs.append(fut)

        def deliver(f) -> None:
            """The PG-state half of the continuation (fan-out, version
            allocation, per-oid queue advance).  Must run under the
            same exclusion as op execution — inline in synchronous
            mode, via the sharded op queue (whose workers take
            pg.op_lock) when an op thread-pool is active."""
            if gen != self._interval_gen:
                # peering raced the encode: the acting set this op was
                # aimed at is gone; the client resends via the Objecter
                pc.inc(l_pipeline_stale_drops)
                return
            err = f.exception()      # resolved — never blocks here
            if err is not None:
                pc.inc(l_pipeline_errors)
            with g_tracer.activate(parent_span), g_oplat.activate(led):
                if err is not None:
                    then(None, err)
                else:
                    then(f.result(), None)

        def on_ready(f) -> None:
            with self._pipeline_lock:
                self.pipeline_inflight -= 1
                try:
                    self._pipeline_futs.remove(f)
                except ValueError:
                    pass
            pc.dec(l_pipeline_inflight)
            g_tracer.finish(sp)
            # submit->resolve wall time INCLUDES the collection-window
            # queue wait, so it must not pollute the sync path's pure
            # codec-latency family — pipelined ops get their own
            self.hist_encode_pipelined.inc(
                (time.perf_counter() - t0) * 1e6, nbytes)
            osd = self.pg.osd
            if getattr(osd, "op_tp", None) is not None:
                # threaded op queue: the flusher thread may hold (or
                # race) another PG's op_lock — taking this PG's lock
                # inline could deadlock AB-BA, and mutating unlocked
                # would race the workers.  Re-enter through the op
                # queue instead; a worker delivers under pg.op_lock.
                from ..common.work_queue import CLASS_CLIENT
                osd.op_wq.enqueue(self.pg.pgid, CLASS_CLIENT,
                                  ("pipeline", self.pg,
                                   lambda: deliver(f)))
            else:
                deliver(f)

        fut.add_done_callback(on_ready)

    # ---- per-object write pipeline ----------------------------------------
    def _enqueue(self, oid: str, op) -> None:
        q = self._oid_queues.setdefault(oid, deque())
        q.append(op)
        if len(q) == 1:
            self._start_op(op)

    def _op_done(self, oid: str) -> None:
        q = self._oid_queues.get(oid)
        if not q:
            return
        q.popleft()
        if q:
            self._start_op(q[0])
        else:
            del self._oid_queues[oid]
            self.extent_cache.clear(oid)

    def _start_op(self, op) -> None:
        # re-enter the submitting op's span context: head-of-queue ops
        # start inline under it anyway, but a QUEUED op starts from
        # _op_done where no (or an unrelated) span is current — the
        # stage ledger re-anchors the same way
        with g_tracer.activate(op.parent_span), \
                g_oplat.activate(op.ledger):
            if isinstance(op, FullWriteOp):
                self._start_full_write(op)
            elif isinstance(op, VectorOp):
                self._start_vector(op)
            else:
                self._start_rmw(op)

    # ---- write path (primary) --------------------------------------------
    def submit_transaction(self, oid: str, data: bytes,
                           on_commit: Callable[[int], None],
                           xattrs: Optional[Dict[str, bytes]] = None,
                           snapset_update: Optional[Tuple[str, bytes]]
                           = None) -> int:
        """Full-object EC write: one batched encode, fan out shards.

        ``xattrs``: full replacement set of user xattrs riding the same
        shard transactions (ECTransaction attr updates); None leaves the
        shards' existing user attrs alone."""
        tid = self.next_tid()
        self._enqueue(oid, FullWriteOp(tid=tid, oid=oid, data=bytes(data),
                                       on_commit=on_commit, xattrs=xattrs,
                                       snapset_update=snapset_update,
                                       parent_span=g_tracer.current(),
                                       ledger=g_oplat.current()))
        return tid

    def submit_vector(self, oid: str, run: Callable,
                      meta_only: bool = False) -> int:
        """Queue an atomic multi-op vector behind this object's other
        writes (see VectorOp)."""
        tid = self.next_tid()
        self._enqueue(oid, VectorOp(tid=tid, oid=oid, run=run,
                                    meta_only=meta_only,
                                    parent_span=g_tracer.current(),
                                    ledger=g_oplat.current()))
        return tid

    def _start_vector(self, op: VectorOp) -> None:
        """Head-of-queue vector execution: fetch state (attrs-only probe
        for pure-metadata vectors; whole-object decode otherwise), run
        the interpreter, start the committed mutation — exactly one
        _op_done fires when the commit (or the read-only reply) lands."""

        def have_state(res: int, body: bytes, size: int,
                       attrs: Dict[str, bytes]) -> None:
            spec = op.run(res, body, size, attrs)
            if spec is None:
                self._op_done(op.oid)
                return
            kind = spec[0]
            if kind == "write":
                _, body2, attrs2, on_commit, _omap = spec
                # _start_full_write's all_commit pops the queue head —
                # which is this VectorOp
                self._start_full_write(FullWriteOp(
                    tid=op.tid, oid=op.oid, data=bytes(body2),
                    on_commit=on_commit, xattrs=attrs2,
                    parent_span=op.parent_span, ledger=op.ledger))
            elif kind == "attrs":
                _, attrs2, on_commit, _omap = spec
                # have_state runs from a read-reply callback: re-anchor
                # the op's ledger so the attr fan's fan_out/ack_gather
                # stages attribute to it
                with g_oplat.activate(op.ledger):
                    self._fan_attrs(op.tid, op.oid, attrs2,
                                    lambda r: (on_commit(r),
                                               self._op_done(op.oid)))
            else:  # ("delete", fan_fn, on_commit)
                _, fan_fn, on_commit = spec
                self.extent_cache.clear(op.oid)
                fan_fn()
                on_commit(0)
                self._op_done(op.oid)

        if op.meta_only:
            self._start_read(
                op.oid, 0, 0, True,
                lambda res, _d, size, attrs: have_state(res, b"", size,
                                                        attrs))
        else:
            self.object_state(op.oid, have_state)

    def _fan_attrs(self, tid: int, oid: str, xattrs: Dict[str, bytes],
                   on_commit: Callable[[int], None]) -> None:
        """Metadata-only mutation: replace the user xattrs on every
        shard without touching the body (a versioned, logged write).
        Only called at the head of the per-oid queue."""
        wr = InflightWrite(tid=tid, oid=oid, client_reply=on_commit,
                           on_all_commit=lambda: on_commit(0),
                           ledger=g_oplat.current())
        acting = self.pg.acting_shards()
        version = self.pg.next_version()
        for shard, osd in acting.items():
            msg = MOSDECSubOpWrite(
                tid=tid, pgid=self.pg.pgid, shard=shard, oid=oid,
                chunk=b"", attr_only=True, xattrs=dict(xattrs),
                version=version)
            wr.pending_shards.add(shard)
            wr.sent_msgs[shard] = (osd, msg)
            self.pg.send_to_osd(osd, msg)
        if wr.ledger is not None:
            wr.ledger.mark("fan_out")
        wr.last_send = self.pg.osd.now
        self.inflight_writes[tid] = wr

    def submit_write(self, oid: str, data: bytes, offset: Optional[int],
                     on_commit: Callable[[int], None]) -> int:
        """Partial write (offset) or append (offset=None): rmw pipeline."""
        tid = self.next_tid()
        self._enqueue(oid, RMWOp(tid=tid, oid=oid, data=bytes(data),
                                 offset=offset, on_commit=on_commit,
                                 parent_span=g_tracer.current(),
                                 ledger=g_oplat.current()))
        return tid

    def _start_full_write(self, op: FullWriteOp) -> None:
        # reached both from _start_op and from a VectorOp's read
        # callback, so re-anchor the span + ledger context here
        with g_tracer.activate(op.parent_span), \
                g_oplat.activate(op.ledger):
            padded = self._pad(op.data)

            def have_shards(shards, err) -> None:
                if err is not None:
                    # the encode future carried an error (semantic —
                    # device failures already degraded to the CPU twin
                    # inside the guard): the client op must still
                    # complete, as EIO
                    op.on_commit(-5)
                    self._op_done(op.oid)
                    return

                def all_commit() -> None:
                    self.extent_cache.replace(op.oid, padded,
                                              len(op.data))
                    op.on_commit(0)
                    self._op_done(op.oid)

                self._fan_out_shards(op.tid, op.oid, shards, chunk_off=0,
                                     partial=False,
                                     new_size=len(op.data),
                                     on_all_commit=all_commit,
                                     client_reply=op.on_commit,
                                     version=self.pg.next_version(),
                                     xattrs=op.xattrs,
                                     snapset_update=op.snapset_update)

            self._encode_pipelined(padded, op.parent_span, have_shards)

    # ---- rmw pipeline (start_rmw, ECBackend.cc:1793) -----------------------
    def _start_rmw(self, op: RMWOp) -> None:
        # 1. learn the object's current (projected) size
        projected = self.extent_cache.projected_size(op.oid)
        if projected is not None:
            self._rmw_have_size(op, projected)
            return
        local = self._local_size(op.oid)
        if local is not None:
            self._rmw_have_size(op, local)
            return
        # degraded primary without its own shard: probe attrs over the wire
        self._start_read(op.oid, 0, 0, True,
                         lambda res, _d, size, _a: self._rmw_have_size(
                             op, max(size, 0) if res in (0, -2) else res,
                             err=res not in (0, -2)))

    def _local_size(self, oid: str) -> Optional[int]:
        """Size from the primary's own shard; None = ask over the wire
        (a fresh primary may not hold its shard yet)."""
        my_shard = self.pg.my_shard()
        if my_shard < 0:
            return None
        store = self.pg.osd.store
        cid = self.shard_cid(my_shard)
        ho = hobject_t(oid, my_shard)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return None
        try:
            return struct.unpack("<Q", store.getattr(cid, ho, SIZE_ATTR))[0]
        except KeyError:
            return store.stat(cid, ho) * self.k

    def _rmw_have_size(self, op: RMWOp, old_size: int,
                       err: bool = False) -> None:
        if err:
            op.on_commit(old_size)  # old_size carries the errno here
            self._op_done(op.oid)
            return
        op.old_size = old_size
        offset = old_size if op.offset is None else op.offset
        op.offset = offset
        w = self.sinfo.get_stripe_width()
        a0 = self.sinfo.logical_to_prev_stripe_offset(offset)
        a1 = self.sinfo.logical_to_next_stripe_offset(offset + len(op.data))
        old_aligned = self.sinfo.logical_to_next_stripe_offset(old_size)
        if getattr(self.ec_impl, "requires_whole_object_rw", False):
            # non-systematic codecs: chunk offsets don't map to logical
            # ranges, so an rmw reads and re-encodes the WHOLE object
            a0 = 0
            a1 = max(a1, old_aligned)
        read_end = min(a1, old_aligned)
        if read_end <= a0:
            self._rmw_have_old(op, a0, a1, b"")
            return
        cached = self.extent_cache.read(op.oid, a0, read_end - a0)
        if cached is not None:
            self._rmw_have_old(op, a0, a1, cached)
            return
        c0 = self.sinfo.aligned_logical_offset_to_chunk_offset(a0)
        c1 = self.sinfo.aligned_logical_offset_to_chunk_offset(read_end)
        self._start_read(
            op.oid, c0, c1 - c0, False,
            lambda res, data, _size, _a: (
                self._rmw_have_old(op, a0, a1, data) if res == 0 or
                (res == -2 and old_size == 0)
                else (op.on_commit(res), self._op_done(op.oid))))

    def _rmw_have_old(self, op: RMWOp, a0: int, a1: int,
                      old_bytes: bytes) -> None:
        """Splice + re-encode the affected range in one device call, then
        fan chunk deltas (try_reads_to_commit, ECBackend.cc:1894).
        Runs from a read-reply callback — re-anchor the span and
        ledger contexts."""
        with g_tracer.activate(op.parent_span), \
                g_oplat.activate(op.ledger):
            buf = bytearray(a1 - a0)
            buf[:len(old_bytes)] = old_bytes
            rel = op.offset - a0
            buf[rel:rel + len(op.data)] = op.data
            new_size = max(op.old_size, op.offset + len(op.data))
            c0 = self.sinfo.aligned_logical_offset_to_chunk_offset(a0)

            def have_shards(shards, err) -> None:
                if err is not None:
                    op.on_commit(-5)
                    self._op_done(op.oid)
                    return

                def all_commit() -> None:
                    self.extent_cache.write(op.oid, a0, bytes(buf),
                                            new_size)
                    op.on_commit(0)
                    self._op_done(op.oid)

                self._fan_out_shards(op.tid, op.oid, shards,
                                     chunk_off=c0,
                                     partial=True, new_size=new_size,
                                     on_all_commit=all_commit,
                                     client_reply=op.on_commit,
                                     version=self.pg.next_version())

            self._encode_pipelined(bytes(buf), op.parent_span,
                                   have_shards)

    def _fan_out_shards(self, tid: int, oid: str,
                        shards: Dict[int, np.ndarray], chunk_off: int,
                        partial: bool, new_size: int,
                        on_all_commit: Callable[[], None],
                        client_reply: Callable[[int], None],
                        version: int = 0,
                        xattrs: Optional[Dict[str, bytes]] = None,
                        snapset_update: Optional[Tuple[str, bytes]]
                        = None) -> None:
        wr = InflightWrite(tid=tid, oid=oid, client_reply=client_reply,
                           on_all_commit=on_all_commit,
                           ledger=g_oplat.current())
        acting = self.pg.acting_shards()
        # propagate the op's trace so shard OSDs open child spans
        # (the Message.h:254 slot riding every sub-op)
        cur_trace = g_tracer.current_trace_id() if g_tracer.enabled else 0
        cur_span = g_tracer.current_span_id() if g_tracer.enabled else 0
        msg_bytes = 0
        for shard, osd in acting.items():
            body = shards[shard] if shard in shards else b""
            if isinstance(body, DeviceShard):
                # in-process fabric: the handle itself rides the
                # message — the body never leaves the device here
                chunk = body
            elif isinstance(body, np.ndarray):
                if body.flags["C_CONTIGUOUS"]:
                    # zero-copy view over the one materialized pack
                    # buffer (ecutil.pack_shards accounted that copy)
                    chunk = body.data
                else:
                    chunk = body.tobytes()
                    msg_bytes += len(chunk)
            else:
                chunk = body
                msg_bytes += len(body)
            msg = MOSDECSubOpWrite(
                tid=tid, pgid=self.pg.pgid, shard=shard, oid=oid,
                chunk=chunk, offset=chunk_off, partial=partial,
                at_version=new_size, version=version, xattrs=xattrs,
                snapset_update=snapset_update,
                trace_id=cur_trace, parent_span_id=cur_span)
            wr.pending_shards.add(shard)
            wr.sent_msgs[shard] = (osd, msg)
            self.pg.send_to_osd(osd, msg)
        if msg_bytes:
            # last stage of the write path's copy ledger: shard chunk
            # buffers materialized into per-shard sub-op messages
            g_devprof.account_host_copy("ec.subop_messages", msg_bytes)
        if wr.ledger is not None:
            # time ledger's counterpart: message build + send loop done
            wr.ledger.mark("fan_out")
        wr.last_send = self.pg.osd.now
        self.inflight_writes[tid] = wr

    def push_chunks(self, oid: str, shard_data: Dict[int, bytes],
                    size: int, on_done: Callable[[], None],
                    version: int = 0,
                    xattrs: Optional[Dict[str, bytes]] = None,
                    targets: Optional[Dict[int, int]] = None) -> int:
        """Recovery push: whole-shard writes to specific shards only
        (RecoveryOp pushes, ECBackend.cc:535-743).  is_push: the
        replica's log already carries the entries (activation), but the
        object's version attr must be stamped so staleness checks see
        current data.  ``xattrs`` restores the object's user attrs on
        the rebuilt shard (the reference pushes attrs with the chunks).
        ``targets`` overrides the shard->osd destinations (realign
        pushes go to UP members that are not acting yet)."""
        tid = self.next_tid()
        wr = InflightWrite(tid=tid, oid=oid, client_reply=lambda _r: None,
                           on_all_commit=on_done)
        acting = targets if targets is not None \
            else self.pg.acting_shards()
        for shard, chunk in shard_data.items():
            if shard not in acting:
                continue
            msg = MOSDECSubOpWrite(
                tid=tid, pgid=self.pg.pgid, shard=shard, oid=oid,
                chunk=chunk, offset=0, partial=False, at_version=size,
                version=version, is_push=True, xattrs=xattrs)
            wr.pending_shards.add(shard)
            wr.sent_msgs[shard] = (acting[shard], msg)
            self.pg.send_to_osd(acting[shard], msg)
        if not wr.pending_shards:
            on_done()
            return tid
        wr.last_send = self.pg.osd.now
        self.inflight_writes[tid] = wr
        return tid

    def read_chunks(self, oid: str,
                    on_done: Callable[
                        [int, Dict[int, bytes], int, Dict[str, bytes]],
                        None]) -> int:
        """Recovery read: raw chunks from the cheapest healthy shard set
        (no decode) — on_done(result, {shard: bytes}, logical_size,
        user_attrs)."""
        return self._start_read(oid, 0, 0, False, on_done, raw=True)

    def repair_read(self, oid: str, lost: int,
                    plan: Dict[int, List[Tuple[int, int]]],
                    on_done: Callable[
                        [int, Dict[int, bytes], int, Dict[str, bytes]],
                        None]) -> int:
        """Sub-chunk repair round (docs/RECOVERY.md): fan a
        repair-contribution read to each helper shard in *plan* (the
        codec's ``minimum_to_decode({lost}, avail)`` answer).  Helpers
        reply with their computed β-sub-chunk contribution instead of
        the whole chunk; ``on_done(result, {helper: contribution},
        logical_size, user_attrs)``.  ANY failed helper fails the round
        (result -5) with no reconstruction retry — the recovery
        orchestrator then falls back to the full-stripe decode path."""
        tid = self.next_tid()
        acting = self.pg.acting_shards()
        rd = InflightRead(tid=tid, oid=oid, on_done=on_done, raw=True,
                          repair_for=lost, ledger=g_oplat.current())
        cur_trace = g_tracer.current_trace_id() if g_tracer.enabled else 0
        cur_span = g_tracer.current_span_id() if g_tracer.enabled else 0
        for shard, subs in plan.items():
            osd = acting.get(shard)
            if osd is None:
                on_done(-5, {}, -1, {})
                return tid
            msg = MOSDECSubOpRead(tid=tid, pgid=self.pg.pgid,
                                  shard=shard, oid=oid,
                                  subchunks=list(subs),
                                  repair_for=lost,
                                  trace_id=cur_trace,
                                  parent_span_id=cur_span)
            rd.pending.add(shard)
            self.pg.send_to_osd(osd, msg)
        if rd.ledger is not None:
            rd.ledger.mark("fan_out")
        self.inflight_reads[tid] = rd
        return tid

    def handle_sub_write(self, msg: MOSDECSubOpWrite, store: MemStore,
                         pg=None) -> MOSDECSubOpWriteReply:
        """Shard-side apply (ECBackend.cc:921-983): one transaction with
        chunk data, size attr, the updated HashInfo, and — for versioned
        client writes — the pg_log entry (the reference appends the log
        entry in the same transaction as the data).

        Full writes replace the shard; partial (rmw) writes splice the
        chunk range and recompute the shard crc over the spliced body —
        the reference similarly rewrites hinfo on overwrite
        (ECTransaction.cc generate_transactions hinfo updates).
        """
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
        ho = hobject_t(msg.oid, msg.shard)
        if msg.version and not msg.is_push and \
                store.collection_exists(cid) and store.exists(cid, ho):
            # resend dedup: the stored version already covers this
            # message — the original apply succeeded and only the ack
            # was lost.  Re-applying would overwrite the rollback stash
            # with POST-write state and duplicate the log entry, so
            # just re-ack (the reference dedups via the pg log's
            # already-applied check in do_request)
            from .pg_log import VERSION_ATTR
            vb = store.getattrs(cid, ho).get(VERSION_ATTR)
            if vb is not None and \
                    struct.unpack("<Q", vb)[0] >= msg.version:
                return MOSDECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                             shard=msg.shard,
                                             committed=True)
        t = Transaction()
        if not store.collection_exists(cid):
            t.create_collection(cid)
        if pg is not None and msg.version and not msg.is_push:
            stash_pre_write_state(t, store, pg, msg.oid, cid, ho,
                                  msg.version)
        if msg.attr_only:
            # metadata-only mutation: replace user attrs, stamp version,
            # log — leave body/size/hinfo untouched.  A touch that
            # CREATES the object must stamp a zero size so reads/stat
            # see a consistent (empty) object, not a corrupt one.
            t.touch(cid, ho)
            if not (store.collection_exists(cid) and store.exists(cid, ho)):
                t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", 0))
            self._apply_user_attrs(t, store, cid, ho, msg.xattrs)
            if msg.version:
                from .pg_log import VERSION_ATTR
                t.setattr(cid, ho, VERSION_ATTR,
                          struct.pack("<Q", msg.version))
            if pg is not None and msg.version and not msg.is_push:
                from .pg_log import LogEntry, OP_MODIFY
                pg.append_log(LogEntry(msg.version, msg.oid, OP_MODIFY), t)
            store.queue_transaction(t)
            return MOSDECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                         shard=msg.shard, committed=True)
        if not msg.partial and isinstance(msg.chunk, DeviceShard):
            # zero-copy store: the device handle becomes the shard
            # body and the fused encode kernel's crc IS the HashInfo
            # digest — no host bytes move, nothing is hashed on host
            t.write_shard(cid, ho, msg.chunk)
            hinfo = struct.pack("<QI", msg.chunk.length, msg.chunk.crc)
            memstore_device_perf_counters().inc(l_msd_crc_device)
        else:
            if not msg.partial:
                t.truncate(cid, ho, 0)
                t.write(cid, ho, 0, msg.chunk)
                body = msg.chunk
            else:
                existing = store.read(cid, ho) \
                    if store.collection_exists(cid) and \
                    store.exists(cid, ho) else b""
                spliced = bytearray(max(len(existing),
                                        msg.offset + len(msg.chunk)))
                spliced[:len(existing)] = existing
                spliced[msg.offset:msg.offset + len(msg.chunk)] = \
                    msg.chunk
                t.truncate(cid, ho, 0)
                t.write(cid, ho, 0, bytes(spliced))
                body = bytes(spliced)
            hi = HashInfo(1)
            hi.append(0, {0: np.frombuffer(body, dtype=np.uint8)})
            hinfo = struct.pack("<QI", hi.total_chunk_size,
                                hi.get_chunk_hash(0))
            memstore_device_perf_counters().inc(l_msd_crc_host)
        t.setattr(cid, ho, SIZE_ATTR, struct.pack("<Q", msg.at_version))
        self._apply_user_attrs(t, store, cid, ho, msg.xattrs)
        t.setattr(cid, ho, HINFO_ATTR, hinfo)
        if msg.version:
            from .pg_log import VERSION_ATTR
            t.setattr(cid, ho, VERSION_ATTR,
                      struct.pack("<Q", msg.version))
        if pg is not None and msg.version and not msg.is_push:
            from .pg_log import LogEntry, OP_MODIFY
            pg.append_log(LogEntry(msg.version, msg.oid, OP_MODIFY), t)
        if pg is not None and msg.snapset_update is not None:
            pg.apply_snapset_update(tuple(msg.snapset_update), t)
        store.queue_transaction(t)
        if pg is not None and not msg.partial:
            pg.data_received(msg.oid)
        return MOSDECSubOpWriteReply(tid=msg.tid, pgid=msg.pgid,
                                     shard=msg.shard, committed=True)

    @staticmethod
    def _apply_user_attrs(t: Transaction, store: MemStore, cid: str, ho,
                          xattrs: Optional[Dict[str, bytes]]) -> None:
        """Full-replacement user-attr application: drop every existing
        ``_u_*`` attr, set the new set.  None = leave attrs alone."""
        if xattrs is None:
            return
        existing = {}
        if store.collection_exists(cid) and store.exists(cid, ho):
            existing = store.getattrs(cid, ho)
        for k in existing:
            if k.startswith(USER_ATTR_PREFIX):
                t.rmattr(cid, ho, k)
        for name, value in xattrs.items():
            t.setattr(cid, ho, USER_ATTR_PREFIX + name, bytes(value))

    def handle_sub_write_reply(self, msg: MOSDECSubOpWriteReply) -> None:
        wr = self.inflight_writes.get(msg.tid)
        if wr is None:
            return
        wr.pending_shards.discard(msg.shard)
        wr.sent_msgs.pop(msg.shard, None)
        if not wr.pending_shards:
            del self.inflight_writes[msg.tid]
            if wr.ledger is not None:
                # the LAST shard ack closes the gather stage; the
                # reply mark (osd.send_op_reply) is the next boundary
                wr.ledger.mark("ack_gather")
            if wr.on_all_commit is not None:
                wr.on_all_commit()
            else:
                wr.client_reply(0)

    def sweep_inflight(self, now: Optional[float] = None,
                       idle: bool = False) -> int:
        """Resend unacked sub-op writes (the reference's messenger
        retries at the Connection layer; this fabric needs an explicit
        timer).  Two drivers: the OSD tick (``now`` = cluster clock,
        resend after ``ec_subwrite_retry_timeout``) and the
        deterministic fabric's idle kick (``idle=True`` — quiescence
        means the message or its ack is provably lost, resend now).
        Bounded by ``ec_subwrite_retry_max`` per write so a down shard
        cannot spin the fabric; past the cap the write waits for
        peering's on_change, exactly as before the timer existed.
        Returns the number of messages resent."""
        timeout = float(g_conf.get_val("ec_subwrite_retry_timeout"))
        if timeout <= 0:
            return 0
        max_resend = int(g_conf.get_val("ec_subwrite_retry_max"))
        pc = pipeline_perf_counters()
        sent = 0
        for wr in list(self.inflight_writes.values()):
            if not wr.pending_shards or wr.resends >= max_resend:
                continue
            if idle:
                # the idle kick re-fires every time the fabric drains,
                # so an unreachable (down/blackholed) target would burn
                # the whole budget inside ONE pump and leave nothing
                # for the paced tick retries after the outage heals —
                # cap idle-driven rounds at two (enough for a dropped
                # send AND a dropped resend)
                if wr.resends >= min(2, max_resend):
                    continue
            elif now is None or now - wr.last_send < timeout:
                continue
            wr.resends += 1
            wr.last_send = self.pg.osd.now if now is None else now
            for shard in sorted(wr.pending_shards):
                ent = wr.sent_msgs.get(shard)
                if ent is None:
                    continue
                osd, msg = ent
                pc.inc(l_pipeline_subwrite_resends)
                g_tracer.event("subwrite_resend", shard=shard,
                               oid=wr.oid, tid=wr.tid,
                               attempt=wr.resends)
                self.pg.send_to_osd(osd, msg)
                sent += 1
        return sent

    # ---- read path (primary) ---------------------------------------------
    def objects_read_and_reconstruct(
            self, oid: str, on_complete: Callable[[int, bytes], None],
            offset: int = 0, length: int = 0) -> int:
        """Client-facing (ranged) read: decode the covering chunk range,
        slice, trim to logical size (ECBackend.cc:1580-1669).  Codecs
        without a systematic layout (regenerating codes) fetch whole
        shards regardless of range — the decoded object is sliced
        logically instead."""
        whole = getattr(self.ec_impl, "requires_whole_object_rw", False)
        if length == 0 or whole:
            c0, c1 = 0, 0
        else:
            a0 = self.sinfo.logical_to_prev_stripe_offset(offset)
            a1 = self.sinfo.logical_to_next_stripe_offset(offset + length)
            c0 = self.sinfo.aligned_logical_offset_to_chunk_offset(a0)
            c1 = self.sinfo.aligned_logical_offset_to_chunk_offset(a1)

        def done(result: int, data: bytes, size: int, _attrs) -> None:
            if result != 0:
                on_complete(result, b"")
                return
            if length == 0:
                body = data[:size] if size >= 0 else data
                on_complete(0, body[offset:])
                return
            a0 = 0 if whole else \
                self.sinfo.logical_to_prev_stripe_offset(offset)
            end = min(offset + length, size) if size >= 0 \
                else offset + length
            if end <= offset:
                on_complete(0, b"")
                return
            on_complete(0, data[offset - a0:end - a0])

        return self._start_read(oid, c0, max(0, c1 - c0), False, done)

    def object_state(self, oid: str,
                     on_done: Callable[
                         [int, bytes, int, Dict[str, bytes]], None]) -> int:
        """Whole-object fetch for the op interpreter: on_done(result,
        logical_bytes, size, user_attrs); result -2 = object absent."""

        def done(result: int, data: bytes, size: int,
                 attrs: Dict[str, bytes]) -> None:
            if result != 0:
                on_done(result, b"", 0, {})
                return
            body = data[:size] if size >= 0 else data
            on_done(0, body, max(size, 0), attrs)

        return self._start_read(oid, 0, 0, False, done)

    def _start_read(self, oid: str, chunk_off: int, chunk_len: int,
                    attrs_only: bool,
                    on_done: Callable[[int, bytes, int], None],
                    raw: bool = False) -> int:
        """Fan MOSDECSubOpRead for a chunk range to the cheapest shard
        set.  Shards the primary knows are missing this object are
        excluded up front (degraded-read gating)."""
        tid = self.next_tid()
        acting = self.pg.acting_shards()
        avail = set(acting) - self.pg.missing_shards_for(oid)
        rd = InflightRead(tid=tid, oid=oid, on_done=on_done,
                          chunk_off=chunk_off, chunk_len=chunk_len,
                          attrs_only=attrs_only, raw=raw,
                          ledger=g_oplat.current())
        cur_trace = g_tracer.current_trace_id() if g_tracer.enabled else 0
        cur_span = g_tracer.current_span_id() if g_tracer.enabled else 0
        if attrs_only:
            # any single healthy shard knows the size attr
            if not avail:
                on_done(-5, b"", -1, {})
                return tid
            shard = min(avail)
            rd.pending.add(shard)
            self.inflight_reads[tid] = rd
            self.pg.send_to_osd(acting[shard], MOSDECSubOpRead(
                tid=tid, pgid=self.pg.pgid, shard=shard, oid=oid,
                attrs_only=True, trace_id=cur_trace,
                parent_span_id=cur_span))
            if rd.ledger is not None:
                rd.ledger.mark("fan_out")
            return tid
        # want the *physical* positions of the data chunks (chunk_mapping
        # remaps logical->physical for lrc/shec layouts)
        want = {self.ec_impl.chunk_index(i) for i in range(self.k)}
        try:
            minimum = self.ec_impl.minimum_to_decode(want, avail)
        except IOError:
            on_done(-5, b"", -1, {})  # EIO: not enough shards
            return tid
        for shard in minimum:
            msg = MOSDECSubOpRead(tid=tid, pgid=self.pg.pgid, shard=shard,
                                  oid=oid, offset=chunk_off,
                                  length=chunk_len,
                                  subchunks=list(minimum[shard]),
                                  trace_id=cur_trace,
                                  parent_span_id=cur_span)
            rd.pending.add(shard)
            self.pg.send_to_osd(acting[shard], msg)
        if rd.ledger is not None:
            # a read round is a fan-out too: the sub-read sends close
            # the stage; the last reply closes ack_gather
            rd.ledger.mark("fan_out")
        self.inflight_reads[tid] = rd
        return tid

    def handle_sub_read(self, msg: MOSDECSubOpRead, store: MemStore
                        ) -> MOSDECSubOpReadReply:
        """Shard-side read + crc check (ECBackend.cc:986-1066).

        The crc always covers the whole stored shard (hinfo is cumulative,
        ECUtil.cc:161-207), so ranged reads verify the full body before
        slicing out [offset, offset+length)."""
        cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
        ho = hobject_t(msg.oid, msg.shard)
        if g_faults.site_armed("osd.shard_read_eio") and \
                g_faults.should_fire(
                    "osd.shard_read_eio",
                    ctx=f"{cid}:{msg.oid}:shard{msg.shard}"):
            # injected media error (bluestore_debug_inject_read_err
            # role): fail THIS shard's read; the primary's reply
            # handler reconstructs from the surviving shards
            fault_perf_counters().inc(l_fault_eio_injected)
            return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                        shard=msg.shard, oid=msg.oid,
                                        result=-5)
        if not store.collection_exists(cid) or not store.exists(cid, ho):
            return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                        shard=msg.shard, oid=msg.oid,
                                        result=-2)  # ENOENT
        data = store.read_shard(cid, ho)
        attrs = store.getattrs(cid, ho)
        hv = attrs.get(HINFO_ATTR)
        if hv is not None:
            total, expect = struct.unpack("<QI", hv)
            if total == len(data) and self._shard_crc(data) != expect:
                # bit rot: fail the shard read so the primary reconstructs
                return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                            shard=msg.shard, oid=msg.oid,
                                            result=-5)
        if msg.repair_for >= 0:
            if isinstance(data, DeviceShard):
                # repair math is host-side numpy: fetch the body
                data = data.materialize()
            # sub-chunk repair helper (docs/RECOVERY.md): compute this
            # shard's β-sub-chunk contribution toward rebuilding shard
            # ``repair_for`` instead of shipping the whole chunk.  The
            # chaos site drops helper fetches so the orchestrator's
            # full-stripe fallback is a tested path, not a hope.
            if g_faults.site_armed("recovery.helper_fetch") and \
                    g_faults.should_fire(
                        "recovery.helper_fetch",
                        ctx=f"{cid}:{msg.oid}:shard{msg.shard}"):
                fault_perf_counters().inc(l_fault_eio_injected)
                return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                            shard=msg.shard,
                                            oid=msg.oid, result=-5)
            contribute = getattr(self.ec_impl, "repair_contribution",
                                 None)
            C = self.sinfo.get_chunk_size()
            if contribute is None or not data or len(data) % C:
                # codec can't help (or torn shard): the orchestrator
                # falls back to the full-stripe decode path
                return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                            shard=msg.shard,
                                            oid=msg.oid, result=-5)
            body = np.frombuffer(data, dtype=np.uint8).reshape(-1, C)
            contrib = contribute(msg.shard, msg.repair_for, body)
            return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                        shard=msg.shard, oid=msg.oid,
                                        data=contrib.tobytes(),
                                        attrs=attrs, result=0)
        if msg.attrs_only:
            data = b""
        elif msg.offset or msg.length:
            if isinstance(data, DeviceShard):
                data = data.materialize()
            end = msg.offset + msg.length if msg.length else len(data)
            data = data[msg.offset:end]
        # a full-body read of a resident shard replies with the HANDLE:
        # on the in-process fabric the body stays in HBM until the
        # primary (or its client) actually touches bytes
        return MOSDECSubOpReadReply(tid=msg.tid, pgid=msg.pgid,
                                    shard=msg.shard, oid=msg.oid,
                                    data=data, attrs=attrs, result=0)

    @staticmethod
    def _shard_crc(data) -> int:
        """crc32c of a stored body in whichever representation it has:
        a still-resident shard verifies on DEVICE (ops/crc32c_device,
        bit-identical kernel — the only d2h is the 4-byte scalar); host
        bytes verify through the classic path."""
        if isinstance(data, DeviceShard):
            dev = data.device_array()
            if dev is not None:
                from ..ops.crc32c_device import crc32c_of_device_array
                memstore_device_perf_counters().inc(l_msd_crc_device)
                return crc32c_of_device_array(dev)
            data = data.materialize()
        memstore_device_perf_counters().inc(l_msd_crc_host)
        return crc32c(data)

    def handle_sub_read_reply(self, msg: MOSDECSubOpReadReply) -> None:
        """Collect shard replies; reconstruct on completion
        (ECBackend.cc:1141-1281)."""
        rd = self.inflight_reads.get(msg.tid)
        if rd is None:
            return
        rd.pending.discard(msg.shard)
        rd.seen += 1
        if rd.repair_for >= 0:
            # sub-chunk repair round: collect contributions; any
            # failure fails the round (the orchestrator falls back to
            # full-stripe decode — no reconstruction retry here)
            if msg.result == 0:
                rd.chunks[msg.shard] = msg.data
                sz = msg.attrs.get(SIZE_ATTR)
                if sz is not None:
                    rd.size = struct.unpack("<Q", sz)[0]
                if not rd.user_attrs:
                    rd.user_attrs = user_attrs_of(msg.attrs)
            else:
                rd.failed.add(msg.shard)
            if rd.pending:
                return
            del self.inflight_reads[msg.tid]
            if rd.ledger is not None:
                rd.ledger.mark("ack_gather")
            if rd.failed:
                rd.on_done(-5, {}, rd.size, rd.user_attrs)
            else:
                rd.on_done(0, dict(rd.chunks), rd.size, rd.user_attrs)
            return
        if msg.result == 0:
            rd.chunks[msg.shard] = msg.data
            sz = msg.attrs.get(SIZE_ATTR)
            if sz is not None:
                rd.size = struct.unpack("<Q", sz)[0]
            if not rd.user_attrs:
                rd.user_attrs = user_attrs_of(msg.attrs)
        else:
            rd.failed.add(msg.shard)
            if msg.result != -2:
                rd.saw_eio = True
                g_tracer.event("shard_read_eio", shard=msg.shard,
                               oid=rd.oid, result=msg.result)
            # retry with reconstruction from any other healthy shards
            acting = self.pg.acting_shards()
            others = (set(acting) - set(rd.chunks) - rd.failed
                      - rd.pending - self.pg.missing_shards_for(rd.oid))
            for shard in others:
                m2 = MOSDECSubOpRead(tid=rd.tid, pgid=self.pg.pgid,
                                     shard=shard, oid=rd.oid,
                                     offset=rd.chunk_off,
                                     length=rd.chunk_len,
                                     attrs_only=rd.attrs_only)
                rd.pending.add(shard)
                self.pg.send_to_osd(acting[shard], m2)
        if rd.pending:
            return
        del self.inflight_reads[msg.tid]
        if rd.ledger is not None:
            rd.ledger.mark("ack_gather")
        if rd.attrs_only:
            if rd.size >= 0:
                rd.on_done(0, b"", rd.size, rd.user_attrs)
            elif rd.failed and not rd.chunks and not rd.saw_eio:
                # every shard answered a clean ENOENT: object absent
                rd.on_done(-2, b"", 0, {})
            else:
                # crc/EIO failures must surface as EIO, never ENOENT —
                # a corrupt object is not an absent one
                rd.on_done(-5, b"", -1, {})
            return
        if not rd.chunks and rd.failed and not rd.saw_eio:
            # all shards report a clean no-such-object
            rd.on_done(-2, b"", 0, {}) if not rd.raw else \
                rd.on_done(-2, {}, 0, {})
            return
        if len(rd.chunks) < self.k:
            rd.on_done(-5, b"" if not rd.raw else {}, rd.size,
                       rd.user_attrs)
            return
        if rd.saw_eio:
            # the op was served despite >=1 failed shard: EC
            # reconstruction from survivors did its job (the graceful-
            # degradation contract for injected/real media errors)
            fault_perf_counters().inc(l_fault_eio_reconstructs)
        if rd.raw:
            # raw consumers (recovery, realign) slice and splice on
            # host — hand them bytes, not handles
            rd.on_done(0, {i: (b.materialize()
                               if isinstance(b, DeviceShard) else b)
                           for i, b in rd.chunks.items()},
                       rd.size, rd.user_attrs)
            return
        arrays = {i: np.frombuffer(b.materialize()
                                   if isinstance(b, DeviceShard) else b,
                                   dtype=np.uint8)
                  for i, b in rd.chunks.items()}
        try:
            # the decode runs from the sub-read-reply dispatch context:
            # re-anchor the op's ledger so its device stages attribute
            # to the read that needed them
            with g_oplat.activate(rd.ledger):
                data = self._decode_timed(
                    sum(len(b) for b in rd.chunks.values()),
                    g_dispatcher.decode_concat, self.sinfo, self.ec_impl,
                    arrays)
        except IOError:
            rd.on_done(-5, b"", rd.size, rd.user_attrs)
            return
        rd.on_done(0, data.tobytes(), rd.size, rd.user_attrs)

    # ---- recovery (ECBackend.cc:535-743) ----------------------------------
    def recover_object(self, oid: str, missing_shards: Set[int],
                       source_chunks: Dict[int, bytes],
                       logical_size: int) -> Dict[int, bytes]:
        """Decode the missing shards' chunks from k sources."""
        arrays = {i: np.frombuffer(b.materialize()
                                   if isinstance(b, DeviceShard) else b,
                                   dtype=np.uint8)
                  for i, b in source_chunks.items()}
        rec = self._decode_timed(
            sum(len(b) for b in source_chunks.values()),
            g_dispatcher.decode, self.sinfo, self.ec_impl, arrays,
            sorted(missing_shards))
        return {i: rec[i].tobytes() for i in rec}
