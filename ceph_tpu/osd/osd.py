"""OSD daemon — dispatch, map handling, heartbeats, recovery driver.

Mirrors the reference OSD's control surface (src/osd/OSD.{h,cc}): messages
enter via ms_fast_dispatch (OSD.cc:6594) and route to PGs; MOSDMap applies
incrementals and advances every PG (handle_osd_map → consume_map); OSD↔OSD
heartbeats detect silent peers and report them to the mon
(OSD::heartbeat, OSD.cc:4888; failure reports :7787); recovery pulls
surviving shards and pushes reconstructed chunks to replacement shards.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Set, Tuple

from ..common import OpTracker, PerfCountersBuilder
from ..crush.constants import CRUSH_ITEM_NONE
from ..msg import (
    Dispatcher, MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDFailure, MOSDMap, MOSDOp, MOSDOpReply,
    MOSDPing, Message, Network,
)
from ..os_store import MemStore, Transaction, hobject_t
from ..osdmap import OSDMap, pg_t
from .ec_backend import HINFO_ATTR, SIZE_ATTR
from .pg import PG

HEARTBEAT_GRACE = 20.0     # osd_heartbeat_grace default (options.cc:2461)
HEARTBEAT_INTERVAL = 6.0   # osd_heartbeat_interval (options.cc:2456)

# perf counter indices (l_osd_* analog, osd/OSD.cc:3099)
L_OSD_FIRST = 1000
L_OSD_OP_W = 1001
L_OSD_OP_R = 1002
L_OSD_SUBOP_W = 1003
L_OSD_SUBOP_R = 1004
L_OSD_RECOVERY_PUSH = 1005
L_OSD_MAP = 1006
L_OSD_OP_LAT = 1007
L_OSD_LAST = 1008


def _build_osd_perf(name: str):
    b = PerfCountersBuilder(name, L_OSD_FIRST, L_OSD_LAST)
    b.add_u64_counter(L_OSD_OP_W, "op_w", "client writes")
    b.add_u64_counter(L_OSD_OP_R, "op_r", "client reads")
    b.add_u64_counter(L_OSD_SUBOP_W, "subop_w", "shard writes")
    b.add_u64_counter(L_OSD_SUBOP_R, "subop_r", "shard reads")
    b.add_u64_counter(L_OSD_RECOVERY_PUSH, "recovery_push",
                      "recovered shard pushes")
    b.add_u64_counter(L_OSD_MAP, "maps", "osdmap epochs consumed")
    b.add_time_avg(L_OSD_OP_LAT, "op_latency", "client op latency")
    return b.create_perf_counters()


class OSD(Dispatcher):
    def __init__(self, network: Network, osd_id: int,
                 mon_name: str = "mon"):
        self.osd_id = osd_id
        self.name = f"osd.{osd_id}"
        self.network = network
        self.mon_name = mon_name
        self.messenger = network.create_messenger(self.name)
        self.messenger.add_dispatcher_head(self)
        self.store = MemStore()
        self.osdmap = OSDMap()
        self.pgs: Dict[Tuple[int, int], PG] = {}
        self._ec_impls: Dict[str, object] = {}
        self.last_ping_reply: Dict[int, float] = {}
        self.reported_failures: Set[int] = set()
        self.now = 0.0
        self.perf_counters = _build_osd_perf(self.name)
        self.op_tracker = OpTracker()
        self._tracked: Dict[Tuple[str, int], object] = {}
        self._recovery_queue: List[PG] = []

    # legacy-style dict view used by tests / admin socket
    @property
    def perf(self) -> Dict[str, int]:
        d = self.perf_counters.dump()
        return {k: v for k, v in d.items() if isinstance(v, int)}

    # ---- EC profile plumbing ----------------------------------------------
    def get_ec_impl(self, pool):
        key = pool.erasure_code_profile or "default"
        impl = self._ec_impls.get(key)
        if impl is None:
            from ..ec import create_erasure_code
            profile = dict(self.osdmap.erasure_code_profiles.get(
                key, {"plugin": "tpu", "k": "2", "m": "1"}))
            profile.setdefault("plugin", "tpu")
            impl = create_erasure_code(profile)
            self._ec_impls[key] = impl
        return impl

    # ---- dispatch ---------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MOSDMap):
            self._handle_osd_map(msg)
        elif isinstance(msg, MOSDOp):
            self._handle_op(msg)
        elif isinstance(msg, MOSDECSubOpWrite):
            self._handle_sub_write(msg)
        elif isinstance(msg, MOSDECSubOpWriteReply):
            pg = self.pgs.get(msg.pgid)
            if pg is not None and pg.backend is not None:
                pg.backend.handle_sub_write_reply(msg)
        elif isinstance(msg, MOSDECSubOpRead):
            self._handle_sub_read(msg)
        elif isinstance(msg, MOSDECSubOpReadReply):
            pg = self.pgs.get(msg.pgid)
            if pg is not None and pg.backend is not None:
                if msg.tid in getattr(self, "_recovery_reads", {}):
                    self._handle_recovery_read_reply(msg)
                else:
                    pg.backend.handle_sub_read_reply(msg)
        elif isinstance(msg, MOSDPing):
            self._handle_ping(msg)

    def reply_to(self, msg: Message, reply: Message) -> None:
        self.messenger.send_message(reply, msg.src)

    # ---- map handling (OSD::handle_osd_map) --------------------------------
    def _handle_osd_map(self, msg: MOSDMap) -> None:
        self.perf_counters.inc(L_OSD_MAP)
        for inc in msg.incrementals:
            if inc.epoch == self.osdmap.epoch + 1:
                self.osdmap.apply_incremental(inc)
        self._consume_map()

    def _consume_map(self) -> None:
        # instantiate PGs this osd serves; advance all
        for pool_id, pool in self.osdmap.pools.items():
            for ps in range(pool.pg_num):
                pg_id = (pool_id, ps)
                up, upp, acting, actp = self.osdmap.pg_to_up_acting_osds(
                    pg_t(pool_id, ps))
                member = self.osd_id in [o for o in acting
                                         if o != CRUSH_ITEM_NONE]
                if member and pg_id not in self.pgs:
                    self.pgs[pg_id] = PG(self, pg_id, pool)
                if pg_id in self.pgs:
                    self.pgs[pg_id].advance_map(self.osdmap)

    # ---- client ops -------------------------------------------------------
    def _handle_op(self, msg: MOSDOp) -> None:
        self.perf_counters.inc(
            L_OSD_OP_W if msg.op in ("write", "writefull", "append",
                                     "delete") else L_OSD_OP_R)
        op = self.op_tracker.create_request(
            msg.trace_id, f"osd_op({msg.op} {msg.pool}/{msg.oid})")
        op.mark_event("queued_for_pg")
        self._tracked[(msg.src, msg.tid)] = op
        pg = self.pgs.get(msg.pgid)
        if pg is None:
            self.send_op_reply(msg.src, MOSDOpReply(
                tid=msg.tid, result=-11, epoch=self.osdmap.epoch))
            return
        op.mark_event("reached_pg")
        pg.do_op(msg)

    def send_op_reply(self, dst: str, reply: MOSDOpReply) -> None:
        """All client replies funnel here so op tracking/latency see them."""
        op = self._tracked.pop((dst, reply.tid), None)
        if op is not None:
            op.mark_event("commit_sent" if reply.result == 0 else "error")
            op.finish()
            self.perf_counters.tinc(L_OSD_OP_LAT, op.duration)
        self.messenger.send_message(reply, dst)

    # ---- shard sub-ops ----------------------------------------------------
    def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        self.perf_counters.inc(L_OSD_SUBOP_W)
        if msg.at_version < 0:  # delete marker
            self._apply_delete(msg)
            return
        pg = self.pgs.get(msg.pgid)
        if msg.shard < 0:
            # replicated full-copy write
            if pg is not None and pg.rep_backend is not None:
                pg.rep_backend.apply_write(msg, self.store)
            return
        if pg is not None and pg.backend is not None:
            reply = pg.backend.handle_sub_write(msg, self.store)
            self.reply_to(msg, reply)

    def _apply_delete(self, msg: MOSDECSubOpWrite) -> None:
        if msg.shard < 0:
            cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
            ho = hobject_t(msg.oid)
        else:
            cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
            ho = hobject_t(msg.oid, msg.shard)
        if self.store.collection_exists(cid):
            t = Transaction()
            t.remove(cid, ho)
            self.store.queue_transaction(t)

    def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        self.perf_counters.inc(L_OSD_SUBOP_R)
        pg = self.pgs.get(msg.pgid)
        if pg is not None and pg.backend is not None:
            reply = pg.backend.handle_sub_read(msg, self.store)
            self.reply_to(msg, reply)
        else:
            self.reply_to(msg, MOSDECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, shard=msg.shard, oid=msg.oid,
                result=-11))

    # ---- heartbeats / failure detection -----------------------------------
    def tick(self, now: float) -> None:
        """Heartbeat tick: ping peers, report silent ones to the mon."""
        self.now = now
        peers = [o for o in range(self.osdmap.max_osd)
                 if o != self.osd_id and self.osdmap.is_up(o)]
        for peer in peers:
            self.messenger.send_message(
                MOSDPing(op=MOSDPing.PING, stamp=now,
                         epoch=self.osdmap.epoch), f"osd.{peer}")
        for peer in peers:
            last = self.last_ping_reply.get(peer, now)
            self.last_ping_reply.setdefault(peer, now)
            if now - last > HEARTBEAT_GRACE and \
                    peer not in self.reported_failures:
                self.reported_failures.add(peer)
                self.messenger.send_message(
                    MOSDFailure(target_osd=peer, failed_since=last,
                                epoch=self.osdmap.epoch), self.mon_name)

    def _handle_ping(self, msg: MOSDPing) -> None:
        if msg.op == MOSDPing.PING:
            self.messenger.send_message(
                MOSDPing(op=MOSDPing.PING_REPLY, stamp=msg.stamp,
                         epoch=self.osdmap.epoch), msg.src)
        else:
            peer = int(msg.src.split(".")[1])
            self.last_ping_reply[peer] = self.now
            self.reported_failures.discard(peer)

    # ---- recovery ---------------------------------------------------------
    def request_recovery(self, pg: PG) -> None:
        if pg not in self._recovery_queue:
            self._recovery_queue.append(pg)

    def run_recovery(self) -> int:
        """Drive queued PG recovery; returns number of pushed shards.

        The primary lists objects on its own shard (it is always a data
        holder after peering), reads k source chunks for any object a
        replacement shard lacks, decodes that shard's chunk and pushes it
        (continue_recovery_op semantics, ECBackend.cc:535-743).
        """
        pushed = 0
        queue, self._recovery_queue = self._recovery_queue, []
        for pg in queue:
            if pg.backend is None:
                pushed += self._recover_replicated(pg)
                continue
            pushed += self._recover_ec(pg)
        return pushed

    def _recover_ec(self, pg: PG) -> int:
        be = pg.backend
        my_shard = pg.my_shard()
        if my_shard < 0:
            return 0
        my_cid = be.shard_cid(my_shard)
        if not self.store.collection_exists(my_cid):
            # new primary without data: pull the object list lazily from
            # another shard via recovery reads below (object registry =
            # union of shard listings; empty until peers push)
            return 0
        pushed = 0
        objects = [ho.oid for ho in self.store.list_objects(my_cid)]
        acting = pg.acting_shards()
        for oid in objects:
            missing: Dict[int, int] = {}
            for shard, osd in acting.items():
                holder = self._peer_osd(osd)
                cid = be.shard_cid(shard)
                ho = hobject_t(oid, shard)
                if holder is None:
                    continue
                if not holder.store.collection_exists(cid) or \
                        not holder.store.exists(cid, ho):
                    missing[shard] = osd
            if not missing:
                continue
            sources: Dict[int, bytes] = {}
            logical = 0
            for shard, osd in acting.items():
                if shard in missing or len(sources) >= be.k:
                    continue
                holder = self._peer_osd(osd)
                if holder is None:
                    continue
                cid = be.shard_cid(shard)
                ho = hobject_t(oid, shard)
                try:
                    sources[shard] = holder.store.read(cid, ho)
                    logical = struct.unpack(
                        "<Q", holder.store.getattr(cid, ho, SIZE_ATTR))[0]
                except KeyError:
                    continue
            if len(sources) < be.k:
                continue
            rec = be.recover_object(oid, set(missing), sources, logical)
            for shard, osd in missing.items():
                push = MOSDECSubOpWrite(
                    tid=be.next_tid(), pgid=pg.pgid, shard=shard, oid=oid,
                    chunk=rec[shard], at_version=logical)
                pg.send_to_osd(osd, push)
                self.perf_counters.inc(L_OSD_RECOVERY_PUSH)
                pushed += 1
        return pushed

    def _recover_replicated(self, pg: PG) -> int:
        cid = pg.rep_backend.cid()
        if not self.store.collection_exists(cid):
            return 0
        pushed = 0
        acting = [o for o in pg.acting if o != CRUSH_ITEM_NONE]
        for ho in self.store.list_objects(cid):
            data = self.store.read(cid, ho)
            size = struct.unpack(
                "<Q", self.store.getattr(cid, ho, SIZE_ATTR))[0]
            for osd in acting:
                holder = self._peer_osd(osd)
                if holder is None or holder.store.exists(cid, ho):
                    continue
                push = MOSDECSubOpWrite(tid=0, pgid=pg.pgid, shard=-1,
                                        oid=ho.oid, chunk=data,
                                        at_version=size)
                pg.send_to_osd(osd, push)
                self.perf_counters.inc(L_OSD_RECOVERY_PUSH)
                pushed += 1
        return pushed

    def _peer_osd(self, osd_id: int) -> Optional["OSD"]:
        """Peer store visibility for recovery planning.

        The reference primary learns peer completeness from pg_log/backfill
        scans over the wire; the single-process equivalent inspects the
        peer's store directly for the *plan*, while all data movement still
        flows through messages.
        """
        ep = self.network.endpoints.get(f"osd.{osd_id}")
        if ep is None or f"osd.{osd_id}" in self.network.down:
            return None
        d = ep.dispatcher
        return d if isinstance(d, OSD) else None

    def _handle_recovery_read_reply(self, msg) -> None:
        pass
