"""OSD daemon — dispatch, map handling, heartbeats, recovery driver.

Mirrors the reference OSD's control surface (src/osd/OSD.{h,cc}): messages
enter via ms_fast_dispatch (OSD.cc:6594) and route to PGs; MOSDMap applies
incrementals and advances every PG (handle_osd_map → consume_map); OSD↔OSD
heartbeats detect silent peers and report them to the mon
(OSD::heartbeat, OSD.cc:4888; failure reports :7787).

Recovery runs entirely over the message fabric (no peer-heap shortcuts):
the primary's per-PG missing sets come from pg_log deltas computed during
peering (PGLog role) or backfill scans; each missing object is recovered
by reading k healthy chunks (MOSDECSubOpRead), decoding the lost shards'
chunks on the codec, and pushing them (MOSDECSubOpWrite) — the
continue_recovery_op flow, ECBackend.cc:535-743.
"""
from __future__ import annotations

import struct
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..common import Dout, OpTracker, PerfCountersBuilder
from ..common.work_queue import (
    CLASS_CLIENT, CLASS_SCRUB, ShardedOpWQ, l_qos_admission_rejections,
    l_qos_queue_depth, l_qos_throttle_events, qos_perf_counters,
)
from ..trace import (g_oplat, g_perf_histograms, g_tracer, latency_axes,
                     latency_in_bytes_axes)
from ..trace.oplat import intake_ledger
from ..crush.constants import CRUSH_ITEM_NONE
from ..msg import (
    Dispatcher, MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDFailure, MOSDMap, MOSDOp, MOSDOpReply,
    MOSDPGInfo, MOSDPGNotify, MOSDPGQuery, MOSDPGRemove, MOSDPGScan,
    MOSDPGScanReply, MOSDPing,
    MOSDRepScrub, MOSDRepScrubMap, Message, Network,
)
from ..os_store import MemStore, Transaction, hobject_t
from ..osdmap import OSDMap, pg_t
from .ec_backend import HINFO_ATTR, SIZE_ATTR
from .pg import PG
from .pg_log import LogEntry, OP_DELETE
from ..common.lockdep import DebugLock

HEARTBEAT_GRACE = 20.0     # osd_heartbeat_grace default (options.cc:2461)
HEARTBEAT_INTERVAL = 6.0   # osd_heartbeat_interval (options.cc:2456)
RECOVERY_RETRY = 10.0      # re-kick a recovery whose reply chain went
                           # silent (a push can race a peer's map epoch
                           # and be dropped pg-less on arrival)

# perf counter indices (l_osd_* analog, osd/OSD.cc:3099)
L_OSD_FIRST = 1000
L_OSD_OP_W = 1001
L_OSD_OP_R = 1002
L_OSD_SUBOP_W = 1003
L_OSD_SUBOP_R = 1004
L_OSD_RECOVERY_PUSH = 1005
L_OSD_MAP = 1006
L_OSD_OP_LAT = 1007
L_OSD_LAST = 1008


def _unpack_pull_meta(attrs: Dict[str, bytes]):
    """Split a replicated pull reply's attr dict into (user_xattrs, omap)."""
    from ..msg.kv import unpack_kv
    from .ec_backend import user_attrs_of
    uattrs = user_attrs_of(attrs)
    omap_blob = attrs.get("_omap_kv")
    omap = unpack_kv(omap_blob) if omap_blob else {}
    return uattrs, omap


def _build_osd_perf(name: str):
    b = PerfCountersBuilder(name, L_OSD_FIRST, L_OSD_LAST)
    b.add_u64_counter(L_OSD_OP_W, "op_w", "client writes")
    b.add_u64_counter(L_OSD_OP_R, "op_r", "client reads")
    b.add_u64_counter(L_OSD_SUBOP_W, "subop_w", "shard writes")
    b.add_u64_counter(L_OSD_SUBOP_R, "subop_r", "shard reads")
    b.add_u64_counter(L_OSD_RECOVERY_PUSH, "recovery_push",
                      "recovered shard pushes")
    b.add_u64_counter(L_OSD_MAP, "maps", "osdmap epochs consumed")
    b.add_time_avg(L_OSD_OP_LAT, "op_latency", "client op latency")
    return b.create_perf_counters()


class OSD(Dispatcher):
    def __init__(self, network: Network, osd_id: int,
                 mon_name: str = "mon", store: Optional[MemStore] = None,
                 mon_names: Optional[List[str]] = None):
        self.osd_id = osd_id
        self.name = f"osd.{osd_id}"
        self.network = network
        self.mon_name = mon_name
        # failure reports go to every monitor (peons forward to the
        # leader), so a dead leader doesn't blind failure detection
        self.mon_names = list(mon_names) if mon_names else [mon_name]
        self.messenger = network.create_messenger(self.name)
        self.messenger.add_dispatcher_head(self)
        self.store = store if store is not None else MemStore()
        from .cls import load_builtin_classes
        load_builtin_classes()      # osd_class_load_list='*'

        self.osdmap = OSDMap()
        self.pgs: Dict[Tuple[int, int], PG] = {}
        self._ec_impls: Dict[str, object] = {}
        self.last_ping_reply: Dict[int, float] = {}
        self.now = 0.0
        self.perf_counters = _build_osd_perf(self.name)
        # 2D latency x bytes distributions (the reference's
        # op_w_latency_in_bytes_histogram surface, perf_histogram.h):
        # always-on host-side math, dumped via `perf histogram dump`
        self.hist_op_w = g_perf_histograms.get(
            self.name, "op_w_latency_in_bytes_histogram",
            latency_in_bytes_axes)
        self.hist_op_r = g_perf_histograms.get(
            self.name, "op_r_latency_in_bytes_histogram",
            latency_in_bytes_axes)
        self.dout = Dout("osd", self.name)
        self.op_tracker = OpTracker(name=self.name)
        self._tracked: Dict[Tuple[str, int], object] = {}
        self._recovery_queue: List[PG] = []
        # recovery orchestration (ceph_tpu/recovery): paced sub-chunk
        # repair rounds, QoS-classed through the recovery dmClock
        # class, per-codec-family bytes-moved accounting
        from ..recovery import RecoveryScheduler
        self.recovery_sched = RecoveryScheduler(self)
        from ..common.config import g_conf
        self.op_wq = ShardedOpWQ(
            wall=bool(g_conf.get_val("osd_op_queue_mclock_wall")))
        # threaded drain (osd_op_tp, OSD.cc:2008): workers take the
        # target PG's lock around each op, like dequeue_op does — real
        # concurrency across shards, lockdep live on the hot path
        self.op_tp = None
        n_threads = int(g_conf.get_val("osd_op_num_threads") or 0)
        if n_threads > 0:
            from ..common.work_queue import ShardedThreadPool
            self.op_tp = ShardedThreadPool(self.op_wq,
                                           self._wq_handle_locked,
                                           n_threads)
        # admission-control throttle windows: client entity ->
        # monotonic expiry.  A client stays listed (and keeps getting
        # EAGAIN+retry_after) until the queue drains below half of
        # osd_op_queue_admission_max AND its window lapsed (docs/QOS.md)
        self._throttled_clients: Dict[str, float] = {}
        # entities granted their own wait-time histogram lane; past the
        # cap newcomers share one overflow lane (bounds the process-
        # global registry under client churn, like ClientDmClock's
        # 64-lane eviction one layer below)
        self._client_hist_lanes: Set[str] = set()
        self._rep_pulls: Dict[int, Callable] = {}
        # OSD-level tids (_rep_pulls, recovery probes, realign pushes)
        # live in a range disjoint from every per-PG backend counter
        # (which starts at 1): a probe reply must never be claimable
        # by — or hijack — a PG's own inflight read with the same tid
        self._pull_tid = 1 << 32
        self._rep_pull_stamps: Dict[int, float] = {}
        # tier ops this OSD issued as a client of the base pool
        # (promote reads / flush writes): tid -> reply callback.
        # Allocated/consumed from worker threads holding only a PG
        # lock, so OSD-level state needs its own mutex
        self._tier_ops: Dict[int, Callable] = {}
        self._tier_tid = 1 << 40     # clear of client tid spaces
        self._tier_lock = DebugLock("OSD::tier_lock")

    def shutdown(self) -> None:
        """Stop background machinery (the threaded op pool's workers
        would otherwise outlive a restarted/replaced daemon and keep
        polling — or executing stale ops against — its old store)."""
        if self.op_tp is not None:
            self.op_tp.stop()
            self.op_tp = None

    # legacy-style dict view used by tests / admin socket
    @property
    def perf(self) -> Dict[str, int]:
        d = self.perf_counters.dump()
        return {k: v for k, v in d.items() if isinstance(v, int)}

    # ---- EC profile plumbing ----------------------------------------------
    def get_ec_impl(self, pool):
        key = pool.erasure_code_profile or "default"
        impl = self._ec_impls.get(key)
        if impl is None:
            from ..ec import create_erasure_code
            profile = dict(self.osdmap.erasure_code_profiles.get(
                key, {"plugin": "tpu", "k": "2", "m": "1"}))
            profile.setdefault("plugin", "tpu")
            impl = create_erasure_code(profile)
            self._ec_impls[key] = impl
        return impl

    # ---- dispatch ---------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MOSDMap):
            self._handle_osd_map(msg)
        elif isinstance(msg, MOSDOpReply):
            # replies to this OSD's own tier ops (promote/flush)
            with self._tier_lock:
                ent = self._tier_ops.pop(msg.tid, None)
            if ent is not None:
                ent[0](msg)
        elif isinstance(msg, MOSDOp):
            self._handle_op(msg)
        elif isinstance(msg, MOSDECSubOpWrite):
            self._handle_sub_write(msg)
        elif isinstance(msg, MOSDECSubOpWriteReply):
            pg = self.pgs.get(msg.pgid)
            if pg is not None and pg.backend is not None:
                pg.backend.handle_sub_write_reply(msg)
            elif pg is not None:
                ack = getattr(pg, "_rep_realign_ack", None)
                if ack is not None:
                    ack(msg.tid)
        elif isinstance(msg, MOSDECSubOpRead):
            self._handle_sub_read(msg)
        elif isinstance(msg, MOSDECSubOpReadReply):
            if msg.tid in self._rep_pulls:
                self._rep_pull_stamps.pop(msg.tid, None)
                self._rep_pulls.pop(msg.tid)(msg)
                return
            pg = self.pgs.get(msg.pgid)
            if pg is not None and pg.backend is not None:
                pg.backend.handle_sub_read_reply(msg)
        elif isinstance(msg, MOSDPGNotify):
            self._handle_pg_notify(msg)
        elif isinstance(msg, MOSDPGRemove):
            self._handle_pg_remove(msg)
        elif isinstance(msg, MOSDPGQuery):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_pg_query(msg)
        elif isinstance(msg, MOSDPGInfo):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_pg_info(msg)
        elif isinstance(msg, MOSDPGScan):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_pg_scan(msg)
        elif isinstance(msg, MOSDPGScanReply):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_pg_scan_reply(msg)
        elif isinstance(msg, MOSDRepScrub):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_rep_scrub(msg)
        elif isinstance(msg, MOSDRepScrubMap):
            pg = self.pgs.get(msg.pgid)
            if pg is not None:
                pg.handle_rep_scrub_map(msg)
        elif isinstance(msg, MOSDPing):
            self._handle_ping(msg)
        else:
            from ..msg.messages import MCommand, MWatchNotify
            if isinstance(msg, MWatchNotify) and \
                    msg.op == MWatchNotify.ACK:
                pg = self.pgs.get(msg.pgid)
                if pg is not None:
                    pg.handle_notify_ack(msg)
            elif isinstance(msg, MCommand):
                self._handle_command(msg)

    def reply_to(self, msg: Message, reply: Message) -> None:
        self.messenger.send_message(reply, msg.src)

    # ---- daemon commands ('ceph tell osd.N', MCommand.h) ------------------
    def _handle_command(self, msg) -> None:
        """Runtime introspection/reconfiguration of THIS live daemon
        over the wire: injectargs (config mutation with observer
        notification), config show/get, perf dump."""
        from ..common.config import g_conf
        from ..msg.messages import MCommandReply
        result, data = g_conf.run_daemon_command(msg.cmd, msg.args, {
            "perf dump": self.perf_counters.dump,
            "dump_ops_in_flight": self.op_tracker.dump_ops_in_flight,
        })
        self.reply_to(msg, MCommandReply(tid=msg.tid, result=result,
                                         data=data))

    # ---- map handling (OSD::handle_osd_map) --------------------------------
    def _handle_osd_map(self, msg: MOSDMap) -> None:
        """Apply and consume epoch by epoch: an interval change inside a
        batch of incrementals (e.g. this osd flapped and the net acting
        set looks unchanged) must still trigger re-peering — the
        reference's same_interval_since check walks every epoch too
        (PG::start_peering_interval)."""
        self.perf_counters.inc(L_OSD_MAP)
        self.dout(7, f"handle_osd_map epochs "
                  f"[{msg.incrementals[0].epoch if msg.incrementals else 0}"
                  f"..{msg.incrementals[-1].epoch if msg.incrementals else 0}]")
        for inc in msg.incrementals:
            if inc.epoch == self.osdmap.epoch + 1:
                was_up = {o for o in range(self.osdmap.max_osd)
                          if self.osdmap.is_up(o)}
                self._persist_incremental(inc)
                self.osdmap.apply_incremental(inc)
                if inc.old_pools:
                    self._purge_deleted_pools(inc.old_pools)
                # a peer newly marked up gets a fresh heartbeat grace and
                # its standing failure report is withdrawn (the
                # reference's send_still_alive cancellation role) —
                # otherwise stale ping state re-reports it instantly
                for o in range(self.osdmap.max_osd):
                    if self.osdmap.is_up(o) and o not in was_up:
                        self.last_ping_reply[o] = self.now
                if self.osd_id < self.osdmap.max_osd and \
                        not self.osdmap.is_up(self.osd_id):
                    # the map says we are down but we are demonstrably
                    # alive: ask to be marked back up, once per epoch
                    # (OSD::_committed_osd_maps "marked down" reboot +
                    # MOSDBoot to the mon)
                    if getattr(self, "_boot_sent_epoch", -1) != \
                            self.osdmap.epoch:
                        self._boot_sent_epoch = self.osdmap.epoch
                        from ..msg.messages import MOSDBoot
                        for mon in self.mon_names:
                            self.messenger.send_message(
                                MOSDBoot(osd=self.osd_id,
                                         epoch=self.osdmap.epoch), mon)
                self._consume_map()

    def _persist_incremental(self, inc) -> None:
        """Store every applied map epoch in the meta collection
        (OSD::handle_osd_map writing inc_osdmap.<e> into coll::meta):
        the on-disk history that lets rebuild-mondb reconstruct a
        LOST mon store from surviving OSDs."""
        from ..msg.wire import encode_blob
        from ..osdmap.encoding import incremental_to_dict
        t = Transaction()
        cid = "meta"
        if not self.store.collection_exists(cid):
            t.create_collection(cid)
        oid = hobject_t(f"inc_osdmap.{inc.epoch}")
        t.touch(cid, oid)
        t.write(cid, oid, 0, encode_blob(incremental_to_dict(inc)))
        self.store.queue_transaction(t)

    # ---- stray PG removal (PG RecoveryState::Stray + OSD::_remove_pg) -----
    def _local_pg_collections(self) -> Dict[Tuple[int, int], List[str]]:
        """(pool, ps) -> local collection names, parsed from the store
        (strays can exist with no PG object after a restart)."""
        from ..os_store import parse_pg_from_cid
        out: Dict[Tuple[int, int], List[str]] = {}
        for cid in self.store.list_collections():
            key = parse_pg_from_cid(cid)
            if key is None:
                continue
            out.setdefault(key, []).append(cid)
        return out

    def _report_strays(self) -> None:
        """Notify the current primary about PGs we hold data for but
        no longer serve; it answers MOSDPGRemove once the PG is clean
        (the reference's stray-notify / purged_strays flow)."""
        interval = 5.0
        # gate the whole scan: listing every collection and running a
        # CRUSH mapping per held PG is too much work for every tick
        if self.now - getattr(self, "_stray_scan_at", -1e9) < interval:
            return
        self._stray_scan_at = self.now
        sent = getattr(self, "_stray_notified", None)
        if sent is None:
            sent = self._stray_notified = {}
        for pg_id, cids in self._local_pg_collections().items():
            pool = self.osdmap.pools.get(pg_id[0])
            if pool is None or pg_id[1] >= pool.pg_num:
                continue          # pool gone / unknown: stay conservative
            up, _upp, acting, actp = self.osdmap.pg_to_up_acting_osds(
                pg_t(*pg_id))
            members = {o for o in list(up) + list(acting)
                       if o != CRUSH_ITEM_NONE}
            if self.osd_id in members or actp < 0 or \
                    actp == self.osd_id:
                sent.pop(pg_id, None)
                continue
            if self.now - sent.get(pg_id, -1e9) < interval:
                continue
            sent[pg_id] = self.now
            held = sorted({int(cid[cid.rindex("s") + 1:])
                           for cid in cids
                           if not cid.endswith("_meta")
                           and "s" in cid.split(".")[-1]})
            lu = self._stray_high_water(pg_id, cids)
            self.messenger.send_message(MOSDPGNotify(
                pgid=pg_id, epoch=self.osdmap.epoch,
                from_osd=self.osd_id, held_shards=held,
                last_update=lu),
                f"osd.{actp}")

    def _stray_high_water(self, pg_id: Tuple[int, int],
                          cids: List[str]) -> int:
        """Highest version this stray can actually serve: log head attr
        plus stored VERSION_ATTRs.  Pushed objects can be newer than the
        stray's own log (realign/backfill), and the primary's
        keep-or-delete decision compares against what the stray can
        serve — under-reporting could authorize deleting the only newer
        copy (mirror of PG.data_high_water, with the same
        committed_txns-keyed cache: this rescans every notify retry)."""
        cache = getattr(self, "_stray_hw_cache", None)
        if cache is None:
            cache = self._stray_hw_cache = {}
        key = self.store.committed_txns
        hit = cache.get(pg_id)
        if hit is not None and hit[0] == key:
            return hit[1]
        from .pg_log import LAST_UPDATE_ATTR, PG_META_OID, VERSION_ATTR
        lu = 0
        mcid = f"{pg_id[0]}.{pg_id[1]}_meta"
        meta = hobject_t(PG_META_OID)
        if self.store.collection_exists(mcid) and \
                self.store.exists(mcid, meta):
            b = self.store.getattrs(mcid, meta).get(LAST_UPDATE_ATTR)
            if b:
                lu = struct.unpack("<Q", b)[0]
        for cid in cids:
            if cid.endswith("_meta"):
                continue
            for ho in self.store.list_objects(cid):
                vb = self.store.getattrs(cid, ho).get(VERSION_ATTR)
                if vb:
                    lu = max(lu, struct.unpack("<Q", vb)[0])
        cache[pg_id] = (key, lu)
        return lu

    def _handle_pg_notify(self, msg: MOSDPGNotify) -> None:
        """Primary: a stray holds our data; authorize removal only when
        this PG is clean and unpinned — while degraded, the stray may
        yet become a recovery source via choose_acting."""
        pg = self.pgs.get(msg.pgid)
        if pg is None or not pg.is_primary():
            return
        from .pg import STATE_ACTIVE
        if pg.state != STATE_ACTIVE or pg._has_missing() or \
                pg._backfill_pending or \
                getattr(pg, "_realigning", False):
            return
        if pg_t(*msg.pgid) in self.osdmap.pg_temp:
            return
        members = {o for o in list(pg.up) + list(pg.acting)
                   if o != CRUSH_ITEM_NONE}
        if msg.from_osd in members:
            return
        high = pg.data_high_water()
        if msg.last_update > high:
            # the stray holds writes we cannot serve: deleting it would
            # destroy the only newer copy — leave it until this PG
            # catches up (or an operator intervenes)
            self.dout(1, f"pg {tuple(msg.pgid)}: stray osd."
                      f"{msg.from_osd} is NEWER than us "
                      f"({msg.last_update} > {high}); "
                      "refusing removal")
            return
        self.messenger.send_message(MOSDPGRemove(
            pgid=msg.pgid, epoch=self.osdmap.epoch),
            f"osd.{msg.from_osd}")

    def _handle_pg_remove(self, msg: MOSDPGRemove) -> None:
        """Stray: delete the local PG copy — re-checked against OUR
        current map (a newer epoch may have made us a member again)."""
        if msg.epoch > self.osdmap.epoch:
            return                # catch up first; primary will re-ack
        pg_id = tuple(msg.pgid)
        pool = self.osdmap.pools.get(pg_id[0])
        if pool is None or pg_id[1] >= pool.pg_num:
            return
        up, _upp, acting, _actp = self.osdmap.pg_to_up_acting_osds(
            pg_t(*pg_id))
        if self.osd_id in {o for o in list(up) + list(acting)
                           if o != CRUSH_ITEM_NONE}:
            return
        n = self._remove_pg_local(pg_id)
        self.dout(3, f"removed stray pg {pg_id} ({n} collections)")

    def next_pull_tid(self) -> int:
        """OSD-level tid (disjoint from per-PG backend counters)."""
        self._pull_tid += 1
        return self._pull_tid

    def get_or_create_pg(self, pg_id: Tuple[int, int]) -> PG:
        if pg_id not in self.pgs:
            self.pgs[pg_id] = PG(self, pg_id,
                                 self.osdmap.pools[pg_id[0]])
        return self.pgs[pg_id]

    def _remove_pg_local(self, pg_id) -> int:
        """Drop one local PG: collections, in-memory object, stray
        bookkeeping (the shared tail of stray removal and pool
        deletion).  Returns collections removed."""
        cids = self._local_pg_collections().get(pg_id, [])
        t = Transaction()
        for cid in cids:
            t.remove_collection(cid)
        if not t.empty():
            self.store.queue_transaction(t)
        self.pgs.pop(pg_id, None)
        getattr(self, "_stray_notified", {}).pop(pg_id, None)
        return len(cids)

    def _purge_deleted_pools(self, pool_ids) -> None:
        """Drop PGs + store collections of explicitly deleted pools
        (PG::on_removal on the pool-deletion epoch).  Driven ONLY by
        incrementals' old_pools — absence from the map is not evidence
        of deletion (a booting OSD briefly holds an empty map while
        its store is full of live data)."""
        gone = set(pool_ids)
        if not gone:
            return
        doomed_ids = set(p for p in self.pgs if p[0] in gone) | \
            set(p for p in self._local_pg_collections() if p[0] in gone)
        for pg_id in doomed_ids:
            self._remove_pg_local(pg_id)

    def _consume_map(self) -> None:
        # instantiate PGs this osd serves
        for pool_id, pool in self.osdmap.pools.items():
            for ps in range(pool.pg_num):
                pg_id = (pool_id, ps)
                up, upp, acting, actp = self.osdmap.pg_to_up_acting_osds(
                    pg_t(pool_id, ps))
                # up-but-not-acting members (pg_temp pinned elsewhere)
                # must exist too: they receive the realign/backfill
                # pushes that let the pin clear
                member = self.osd_id in [o for o in list(acting) +
                                         list(up)
                                         if o != CRUSH_ITEM_NONE]
                if member:
                    self.get_or_create_pg(pg_id)
        # pg_num grew past a local layout's record: split before any PG
        # advances (OSD::split_pgs) — including layouts held WITHOUT
        # membership: an OSD down through the split epoch can be
        # remapped off the parent yet still serve a child, and its
        # stranded objects must reach the child collections (stray
        # removal would otherwise delete them with the parent)
        from .pg import stored_pg_num_of
        for pg_id in set(self._local_pg_collections()) | set(self.pgs):
            pool = self.osdmap.pools.get(pg_id[0])
            if pool is None or pg_id[1] >= pool.pg_num:
                continue
            pg = self.pgs.get(pg_id)
            known = pg.known_pg_num if pg is not None else \
                (stored_pg_num_of(self.store, pg_id) or pool.pg_num)
            if known < pool.pg_num:
                self.get_or_create_pg(pg_id).split_children()
        # advance all (children included)
        for pg_id in list(self.pgs):
            self.pgs[pg_id].advance_map(self.osdmap)

    # ---- client ops -------------------------------------------------------
    def _admit_op(self, msg: MOSDOp) -> bool:
        """Overload admission control (docs/QOS.md): once the op-queue
        depth crosses ``osd_op_queue_admission_max``, new CLIENT ops
        are shed with an EAGAIN + retry_after reply instead of growing
        the queue unboundedly.  A shed client stays throttled — a
        depth-hysteresis window (plus an optional wall-clock window) —
        until the queue drains below half the cap, so one abusive
        client's replays cannot re-fill the queue the instant a slot
        opens.  Internal clients (tier ops from other OSDs, daemons)
        are exempt: an EAGAIN loop inside the cluster would be a
        livelock, not backpressure."""
        from ..common.config import g_conf
        admission_max = int(
            g_conf.get_val("osd_op_queue_admission_max") or 0)
        if admission_max <= 0 or not msg.src.startswith("client"):
            return True
        qos = qos_perf_counters()
        depth = len(self.op_wq)
        qos.set(l_qos_queue_depth, depth)
        low_water = max(1, admission_max // 2)
        if len(self._throttled_clients) > 64 and depth < low_water:
            # opportunistic prune under entity churn — same condition
            # as the per-client clear below, applied to clients that
            # never came back (their windows would otherwise pin map
            # entries forever)
            # throttle windows are wall seconds BY CONTRACT:
            # retry_after is handed to real clients on real
            # sockets (QoS wall mode)
            now = time.monotonic()  # lint: allow[no-wall-clock]
            self._throttled_clients = {
                c: u for c, u in self._throttled_clients.items()
                if u > now}
        until = self._throttled_clients.get(msg.src)
        shed = depth >= admission_max or (
            until is not None and
            (depth >= low_water  # lint: allow[no-wall-clock]
             or time.monotonic() < until))
        if not shed:
            if until is not None:
                del self._throttled_clients[msg.src]
            return True
        window = float(g_conf.get_val("osd_op_queue_throttle_window"))
        if until is None:
            # first shed for this client: open its throttle window
            # (never re-extended on replays, or a retrying client
            # could be starved forever in wall mode)
            qos.inc(l_qos_throttle_events)
            self._throttled_clients[msg.src] = \
                time.monotonic() + window  # lint: allow[no-wall-clock]
        qos.inc(l_qos_admission_rejections)
        self.messenger.send_message(MOSDOpReply(
            tid=msg.tid, result=-11, epoch=self.osdmap.epoch,
            retry_after=max(window, 1e-3)), msg.src)
        return False

    def _handle_op(self, msg: MOSDOp) -> None:
        """Client op intake: lands in the sharded op queue (one PG's
        ops stay FIFO in their shard, OSD.cc ShardedOpWQ) and drains
        through the mClock arbiter — under bursts, QoS decides order.
        The client-tier dmClock lane is keyed by the sending entity
        (msg.src), so one abusive client cannot starve the rest."""
        # stage ledger: adopt the client's submit stamp (client_flight)
        # or open one here; the admission verdict is the next boundary
        led = intake_ledger(msg, self.name)
        if not self._admit_op(msg):
            return
        led.mark("admission")
        is_write = msg.op in ("write", "writefull", "append", "delete") \
            or any(o.op in ("write", "writefull", "append", "delete")
                   for o in msg.ops)
        self.perf_counters.inc(L_OSD_OP_W if is_write else L_OSD_OP_R)
        op = self.op_tracker.create_request(
            msg.trace_id, f"osd_op({msg.op} {msg.pool}/{msg.oid})")
        op.mark_event("queued_for_pg")
        # latency x bytes accounting resolved at reply time
        op.is_write = is_write
        op.num_bytes = len(msg.data) + sum(len(o.data) for o in msg.ops)
        op.queued_at = time.perf_counter()
        if g_tracer.enabled:
            # child of the client's root span; activated around do_op so
            # EC encode / kernel spans attach below it
            op.span = g_tracer.begin(
                f"osd_op:{msg.op or 'vector'}:{msg.oid}",
                daemon=self.name, trace_id=msg.trace_id,
                parent_id=msg.parent_span_id)
            if led.span is None:
                # no client-side root (tracing enabled after submit /
                # TCP arrival): the stage ledger rides the OSD's span
                led.span = op.span
        op.oplat = led
        self._tracked[(msg.src, msg.tid)] = op
        self.op_wq.enqueue(msg.pgid, CLASS_CLIENT, ("op", msg),
                           client=msg.src)
        from ..common.config import g_conf
        if bool(g_conf.get_val("osd_op_queue_batch_intake")):
            # burst intake (the traffic harness's mode): leave the op
            # queued so one fabric pump's worth of concurrent client
            # traffic accumulates and the mClock tiers arbitrate a REAL
            # burst; workers (threaded) or the cluster idle kick
            # (synchronous) drain at quiescence
            if self.op_tp is not None:
                self.op_tp.kick()
            return
        self.drain_ops()

    def drain_ops(self, max_ops: int = 0) -> int:
        if self.op_tp is not None:
            # workers drain concurrently; block until handled so the
            # in-process fabric's pump loops keep their semantics
            self.op_tp.flush()
            return 0
        return self.op_wq.drain(self._wq_handle, max_ops)

    def _wq_handle_locked(self, item) -> None:
        """Thread-pool handler: serialize per PG via its DebugLock (the
        reference's pg->lock() in dequeue_op, OSD.cc:9262)."""
        kind = item[0]
        if kind == "op":
            pg = self.pgs.get(item[1].pgid)
        else:
            pg = item[1]
        if pg is not None:
            with pg.op_lock:
                self._wq_handle(item)
        else:
            self._wq_handle(item)

    def _wq_handle(self, item) -> None:
        kind = item[0]
        if kind == "op":
            msg = item[1]
            pg = self.pgs.get(msg.pgid)
            if pg is None:
                self.send_op_reply(msg.src, MOSDOpReply(
                    tid=msg.tid, result=-11, epoch=self.osdmap.epoch))
                return
            tracked = self._tracked.get((msg.src, msg.tid))
            if tracked is not None:
                tracked.mark_event("reached_pg")
                t0 = getattr(tracked, "queued_at", None)
                if t0 is not None and msg.src:
                    # per-client queue-wait distribution (intake ->
                    # dequeue): the dmClock tier's effect made visible
                    # per entity on perf dump + mgr Prometheus
                    g_perf_histograms.get(
                        self._client_hist_lane(msg.src),
                        "client_queue_wait_latency_histogram",
                        latency_axes).inc(
                            (time.perf_counter() - t0) * 1e6)
            led = getattr(msg, "_oplat", None)
            if led is not None:
                # op-thread start: the interval since the lane pop is
                # the dequeue handoff (thread wakeup / shard transit)
                led.mark("dequeue_handoff")
            if tracked is not None and tracked.span is not None:
                with g_tracer.activate(tracked.span), \
                        g_oplat.activate(led):
                    pg.do_op(msg)
            else:
                with g_oplat.activate(led):
                    pg.do_op(msg)
        elif kind == "scrub":
            item[1].start_scrub(deep=item[2] if len(item) > 2 else False)
        elif kind == "pipeline":
            # deferred EC write-pipeline continuation (fan-out under
            # the PG lock — _wq_handle_locked took it via item[1])
            item[2]()
        elif kind == "recovery":
            # a repair round admitted by the recovery scheduler: it
            # reached here through the CLASS_RECOVERY dmClock lane, so
            # client vs repair ordering was the arbiter's call
            item[2]()

    def _client_hist_lane(self, src: str) -> str:
        if src in self._client_hist_lanes:
            return src
        if len(self._client_hist_lanes) >= 64:
            return "client.other"
        self._client_hist_lanes.add(src)
        return src

    def send_op_reply(self, dst: str, reply: MOSDOpReply) -> None:
        """All client replies funnel here so op tracking/latency see them."""
        op = self._tracked.pop((dst, reply.tid), None)
        if op is not None:
            op.mark_event("commit_sent" if reply.result == 0 else "error")
            led = getattr(op, "oplat", None)
            if led is not None:
                # the ledger's final boundary: everything since the
                # last mark (ack gathering's tail, reply build) is the
                # reply stage, and the op counts as fully accounted
                led.mark("reply")
                g_oplat.note_op()
            if op.span is not None:
                g_tracer.finish(op.span)
            op.finish()
            self.perf_counters.tinc(L_OSD_OP_LAT, op.duration)
            if getattr(op, "is_write", False):
                # write axis: payload bytes captured at intake
                self.hist_op_w.inc(op.duration * 1e6,
                                   getattr(op, "num_bytes", 0))
            else:
                # read axis: OUT bytes (reads carry no payload in; the
                # reference's op_r histogram also sizes by outdata).
                # Vector replies duplicate the last per-op payload into
                # reply.data, so count op_results OR data, never both
                out_bytes = sum(len(d) for _r, d in reply.op_results) \
                    if reply.op_results else len(reply.data)
                self.hist_op_r.inc(op.duration * 1e6, out_bytes)
        self.messenger.send_message(reply, dst)

    # ---- shard sub-ops ----------------------------------------------------
    def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        self.perf_counters.inc(L_OSD_SUBOP_W)
        if g_tracer.enabled and msg.parent_span_id:
            with g_tracer.span(f"sub_write:s{msg.shard}",
                               daemon=self.name, trace_id=msg.trace_id,
                               parent_id=msg.parent_span_id):
                self._do_handle_sub_write(msg)
        else:
            self._do_handle_sub_write(msg)

    def _do_handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        if msg.snapset_only:
            pg = self.pgs.get(msg.pgid)
            if pg is not None and msg.snapset_update is not None:
                t = Transaction()
                pg.apply_snapset_update(tuple(msg.snapset_update), t)
                self.store.queue_transaction(t)
                if msg.tid:
                    # acked fan-out (docs/ROBUSTNESS.md "unacked
                    # write-path classes"): a replayed snapset update
                    # is a full-blob replacement, so re-applying is
                    # idempotent — ack unconditionally
                    self.reply_to(msg, MOSDECSubOpWriteReply(
                        tid=msg.tid, pgid=msg.pgid, shard=msg.shard))
            return
        if msg.at_version < 0:  # delete marker
            self._apply_delete(msg)
            return
        pg = self.pgs.get(msg.pgid)
        if msg.shard < 0:
            # replicated full-copy write
            if pg is not None and pg.rep_backend is not None:
                pg.rep_backend.apply_write(msg, self.store)
                if msg.is_push and msg.tid:
                    # realign pushes are acked so the sender clears
                    # the pg_temp pin only once the copy is durable
                    self.messenger.send_message(MOSDECSubOpWriteReply(
                        tid=msg.tid, pgid=msg.pgid, shard=-1), msg.src)
            return
        if pg is not None and pg.backend is not None:
            reply = pg.backend.handle_sub_write(msg, self.store, pg=pg)
            self.reply_to(msg, reply)

    def _apply_delete(self, msg: MOSDECSubOpWrite) -> None:
        if msg.shard < 0:
            cid = f"{msg.pgid[0]}.{msg.pgid[1]}"
            ho = hobject_t(msg.oid)
        else:
            cid = f"{msg.pgid[0]}.{msg.pgid[1]}s{msg.shard}"
            ho = hobject_t(msg.oid, msg.shard)
        pg = self.pgs.get(msg.pgid)
        if msg.tid and pg is not None and msg.version:
            # resend dedup (tid-carrying client-delete fan-outs only —
            # recovery delete fans keep tid 0 and may legitimately
            # arrive with the log entry already merged): our log holds
            # this delete, so the original apply landed and only the
            # ack was lost.  Re-applying would overwrite the rollback
            # stash with post-delete state; just re-ack.  Versions
            # append monotonically, so scan from the tail and stop at
            # the first older entry — first arrivals pay O(1).
            for e in reversed(pg.pg_log.entries):
                if e.version < msg.version:
                    break
                if e.version == msg.version and e.oid == msg.oid:
                    self.reply_to(msg, MOSDECSubOpWriteReply(
                        tid=msg.tid, pgid=msg.pgid, shard=msg.shard))
                    return
        t = Transaction()
        if pg is not None and pg.backend is not None and msg.version:
            # EC shards stash the pre-delete state like writes do, so a
            # delete that reached too few shards can be rolled back
            from .ec_backend import stash_pre_write_state
            stash_pre_write_state(t, self.store, pg, msg.oid, cid, ho,
                                  msg.version)
        if self.store.collection_exists(cid):
            t.remove(cid, ho)
        if pg is not None and msg.version:
            pg.append_log(LogEntry(msg.version, msg.oid, OP_DELETE), t)
        if not t.empty():
            self.store.queue_transaction(t)
        if pg is not None:
            pg.data_received(msg.oid)  # debt settled: object is gone
        if msg.tid:
            self.reply_to(msg, MOSDECSubOpWriteReply(
                tid=msg.tid, pgid=msg.pgid, shard=msg.shard))

    def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        self.perf_counters.inc(L_OSD_SUBOP_R)
        if g_tracer.enabled and msg.parent_span_id:
            with g_tracer.span(f"sub_read:s{msg.shard}",
                               daemon=self.name, trace_id=msg.trace_id,
                               parent_id=msg.parent_span_id):
                self._do_handle_sub_read(msg)
        else:
            self._do_handle_sub_read(msg)

    def _do_handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        pg = self.pgs.get(msg.pgid)
        if pg is None:
            self.reply_to(msg, MOSDECSubOpReadReply(
                tid=msg.tid, pgid=msg.pgid, shard=msg.shard, oid=msg.oid,
                result=-11))
            return
        if msg.shard < 0:
            # replicated full-object read (recovery pulls)
            if pg.rep_backend is not None:
                exists, data, uattrs, omap = \
                    pg.rep_backend.object_state(msg.oid)
            else:
                exists = False
            if not exists:
                self.reply_to(msg, MOSDECSubOpReadReply(
                    tid=msg.tid, pgid=msg.pgid, shard=-1, oid=msg.oid,
                    result=-2))
            else:
                from .ec_backend import USER_ATTR_PREFIX
                attrs = {SIZE_ATTR: struct.pack("<Q", len(data))}
                for k, v in uattrs.items():
                    attrs[USER_ATTR_PREFIX + k] = v
                # omap rides the attr dict under a reserved key (the
                # reference pushes omap in its own push payload section)
                if omap:
                    from ..msg.kv import pack_kv
                    attrs["_omap_kv"] = pack_kv(omap)
                self.reply_to(msg, MOSDECSubOpReadReply(
                    tid=msg.tid, pgid=msg.pgid, shard=-1, oid=msg.oid,
                    data=data, result=0, attrs=attrs))
            return
        if pg.backend is not None:
            reply = pg.backend.handle_sub_read(msg, self.store)
            self.reply_to(msg, reply)

    # ---- heartbeats / failure detection -----------------------------------
    def tick(self, now: float) -> None:
        """Heartbeat tick: ping peers, report silent ones to the mon."""
        self.now = now
        # flush EC dispatch batches whose collection window expired
        # (async submitters without a result() demand rely on this)
        from ..dispatch import g_dispatcher
        g_dispatcher.poll()
        # probe-cadence floor for the chip-health scoreboard: traffic
        # that flushed since the last skew probe guarantees the NEXT
        # mesh flush probes, so a quiet cluster's Nth-flush counter
        # cannot starve the skew signal (mesh/chipstat.py; pure int
        # reads, zero cost with sampling off)
        from ..mesh import g_chipstat
        g_chipstat.tick_kick()
        peers = [o for o in range(self.osdmap.max_osd)
                 if o != self.osd_id and self.osdmap.is_up(o)]
        for peer in peers:
            self.messenger.send_message(
                MOSDPing(op=MOSDPing.PING, stamp=now,
                         epoch=self.osdmap.epoch), f"osd.{peer}")
        self.maybe_schedule_scrubs()
        self._report_strays()
        self.report_pg_stats()
        # drain repair rounds parked by pacing (slots may have freed
        # outside the completion path, e.g. a fallback round)
        self.recovery_sched.kick()
        # map says down but we are alive: keep asking back in every tick
        # (the reference's OSD::start_boot retries; a single send can be
        # lost while connections re-establish after a daemon reboot)
        if 0 <= self.osd_id < self.osdmap.max_osd and \
                self.osdmap.epoch > 0 and \
                not self.osdmap.is_up(self.osd_id):
            from ..msg.messages import MOSDBoot
            for mon in self.mon_names:
                self.messenger.send_message(
                    MOSDBoot(osd=self.osd_id, epoch=self.osdmap.epoch),
                    mon)
        # sweep probe callbacks whose replies died with their peer
        for tid in [t for t, t0 in self._rep_pull_stamps.items()
                    if now - t0 > 60.0]:
            self._rep_pull_stamps.pop(tid, None)
            self._rep_pulls.pop(tid, None)
        if self.op_tp is None and self.op_wq.wall and len(self.op_wq):
            # synchronous wall-clock mode: rate-blocked ops queued with
            # no worker threads must be re-driven from the tick, or a
            # pause in client traffic strands them forever
            self.drain_ops()
        for pg in self.pgs.values():
            if pg._notifies:
                pg.sweep_notifies()
            pg.retry_pending_pg_temp()
            pg.retry_peering()
            if pg.backend is not None and pg.backend.inflight_writes:
                # in-flight sweep: resend unacked EC sub-op writes so a
                # messenger-level drop cannot wedge the per-oid write
                # pipeline until peering (docs/ROBUSTNESS.md)
                pg.backend.sweep_inflight(now)
            pg.maybe_realign()
            if pg.tier is not None and pg.is_primary():
                pg.tier.agent_work(now)
            # stuck recoveries (reply chain lost to a map race or a
            # mid-flight death): forget and re-drive them
            stale = [oid for oid, t0 in pg._recovering_since.items()
                     if now - t0 > RECOVERY_RETRY]
            for oid in stale:
                pg._recovering_since.pop(oid, None)
                if oid in pg._recovering:
                    self.dout(3, f"recovery of {oid} pg {pg.pgid} "
                              "stalled; re-kicking")
                    pg._recovering.discard(oid)
                    self.request_recovery(pg)
        # tier ops whose reply never came (base primary died, message
        # lost): fail them so promotes/flushes unwind and retry
        with self._tier_lock:
            expired = [(tid, ent) for tid, ent in self._tier_ops.items()
                       if now - ent[1] > RECOVERY_RETRY]
            for tid, _ent in expired:
                del self._tier_ops[tid]
        for tid, (cb, _t0) in expired:
            cb(MOSDOpReply(tid=tid, result=-110))
        for peer in peers:
            last = self.last_ping_reply.get(peer, now)
            self.last_ping_reply.setdefault(peer, now)
            if now - last > HEARTBEAT_GRACE:
                self.dout(1, f"heartbeat: no reply from osd.{peer} "
                          f"since {last:.1f}, reporting failure")
                # keep re-sending while the peer stays silent: the mon
                # leadership may change mid-outage and a one-shot report
                # to a dead leader would blind failure detection (the
                # reference OSD also re-reports until the mark)
                for mon in self.mon_names:
                    self.messenger.send_message(
                        MOSDFailure(target_osd=peer, failed_since=last,
                                    epoch=self.osdmap.epoch,
                                    reporter=self.name), mon)

    def report_pg_stats(self, mgr_name: str = "mgr",
                        every: int = 5) -> None:
        """Primary PGs report object counts + logical bytes to the mgr
        (MPGStats / MgrClient role); the network drops the send when no
        mgr exists.  Logical size comes from SIZE_ATTR (un-padded), so
        replicated and EC pools account the same bytes.  The store scan
        is O(objects), so it runs every ``every``-th tick (the
        reference's mgr_stats_period), starting with the first."""
        self._stats_tick = getattr(self, "_stats_tick", -1) + 1
        if every > 1 and self._stats_tick % every:
            return
        from ..msg.messages import MPGStats
        from .ec_backend import SIZE_ATTR
        from .pg_log import PG_META_OID
        stats = []
        for pgid, pg in self.pgs.items():
            if not pg.is_primary():
                continue
            cids = pg.data_cids()
            n_obj = n_bytes = 0
            for cid in cids:
                if not self.store.collection_exists(cid):
                    continue
                for ho in self.store.list_objects(cid):
                    if ho.oid == PG_META_OID:
                        continue
                    n_obj += 1
                    sz = self.store.getattrs(cid, ho).get(SIZE_ATTR)
                    if sz is not None:
                        n_bytes += struct.unpack("<Q", sz)[0]
                    else:
                        n_bytes += self.store.stat(cid, ho)
            stats.append((pgid[0], pgid[1], n_obj, n_bytes))
        # osd_stat_t role: total logical bytes on this OSD's primary
        # PGs against the configured capacity.  Sent even when the
        # stats list is empty — an OSD whose primaries all moved away
        # must not leave its last (possibly full) usage pinned at the
        # mgr.  Replica-only bytes are invisible to this logical
        # accounting — a known lite-ism.
        from ..common.config import g_conf
        capacity = int(g_conf.get_val("osd_capacity_bytes") or 0)
        total = sum(b for (_p, _s, _o, b) in stats)
        self.messenger.send_message(MPGStats(
            osd=self.osd_id, epoch=self.osdmap.epoch,
            pg_stats=stats, store_bytes=total,
            store_capacity=capacity), mgr_name)

    def clog(self, level: str, message: str) -> None:
        """Send a cluster-log entry to the mons (clog->error()/info()
        role).  Every mon gets a copy, like the failure-report loop
        above — a single-target send dies with that mon.  Peons forward
        to the leader, which dedups identical (stamp, who, message)
        arrivals so the fan-out still commits exactly once."""
        from ..msg.messages import MLog
        for mon in self.mon_names:
            self.messenger.send_message(MLog(
                who=self.name, level=level, message=message,
                stamp=self.now), mon)

    def maybe_schedule_scrubs(self) -> None:
        """Periodic background scrub scheduling (the OSD's scrub
        scheduler role, OSD.cc sched_scrub): each primary PG scrubs
        every osd_scrub_min_interval seconds, staggered per PG so a
        whole cluster never scrubs at one instant (the reference
        randomizes with osd_scrub_interval_randomize_ratio)."""
        from ..common.config import g_conf
        if not g_conf.get_val("osd_scrub_auto"):
            return
        interval = float(g_conf.get_val("osd_scrub_min_interval"))
        deep_interval = float(g_conf.get_val("osd_deep_scrub_interval"))
        for pg in self.pgs.values():
            if not pg.is_primary():
                continue
            frac = (hash(pg.pgid) % 997) / 997.0
            stagger = frac * interval * 0.1
            # a due shallow scrub is upgraded to deep when the (longer)
            # deep interval has also lapsed — the reference's
            # sched_scrub deep-upgrade decision.  The deep stagger
            # scales with ITS interval: data-reading scrubs are the
            # ones that must not all fire in one tick
            deep = (self.now - pg.last_deep_scrub_stamp
                    >= deep_interval + frac * deep_interval * 0.1)
            if deep or self.now - pg.last_scrub_stamp >= \
                    interval + stagger:
                self.dout(5, f"sched_scrub pg {pg.pgid}"
                             f"{' (deep)' if deep else ''}")
                # start_scrub stamps on an ACTUAL start; a PG that is
                # peering right now simply retries next tick
                self.op_wq.enqueue(pg.pgid, CLASS_SCRUB,
                                   ("scrub", pg, deep))
        self.drain_ops()

    def _handle_ping(self, msg: MOSDPing) -> None:
        if msg.op == MOSDPing.PING:
            self.messenger.send_message(
                MOSDPing(op=MOSDPing.PING_REPLY, stamp=msg.stamp,
                         epoch=self.osdmap.epoch), msg.src)
        else:
            peer = int(msg.src.split(".")[1])
            self.last_ping_reply[peer] = self.now
        if msg.epoch > self.osdmap.epoch:
            # a peer runs a newer map than ours — our MOSDMap delivery
            # was lost (droppable fabric): re-subscribe for the full
            # history (OSD::osdmap_subscribe on a detected gap).
            # Rate-limited by time, not epoch, so a lost subscribe or
            # reply just retries on the next heartbeat round.
            if self.now - getattr(self, "_map_catchup_at", -1e9) > 2.0:
                self._map_catchup_at = self.now
                from ..msg.messages import MMonSubscribe
                for mon in self.mon_names:
                    self.messenger.send_message(MMonSubscribe(), mon)

    # ---- tier client (Objecter-lite for promote/flush) ---------------------
    def tier_submit(self, pool_id: int, oid: str, ops,
                    on_reply: Callable) -> None:
        """Send an op vector to *pool_id*'s primary on this OSD's own
        behalf (the cache PG acting as a client of its base pool —
        PrimaryLogPG's copy-from/flush ops role).  An unreachable or
        unanswering target fails the op via the tick timeout sweep so
        callers never park forever."""
        from ..osdmap.types import ceph_stable_mod
        pool = self.osdmap.get_pg_pool(pool_id)
        primary = -1
        ps = 0
        if pool is not None:
            raw = self.osdmap.map_to_pg(pool_id, oid)
            ps = ceph_stable_mod(raw.ps, pool.pg_num, pool.pg_num_mask)
            *_, _acting, primary = self.osdmap.pg_to_up_acting_osds(
                pg_t(pool_id, ps))
        if pool is None or primary < 0:
            # park the failure for the next tick sweep: failing INLINE
            # would recurse promote -> tier_submit -> promote with no
            # base case while the target stays unreachable
            with self._tier_lock:
                self._tier_tid += 1
                self._tier_ops[self._tier_tid] = (
                    on_reply, self.now - RECOVERY_RETRY - 1.0)
            return
        with self._tier_lock:
            self._tier_tid += 1
            tid = self._tier_tid
            self._tier_ops[tid] = (on_reply, self.now)
        self.messenger.send_message(
            MOSDOp(tid=tid, pool=pool_id, oid=oid, pgid=(pool_id, ps),
                   epoch=self.osdmap.epoch, ops=list(ops)),
            f"osd.{primary}")

    # ---- recovery (message-driven; ECBackend.cc:535-743) -------------------
    def request_recovery(self, pg: PG) -> None:
        if pg not in self._recovery_queue:
            self._recovery_queue.append(pg)

    def run_recovery(self) -> int:
        """Drive queued PG recovery; returns recoveries initiated.  All
        data movement is messages; completions chain through the fabric."""
        started = 0
        queue, self._recovery_queue = self._recovery_queue, []
        for pg in queue:
            started += self._continue_pg_recovery(pg)
        return started

    def _continue_pg_recovery(self, pg: PG) -> int:
        if not pg.is_primary():
            return 0
        started = 0
        # own shard first: the primary's store must become authoritative
        # before backfill diffs use it
        my = pg.my_shard()
        shards = sorted(pg.missing, key=lambda s: (s != my, s))
        for shard in shards:
            for oid in list(pg.missing.get(shard, {})):
                if oid not in pg._recovering:
                    self.recover_oid(pg, oid)
                    started += 1
        return started

    def recover_oid(self, pg: PG, oid: str) -> None:
        """Recover one object on every shard missing it."""
        if oid in pg._recovering:
            return
        targets = {s: pg.missing[s][oid]
                   for s in pg.missing if oid in pg.missing[s]}
        if not targets:
            pg.recovery_done_for(oid)
            return
        pg._recovering.add(oid)
        pg._recovering_since[oid] = self.now
        self.dout(5, f"recover_oid {oid} pg {pg.pgid} "
                  f"targets {sorted(targets)}", )
        if all(op == OP_DELETE for (_v, op) in targets.values()):
            for s, (v, _op) in targets.items():
                osd = pg.acting_shards().get(s)
                if osd is not None:
                    pg.send_to_osd(osd, MOSDECSubOpWrite(
                        tid=0, pgid=pg.pgid,
                        shard=s if pg.backend is not None else -1,
                        oid=oid, chunk=b"", at_version=-1, version=v))
                pg.missing[s].pop(oid, None)
            pg.recovery_done_for(oid)
            return
        if pg.backend is not None:
            self._recover_ec_oid(pg, oid, targets)
        else:
            self._recover_rep_oid(pg, oid, targets)

    def _recover_ec_oid(self, pg: PG, oid: str,
                        targets: Dict[int, Tuple[int, str]]) -> None:
        needed = sorted(s for s, (_v, op) in targets.items()
                        if op != OP_DELETE)
        # probe phase: a "missing" peer may already hold the object at
        # the target version — the primary's log-delta cannot see data
        # that landed ahead of the log entries (realign pushes,
        # interrupted prior recoveries).  A version-matching reply
        # settles the debt without moving bytes; mismatches fall
        # through to the decode+push path.
        from .pg_log import VERSION_ATTR
        acting = pg.acting_shards()
        probes = [s for s in needed
                  if s in acting and self.osdmap.is_up(acting[s])]
        state = {"left": len(probes)}
        # generation guard: replies from a SUPERSEDED probe round (the
        # recovery was re-kicked after RECOVERY_RETRY) must not run
        # after_probes a second time concurrently with the new round
        generation = pg._recovering_since.get(oid)

        def current() -> bool:
            return pg._recovering_since.get(oid) == generation

        def after_probes() -> None:
            remaining = sorted(s for s in needed
                               if oid in pg.missing.get(s, {}))
            if not remaining:
                for s in needed:
                    if not pg.missing.get(s):
                        pg.send_backfill_complete(s)
                pg.recovery_done_for(oid)
                pg._maybe_clean()
                return
            self._recover_ec_oid_push(pg, oid, targets, remaining)

        if not probes:
            self._recover_ec_oid_push(pg, oid, targets, needed)
            return
        for s in probes:
            v_expect = targets[s][0]
            tid = self.next_pull_tid()

            def on_probe(reply, s=s, v_expect=v_expect) -> None:
                if not current():
                    return              # superseded round's late reply
                vb = reply.attrs.get(VERSION_ATTR) \
                    if reply.result == 0 and reply.oid == oid \
                    and reply.shard == s else None
                if vb is not None and \
                        struct.unpack("<Q", vb)[0] >= v_expect:
                    pg.missing.get(s, {}).pop(oid, None)
                state["left"] -= 1
                if state["left"] == 0:
                    after_probes()
            self._rep_pulls[tid] = on_probe
            self._rep_pull_stamps[tid] = self.now
            pg.send_to_osd(acting[s], MOSDECSubOpRead(
                tid=tid, pgid=pg.pgid, shard=s, oid=oid,
                attrs_only=True))

    def _recover_ec_oid_push(self, pg: PG, oid: str,
                             targets: Dict[int, Tuple[int, str]],
                             needed) -> None:
        # repair-optimal path first (ceph_tpu/recovery): a single lost
        # shard of a regenerating-code pool rebuilds from d sub-chunk
        # helper contributions instead of k whole chunks; the scheduler
        # owns pacing/QoS/accounting and falls back here on any failure
        if self.recovery_sched.try_repair(pg, oid, targets,
                                          list(needed)):
            return
        self._recover_ec_oid_fullstripe(pg, oid, targets, needed)

    def _recover_ec_oid_fullstripe(self, pg: PG, oid: str,
                                   targets: Dict[int, Tuple[int, str]],
                                   needed) -> None:
        be = pg.backend

        def on_chunks(result: int, chunks: Dict[int, bytes],
                      size: int, attrs: Dict[str, bytes]) -> None:
            if result != 0:
                # sources unavailable right now; retry on the next kick
                pg._recovering.discard(oid)
                self.request_recovery(pg)
                return
            self.recovery_sched.note_fullstripe(
                be.ec_impl, sum(len(b) for b in chunks.values()),
                len(needed))
            rec = be.recover_object(oid, set(needed), chunks, size)
            version = max(v for (v, _op) in targets.values())

            def pushed() -> None:
                self.dout(5, f"recovery push of {oid} acked by "
                          f"{sorted(needed)}")
                for s in needed:
                    pg.missing.get(s, {}).pop(oid, None)
                    if not pg.missing.get(s):
                        pg.send_backfill_complete(s)
                self.perf_counters.inc(L_OSD_RECOVERY_PUSH, len(needed))
                pg.recovery_done_for(oid)

            self.dout(5, f"recovery pushing {oid} -> shards "
                      f"{sorted(needed)} acting {pg.acting}")
            self.recovery_sched.note_push(
                sum(len(rec[s]) for s in needed))
            be.push_chunks(oid, {s: rec[s] for s in needed}, size, pushed,
                           version=version, xattrs=attrs)

        be.read_chunks(oid, on_chunks)

    def _recover_rep_oid(self, pg: PG, oid: str,
                         targets: Dict[int, Tuple[int, str]]) -> None:
        data = pg.rep_backend.read(oid)
        my = pg.my_shard()
        if data is not None and my not in targets:
            # our copy is current (we are not in the missing set)
            self._push_rep(pg, oid, data, targets)
            return
        # primary lacks its own copy — or holds a STALE one (it is in
        # targets): pushing local bytes would resurrect pre-flap data,
        # so pull the authoritative copy from a healthy peer first
        srcs = [s for s, osd in pg.acting_shards().items()
                if s not in targets and osd != self.osd_id]
        if not srcs:
            pg._recovering.discard(oid)
            return
        self._pull_tid += 1
        tid = self._pull_tid

        def on_pull(msg: MOSDECSubOpReadReply) -> None:
            if msg.result != 0:
                pg._recovering.discard(oid)
                self.request_recovery(pg)
                return
            # apply locally, then fan to the other missing shards
            my = pg.my_shard()
            v = targets.get(my, (0, ""))[0]
            uattrs, omap = _unpack_pull_meta(msg.attrs)
            wr = MOSDECSubOpWrite(tid=0, pgid=pg.pgid, shard=-1, oid=oid,
                                  chunk=msg.data, offset=0, partial=False,
                                  at_version=len(msg.data), version=v,
                                  is_push=True, xattrs=uattrs, omap=omap)
            pg.rep_backend.apply_write(wr, self.store)
            pg.missing.get(my, {}).pop(oid, None)
            rest = {s: t for s, t in targets.items() if s != my}
            self._push_rep(pg, oid, msg.data, rest,
                           xattrs=uattrs, omap=omap)

        self._rep_pulls[tid] = on_pull
        # stamped like the probe path: the sweep in tick() reaps this
        # closure if the source dies before replying
        self._rep_pull_stamps[tid] = self.now
        pg.send_to_osd(pg.acting_shards()[srcs[0]], MOSDECSubOpRead(
            tid=tid, pgid=pg.pgid, shard=-1, oid=oid))

    def _push_rep(self, pg: PG, oid: str, data: bytes,
                  targets: Dict[int, Tuple[int, str]],
                  xattrs: Optional[Dict[str, bytes]] = None,
                  omap: Optional[Dict[str, bytes]] = None) -> None:
        if xattrs is None and pg.rep_backend is not None:
            # pushing our own authoritative copy: include its metadata
            _ex, _d, xattrs, omap = pg.rep_backend.object_state(oid)
        acting = pg.acting_shards()
        for s, (v, _op) in targets.items():
            osd = acting.get(s)
            if osd is None or osd == self.osd_id:
                continue
            pg.send_to_osd(osd, MOSDECSubOpWrite(
                tid=0, pgid=pg.pgid, shard=-1, oid=oid, chunk=data,
                offset=0, partial=False, at_version=len(data),
                version=v, is_push=True, xattrs=xattrs, omap=omap))
            self.perf_counters.inc(L_OSD_RECOVERY_PUSH)
        for s in list(targets):
            pg.missing.get(s, {}).pop(oid, None)
        # NOTE: no send_backfill_complete here — rep pushes are
        # fire-and-forget (no ack path), so adopting the log now could
        # mask a lost push as a complete replica.  A log-less rep
        # target is merely re-pushed on the next peering round (any
        # single copy serves reads, unlike EC's k-source requirement).
        pg.recovery_done_for(oid)
