"""PGLog — per-PG operation log enabling log-bounded (delta) recovery.

Mirrors the reference's src/osd/PGLog.{h,cc} role: every mutation appends
a (version, oid, op) entry on every shard in the same transaction as the
data write; after a flap, the primary computes each peer's missing set by
replaying only the log suffix past the peer's last_update instead of
rescanning stores.  A peer whose last_update fell behind the log tail is
beyond log-bounded repair and goes through backfill (full listing diff),
like the reference's backfill path.

Entries persist in the shard store: a per-PG meta object holds the log in
omap (key = zero-padded version) and last_update/tail as attrs, so a
restarted OSD resumes from its on-disk state (OSD.cc:2469+ resume model).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..os_store import MemStore, Transaction, hobject_t

OP_MODIFY = "m"
OP_DELETE = "d"

PG_META_OID = "_pgmeta"          # per-shard-collection meta object
SNAPSET_KEY_PREFIX = "ss\x00"    # meta omap namespace for per-oid snapsets

# snapset entry kinds (SnapSet clone bookkeeping, osd_types.h SnapSet)
SNAP_CLONE = 1       # a clone object exists for this seq
SNAP_WHITEOUT = 0    # object did not exist when this seq was crossed
SNAP_TRIMMED = 2     # tombstone: entries up to this seq were trimmed —
                     # keeps stale peers from resurrecting dead clones
LAST_UPDATE_ATTR = "_last_update"
LOG_TAIL_ATTR = "_log_tail"
VERSION_ATTR = "_version"        # per-object: pg_log version of its data

DEFAULT_LOG_ENTRIES = 500        # osd_min_pg_log_entries-style bound


@dataclass(frozen=True)
class LogEntry:
    version: int
    oid: str
    op: str        # OP_MODIFY | OP_DELETE

    def encode(self) -> bytes:
        o = self.oid.encode()
        return struct.pack("<QB", self.version,
                           1 if self.op == OP_DELETE else 0) + o

    @classmethod
    def decode(cls, b: bytes) -> "LogEntry":
        version, d = struct.unpack_from("<QB", b)
        return cls(version=version, oid=b[9:].decode(),
                   op=OP_DELETE if d else OP_MODIFY)


class PGLog:
    """In-memory log mirror with store-backed persistence."""

    def __init__(self, max_entries: int = DEFAULT_LOG_ENTRIES):
        self.entries: List[LogEntry] = []
        self.tail = 0          # every version <= tail has been trimmed
        self.head = 0          # last_update
        self.max_entries = max_entries

    # ---- mutation ----------------------------------------------------------
    def append(self, entry: LogEntry, t: Transaction, cid: str) -> None:
        """Record the entry and stage its persistence into *t* (same
        transaction as the data mutation, the reference's atomicity)."""
        assert entry.version > self.head, (entry.version, self.head)
        self.entries.append(entry)
        self.head = entry.version
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        t.omap_setkeys(cid, meta, {self._key(entry.version): entry.encode()})
        t.setattr(cid, meta, LAST_UPDATE_ATTR, struct.pack("<Q", self.head))
        if len(self.entries) > self.max_entries:
            self._trim(t, cid)

    def _trim(self, t: Transaction, cid: str) -> None:
        drop = self.entries[:-self.max_entries]
        self.entries = self.entries[-self.max_entries:]
        self.tail = self.entries[0].version - 1 if self.entries else self.head
        meta = hobject_t(PG_META_OID)
        t.omap_rmkeys(cid, meta, [self._key(e.version) for e in drop])
        # rollback stashes are only consumable while their entry can
        # still be divergent-rewound, i.e. while the oid has an in-log
        # entry; once its last entry trims, drop the stash (the
        # reference similarly trims rollback info past can_rollback_to)
        live = {e.oid for e in self.entries}
        dead = sorted({ROLLBACK_KEY_PREFIX + e.oid for e in drop
                       if e.oid not in live})
        if dead:
            t.omap_rmkeys(cid, meta, dead)
        t.setattr(cid, meta, LOG_TAIL_ATTR, struct.pack("<Q", self.tail))

    @staticmethod
    def _key(version: int) -> str:
        return f"{version:020d}"

    # ---- queries -----------------------------------------------------------
    def entries_after(self, version: int) -> Optional[List[LogEntry]]:
        """Log suffix past *version*, or None when the log was trimmed
        beyond it (-> backfill)."""
        if version < self.tail:
            return None
        return [e for e in self.entries if e.version > version]

    def missing_after(self, version: int
                      ) -> Optional[Dict[str, Tuple[int, str]]]:
        """oid -> (latest version, op) for everything changed past
        *version*; None = out of log bounds."""
        suffix = self.entries_after(version)
        if suffix is None:
            return None
        out: Dict[str, Tuple[int, str]] = {}
        for e in suffix:
            out[e.oid] = (e.version, e.op)
        return out

    def merge_authoritative(self, entries: List[LogEntry], t: Transaction,
                            cid: str) -> None:
        """Adopt an authoritative log suffix (primary catching up to a
        peer that saw newer writes — the GetLog step)."""
        for e in entries:
            if e.version > self.head:
                self.append(e, t, cid)

    def rewind_to(self, version: int, t: Transaction,
                  cid: str) -> List[LogEntry]:
        """Drop every entry past *version* and move the head back
        (rewind_divergent_log, src/osd/PGLog.cc): the divergent suffix
        is returned (ascending) so the caller can roll the touched
        objects back.  Persistence rides *t* like append's."""
        dropped = [e for e in self.entries if e.version > version]
        if not dropped:
            return []
        self.entries = [e for e in self.entries if e.version <= version]
        self.head = max(version, self.tail)
        meta = hobject_t(PG_META_OID)
        t.touch(cid, meta)
        t.omap_rmkeys(cid, meta, [self._key(e.version) for e in dropped])
        t.setattr(cid, meta, LAST_UPDATE_ATTR, struct.pack("<Q", self.head))
        return dropped

    def split_into(self, child: "PGLog", child_oids,
                   t_parent: Transaction, parent_cid: str,
                   t_child: Transaction, child_cid: str) -> None:
        """Move entries for *child_oids* out of this log into *child*
        (PGLog::split_into role).  Both logs keep the parent's
        head/tail so peering version comparisons stay consistent
        across the identically-split replicas; persistence rides the
        two transactions."""
        meta = hobject_t(PG_META_OID)
        child_entries = [e for e in self.entries if e.oid in child_oids]
        self.entries = [e for e in self.entries
                        if e.oid not in child_oids]
        child.head = self.head
        child.tail = self.tail
        child.entries = child_entries
        t_parent.touch(parent_cid, meta)
        t_parent.omap_rmkeys(parent_cid, meta,
                             [self._key(e.version)
                              for e in child_entries])
        t_child.touch(child_cid, meta)
        t_child.omap_setkeys(child_cid, meta,
                             {self._key(e.version): e.encode()
                              for e in child_entries})
        for t, cid in ((t_parent, parent_cid), (t_child, child_cid)):
            t.setattr(cid, meta, LAST_UPDATE_ATTR,
                      struct.pack("<Q", self.head))
            t.setattr(cid, meta, LOG_TAIL_ATTR,
                      struct.pack("<Q", self.tail))

    # ---- persistence -------------------------------------------------------
    def load(self, store: MemStore, cid: str) -> None:
        meta = hobject_t(PG_META_OID)
        if not store.collection_exists(cid) or not store.exists(cid, meta):
            return
        attrs = store.getattrs(cid, meta)
        if LAST_UPDATE_ATTR in attrs:
            self.head = struct.unpack("<Q", attrs[LAST_UPDATE_ATTR])[0]
        if LOG_TAIL_ATTR in attrs:
            self.tail = struct.unpack("<Q", attrs[LOG_TAIL_ATTR])[0]
        omap = store.omap_get(cid, meta)
        self.entries = sorted(
            (LogEntry.decode(v) for k, v in omap.items()
             if k.isdigit()),       # skip snapset/rollback namespaces
            key=lambda e: e.version)
        if self.entries:
            self.head = max(self.head, self.entries[-1].version)


# ---- rollback stashes (EC interrupted-write consistency) -------------------
#
# The reference makes EC writes atomic-per-stripe by writing append-only
# and recording roll-back info in the PG log (ECTransaction.h rollback
# extents; doc/dev/osd_internals/erasure_coding/ecbackend.rst:1-27).  The
# equivalent here: every versioned shard apply stashes the object's
# pre-write state (body + attrs) in the meta object's omap first, in the
# SAME transaction, so peering can restore it if the write proves
# divergent (reached fewer than k shards before the primary died).  One
# stash per object — writes on one object serialize through the backend's
# per-object queue, so at most one write per object is ever in flight.

ROLLBACK_KEY_PREFIX = "rb\x00"   # meta omap namespace for the stashes


def encode_rollback(replaced_version: int, prev_exists: bool,
                    prev_data: bytes,
                    prev_attrs: Dict[str, bytes]) -> bytes:
    parts = [struct.pack("<QBI", replaced_version,
                         1 if prev_exists else 0, len(prev_data)),
             prev_data, struct.pack("<I", len(prev_attrs))]
    for k, v in prev_attrs.items():
        kb = k.encode()
        parts.append(struct.pack("<II", len(kb), len(v)))
        parts.append(kb)
        parts.append(v)
    return b"".join(parts)


def decode_rollback(blob: bytes
                    ) -> Tuple[int, bool, bytes, Dict[str, bytes]]:
    version, exists, dlen = struct.unpack_from("<QBI", blob)
    off = 13
    data = blob[off:off + dlen]
    off += dlen
    (n,) = struct.unpack_from("<I", blob, off)
    off += 4
    attrs: Dict[str, bytes] = {}
    for _ in range(n):
        klen, vlen = struct.unpack_from("<II", blob, off)
        off += 8
        attrs[blob[off:off + klen].decode()] = blob[off + klen:
                                                    off + klen + vlen]
        off += klen + vlen
    return version, bool(exists), data, attrs


def stage_rollback(t: Transaction, cid: str, oid: str,
                   blob: bytes) -> None:
    meta = hobject_t(PG_META_OID)
    t.touch(cid, meta)
    t.omap_setkeys(cid, meta, {ROLLBACK_KEY_PREFIX + oid: blob})


def clear_rollback(t: Transaction, cid: str, oid: str) -> None:
    meta = hobject_t(PG_META_OID)
    t.omap_rmkeys(cid, meta, [ROLLBACK_KEY_PREFIX + oid])


def load_rollback(store: MemStore, cid: str, oid: str
                  ) -> Optional[Tuple[int, bool, bytes, Dict[str, bytes]]]:
    meta = hobject_t(PG_META_OID)
    if not store.collection_exists(cid) or not store.exists(cid, meta):
        return None
    blob = store.omap_get(cid, meta).get(ROLLBACK_KEY_PREFIX + oid)
    return decode_rollback(blob) if blob else None


# ---- snapsets (per-head clone bookkeeping in the same meta object) ---------

def encode_snapset(entries: List[Tuple[int, int]]) -> bytes:
    """[(seq, kind)] sorted ascending -> packed bytes."""
    return b"".join(struct.pack("<QB", s, k) for s, k in entries)


def decode_snapset(blob: bytes) -> List[Tuple[int, int]]:
    out = []
    for off in range(0, len(blob), 9):
        s, k = struct.unpack_from("<QB", blob, off)
        out.append((s, k))
    return out


def stage_snapset(t: Transaction, cid: str, oid: str, blob: bytes) -> None:
    """Stage a snapset write/removal into the meta object (same
    transaction as the data mutation it accompanies)."""
    meta = hobject_t(PG_META_OID)
    t.touch(cid, meta)
    key = SNAPSET_KEY_PREFIX + oid
    if blob:
        t.omap_setkeys(cid, meta, {key: blob})
    else:
        t.omap_rmkeys(cid, meta, [key])


def load_snapsets(store: MemStore, cid: str) -> Dict[str, List[Tuple[int, int]]]:
    meta = hobject_t(PG_META_OID)
    if not store.collection_exists(cid) or not store.exists(cid, meta):
        return {}
    out = {}
    for k, v in store.omap_get(cid, meta).items():
        if k.startswith(SNAPSET_KEY_PREFIX):
            out[k[len(SNAPSET_KEY_PREFIX):]] = decode_snapset(v)
    return out
