"""ceph_tpu — a TPU-native framework providing Ceph's OSD-side compute capabilities.

Built from scratch in JAX/XLA (device path) + numpy/C++ (host oracles),
re-designed TPU-first rather than ported.  Reference for semantics (not code):
gencer/ceph v12.1.2, mounted read-only at /root/reference.

Subpackages
-----------
- ``ceph_tpu.gf``      GF(2^8) arithmetic, RS matrix generation (host math core)
- ``ceph_tpu.ec``      ErasureCodeInterface-compatible plugin stack (jerasure/isa
                       semantics, LRC, SHEC, XOR) with host and TPU backends
- ``ceph_tpu.ops``     device kernels (GF(2^8) MXU bit-matmul incl. a Pallas
                       variant, batched stripes, straw2 draw)
- ``ceph_tpu.crush``   CRUSH: data model, builder, exact host mapper, compiler,
                       tester, and the vmapped device mapper
- ``ceph_tpu.osd``     OSDMap/epochs, batch PG mapping, ECUtil striping,
                       ECBackend-style rmw + recovery, memstore
- ``ceph_tpu.msg``     messenger fabric: in-process + TCP transports, wire codec
- ``ceph_tpu.cluster`` vstart-lite single-process mini-cluster
- ``ceph_tpu.trace``   observability: cross-daemon spans, perf histograms,
                       slow-op flight recorder
- ``ceph_tpu.parallel``device mesh / sharding helpers (dp over stripes, tp over
                       shards, multi-host ready)
- ``ceph_tpu.tools``   crushtool / osdmaptool / ec benchmark CLI equivalents
- ``ceph_tpu.utils``   buffers, config registry, perf counters, crc32c, hashes
"""

__version__ = "0.1.0"
