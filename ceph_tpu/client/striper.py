"""RadosStriper — one logical object striped across many rados objects.

The libradosstriper analog (src/libradosstriper/RadosStriperImpl.cc):
a logical "striped object" is RAID0'd over ordinary rados objects with
the reference's layout parameters (stripe_unit, stripe_count,
object_size; ErasureCodeInterface.h:60-78 documents the same
decomposition OSD-side).  Unit u of the logical stream lands in

    column     = u % stripe_count
    object_set = u // (units_per_object * stripe_count)
    objectno   = object_set * stripe_count + column

and backing objects are named ``{soid}.{objectno:016x}`` exactly like
the striper's convention.  The logical size lives in an xattr on the
first object (striper.size), holes read back as zeros (sparse
semantics), and every data op decomposes into ordinary rados ops — so
EC coding, snapshots, scrub, recovery all apply to striped content
with no extra machinery.

This is the client-side face of the framework's batched-stripe design:
large logical writes become many fixed-size object writes the OSD
batches into single device encode calls.
"""
from __future__ import annotations

import struct
from typing import List, Tuple

import errno as _errno

from .rados import ObjectOperation, RadosClient

# genuinely missing (vs transient): object or size attr absent
_ABSENT = (_errno.ENOENT, _errno.ENODATA)


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) in _ABSENT

SIZE_XATTR = "striper.size"          # reference XATTR_SIZE
TRIM_XATTR = "striper.trim_upto"     # pending-shrink high-water mark


class RadosStriper:
    def __init__(self, client: RadosClient, pool: str,
                 stripe_unit: int = 65536, stripe_count: int = 4,
                 object_size: int = 1 << 20):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a multiple of "
                             "stripe_unit")
        self.client = client
        self.pool = pool
        self.su = stripe_unit
        self.sc = stripe_count
        self.os_ = object_size
        self.upo = object_size // stripe_unit   # units per object

    # ---- layout ------------------------------------------------------------
    def _obj_name(self, soid: str, objectno: int) -> str:
        return f"{soid}.{objectno:016x}"

    def _extents(self, offset: int, length: int
                 ) -> List[Tuple[int, int, int, int]]:
        """(objectno, obj_offset, logical_offset, run_length) covering
        [offset, offset+length): the file_to_extents decomposition."""
        out = []
        pos = offset
        end = offset + length
        while pos < end:
            u = pos // self.su
            within = pos % self.su
            column = u % self.sc
            set_ = u // (self.upo * self.sc)
            row_in_set = (u // self.sc) % self.upo
            objectno = set_ * self.sc + column
            obj_off = row_in_set * self.su + within
            run = min(self.su - within, end - pos)
            out.append((objectno, obj_off, pos, run))
            pos += run
        return out

    # ---- size bookkeeping --------------------------------------------------
    def stat(self, soid: str) -> int:
        v = self.client.getxattr(self.pool, self._obj_name(soid, 0),
                                 SIZE_XATTR)
        return struct.unpack("<Q", v)[0]

    def _grow_size(self, soid: str, new_end: int) -> None:
        first = self._obj_name(soid, 0)
        try:
            cur = self.stat(soid)
        except IOError as e:
            if not _absent(e):
                raise            # transient: never shrink the size
            cur = -1
        if new_end > cur:
            op = (ObjectOperation().create(exclusive=False)
                  .set_xattr(SIZE_XATTR, struct.pack("<Q", new_end)))
            r, _ = self.client.operate(self.pool, first, op)
            if r < 0:
                raise IOError(f"striper size update: {r}")

    # ---- data ops ----------------------------------------------------------
    def write(self, soid: str, data: bytes, offset: int = 0) -> int:
        data = bytes(data)
        for objectno, obj_off, lpos, run in self._extents(offset,
                                                          len(data)):
            chunk = data[lpos - offset:lpos - offset + run]
            r = self.client.write(self.pool,
                                  self._obj_name(soid, objectno),
                                  chunk, obj_off)
            if r < 0:
                return r
        self._grow_size(soid, offset + len(data))
        return 0

    def write_full(self, soid: str, data: bytes) -> int:
        self.remove(soid, _ignore_missing=True)
        return self.write(soid, data, 0)

    def append(self, soid: str, data: bytes) -> int:
        try:
            size = self.stat(soid)
        except IOError as e:
            if not _absent(e):
                raise            # transient: appending at 0 would clobber
            size = 0
        return self.write(soid, data, size)

    def read(self, soid: str, offset: int = 0, length: int = 0) -> bytes:
        size = self.stat(soid)
        end = size if not length else min(offset + length, size)
        if end <= offset:
            return b""
        out = bytearray(end - offset)
        for objectno, obj_off, lpos, run in self._extents(
                offset, end - offset):
            try:
                piece = self.client.read(
                    self.pool, self._obj_name(soid, objectno),
                    offset=obj_off, length=run)
            except IOError as e:
                if not _absent(e):
                    raise        # transient/EIO must surface, not zero-fill
                piece = b""                   # sparse hole reads zeros
            out[lpos - offset:lpos - offset + len(piece)] = piece
        return bytes(out)

    def _kept_in_object(self, objectno: int, size: int) -> int:
        """Bytes of this backing object that lie below the logical
        *size* — contiguous from the object's start because its rows'
        logical offsets increase monotonically."""
        column = objectno % self.sc
        set_ = objectno // self.sc
        kept = 0
        for r in range(self.upo):
            u = set_ * self.upo * self.sc + r * self.sc + column
            kept_r = min(self.su, max(0, size - u * self.su))
            if kept_r == 0:
                break
            kept += kept_r
            if kept_r < self.su:
                break
        return kept

    def _all_objectnos(self, size: int) -> range:
        if size <= 0:
            return range(1)
        last_set = (size - 1) // (self.su * self.upo * self.sc)
        return range((last_set + 1) * self.sc)

    def truncate(self, soid: str, size: int) -> int:
        """Retry-safe two-phase shrink: (1) record the new size AND a
        trim high-water mark covering any previously failed shrink, so
        reads never claim destroyed bytes; (2) trim the backing
        objects over the whole marked span; (3) clear the mark.  A
        failure between phases leaves orphan bytes that the NEXT
        truncate/grow call re-trims (the mark survives)."""
        old = self.stat(soid)
        first = self._obj_name(soid, 0)
        try:
            prev_mark = struct.unpack(
                "<Q", self.client.getxattr(self.pool, first,
                                           TRIM_XATTR))[0]
        except IOError as e:
            if not _absent(e):
                raise
            prev_mark = 0
        span = max(old, prev_mark)
        # bytes above min(size, old) were either destroyed by THIS call
        # or by a previously failed shrink (the mark) — both must trim,
        # even when the new size grows past the old one (those bytes
        # must read as zeros, not resurrect)
        keep_to = min(size, old)
        op = (ObjectOperation().create(exclusive=False)
              .set_xattr(SIZE_XATTR, struct.pack("<Q", size))
              .set_xattr(TRIM_XATTR, struct.pack("<Q", span)))
        r, _ = self.client.operate(self.pool, first, op)
        if r < 0:
            return r
        if keep_to < span:
            for objectno in self._all_objectnos(span):
                kept = self._kept_in_object(objectno, keep_to)
                name = self._obj_name(soid, objectno)
                if kept == 0 and objectno != 0:
                    r2 = self.client.remove(self.pool, name)
                    if r2 not in (0, -2):
                        return r2     # mark persists; retry re-trims
                else:
                    r2 = self.client.truncate(self.pool, name, kept)
                    if r2 not in (0, -2):
                        return r2
        r, _ = self.client.operate(self.pool, first, ObjectOperation()
                                   .set_xattr(TRIM_XATTR,
                                              struct.pack("<Q", keep_to)))
        return r

    def remove(self, soid: str, _ignore_missing: bool = False) -> int:
        try:
            size = self.stat(soid)
        except IOError:
            return 0 if _ignore_missing else -2
        # a shrink that died mid-trim leaves backing objects in
        # (size, mark]; deleting only up to size would orphan them and a
        # recreated striped object could resurrect their bytes as data
        try:
            mark = struct.unpack(
                "<Q", self.client.getxattr(self.pool,
                                           self._obj_name(soid, 0),
                                           TRIM_XATTR))[0]
        except IOError as e:
            if not _absent(e):
                raise
            mark = 0
        for objectno in self._all_objectnos(max(size, mark)):
            self.client.remove(self.pool, self._obj_name(soid, objectno))
        return 0
