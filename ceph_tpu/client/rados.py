"""RadosClient — librados-lite over an Objecter-style op state machine.

Mirrors the client stack's shape (src/librados/IoCtxImpl.cc:642,692 →
osdc/Objecter.cc op_submit/_calc_target): every op computes its target PG
from the client's OSDMap copy (object_locator_to_pg → raw_pg_to_pg →
acting primary), sends an MOSDOp to that OSD, and resends after a map
refresh when the target was wrong or silent — the Objecter's
recalc-on-every-epoch behavior.
"""
from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

from ..msg import (
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE, CEPH_OSD_OP_READ,
    CEPH_OSD_OP_STAT, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
    Dispatcher, MOSDMap, MOSDOp, MOSDOpReply, Message, Network,
)
from ..msg.messages import (
    CEPH_OSD_CMPXATTR_OP_EQ, CEPH_OSD_OP_ASSERT_VER,
    CEPH_OSD_OP_CALL, CEPH_OSD_OP_CMPXATTR, CEPH_OSD_OP_COPY_FROM,
    CEPH_OSD_OP_CREATE,
    CEPH_OSD_OP_FLAG_EXCL, CEPH_OSD_OP_GETXATTR, CEPH_OSD_OP_GETXATTRS,
    CEPH_OSD_OP_OMAPGETVALS, CEPH_OSD_OP_OMAPRMKEYS,
    CEPH_OSD_OP_OMAPSETKEYS, CEPH_OSD_OP_RMXATTR, CEPH_OSD_OP_SETXATTR,
    CEPH_OSD_OP_TRUNCATE, CEPH_OSD_OP_ZERO, OSDOp, new_trace_id,
)
from ..msg.kv import pack_kv as _pack_kv, pack_keys as _pack_keys, \
    unpack_kv as _unpack_kv
from ..osdmap import OSDMap, ceph_stable_mod, pg_t
from ..trace.oplat import stamp_client

MAX_ATTEMPTS = 8


def _ioerror(api: str, oid: str, result: int) -> IOError:
    """IOError with the errno attached so callers can branch on the
    CODE (ENOENT vs transient) instead of parsing the message."""
    e = IOError(f"{api} {oid}: {result}")
    e.errno = -result        # positive errno convention
    return e


class NotifyTimeout(IOError):
    """notify() timed out on silent watchers; .replies carries the
    acks that DID arrive (rados_notify2: error + reply buffer)."""

    def __init__(self, msg: str, replies):
        super().__init__(msg)
        self.replies = replies




class ObjectOperation:
    """Builder for an atomic multi-op vector (librados
    ObjectWriteOperation/ObjectReadOperation, executed by the OSD's
    do_osd_ops interpreter in order, all-or-nothing)."""

    def __init__(self):
        self.ops: list = []

    # -- data ops --
    def create(self, exclusive: bool = True) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_CREATE,
                              flags=CEPH_OSD_OP_FLAG_EXCL
                              if exclusive else 0))
        return self

    def write(self, data: bytes, offset: int) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_WRITE, data=bytes(data),
                              offset=offset))
        return self

    def write_full(self, data: bytes) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_WRITEFULL, data=bytes(data)))
        return self

    def append(self, data: bytes) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_APPEND, data=bytes(data)))
        return self

    def truncate(self, size: int) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_TRUNCATE, offset=size))
        return self

    def zero(self, offset: int, length: int) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_ZERO, offset=offset,
                              length=length))
        return self

    def remove(self) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_DELETE))
        return self

    def read(self, offset: int = 0, length: int = 0) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_READ, offset=offset,
                              length=length))
        return self

    def stat(self) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_STAT))
        return self

    # -- xattrs --
    def set_xattr(self, name: str, value: bytes) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_SETXATTR, name=name,
                              data=bytes(value)))
        return self

    def get_xattr(self, name: str) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_GETXATTR, name=name))
        return self

    def get_xattrs(self) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_GETXATTRS))
        return self

    def rm_xattr(self, name: str) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_RMXATTR, name=name))
        return self

    def assert_version(self, version: int) -> "ObjectOperation":
        """Abort the vector with -ERANGE unless the object's version
        still equals *version* (rados assert_version guard)."""
        self.ops.append(OSDOp(op=CEPH_OSD_OP_ASSERT_VER, offset=version))
        return self

    def call(self, cls: str, method: str,
             inp: bytes = b"") -> "ObjectOperation":
        """Invoke an object-class method on the OSD
        (ObjectOperation::exec / rados_exec; src/cls)."""
        self.ops.append(OSDOp(op=CEPH_OSD_OP_CALL,
                              name=f"{cls}.{method}", data=bytes(inp)))
        return self

    def copy_from(self, src_oid: str,
                  src_pool: int = -1) -> "ObjectOperation":
        """Replace this object with a server-side copy of *src_oid*
        (ObjectWriteOperation::copy_from; -1 = same pool — pool ids
        start at 0, so 0 is a real pool)."""
        self.ops.append(OSDOp(op=CEPH_OSD_OP_COPY_FROM, name=src_oid,
                              offset=src_pool))
        return self

    def cmp_xattr(self, name: str, value: bytes,
                  comparison: int = CEPH_OSD_CMPXATTR_OP_EQ
                  ) -> "ObjectOperation":
        """Guard: abort the whole vector with ECANCELED on mismatch."""
        self.ops.append(OSDOp(op=CEPH_OSD_OP_CMPXATTR, name=name,
                              data=bytes(value), flags=comparison))
        return self

    # -- omap (replicated pools only) --
    def omap_set(self, kv) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_OMAPSETKEYS,
                              data=_pack_kv(kv)))
        return self

    def omap_rm_keys(self, keys) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_OMAPRMKEYS,
                              data=_pack_keys(keys)))
        return self

    def omap_get(self) -> "ObjectOperation":
        self.ops.append(OSDOp(op=CEPH_OSD_OP_OMAPGETVALS))
        return self


class RadosClient(Dispatcher):
    def __init__(self, network: Network, mon, name: str = "client.0"):
        self.network = network
        self.mon = mon
        self.name = name
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        # per-instance random base (the reference scopes tids to the
        # mon session/connection): a restarted client with the same
        # entity name must not replay-match another instance's cached
        # command acks
        import secrets as _secrets
        self._tid = _secrets.randbits(44) << 16
        self._replies: Dict[int, MOSDOpReply] = {}
        # cookie -> (callback, pool_id, oid, last_known_primary)
        self._watches: Dict[int, list] = {}
        self._next_cookie = 1
        self._linger_tids: Dict[int, int] = {}   # in-flight re-register
        self._linger_retries: Dict[int, int] = {}
        # pool id -> (snapc_seq, [snap ids, newest first]): the write
        # SnapContext for selfmanaged-snap pools (librados
        # selfmanaged_snap_set_write_ctx; rides every mutating MOSDOp)
        self._write_snapc: Dict[int, Tuple[int, list]] = {}
        self._mon_acks: Dict[int, object] = {}
        mon.subscribe(name)
        mon.send_full_map(name)
        network.pump()

    # ---- dispatch ---------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        from ..msg.messages import MCommandReply, MMonCommandAck, \
            MWatchNotify
        if isinstance(msg, (MMonCommandAck, MCommandReply)):
            # _mon_acks doubles as the reply slot for daemon commands
            self._mon_acks[msg.tid] = msg
            return
        if isinstance(msg, MOSDMap):
            applied = False
            for inc in msg.incrementals:
                if inc.epoch == self.osdmap.epoch + 1:
                    self.osdmap.apply_incremental(inc)
                    applied = True
            if applied:
                self._reregister_watches()
        elif isinstance(msg, MOSDOpReply):
            cookie = self._linger_tids.pop(msg.tid, None)
            if cookie is not None:
                if msg.result == -11 and cookie in self._watches and \
                        self._linger_retries.get(cookie, 0) < 50:
                    # target PG still peering: keep lingering (the
                    # Objecter retries linger ops until they land)
                    self._linger_retries[cookie] = \
                        self._linger_retries.get(cookie, 0) + 1
                    self._send_watch_register(cookie)
                else:
                    self._linger_retries.pop(cookie, None)
                return
            self._replies[msg.tid] = msg
        elif isinstance(msg, MWatchNotify) and \
                msg.op == MWatchNotify.NOTIFY:
            w = self._watches.get(msg.cookie)
            reply = b""
            if w is not None:
                try:
                    reply = w[0](msg.notify_id, msg.payload) or b""
                except Exception:
                    reply = b""
            self.messenger.send_message(MWatchNotify(
                op=MWatchNotify.ACK, pgid=msg.pgid, oid=msg.oid,
                cookie=msg.cookie, notify_id=msg.notify_id,
                payload=bytes(reply)), msg.src)

    # ---- Objecter-lite ----------------------------------------------------
    def _calc_target(self, pool_id: int, oid: str):
        pool = self.osdmap.get_pg_pool(pool_id)
        if pool is None:
            # the pool vanished between resolution and submit (pool
            # deletion): surface librados's clean ENOENT, not KeyError
            raise _ioerror("op", f"pool {pool_id}", -2)
        if pool.read_tier >= 0:
            # cache tier overlay: ops retarget to the cache pool
            # (Objecter op_target read_tier/write_tier resolution)
            tier = self.osdmap.get_pg_pool(pool.read_tier)
            if tier is not None:
                pool_id, pool = pool.read_tier, tier
        raw = self.osdmap.map_to_pg(pool_id, oid)
        ps = ceph_stable_mod(raw.ps, pool.pg_num, pool.pg_num_mask)
        pg = pg_t(pool_id, ps)
        *_, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return (pool_id, ps), primary

    def _submit(self, pool_id: int, oid: str, op: str = "",
                data: bytes = b"", offset: int = 0, length: int = 0,
                ops: Optional[list] = None,
                snapid: int = 0) -> MOSDOpReply:
        # ONE trace id for the logical op: resend attempts are the same
        # op (the reference's ZTracer trace survives Objecter retries),
        # and the client's root span parents every daemon-side child
        from ..trace import g_tracer
        trace_id = new_trace_id()
        span = g_tracer.begin(f"client_op:{op or 'vector'}:{oid}",
                              daemon=self.name, trace_id=trace_id)
        try:
            with g_tracer.activate(span):
                return self._submit_attempts(
                    pool_id, oid, op, data, offset, length, ops, snapid,
                    trace_id, span.span_id if span is not None else 0)
        finally:
            g_tracer.finish(span)

    def _submit_attempts(self, pool_id: int, oid: str, op: str,
                         data: bytes, offset: int, length: int,
                         ops: Optional[list], snapid: int,
                         trace_id: int, span_id: int) -> MOSDOpReply:
        import time as _time
        reply = None
        tid = self._tid
        attempt = throttle_waits = 0
        while attempt < MAX_ATTEMPTS:
            pgid, primary = self._calc_target(pool_id, oid)
            self._tid += 1
            tid = self._tid
            if primary >= 0:
                sc_seq, sc_snaps = self._write_snapc.get(pool_id, (0, []))
                msg = MOSDOp(tid=tid, pool=pgid[0], oid=oid, pgid=pgid,
                             op=op, data=data, offset=offset,
                             length=length, epoch=self.osdmap.epoch,
                             ops=list(ops) if ops else [],
                             snapid=snapid,
                             snapc_seq=sc_seq, snapc_snaps=list(sc_snaps),
                             trace_id=trace_id,
                             parent_span_id=span_id)
                # stage-latency ledger: the submit stamp opens the
                # op's time ledger; the OSD's intake mark turns it
                # into the client_flight stage (trace/oplat.py).  A
                # resend is a fresh arrival and gets a fresh ledger.
                stamp_client(msg, self.name)
                self.messenger.send_message(msg, f"osd.{primary}")
                self.network.pump()
            reply = self._replies.pop(tid, None)
            if reply is not None and reply.result == -11 and \
                    getattr(reply, "retry_after", 0.0) > 0:
                # admission-control throttle (docs/QOS.md): the op was
                # SHED at intake, not misrouted — back off and resend
                # without burning a map-refresh attempt.  Bounded so a
                # permanently saturated OSD still surfaces EAGAIN; the
                # pump between resends is what drains the queue on the
                # deterministic fabric.
                throttle_waits += 1
                if throttle_waits <= 256:
                    if not self.network.pump():
                        # nothing moved (remote daemons still working):
                        # honor the hint briefly before resending — on
                        # the in-process fabric the pump IS the drain,
                        # so a wall-sleep there is pure dead time
                        _time.sleep(min(reply.retry_after, 0.02))
                    continue
            if reply is not None and reply.result != -11:
                return reply
            attempt += 1
            # wrong/silent primary: refresh the map and retry
            self.mon.send_full_map(self.name)
            self.network.pump()
        return reply if reply is not None else MOSDOpReply(tid=tid,
                                                           result=-110)

    def operate(self, pool: str, oid: str, op: ObjectOperation,
                snap=None) -> Tuple[int, list]:
        """Execute an atomic multi-op vector; returns (result,
        [(per-op result, per-op data), ...]) — rados_*_op_operate.
        With ``snap`` the vector runs read-only against that pool
        snapshot's view."""
        snapid = self._resolve_snapid(pool, snap) if snap else 0
        r = self._submit(self.lookup_pool(pool), oid, ops=op.ops,
                         snapid=snapid)
        return r.result, list(r.op_results)

    def _submit_to_pg(self, pgid, op: str, data: bytes = b"",
                      length: int = 0) -> MOSDOpReply:
        """Send a PG-targeted op (no object) to the PG's primary with
        the same refresh-and-resend loop as _submit."""
        for attempt in range(MAX_ATTEMPTS):
            if self.osdmap.get_pg_pool(pgid[0]) is None:
                raise _ioerror("op", f"pool {pgid[0]}", -2)
            *_, acting, primary = self.osdmap.pg_to_up_acting_osds(
                pg_t(*pgid))
            self._tid += 1
            tid = self._tid
            if primary >= 0:
                self.messenger.send_message(MOSDOp(
                    tid=tid, pool=pgid[0], pgid=tuple(pgid), op=op,
                    data=data, length=length, epoch=self.osdmap.epoch,
                    trace_id=new_trace_id()), f"osd.{primary}")
                self.network.pump()
            reply = self._replies.pop(tid, None)
            if reply is not None and reply.result != -11:
                return reply
            self.mon.send_full_map(self.name)
            self.network.pump()
        return reply if reply is not None else \
            MOSDOpReply(tid=tid, result=-110)

    def list_objects(self, pool: str, page: int = 512):
        """Iterate every head object in the pool (rados_nobjects_list):
        a PGLS op per PG with cursor pagination, like the Objecter's
        pg-targeted listing ops (PrimaryLogPG do_pg_op PGNLS)."""
        from ..msg.messages import CEPH_OSD_OP_PGLS
        pid = self.lookup_pool(pool)
        p = self.osdmap.get_pg_pool(pid)
        for ps in range(p.pg_num):
            cursor = b""
            while True:
                reply = self._submit_to_pg((pid, ps), CEPH_OSD_OP_PGLS,
                                           data=cursor, length=page)
                if reply.result < 0:
                    raise _ioerror("pgls", f"{pid}.{ps}", reply.result)
                import json as _json
                names = _json.loads(reply.data) if reply.data else []
                yield from names
                if reply.result != 1:       # no more pages in this PG
                    break
                cursor = names[-1].encode()

    def lookup_pool(self, name: str) -> int:
        pid = self.osdmap.lookup_pg_pool_name(name)
        if pid < 0:
            raise KeyError(f"no pool {name!r}")
        return pid

    # ---- public API (librados verbs) --------------------------------------
    def write_full(self, pool: str, oid: str, data: bytes) -> int:
        r = self._submit(self.lookup_pool(pool), oid,
                         CEPH_OSD_OP_WRITEFULL, bytes(data))
        return r.result

    def write(self, pool: str, oid: str, data: bytes, offset: int) -> int:
        """Offset write (librados rados_write): rmw on EC pools."""
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_WRITE,
                         bytes(data), offset=offset)
        return r.result

    def append(self, pool: str, oid: str, data: bytes) -> int:
        """Append at the current object size (rados_append)."""
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_APPEND,
                         bytes(data))
        return r.result

    def read(self, pool: str, oid: str, offset: int = 0,
             length: int = 0, snap=None) -> bytes:
        """Read the head, or — with ``snap`` (name or id) — the object's
        state as of that pool snapshot (rados snap read)."""
        snapid = self._resolve_snapid(pool, snap) if snap else 0
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_READ,
                         offset=offset, length=length, snapid=snapid)
        if r.result < 0:
            raise _ioerror("read", oid, r.result)
        return r.data

    def mon_command(self, cmd: str, **args):
        """Run a mon administrative command by name (librados
        mon_command / 'ceph tell mon').  In-process Monitors execute
        directly; over TCP this sends MMonCommand and waits for the
        ack.  Both paths take the Monitor method's own kwargs and
        return its return value."""
        if hasattr(self.mon, cmd):
            value = getattr(self.mon, cmd)(**args)
            self.mon.publish()
            self.network.pump()
            return value
        from ..msg.messages import MMonCommand
        self._tid += 1
        tid = self._tid
        for attempt in range(MAX_ATTEMPTS):
            # re-read each attempt: a silent mon triggers hunting
            mon_name = getattr(self.mon, "mon_name", "mon")
            self.messenger.send_message(MMonCommand(
                tid=tid, cmd=cmd, args=dict(args)), mon_name)
            self.network.pump()
            ack = self._mon_acks.pop(tid, None)
            if ack is None and attempt and attempt % 3 == 0 \
                    and hasattr(self.mon, "hunt"):
                # only a SILENT mon triggers hunting; an answering
                # one (even with EAGAIN mid-election) keeps the bind
                self.mon.hunt()
            if ack is not None:
                if ack.result == -11:
                    continue    # EAGAIN: mon electing / leadership moved
                if ack.result < 0:
                    raise ValueError(ack.data.get("error",
                                                  f"mon {ack.result}"))
                return ack.data.get("value")
        raise _ioerror("mon_command", cmd, -110)

    def _daemon_command(self, target: str, cmd: str, args: dict):
        from ..msg.messages import MCommand
        self._tid += 1
        tid = self._tid
        for _attempt in range(MAX_ATTEMPTS):
            self.messenger.send_message(
                MCommand(tid=tid, cmd=cmd, args=dict(args)), target)
            self.network.pump()
            rep = self._mon_acks.pop(tid, None)
            if rep is not None:
                if rep.result < 0:
                    raise ValueError(rep.data.get(
                        "error", f"{target} {rep.result}"))
                return rep.data
        raise _ioerror("daemon_command", cmd, -110)

    def osd_command(self, osd_id: int, cmd: str, **args):
        """Run a command on a LIVE osd daemon over the wire
        ('ceph tell osd.N', MCommand.h): injectargs / config show /
        config get / perf dump / dump_ops_in_flight."""
        return self._daemon_command(f"osd.{osd_id}", cmd, args)

    def mds_command(self, mds_name: str, cmd: str, **args):
        """'ceph tell mds.<name>': the same wire command pair against
        a live metadata server (injectargs / config show / config get
        / session ls / status)."""
        return self._daemon_command(mds_name, cmd, args)

    # ---- pool snapshots (rados_ioctx_snap_*) -------------------------------
    def _resolve_snapid(self, pool: str, snap) -> int:
        if isinstance(snap, int):
            return snap
        p = self.osdmap.get_pg_pool(self.lookup_pool(pool))
        for sid, name in p.snaps.items():
            if name == snap:
                return sid
        raise KeyError(f"no snap {snap!r} on pool {pool!r}")

    def snap_create(self, pool: str, name: str) -> int:
        return self.mon_command("pool_snap_create", pool_name=pool,
                                snap_name=name)

    def snap_remove(self, pool: str, name: str) -> int:
        return self.mon_command("pool_snap_rm", pool_name=pool,
                                snap_name=name)

    def snap_list(self, pool: str) -> Dict[int, str]:
        p = self.osdmap.get_pg_pool(self.lookup_pool(pool))
        return dict(p.snaps)

    # ---- advisory locks (rados_lock_exclusive/shared -> cls_lock,
    # src/cls/lock/cls_lock_client.cc) ---------------------------------
    def _lock_exec(self, pool: str, oid: str, method: str,
                   payload: dict) -> int:
        import json as _json
        ret, _ = self.exec(pool, oid, "lock", method,
                           _json.dumps(payload).encode())
        return ret

    def lock_exclusive(self, pool: str, oid: str, name: str,
                       cookie: str = "", description: str = "",
                       duration: float = 0) -> int:
        from ..osd.cls_lock import LOCK_EXCLUSIVE
        return self._lock_exec(pool, oid, "lock", {
            "name": name, "type": LOCK_EXCLUSIVE, "cookie": cookie,
            "description": description, "duration": duration})

    def lock_shared(self, pool: str, oid: str, name: str,
                    cookie: str = "", tag: str = "",
                    description: str = "", duration: float = 0) -> int:
        from ..osd.cls_lock import LOCK_SHARED
        return self._lock_exec(pool, oid, "lock", {
            "name": name, "type": LOCK_SHARED, "cookie": cookie,
            "tag": tag, "description": description,
            "duration": duration})

    def unlock(self, pool: str, oid: str, name: str,
               cookie: str = "") -> int:
        return self._lock_exec(pool, oid, "unlock",
                               {"name": name, "cookie": cookie})

    def break_lock(self, pool: str, oid: str, name: str, entity: str,
                   cookie: str = "") -> int:
        return self._lock_exec(pool, oid, "break_lock",
                               {"name": name, "entity": entity,
                                "cookie": cookie})

    def list_lockers(self, pool: str, oid: str, name: str) -> dict:
        import json as _json
        ret, out = self.exec(pool, oid, "lock", "get_info",
                             _json.dumps({"name": name}).encode())
        if ret < 0:
            raise _ioerror("list_lockers", oid, ret)
        return _json.loads(out)

    # ---- selfmanaged snaps (librados rados_ioctx_selfmanaged_snap_*):
    # the mon only allocates/retires ids; snapshot membership lives in
    # the write SnapContext this client attaches to mutations ----------
    def selfmanaged_snap_create(self, pool: str) -> int:
        return self.mon_command("selfmanaged_snap_create",
                                pool_name=pool)

    def selfmanaged_snap_remove(self, pool: str, snapid: int) -> None:
        self.mon_command("selfmanaged_snap_remove", pool_name=pool,
                         snapid=snapid)
        pid = self.lookup_pool(pool)
        seq, snaps = self._write_snapc.get(pid, (0, []))
        if snapid in snaps:
            self.set_write_ctx(pool, seq,
                               [s for s in snaps if s != snapid])

    def set_write_ctx(self, pool: str, seq: int, snaps) -> None:
        """Set the SnapContext attached to this pool's writes: ``seq``
        the newest snap id, ``snaps`` every live snap (any order; sent
        newest-first like the reference sorts it)."""
        snaps = sorted(snaps, reverse=True)
        if snaps and (seq < snaps[0] or len(set(snaps)) != len(snaps)):
            raise ValueError("invalid snap context")
        pid = self.lookup_pool(pool)
        if seq > 0 and not self.osdmap.get_pg_pool(pid).selfmanaged:
            # a snapc on a pool-snapshot pool would shadow the pool
            # snapc and corrupt its snapshots (reference: EINVAL)
            raise ValueError(
                f"pool {pool!r} is not in selfmanaged snap mode")
        self._write_snapc[pid] = (seq, snaps)

    def rollback(self, pool: str, oid: str, snap) -> int:
        """Restore the head — data AND xattrs — to its state at the
        snap (rados_ioctx_snap_rollback; composed client-side from
        snap-view reads + one atomic head vector).  The final vector is
        guarded with assert_version on the head version observed before
        the reads, so a write landing mid-compose aborts the vector
        (-ERANGE) and the rollback recomposes instead of silently
        overwriting it."""
        pid = self.lookup_pool(pool)
        snapid = self._resolve_snapid(pool, snap)
        for _ in range(MAX_ATTEMPTS):
            rv = self._submit(pid, oid, CEPH_OSD_OP_STAT)
            if rv.result == -2:
                head_ver = 0
            elif rv.result < 0:
                raise IOError(f"rollback stat {oid}: {rv.result}")
            else:
                head_ver = rv.version
            r = self._submit(pid, oid, CEPH_OSD_OP_READ, snapid=snapid)
            if r.result == -2:
                # object did not exist at the snap: remove the head
                r2, _ = self.operate(pool, oid, ObjectOperation()
                                     .assert_version(head_ver).remove())
                if r2 == -34:
                    continue        # head moved under us: recompose
                return 0 if r2 == -2 else r2    # no head either: no-op
            if r.result < 0:
                # transient failure (EIO/degraded): never touch the head
                raise IOError(f"rollback read {oid}@{snap}: {r.result}")
            rs, res = self.operate(pool, oid,
                                   ObjectOperation().get_xattrs(),
                                   snap=snap)
            if rs < 0 and rs != -2:
                # transient xattr-read failure would silently strip the
                # snap-time xattrs while the data restore succeeds —
                # same contract as the data read: never touch the head
                raise IOError(f"rollback xattrs {oid}@{snap}: {rs}")
            snap_attrs = _unpack_kv(res[0][1]) if rs == 0 else {}
            try:
                head_attrs = self.getxattrs(pool, oid)
            except IOError as e:
                if e.errno != 2:            # ENOENT = no head attrs
                    raise
                head_attrs = {}
            op = ObjectOperation().assert_version(head_ver) \
                                  .write_full(r.data)
            for k in head_attrs:
                if k not in snap_attrs:
                    op.rm_xattr(k)
            for k, v in snap_attrs.items():
                op.set_xattr(k, v)
            r2, _ = self.operate(pool, oid, op)
            if r2 != -34:
                return r2
        return -34

    def stat(self, pool: str, oid: str, snap=None) -> int:
        snapid = self._resolve_snapid(pool, snap) if snap else 0
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_STAT,
                         snapid=snapid)
        if r.result < 0:
            raise _ioerror("stat", oid, r.result)
        return struct.unpack("<Q", r.data)[0]

    def exec(self, pool: str, oid: str, cls: str, method: str,
             inp: bytes = b"", snap=None) -> "tuple[int, bytes]":
        """Run an object-class method (rados_exec): returns
        (method ret, output bytes).  With ``snap`` a READ-ONLY method
        runs against the object's state at that snapshot (the vector
        interpreter resolves the clone like any snap read)."""
        r, res = self.operate(pool, oid,
                              ObjectOperation().call(cls, method, inp),
                              snap=snap)
        if r < 0:
            return r, b""
        return res[0][0], res[0][1]

    def copy(self, pool: str, dst: str, src: str,
             src_pool: Optional[str] = None) -> int:
        """Server-side copy (rados_copy role): dst <= src."""
        spid = self.lookup_pool(src_pool) if src_pool \
            else self.lookup_pool(pool)
        r, _ = self.operate(pool, dst,
                            ObjectOperation().copy_from(src, spid))
        return r

    def get_version(self, pool: str, oid: str) -> int:
        """Current object version (the stat reply's user_version) —
        pairs with ObjectOperation.assert_version guards."""
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_STAT)
        if r.result < 0:
            raise _ioerror("stat", oid, r.result)
        return r.version

    def remove(self, pool: str, oid: str) -> int:
        return self._submit(self.lookup_pool(pool), oid,
                            CEPH_OSD_OP_DELETE).result

    # -- xattr / omap / extent convenience verbs (librados rados_*) ----------
    def setxattr(self, pool: str, oid: str, name: str,
                 value: bytes) -> int:
        r, _ = self.operate(pool, oid,
                            ObjectOperation().set_xattr(name, value))
        return r

    def getxattr(self, pool: str, oid: str, name: str) -> bytes:
        r, res = self.operate(pool, oid,
                              ObjectOperation().get_xattr(name))
        if r < 0:
            raise _ioerror(f"getxattr .{name}", oid, r)
        return res[0][1]

    def getxattrs(self, pool: str, oid: str) -> Dict[str, bytes]:
        r, res = self.operate(pool, oid, ObjectOperation().get_xattrs())
        if r < 0:
            raise _ioerror("getxattrs", oid, r)
        return _unpack_kv(res[0][1])

    def rmxattr(self, pool: str, oid: str, name: str) -> int:
        r, _ = self.operate(pool, oid, ObjectOperation().rm_xattr(name))
        return r

    def truncate(self, pool: str, oid: str, size: int) -> int:
        r, _ = self.operate(pool, oid, ObjectOperation().truncate(size))
        return r

    def zero(self, pool: str, oid: str, offset: int, length: int) -> int:
        r, _ = self.operate(pool, oid,
                            ObjectOperation().zero(offset, length))
        return r

    def create(self, pool: str, oid: str, exclusive: bool = True) -> int:
        r, _ = self.operate(pool, oid,
                            ObjectOperation().create(exclusive))
        return r

    def omap_set(self, pool: str, oid: str, kv: Dict[str, bytes]) -> int:
        r, _ = self.operate(pool, oid, ObjectOperation().omap_set(kv))
        return r

    def omap_get(self, pool: str, oid: str) -> Dict[str, bytes]:
        r, res = self.operate(pool, oid, ObjectOperation().omap_get())
        if r < 0:
            raise _ioerror("omap_get", oid, r)
        return _unpack_kv(res[0][1])

    def omap_rm_keys(self, pool: str, oid: str, keys) -> int:
        r, _ = self.operate(pool, oid,
                            ObjectOperation().omap_rm_keys(keys))
        return r

    # ---- watch / notify (rados_watch / rados_notify) -----------------------
    def _reregister_watches(self) -> None:
        """After a map change, re-send watch registrations whose PG
        primary moved — the new primary's watcher table starts empty
        (the linger-op resend in Objecter::_linger_submit)."""
        for cookie, w in self._watches.items():
            _cb, pool_id, oid, last_primary = w
            _pgid, primary = self._calc_target(pool_id, oid)
            if primary != last_primary and primary >= 0:
                w[3] = primary
                self._linger_retries[cookie] = 0
                self._send_watch_register(cookie)

    def _send_watch_register(self, cookie: int) -> None:
        from ..msg.messages import CEPH_OSD_OP_WATCH
        w = self._watches.get(cookie)
        if w is None:
            return
        _cb, pool_id, oid, _lp = w
        pgid, primary = self._calc_target(pool_id, oid)
        if primary < 0:
            return
        w[3] = primary
        self._tid += 1
        self._linger_tids[self._tid] = cookie
        self.messenger.send_message(MOSDOp(
            tid=self._tid, pool=pool_id, oid=oid, pgid=pgid,
            op=CEPH_OSD_OP_WATCH, offset=cookie,
            epoch=self.osdmap.epoch,
            trace_id=new_trace_id()), f"osd.{primary}")

    def watch(self, pool: str, oid: str, callback) -> int:
        """Register *callback(notify_id, payload) -> reply_bytes* for
        notifies on the object; returns the watch cookie.  Watches
        re-register automatically when the PG's primary moves."""
        from ..msg.messages import CEPH_OSD_OP_WATCH
        cookie = self._next_cookie
        self._next_cookie += 1
        pool_id = self.lookup_pool(pool)
        _pgid, primary = self._calc_target(pool_id, oid)
        self._watches[cookie] = [callback, pool_id, oid, primary]
        r = self._submit(pool_id, oid, CEPH_OSD_OP_WATCH, offset=cookie)
        if r.result < 0:
            del self._watches[cookie]
            raise IOError(f"watch {oid}: {r.result}")
        return cookie

    def unwatch(self, pool: str, oid: str, cookie: int) -> int:
        from ..msg.messages import CEPH_OSD_OP_UNWATCH
        self._watches.pop(cookie, None)
        return self._submit(self.lookup_pool(pool), oid,
                            CEPH_OSD_OP_UNWATCH, offset=cookie).result

    def notify(self, pool: str, oid: str, payload: bytes = b"",
               timeout: int = 30) -> Dict[str, bytes]:
        """Broadcast to the object's watchers; returns
        {"client:cookie": reply_payload} once every live watcher acked
        (rados_notify2 semantics)."""
        from ..msg.messages import CEPH_OSD_OP_NOTIFY
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_NOTIFY,
                         data=bytes(payload), length=timeout)
        if r.result == -110:
            raise NotifyTimeout(f"notify {oid} timed out",
                                _unpack_kv(r.data))
        if r.result < 0:
            raise IOError(f"notify {oid}: {r.result}")
        return _unpack_kv(r.data)
