"""RadosClient — librados-lite over an Objecter-style op state machine.

Mirrors the client stack's shape (src/librados/IoCtxImpl.cc:642,692 →
osdc/Objecter.cc op_submit/_calc_target): every op computes its target PG
from the client's OSDMap copy (object_locator_to_pg → raw_pg_to_pg →
acting primary), sends an MOSDOp to that OSD, and resends after a map
refresh when the target was wrong or silent — the Objecter's
recalc-on-every-epoch behavior.
"""
from __future__ import annotations

import struct
from typing import Dict, Optional

from ..msg import (
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE, CEPH_OSD_OP_READ,
    CEPH_OSD_OP_STAT, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
    Dispatcher, MOSDMap, MOSDOp, MOSDOpReply, Message, Network,
)
from ..msg.messages import new_trace_id
from ..osdmap import OSDMap, ceph_stable_mod, pg_t

MAX_ATTEMPTS = 8


class RadosClient(Dispatcher):
    def __init__(self, network: Network, mon, name: str = "client.0"):
        self.network = network
        self.mon = mon
        self.name = name
        self.messenger = network.create_messenger(name)
        self.messenger.add_dispatcher_head(self)
        self.osdmap = OSDMap()
        self._tid = 0
        self._replies: Dict[int, MOSDOpReply] = {}
        mon.subscribe(name)
        mon.send_full_map(name)
        network.pump()

    # ---- dispatch ---------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MOSDMap):
            for inc in msg.incrementals:
                if inc.epoch == self.osdmap.epoch + 1:
                    self.osdmap.apply_incremental(inc)
        elif isinstance(msg, MOSDOpReply):
            self._replies[msg.tid] = msg

    # ---- Objecter-lite ----------------------------------------------------
    def _calc_target(self, pool_id: int, oid: str):
        pool = self.osdmap.get_pg_pool(pool_id)
        raw = self.osdmap.map_to_pg(pool_id, oid)
        ps = ceph_stable_mod(raw.ps, pool.pg_num, pool.pg_num_mask)
        pg = pg_t(pool_id, ps)
        *_, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return (pool_id, ps), primary

    def _submit(self, pool_id: int, oid: str, op: str, data: bytes = b"",
                offset: int = 0, length: int = 0) -> MOSDOpReply:
        for attempt in range(MAX_ATTEMPTS):
            pgid, primary = self._calc_target(pool_id, oid)
            self._tid += 1
            tid = self._tid
            if primary >= 0:
                msg = MOSDOp(tid=tid, pool=pool_id, oid=oid, pgid=pgid,
                             op=op, data=data, offset=offset,
                             length=length, epoch=self.osdmap.epoch,
                             trace_id=new_trace_id())
                self.messenger.send_message(msg, f"osd.{primary}")
                self.network.pump()
            reply = self._replies.pop(tid, None)
            if reply is not None and reply.result != -11:
                return reply
            # wrong/silent primary: refresh the map and retry
            self.mon.send_full_map(self.name)
            self.network.pump()
        return reply if reply is not None else MOSDOpReply(tid=tid,
                                                           result=-110)

    def lookup_pool(self, name: str) -> int:
        pid = self.osdmap.lookup_pg_pool_name(name)
        if pid < 0:
            raise KeyError(f"no pool {name!r}")
        return pid

    # ---- public API (librados verbs) --------------------------------------
    def write_full(self, pool: str, oid: str, data: bytes) -> int:
        r = self._submit(self.lookup_pool(pool), oid,
                         CEPH_OSD_OP_WRITEFULL, bytes(data))
        return r.result

    def write(self, pool: str, oid: str, data: bytes, offset: int) -> int:
        """Offset write (librados rados_write): rmw on EC pools."""
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_WRITE,
                         bytes(data), offset=offset)
        return r.result

    def append(self, pool: str, oid: str, data: bytes) -> int:
        """Append at the current object size (rados_append)."""
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_APPEND,
                         bytes(data))
        return r.result

    def read(self, pool: str, oid: str, offset: int = 0,
             length: int = 0) -> bytes:
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_READ,
                         offset=offset, length=length)
        if r.result < 0:
            raise IOError(f"read {oid}: {r.result}")
        return r.data

    def stat(self, pool: str, oid: str) -> int:
        r = self._submit(self.lookup_pool(pool), oid, CEPH_OSD_OP_STAT)
        if r.result < 0:
            raise IOError(f"stat {oid}: {r.result}")
        return struct.unpack("<Q", r.data)[0]

    def remove(self, pool: str, oid: str) -> int:
        return self._submit(self.lookup_pool(pool), oid,
                            CEPH_OSD_OP_DELETE).result
