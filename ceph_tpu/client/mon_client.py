"""MonClient — the client's mon stub for cross-process clusters.

RadosClient drives its monitor through two calls (subscribe +
send_full_map).  In-process clusters hand it the Monitor object; across
process boundaries this stub speaks MMonSubscribe over the wire instead
(src/mon/MonClient.h role: the client-side session with the mon).
"""
from __future__ import annotations

from ..msg.messages import MMonSubscribe


class MonClient:
    def __init__(self, network, mon_name: str = "mon",
                 mon_names=None):
        self.network = network
        self.mon_name = mon_name
        # the full roster for hunting (MonClient::_reopen_session /
        # hunt): when the bound mon goes silent, rotate to the next
        self.mon_names = list(mon_names or [mon_name])

    def hunt(self) -> str:
        """Rotate to the next monitor in the roster (the reference's
        hunting when the current mon connection goes dead)."""
        if len(self.mon_names) > 1:
            i = self.mon_names.index(self.mon_name) \
                if self.mon_name in self.mon_names else -1
            self.mon_name = self.mon_names[(i + 1)
                                           % len(self.mon_names)]
        return self.mon_name

    def subscribe(self, name: str) -> None:
        """Subscribe and fetch are ONE wire operation here: the mon
        answers every MMonSubscribe with the full history."""
        self.network.send(name, self.mon_name, MMonSubscribe())

    # RadosClient calls both on its monitor handle; over the wire they
    # are the same request
    send_full_map = subscribe
