from .rados import ObjectOperation, RadosClient
from .striper import RadosStriper

__all__ = ["ObjectOperation", "RadosClient", "RadosStriper"]
