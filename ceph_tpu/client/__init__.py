from .rados import ObjectOperation, RadosClient

__all__ = ["ObjectOperation", "RadosClient"]
