from .rados import RadosClient

__all__ = ["RadosClient"]
