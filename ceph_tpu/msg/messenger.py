"""In-process messenger fabric for the vstart-lite cluster.

The reference runs seven epoll-driven AsyncMessengers per OSD
(src/msg/async/, src/ceph_osd.cc:476-501); for a single-process TPU-side
cluster the equivalent is a deterministic dispatch fabric: entities
register Dispatchers by name, sends enqueue onto one FIFO, and pump()
drains it to quiescence.  Determinism is what the test tiers need (SURVEY
§4); fault injection (down entities, blackholed links, drop hooks) hangs
off the fabric exactly where the Thrasher kills sockets in the reference
(qa/tasks/ceph_manager.py:195,360).
"""
from __future__ import annotations

from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

from .messages import Message


_g_faults = None


def _faults():
    # deferred: the fault registry is dependency-light, but importing it
    # at module load would couple msg/ to common/ for every consumer of
    # the wire types; the first check pays the import once
    global _g_faults
    if _g_faults is None:
        from ..fault import g_faults
        _g_faults = g_faults
    return _g_faults


class Dispatcher:
    """Receiver interface (msg/Dispatcher.h)."""

    def ms_fast_dispatch(self, msg: Message) -> None:
        raise NotImplementedError

    def ms_handle_reset(self, peer: str) -> None:
        pass


class Connection:
    """Send handle pinned to a destination (msg/Connection.h)."""

    def __init__(self, network: "Network", src: str, dst: str):
        self.network = network
        self.src = src
        self.dst = dst

    def send_message(self, msg: Message) -> None:
        self.network.send(self.src, self.dst, msg)


class Messenger:
    """Per-entity endpoint (Messenger::create analog)."""

    def __init__(self, network: "Network", name: str):
        self.network = network
        self.name = name
        self.dispatcher: Optional[Dispatcher] = None

    def add_dispatcher_head(self, d: Dispatcher) -> None:
        self.dispatcher = d

    def get_connection(self, dst: str) -> Connection:
        return Connection(self.network, self.name, dst)

    def send_message(self, msg: Message, dst: str) -> None:
        self.network.send(self.name, dst, msg)


class Network:
    """The single-process cluster fabric with fault injection."""

    def __init__(self):
        self.endpoints: Dict[str, Messenger] = {}
        self.queue: deque = deque()
        self.down: Set[str] = set()
        self.blackholed: Set[Tuple[str, str]] = set()
        self.drop_hook: Optional[Callable[[str, str, Message], bool]] = None
        self.delivered = 0
        self.dropped = 0
        self.pumping = False
        # idle kickers: called when the queue drains; a hook returning
        # True did deferred work (flushed a dispatch batch, resent a
        # lost sub-write) and pump loops to deliver what it enqueued.
        # This is how "drain to quiescence" stays true once the EC
        # write path is continuation-driven: an encode parked in the
        # dispatch scheduler's collection window is not quiescent.
        self.idle_hooks: List[Callable[[], bool]] = []

    def create_messenger(self, name: str) -> Messenger:
        m = Messenger(self, name)
        self.endpoints[name] = m
        return m

    # ---- fault injection (Thrasher hooks) ---------------------------------
    def set_down(self, name: str, down: bool = True) -> None:
        if down:
            self.down.add(name)
        else:
            self.down.discard(name)

    def blackhole(self, src: str, dst: str, on: bool = True) -> None:
        if on:
            self.blackholed.add((src, dst))
        else:
            self.blackholed.discard((src, dst))

    # ---- delivery ---------------------------------------------------------
    def send(self, src: str, dst: str, msg: Message) -> None:
        msg.src = src
        self.queue.append((src, dst, msg))

    def add_idle_hook(self, hook: Callable[[], bool]) -> None:
        self.idle_hooks.append(hook)

    def pump(self, max_msgs: int = 100000) -> int:
        """Deliver queued messages until quiescent — including deferred
        work the idle hooks surface (pipelined dispatch flushes,
        sub-write resends); returns the delivery count."""
        if self.pumping:
            return 0  # re-entrant sends drain in the outer pump
        self.pumping = True
        n = 0
        try:
            while n < max_msgs:
                if not self.queue:
                    # quiescent: give the idle kickers one round; any
                    # that did work may have enqueued messages (hook
                    # bounds — resend caps, finite dispatch queues —
                    # guarantee this terminates)
                    if not any([h() for h in self.idle_hooks]):
                        break
                    continue
                src, dst, msg = self.queue.popleft()
                n += 1
                if (src in self.down or dst in self.down
                        or (src, dst) in self.blackholed):
                    self.dropped += 1
                    continue
                if self.drop_hook and self.drop_hook(src, dst, msg):
                    self.dropped += 1
                    continue
                if _faults().site_armed("msg.drop") and \
                        _faults().should_fire(
                            "msg.drop",
                            ctx=f"{type(msg).__name__} {src}>{dst}"):
                    # the `ms inject socket failures` analog: the armed
                    # trigger (prob/nth/once, match=-scoped) decides
                    from ..fault import (fault_perf_counters,
                                         l_fault_msg_drops)
                    fault_perf_counters().inc(l_fault_msg_drops)
                    self.dropped += 1
                    continue
                ep = self.endpoints.get(dst)
                if ep is None or ep.dispatcher is None:
                    # non-local destination: transports (msg/tcp.py) route
                    # it onward; the base fabric drops it
                    if self._route_remote(src, dst, msg):
                        self.delivered += 1
                    else:
                        self.dropped += 1
                    continue
                self.delivered += 1
                ep.dispatcher.ms_fast_dispatch(msg)
        finally:
            self.pumping = False
        return n

    def _route_remote(self, src: str, dst: str, msg: Message) -> bool:
        """Hook for cross-process transports; False = undeliverable.
        Runs AFTER the down/blackhole/drop filters, so fault injection
        applies identically to local and remote peers."""
        return False
