"""Message types for the mini-cluster fabric.

Named after the reference wire messages (src/messages/M*.h) so the data
path reads the same: client ops (MOSDOp/MOSDOpReply), EC shard sub-ops
(MOSDECSubOpWrite/..., src/osd/ECMsgTypes.h payloads), heartbeats
(MOSDPing), failure reports, and map publication (MOSDMap).  Every message
carries the op's trace id end to end (the ZTracer::Trace slot on
msg/Message.h:254).
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

_trace_counter = itertools.count(1)


def new_trace_id() -> int:
    return next(_trace_counter)


@dataclass
class Message:
    src: str = ""
    trace_id: int = 0
    # the sender's active span (trace/span.py): receivers open child
    # spans under it, giving cross-daemon span trees — the blkin
    # parent-handle half of the Message.h:254 trace slot.  0 = no
    # parent (tracing off or a root message).
    parent_span_id: int = 0

    def name(self) -> str:
        return type(self).__name__


# client op codes (the do_osd_ops interpreter's vocabulary,
# src/osd/PrimaryLogPG.cc do_osd_ops: CEPH_OSD_OP_{READ,WRITE,WRITEFULL,...})
CEPH_OSD_OP_READ = "read"            # ranged read (offset/length)
CEPH_OSD_OP_WRITE = "write"          # offset write (rmw on EC pools)
CEPH_OSD_OP_WRITEFULL = "writefull"  # whole-object replace
CEPH_OSD_OP_APPEND = "append"        # write at current object size
CEPH_OSD_OP_DELETE = "delete"
CEPH_OSD_OP_STAT = "stat"
CEPH_OSD_OP_CREATE = "create"        # create; flags=EXCL -> EEXIST if present
CEPH_OSD_OP_TRUNCATE = "truncate"    # resize (shrink or zero-extend)
CEPH_OSD_OP_ZERO = "zero"            # zero an extent (never extends)
CEPH_OSD_OP_SETXATTR = "setxattr"
CEPH_OSD_OP_GETXATTR = "getxattr"
CEPH_OSD_OP_GETXATTRS = "getxattrs"
CEPH_OSD_OP_RMXATTR = "rmxattr"
CEPH_OSD_OP_CMPXATTR = "cmpxattr"    # guard; flags = comparison operator
CEPH_OSD_OP_OMAPSETKEYS = "omap_setkeys"   # replicated pools only
CEPH_OSD_OP_OMAPRMKEYS = "omap_rmkeys"
CEPH_OSD_OP_OMAPGETVALS = "omap_getvals"
CEPH_OSD_OP_CALL = "call"            # object-class method (src/cls);
                                     # name = "cls.method", data = input
CEPH_OSD_OP_COPY_FROM = "copy_from"  # copy another object into this one
                                     # (PrimaryLogPG do_copy_from);
                                     # name = src oid, offset = src pool
CEPH_OSD_OP_ASSERT_VER = "assert_ver"  # guard: object version == offset
                                     # (mismatch -> -ERANGE, like
                                     # PrimaryLogPG.cc do_osd_ops
                                     # CEPH_OSD_OP_ASSERT_VER)
CEPH_OSD_OP_WATCH = "watch"          # register interest (cookie in offset)
CEPH_OSD_OP_UNWATCH = "unwatch"
CEPH_OSD_OP_NOTIFY = "notify"        # broadcast to watchers, await acks
CEPH_OSD_OP_PGLS = "pgls"            # list this PG's head objects
                                     # (CEPH_OSD_OP_PGNLS; data = cursor,
                                     # length = max entries)

# cmpxattr comparison operators (include/rados.h CEPH_OSD_CMPXATTR_OP_*)
CEPH_OSD_CMPXATTR_OP_EQ = 1
CEPH_OSD_CMPXATTR_OP_NE = 2
CEPH_OSD_CMPXATTR_OP_GT = 3
CEPH_OSD_CMPXATTR_OP_GTE = 4
CEPH_OSD_CMPXATTR_OP_LT = 5
CEPH_OSD_CMPXATTR_OP_LTE = 6

# create flags
CEPH_OSD_OP_FLAG_EXCL = 1


@dataclass
class OSDOp:
    """One op of an MOSDOp vector (the OSDOp struct in osd_types.h:
    opcode + extent + payload + xattr name, executed in order by the
    do_osd_ops interpreter)."""
    op: str = CEPH_OSD_OP_READ
    offset: int = 0
    length: int = 0
    data: bytes = b""
    name: str = ""           # xattr name
    flags: int = 0           # cmpxattr operator / create EXCL


@dataclass
class MOSDOp(Message):
    """Client -> primary OSD op (src/messages/MOSDOp.h).

    Carries either one legacy single op (``op``/``offset``/``length``/
    ``data``) or a multi-op vector (``ops``, like the reference's
    vector<OSDOp>) executed atomically in order."""
    tid: int = 0
    pool: int = 0
    oid: str = ""
    pgid: Tuple[int, int] = (0, 0)      # (pool, ps)
    op: str = CEPH_OSD_OP_READ
    offset: int = 0
    length: int = 0
    data: bytes = b""
    epoch: int = 0
    ops: List["OSDOp"] = field(default_factory=list)
    snapid: int = 0          # read at this pool snap (0 = head)
    # client-supplied write SnapContext for selfmanaged-snap pools
    # (MOSDOp snapc, src/messages/MOSDOp.h; empty = use the pool snapc)
    snapc_seq: int = 0
    snapc_snaps: List[int] = field(default_factory=list)


@dataclass
class MOSDOpReply(Message):
    tid: int = 0
    result: int = 0
    data: bytes = b""
    epoch: int = 0
    # per-op (result, data) for vector ops, parallel to MOSDOp.ops up to
    # the first failing op (the reference returns per-op rval/outdata)
    op_results: List[Tuple[int, bytes]] = field(default_factory=list)
    # object version at reply time (the reference's reply user_version);
    # stamped on stat replies so clients can build assert_ver guards
    version: int = 0
    # admission-control throttle hint (docs/QOS.md): result=-11 with
    # retry_after > 0 means "op was SHED at intake, back off this many
    # seconds and resend" — distinct from the peering EAGAIN, which the
    # Objecter answers with a map refresh.  Omitted from the wire when
    # 0.0 so the archived encoding corpus stays byte-identical.
    retry_after: float = 0.0


@dataclass
class MOSDECSubOpWrite(Message):
    """Primary -> shard EC write (src/messages/MOSDECSubOpWrite.h,
    payload ECSubWrite in osd/ECMsgTypes.h)."""
    tid: int = 0
    pgid: Tuple[int, int] = (0, 0)
    shard: int = 0
    oid: str = ""
    chunk: bytes = b""
    offset: int = 0          # chunk-granularity offset into the shard
    partial: bool = False    # False = whole-shard replace; True = rmw splice
    hash_epoch: int = 0
    at_version: int = 0      # logical object size after the write
    version: int = 0         # pg_log version of this mutation (0 = none)
    is_push: bool = False    # recovery push: stamp the version attr but
    trim_to: int = 0         # do not re-append the (already merged) log
    # user xattr / omap payload (attrs ride every shard like the
    # reference's ECSubWrite transactions; omap is replicated-only)
    xattrs: Optional[Dict[str, bytes]] = None   # full replacement set
    omap: Optional[Dict[str, bytes]] = None     # full replacement (rep only)
    attr_only: bool = False  # metadata-only mutation: leave the body alone
    # snapshot bookkeeping riding the same shard transaction: update the
    # PG meta snapset for (head_oid, packed_entries); b"" removes it
    snapset_update: Optional[Tuple[str, bytes]] = None
    snapset_only: bool = False  # pure meta message: touch no object


@dataclass
class MOSDECSubOpWriteReply(Message):
    tid: int = 0
    pgid: Tuple[int, int] = (0, 0)
    shard: int = 0
    committed: bool = True


@dataclass
class MOSDECSubOpRead(Message):
    """Primary -> shard EC read (ECSubRead payload)."""
    tid: int = 0
    pgid: Tuple[int, int] = (0, 0)
    shard: int = 0
    oid: str = ""
    offset: int = 0          # chunk-granularity offset into the shard
    length: int = 0          # 0 = to end of shard
    attrs_only: bool = False  # stat/size probe: no payload wanted
    subchunks: List[Tuple[int, int]] = field(default_factory=list)
    # >= 0: sub-chunk repair read — the helper computes and returns its
    # β-sub-chunk contribution toward rebuilding this shard id instead
    # of shipping the chunk (regenerating codes, docs/RECOVERY.md).
    # Omitted from the wire when -1, so pre-repair frames and the
    # pinned encoding corpus stay byte-identical.
    repair_for: int = -1


@dataclass
class MOSDECSubOpReadReply(Message):
    tid: int = 0
    pgid: Tuple[int, int] = (0, 0)
    shard: int = 0
    oid: str = ""
    data: bytes = b""
    result: int = 0
    attrs: Dict[str, bytes] = field(default_factory=dict)


@dataclass
class MOSDPGQuery(Message):
    """Primary -> acting shard: report your PG state (peering GetInfo,
    src/messages/MOSDPGQuery.h).  log_since >= 0 additionally requests the
    log suffix past that version (the GetLog step folded in)."""
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0
    log_since: int = -1
    # >= 0: before replying, rewind your divergent log entries past this
    # version and roll the touched objects back (rewind_divergent_log)
    rewind_to: int = -1


@dataclass
class MOSDPGInfo(Message):
    """Shard -> primary peering reply (MOSDPGInfo/MOSDPGLog roles):
    last_update/log_tail, the replica's own missing set (objects whose
    log entry was merged but whose data never arrived — pg_missing_t),
    and an optional serialized log suffix."""
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0
    last_update: int = 0
    log_tail: int = 0
    log_entries: List[bytes] = field(default_factory=list)
    missing_oids: List[Tuple[str, int]] = field(default_factory=list)
    # per-head snapset blobs: clone bookkeeping must survive primary
    # failover/backfill, so it rides peering like the log does
    snapsets: List[Tuple[str, bytes]] = field(default_factory=list)
    # snaps this replica knows were fully trimmed
    # (pg_info_t.purged_snaps role) — unioned at peering so a primary
    # that died mid-trim is finished by its successor, never redone
    purged_snaps: List[int] = field(default_factory=list)
    # backfill completion (last_backfill == MAX role): the target holds
    # every object the primary knew, so it adopts the primary's log
    # WHOLESALE (entries + head + tail) — without this a pushed-only
    # shard keeps last_update 0 and every later peering re-treats it as
    # missing everything
    adopt_log: bool = False
    # which EC shard collections this OSD actually HOLDS data for —
    # acting positions can shuffle on remap, and the pg_log alone can't
    # tell a data-bearing replica from a freshly assigned one
    held_shards: List[int] = field(default_factory=list)


@dataclass
class MOSDPGNotify(Message):
    """Stray -> primary: I hold data for a PG I no longer serve
    (MOSDPGNotify stray-notify role).  The primary answers with
    MOSDPGRemove once the PG is clean everywhere it IS served."""
    pgid: Tuple[int, int] = (0, 0)
    epoch: int = 0
    from_osd: int = -1
    held_shards: List[int] = field(default_factory=list)
    # the stray's pg_log head: a primary must never authorize deleting
    # a copy NEWER than what it can serve itself
    last_update: int = 0


@dataclass
class MOSDPGRemove(Message):
    """Primary -> stray: your copy is no longer needed; delete it
    (src/messages/MOSDPGRemove.h; OSD::_remove_pg role)."""
    pgid: Tuple[int, int] = (0, 0)
    epoch: int = 0


@dataclass
class MOSDPGScan(Message):
    """Primary -> shard: list your objects (backfill scan,
    src/messages/MOSDPGScan.h)."""
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0


@dataclass
class MOSDPGScanReply(Message):
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0
    objects: List[Tuple[str, int]] = field(default_factory=list)
    # (oid, version) per object on the shard


@dataclass
class MWatchNotify(Message):
    """Watch/notify events (src/messages/MWatchNotify.h): the primary
    fans NOTIFY to every watcher's client; watchers reply NOTIFY_ACK;
    the primary completes the notifier once every live watcher acked
    (Watch.cc / PrimaryLogPG::do_osd_op_effects roles)."""
    NOTIFY = "notify"
    ACK = "notify_ack"
    op: str = NOTIFY
    pgid: Tuple[int, int] = (0, 0)
    oid: str = ""
    cookie: int = 0
    notify_id: int = 0
    payload: bytes = b""


@dataclass
class MOSDPGTemp(Message):
    """Primary -> mon: pin this PG's acting set to *temp* until the
    data realigns (OSD::send_pg_temp / MOSDPGTemp.h; empty temp clears
    the pin).  The choose_acting answer when CRUSH shuffles surviving
    shards to new positions."""
    pgid: Tuple[int, int] = (0, 0)
    epoch: int = 0
    temp: List[int] = field(default_factory=list)


@dataclass
class MOSDRepScrub(Message):
    """Primary -> shard: build and return a scrub map of your chunks
    (src/messages/MOSDRepScrub.h role).  ``deep`` mirrors the
    reference's shallow/deep split (PG::Scrubber::deep): shallow
    compares metadata only (size/attrs/omap digests, no data read);
    deep additionally reads every object and checksums the bytes."""
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0
    deep: bool = False


@dataclass
class MOSDRepScrubMap(Message):
    """Shard -> primary scrub results (ScrubMap role): per object the
    stored size, whether the shard's local integrity check passed
    (HashInfo crc on deep, HashInfo-total-vs-size on shallow), the data
    digest (crc32c; -1 on shallow scrubs, which never read data), and
    the attr/omap digests for cross-replica metadata comparison."""
    pgid: Tuple[int, int] = (0, 0)
    shard: int = -1
    epoch: int = 0
    objects: List[Tuple[str, int, bool, int, int, int, bool]] = \
        field(default_factory=list)
    # (oid, size, local_ok, data_digest, attrs_digest, omap_digest,
    #  digest_validated) — the last flag marks copies whose bytes
    #  provably match a write-time recorded digest (hinfo / data_digest)
    deep: bool = False


@dataclass
class MOSDPing(Message):
    """OSD<->OSD heartbeat (src/messages/MOSDPing.h)."""
    PING = "ping"
    PING_REPLY = "ping_reply"
    op: str = PING
    stamp: float = 0.0
    epoch: int = 0


@dataclass
class MOSDFailure(Message):
    """OSD -> mon failure report (src/messages/MOSDFailure.h).

    ``reporter`` survives peon->leader forwarding (src is stomped by
    every send), keeping the reporter-quorum count honest."""
    target_osd: int = -1
    failed_since: float = 0.0
    epoch: int = 0
    reporter: str = ""


@dataclass
class MMonElection(Message):
    """Mon <-> mon election (src/mon/Elector.cc / MMonElection.h roles):
    propose/ack/victory; lowest reachable rank wins."""
    OP_PROPOSE = "propose"
    OP_ACK = "ack"
    OP_VICTORY = "victory"
    op: str = OP_PROPOSE
    epoch: int = 0              # election epoch (odd = electing, even = won)
    rank: int = -1
    quorum: List[int] = field(default_factory=list)


@dataclass
class MMonPaxos(Message):
    """Mon <-> mon map replication (src/mon/Paxos.cc phases, simplified
    to the leader-driven begin/accept/commit + collect recovery)."""
    OP_COLLECT = "collect"
    OP_LAST = "last"
    OP_BEGIN = "begin"
    OP_ACCEPT = "accept"
    OP_COMMIT = "commit"
    op: str = OP_COLLECT
    rank: int = -1
    pn: int = 0                 # proposal number (election epoch based)
    last_committed: int = 0
    values: List[Any] = field(default_factory=list)
    # values = incremental dicts (osdmap/encoding) being replicated
    # LAST replies also surface any staged-but-uncommitted value so a
    # new leader can finish a possibly-majority-accepted proposal
    # (Paxos.cc handle_last uncommitted_v/uncommitted_pn)
    uncommitted_pn: int = -1
    uncommitted_value: Optional[Any] = None


@dataclass
class MOSDBoot(Message):
    """OSD -> mon: I am alive, mark me up (src/messages/MOSDBoot.h;
    sent at init and when a live osd sees itself marked down)."""
    osd: int = -1
    epoch: int = 0


@dataclass
class MMonSubscribe(Message):
    """Client/daemon -> mon: subscribe to map updates and get the full
    history now (src/messages/MMonSubscribe.h, 'osdmap' what)."""
    what: str = "osdmap"


@dataclass
class MMonCommand(Message):
    """Client -> mon administrative command (src/messages/
    MMonCommand.h; the 'ceph tell mon' / librados mon_command path).
    ``cmd`` names a registered mon command, ``args`` its parameters."""
    tid: int = 0
    cmd: str = ""
    args: Dict[str, Any] = field(default_factory=dict)
    # set when a peon relays the command to the leader (MForward role,
    # src/messages/MForward.h): the original client the ack must reach
    reply_to: str = ""


@dataclass
class MMonCommandAck(Message):
    """Mon -> client command completion (MMonCommandAck.h): result
    errno + a JSON-ish payload dict.  ``reply_to`` mirrors the request's
    relay field: a peon receiving an ack with it set forwards the ack to
    that client (the route_message leg of MForward)."""
    tid: int = 0
    result: int = 0
    data: Dict[str, Any] = field(default_factory=dict)
    reply_to: str = ""


@dataclass
class MPGStats(Message):
    """OSD -> mgr per-PG usage stats (src/messages/MPGStats.h role):
    each primary reports its PGs' object counts and logical bytes, the
    mgr aggregates per pool — the usage feed for pg_autoscaler and
    `ceph df`-style accounting."""
    osd: int = -1
    epoch: int = 0
    # [(pool, ps, num_objects, num_bytes)]
    pg_stats: List[Tuple[int, int, int, int]] = field(default_factory=list)
    # osd_stat_t role: logical bytes stored on this OSD and its
    # configured capacity (0 = unlimited) for full-ratio accounting
    store_bytes: int = 0
    store_capacity: int = 0


@dataclass
class MLog(Message):
    """Daemon -> mon cluster-log entry (src/messages/MLog.h role):
    queued by the leader and paxos-committed with the next epoch, so
    `ceph log last` reads one replicated, failover-proof history."""
    who: str = ""
    level: str = "INF"          # DBG/INF/WRN/ERR (clog levels)
    message: str = ""
    stamp: float = -1.0         # sender clock; -1 = unset (0.0 is a
    # legitimate time-zero stamp and must survive the fan-in dedup)


@dataclass
class MMonPing(Message):
    """Mon <-> mon liveness (the elector's keepalives)."""
    PING = "ping"
    REPLY = "reply"
    op: str = PING
    rank: int = -1
    stamp: float = 0.0


@dataclass
class MOSDMap(Message):
    """Mon -> everyone map publication (src/messages/MOSDMap.h); carries
    incrementals from ``first`` to ``last``."""
    first: int = 0
    last: int = 0
    incrementals: List[Any] = field(default_factory=list)


@dataclass
class MClientRequest(Message):
    """Client -> MDS metadata operation (src/messages/MClientRequest.h
    role): ``op`` names an MDS handler (mkdir/create/open/...), ``args``
    its parameters.  Every metadata mutation crosses the MDS — clients
    never write the metadata pool directly in MDS mode."""
    tid: int = 0
    op: str = ""
    args: Dict[str, Any] = field(default_factory=dict)
    # stable across failover retries: the promoted MDS dedups mutating
    # ops it already replayed from the journal by this id
    reqid: str = ""


@dataclass
class MClientReply(Message):
    """MDS -> client completion (MClientReply.h): errno-style result
    plus a JSON-ish payload (inode attrs, cap grant, snap context)."""
    tid: int = 0
    result: int = 0
    data: Dict[str, Any] = field(default_factory=dict)


@dataclass
class MClientCaps(Message):
    """MDS <-> client capability traffic (MClientCaps.h role).

    op: 'revoke' (MDS asks the holder to drop ``caps``; the holder
    flushes buffered data first), 'flush' (client -> MDS: buffered
    state written back, carries the wrstat payload), 'grant' (MDS ->
    client: caps now held).  ``seq`` orders revoke/flush rounds."""
    OP_REVOKE = "revoke"
    OP_FLUSH = "flush"
    OP_GRANT = "grant"
    op: str = ""
    ino: int = 0
    caps: int = 0
    seq: int = 0
    data: Dict[str, Any] = field(default_factory=dict)


# cephfs capability bits (a lite slice of CEPH_CAP_*)
CEPH_CAP_FILE_CACHE = 1     # may cache reads
CEPH_CAP_FILE_BUFFER = 2    # may buffer writes (write-back)


@dataclass
class MMDSBeacon(Message):
    """MDS -> mon liveness + state beacon (src/messages/MMDSBeacon.h
    role): the MDSMonitor builds the fsmap from these — first beacon
    becomes active, later ones standby, and a stale active is failed
    over to a live standby."""
    name: str = ""
    state: str = "standby"      # what the daemon believes it is
    seq: int = 0


@dataclass
class MCommand(Message):
    """Client -> any daemon administrative command
    (src/messages/MCommand.h; the 'ceph tell osd.N' path): runtime
    introspection/reconfiguration of a LIVE daemon over the wire."""
    tid: int = 0
    cmd: str = ""
    args: Dict[str, Any] = field(default_factory=dict)


@dataclass
class MCommandReply(Message):
    """Daemon -> client command completion (MCommandReply.h)."""
    tid: int = 0
    result: int = 0
    data: Dict[str, Any] = field(default_factory=dict)
