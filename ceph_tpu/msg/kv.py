"""Length-prefixed key/value and key-list codecs shared by the client
builders and the OSD op interpreter (the bufferlist map encodings of
include/encoding.h used for getxattrs/omap payloads)."""
from __future__ import annotations

import struct
from typing import Dict, Iterable, List


def pack_kv(kv: Dict[str, bytes]) -> bytes:
    out = []
    for k, v in kv.items():
        kb = k.encode()
        vb = bytes(v)
        out.append(struct.pack("<I", len(kb)) + kb +
                   struct.pack("<I", len(vb)) + vb)
    return b"".join(out)


def unpack_kv(buf: bytes) -> Dict[str, bytes]:
    pos, kv = 0, {}
    while pos < len(buf):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        k = buf[pos:pos + n].decode()
        pos += n
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        kv[k] = buf[pos:pos + n]
        pos += n
    return kv


def pack_keys(keys: Iterable[str]) -> bytes:
    out = []
    for k in keys:
        kb = k.encode()
        out.append(struct.pack("<I", len(kb)) + kb)
    return b"".join(out)


def unpack_keys(buf: bytes) -> List[str]:
    pos, keys = 0, []
    while pos < len(buf):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        keys.append(buf[pos:pos + n].decode())
        pos += n
    return keys
