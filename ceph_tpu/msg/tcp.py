"""TCP messenger — the cross-process transport (AsyncMessenger role).

The reference runs epoll-driven AsyncMessengers speaking a framed wire
protocol between daemon processes (src/msg/async/AsyncMessenger.h:74);
this is the equivalent thin shim: a ``TcpNetwork`` extends the in-process
fabric so entities living in *other* processes are reachable through
length-prefixed wire frames (msg/wire.py) over plain sockets.

Topology is static like a mon map: every process knows the
entity -> (host, port) directory.  Local sends short-circuit through the
in-process queue; remote sends frame and ship.  ``pump()`` drains both
the local queue and any readable sockets until traffic quiesces, so the
callers' deterministic pump loops keep working across processes.
"""
from __future__ import annotations

import os
import socket
import struct
import time
from collections import deque
from typing import Dict, Optional, Tuple

from .messenger import Network
from .messages import Message
from .wire import decode_blob, decode_message, encode_blob, encode_message

_HDR = struct.Struct("<I H B")   # frame length, dst-name length, comp algo

# auth control frames reuse the MSG header with a dst-name-length
# sentinel no real name can reach; the comp byte carries the opcode
_AUTH_DLEN = 0xFFFF
_A_KDC_HELLO, _A_KDC_CHALLENGE, _A_KDC_PROVE, _A_KDC_REPLY = 1, 2, 3, 4
_A_AUTHORIZER, _A_AUTH_REPLY = 5, 6
_A_AUTH_HELLO, _A_AUTH_CHALLENGE = 7, 8
_SIG_LEN = 8                     # per-frame HMAC trailer when authed

# lossless-session frames (Messenger Policy lossless_peer role):
# 0xFFFD = seq-wrapped message, 0xFFFC = ack, 0xFFFB = session hello
_SEQ_DLEN, _ACK_DLEN, _SESS_DLEN = 0xFFFD, 0xFFFC, 0xFFFB
_S_HELLO, _S_HELLO_ACK = 1, 2
_DAEMON_SERVICES = ("mon", "osd", "mgr")
MAX_UNACKED = 10000              # per-peer resend-queue bound


class _AuthFailed(Exception):
    pass


class TcpAuth:
    """Per-process auth state for a TcpNetwork (cephx on the wire).

    ``entity`` is the process's principal; its secret comes from the
    keyring file.  The mon process passes ``kdc=True`` and the FULL
    keyring — it hosts the CephxServer and answers KDC frames on its
    unauthenticated inbound sockets (the cephx bootstrap path).
    Daemons/clients hold only their own entry.

    Caveat vs the reference: a process authenticates as ONE principal,
    so inbound src names are enforced at service granularity
    (client.* may not claim osd.*), not per-entity.
    """

    def __init__(self, entity: str, keyring_path: str, kdc: bool = False):
        from ..auth import (CephxClient, CephxServer,
                            CephxServiceVerifier, Keyring, entity_service)
        keyring = Keyring.load(keyring_path)
        secret = keyring.get(entity)
        if secret is None:
            raise ValueError(f"keyring has no key for {entity!r}")
        self.entity = entity
        self.service = entity_service(entity)
        self.client = CephxClient(entity, secret)
        self.server: Optional[CephxServer] = None
        self.verifier: Optional[CephxServiceVerifier] = None
        if kdc:
            self.server = CephxServer(keyring)
            # the mon authenticates itself against its own KDC in-memory
            ch = self.server.get_challenge(entity)
            cch, proof = self.client.make_proof(ch)
            self.client.handle_reply(
                self.server.authenticate(entity, ch, cch, proof))
            self.ensure_verifier()

    def ensure_verifier(self) -> None:
        """Build (or refresh) the service verifier from the latest
        rotating keys the KDC handed us."""
        if self.service not in self.client.rotating:
            return
        from ..auth import CephxServiceVerifier
        if self.verifier is None:
            self.verifier = CephxServiceVerifier(
                self.service, self.client.rotating[self.service])
        else:
            self.verifier.update_rotating(
                self.client.rotating[self.service])

# frame compression algorithm ids (Compressor::COMP_ALG_* role); the
# receiver decodes by the frame's id, so peers may use different configs
_COMP_IDS = {"none": 0, "zlib": 1, "snappy": 2, "zstd": 3, "lz4": 4}
_COMP_NAMES = {v: k for k, v in _COMP_IDS.items()}


class TcpNetwork(Network):
    """One per process: hosts local entities, routes to remote ones.

    ``compression`` compresses outbound frame payloads at least
    ``compress_min`` bytes long (ms_compress role; BlueStore-style
    plugin via ceph_tpu.compressor)."""

    def __init__(self, listen_addr: Tuple[str, int],
                 directory: Dict[str, Tuple[str, int]],
                 compression: str = "none", compress_min: int = 1024,
                 auth: Optional[TcpAuth] = None,
                 entity: Optional[str] = None):
        super().__init__()
        from ..compressor import create_compressor
        self.auth = auth
        # outbound socket -> session key; inbound socket -> state dict
        self._out_sk: Dict[socket.socket, bytes] = {}
        self._in_auth: Dict[socket.socket, Dict] = {}
        self.auth_rejects = 0
        # ---- lossless-peer session state (msg/Messenger.h Policy) ----------
        # the process principal decides the policy: daemon<->daemon
        # links are lossless (seq + ack + reconnect-resend), anything
        # involving a client stays lossy (drop on broken socket)
        self.local_entity = entity or (auth.entity if auth else None)
        # dst -> {next_seq, unacked deque[(seq, frame)], sock, retry_at,
        #         backoff}
        self._sess_tx: Dict[str, Dict] = {}
        # peer entity -> highest seq delivered (survives reconnects)
        self._sess_rx: Dict[str, int] = {}
        # this process's session incarnation.  A rebooted daemon restarts
        # its send seqs at 1; without an incarnation check the old
        # session's high-water mark at the receiver silently swallows
        # every post-reboot frame as a duplicate, AND the stale hello
        # ack makes the newcomer trim its queue as already-delivered.
        # The reference detects this as a peer reset in the connect
        # handshake (msg/simple/Pipe.cc "existing connection reset",
        # addr nonce + connect_seq) and zeroes in_seq the same way.
        import os as _os
        # 63 bits: the wire TLV int is signed 64-bit
        self._sess_nonce = (int.from_bytes(_os.urandom(8), "little")
                            >> 1) | 1
        # peer entity -> the incarnation its _sess_rx entry belongs to
        self._sess_rx_nonce: Dict[str, int] = {}
        # inbound socket -> peer entity (from session hello)
        self._sess_peer: Dict[socket.socket, str] = {}
        # outbound socket -> dst name (for routing acks back to tx state)
        self._sock_dst: Dict[socket.socket, str] = {}
        # sockets mid-handshake: _poll_sockets must not read them
        self._handshaking: set = set()
        # outbound socket -> rx buffer (ack frames from the peer)
        self._obuf: Dict[socket.socket, bytearray] = {}
        self.dup_dropped = 0
        self.resent = 0
        self.compression = compression
        self.compress_min = compress_min
        self._comp = create_compressor(compression)
        self._comp_id = _COMP_IDS[compression]
        self._decomps = {0: create_compressor("none")}
        self.directory = dict(directory)
        self.listen_addr = listen_addr
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(listen_addr)
        self._listener.listen(16)
        self._listener.setblocking(False)
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._accepted: list = []
        self._rxbuf: Dict[socket.socket, bytearray] = {}

    # ---- sending -----------------------------------------------------------
    # Network.send enqueues everything; pump() applies the fault-injection
    # filters and calls _route_remote for non-local destinations, so
    # down/blackhole/drop semantics are identical across the boundary.
    def _encode_payload(self, msg: Message) -> Tuple[bytes, int]:
        payload = encode_message(msg)
        comp_id = 0
        if self._comp_id and len(payload) >= self.compress_min:
            compressed = self._comp.compress(payload)
            # keep the raw buffer when compression doesn't help
            # (incompressible EC shard data expands under zlib)
            if len(compressed) < len(payload):
                payload = compressed
                comp_id = self._comp_id
        return payload, comp_id

    def _lossless(self, dst: str) -> bool:
        if self.local_entity is None:
            return False
        from ..auth import entity_service
        return entity_service(self.local_entity) in _DAEMON_SERVICES \
            and entity_service(dst) in _DAEMON_SERVICES

    def _route_remote(self, src: str, dst: str, msg: Message) -> bool:
        addr = self.directory.get(dst)
        if addr is None or tuple(addr) == tuple(self.listen_addr):
            return False  # unknown, or points back here with no endpoint
        payload, comp_id = self._encode_payload(msg)
        dname = dst.encode()
        if self._lossless(dst):
            wrapped = struct.pack("<Q H", 0, len(dname)) + dname + payload
            return self._send_lossless(dst, tuple(addr), comp_id, wrapped)
        frame = _HDR.pack(len(payload), len(dname), comp_id) \
            + dname + payload
        addr = tuple(addr)
        try:
            s = self._peer(addr, dst)
            self._transmit(s, frame)
            return True
        except Exception:
            # OSError / _AuthFailed / malformed peer handshake bytes
            # (struct.error, bad TLV): drop the connection, never die
            self._drop_conn(addr)
            return False

    def _transmit(self, s: socket.socket, frame: bytes) -> None:
        if self.auth is not None:
            from ..auth import hmac_tag
            frame += hmac_tag(self._out_sk[s], frame, _SIG_LEN)
        s.sendall(frame)

    def _drop_conn(self, addr: Tuple[str, int]) -> None:
        s = self._conns.pop(addr, None)
        if s is not None:
            self._out_sk.pop(s, None)
            self._sock_dst.pop(s, None)
            self._obuf.pop(s, None)
            try:
                s.close()
            except OSError:
                pass

    # ---- lossless-peer sessions (reconnect + resend, exactly-once) ---------
    def _send_lossless(self, dst: str, addr: Tuple[str, int],
                       comp_id: int, wrapped: bytes) -> bool:
        """Queue a seq-wrapped frame for *dst* and try to ship it; a
        broken socket keeps the frame queued for reconnect-resend
        instead of dropping it (Policy lossless_peer)."""
        tx = self._sess_tx.setdefault(
            dst, {"next_seq": 1, "unacked": deque(),
                  "retry_at": 0.0, "backoff": 0.25})
        if len(tx["unacked"]) >= MAX_UNACKED:
            from ..common.dout import dlog
            dlog("msg", 0, f"lossless queue to {dst} overflowed "
                 f"({MAX_UNACKED}); dropping message")
            return False
        seq = tx["next_seq"]
        tx["next_seq"] = seq + 1
        # stamp the real seq into the wrapper built by the caller
        wrapped = struct.pack("<Q", seq) + wrapped[8:]
        frame = _HDR.pack(len(wrapped), _SEQ_DLEN, comp_id) + wrapped
        tx["unacked"].append((seq, frame))
        self._flush_dst(dst, addr)
        return True

    def _flush_dst(self, dst: str, addr: Tuple[str, int]) -> None:
        """(Re)connect to *dst* if needed and push every unacked frame
        the current socket hasn't carried yet."""
        tx = self._sess_tx[dst]
        now = time.monotonic()
        if self._conns.get(addr) is None and now < tx["retry_at"]:
            return                       # in reconnect backoff
        try:
            s = self._peer(addr, dst)
            if tx.get("sock") is not s:
                # fresh socket: session hello tells the peer who we
                # are and returns its delivered high-water mark
                acked = self._session_hello(s, dst)
                while tx["unacked"] and tx["unacked"][0][0] <= acked:
                    tx["unacked"].popleft()
                for _seq, frame in list(tx["unacked"]):
                    self._transmit(s, frame)
                    self.resent += 1
                tx["sock"] = s
                self._sock_dst[s] = dst
            else:
                _seq, frame = tx["unacked"][-1]
                self._transmit(s, frame)
            tx["backoff"] = 0.25
        except Exception:
            self._drop_conn(addr)
            tx["sock"] = None
            tx["retry_at"] = now + tx["backoff"]
            tx["backoff"] = min(tx["backoff"] * 2, 5.0)

    def _session_hello(self, s: socket.socket, dst: str) -> int:
        """-> peer's last delivered seq from us (for resend trimming)."""
        body = encode_blob({"entity": self.local_entity,
                            "nonce": self._sess_nonce})
        s.sendall(_HDR.pack(len(body), _SESS_DLEN, _S_HELLO) + body)
        op, reply = self._read_ctrl_frame(s, _SESS_DLEN)
        if op != _S_HELLO_ACK or "last_seq" not in reply:
            raise _AuthFailed(reply.get("error", "bad session hello ack"))
        return int(reply["last_seq"])

    def _flush_lossless(self) -> None:
        """Retry peers with backlogged unacked frames (called from
        pump); reconnection does the resend."""
        for dst, tx in list(self._sess_tx.items()):
            if not tx["unacked"]:
                continue
            addr = self.directory.get(dst)
            if addr is None:
                continue
            addr = tuple(addr)
            # resend when disconnected OR when the live socket never
            # ran this session's hello/resend (e.g. a fresh connection
            # made for ticket renewal replaced the session socket)
            if self._conns.get(addr) is not tx.get("sock") or \
                    tx.get("sock") is None:
                self._flush_dst(dst, addr)

    def _peer(self, addr: Tuple[str, int],
              dst: str = "") -> socket.socket:
        s = self._conns.get(addr)
        if s is None:
            s = socket.create_connection(addr, timeout=5.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                if self.auth is not None:
                    self._auth_outbound(s, addr, dst)
            except Exception:
                try:
                    s.close()
                except OSError:
                    pass
                raise
            self._conns[addr] = s
        return s

    # ---- auth handshakes ---------------------------------------------------
    def _send_auth_frame(self, s: socket.socket, op: int,
                         body: Dict) -> None:
        payload = encode_blob(body)
        s.sendall(_HDR.pack(len(payload), _AUTH_DLEN, op) + payload)

    def _read_auth_frame(self, s: socket.socket) -> Tuple[int, Dict]:
        return self._read_ctrl_frame(s, _AUTH_DLEN)

    def _read_ctrl_frame(self, s: socket.socket,
                         want_dlen: int) -> Tuple[int, Dict]:
        """Read one control frame (auth or session handshake), serving
        OUR inbound sockets while waiting — two daemons handshaking
        with each other concurrently would otherwise deadlock until
        both time out."""
        buf = b""
        deadline = time.monotonic() + 5.0
        s.settimeout(0.05)
        self._handshaking.add(s)
        try:
            while time.monotonic() < deadline:
                try:
                    chunk = s.recv(1 << 16)
                    if not chunk:
                        raise _AuthFailed("peer closed during handshake")
                    buf += chunk
                except socket.timeout:
                    self._poll_sockets(0.0)
                    continue
                if len(buf) < _HDR.size:
                    continue
                plen, dlen, op = _HDR.unpack_from(buf, 0)
                if dlen != want_dlen:
                    raise _AuthFailed("unexpected frame during handshake")
                if len(buf) >= _HDR.size + plen:
                    return op, decode_blob(buf[_HDR.size:_HDR.size + plen])
            raise _AuthFailed("handshake timed out")
        finally:
            self._handshaking.discard(s)
            try:
                s.settimeout(5.0)
            except OSError:
                pass

    def _auth_outbound(self, s: socket.socket, addr: Tuple[str, int],
                       dst: str) -> None:
        """Authenticate a fresh outbound connection: bootstrap with the
        KDC if needed, then present an authorizer for dst's service."""
        from ..auth import AuthError, entity_service
        a = self.auth
        mon_addr = tuple(self.directory.get("mon", ("", 0)))
        if a.client.needs_renewal():
            # missing OR near-expiry tickets: (re)run the KDC exchange
            # — on this socket if it goes to the mon, else over a fresh
            # mon connection (expired tickets would otherwise lock the
            # daemon out of every reconnect forever)
            if addr != mon_addr:
                self._drop_conn(mon_addr)
                self._peer(mon_addr, "mon")
            else:
                self._kdc_exchange(s)
        service = entity_service(dst) if dst else "mon"
        # fetch the connection-bound server challenge first, so a
        # recorded authorizer can't re-authenticate a new connection
        self._send_auth_frame(s, _A_AUTH_HELLO, {})
        op, body = self._read_auth_frame(s)
        if op != _A_AUTH_CHALLENGE or "challenge" not in body:
            raise _AuthFailed(body.get("error", "no authorizer challenge"))
        try:
            auth_msg, sk, nonce = a.client.build_authorizer(
                service, body["challenge"])
        except AuthError as e:
            raise _AuthFailed(str(e))
        self._send_auth_frame(s, _A_AUTHORIZER, auth_msg)
        op, reply = self._read_auth_frame(s)
        if op != _A_AUTH_REPLY or not reply.get("ok") or \
                not a.client.check_authorizer_reply(
                    sk, nonce, reply.get("reply", b"")):
            from ..common.dout import dlog
            dlog("msg", 0, f"authorizer for {dst!r} rejected: "
                 f"{reply.get('error', 'bad reply proof')}")
            raise _AuthFailed("authorizer rejected")
        self._out_sk[s] = sk

    def _kdc_exchange(self, s: socket.socket) -> None:
        """cephx bootstrap on an un-authed mon connection."""
        a = self.auth
        self._send_auth_frame(s, _A_KDC_HELLO, {"entity": a.entity})
        op, body = self._read_auth_frame(s)
        if op != _A_KDC_CHALLENGE or "challenge" not in body:
            raise _AuthFailed(body.get("error", "no KDC challenge"))
        cch, proof = a.client.make_proof(body["challenge"])
        self._send_auth_frame(s, _A_KDC_PROVE, {
            "entity": a.entity, "server_challenge": body["challenge"],
            "client_challenge": cch, "proof": proof})
        op, body = self._read_auth_frame(s)
        if op != _A_KDC_REPLY or not body.get("ok"):
            from ..common.dout import dlog
            dlog("msg", 0, "KDC rejected "
                 f"{a.entity!r}: {body.get('error', '?')}")
            raise _AuthFailed("KDC rejected credentials")
        a.client.handle_reply(body["blob"])
        a.ensure_verifier()

    def authenticate(self) -> bool:
        """Force the KDC exchange now (daemon boot path), so inbound
        authorizers can be verified before any outbound traffic."""
        if self.auth is None or self.auth.client.authenticated():
            return True
        try:
            self._peer(tuple(self.directory["mon"]), "mon")
            return True
        except (_AuthFailed, OSError, KeyError):
            return False

    # ---- receiving ---------------------------------------------------------
    def _poll_sockets(self, wait: float) -> int:
        import select
        outbound = [s for s in self._conns.values()
                    if s not in self._handshaking]
        socks = [self._listener] + self._accepted + outbound
        try:
            readable, _, _ = select.select(socks, [], [], wait)
        except OSError:
            return 0
        n = 0
        for s in readable:
            if s is self._listener:
                try:
                    conn, _peer = self._listener.accept()
                    conn.setblocking(False)
                    self._accepted.append(conn)
                    self._rxbuf[conn] = bytearray()
                except OSError:
                    pass
                continue
            if s in self._handshaking:
                continue          # the blocking exchange owns this fd
            is_outbound = s not in self._rxbuf and s in outbound
            try:
                data = s.recv(1 << 20)
            except (OSError, socket.timeout):
                data = b""
            if not data:
                if is_outbound:
                    # peer closed our outbound connection: drop it so
                    # the next send (or lossless flush) reconnects
                    for addr, c in list(self._conns.items()):
                        if c is s:
                            self._drop_conn(addr)
                    continue
                self._accepted.remove(s)
                self._rxbuf.pop(s, None)
                self._in_auth.pop(s, None)
                self._sess_peer.pop(s, None)
                continue
            if is_outbound:
                buf = self._obuf.setdefault(s, bytearray())
                buf.extend(data)
                self._drain_outbound(s, buf)
                continue
            buf = self._rxbuf[s]
            buf.extend(data)
            n += self._drain_frames(s, buf)
        return n

    def _drain_outbound(self, s: socket.socket, buf: bytearray) -> None:
        """Outbound sockets only carry session ACK frames inbound."""
        while len(buf) >= _HDR.size:
            plen, dlen, _op = _HDR.unpack_from(buf, 0)
            total = _HDR.size + plen
            if len(buf) < total:
                break
            payload = bytes(buf[_HDR.size:total])
            del buf[:total]
            if dlen != _ACK_DLEN or plen != 8:
                continue          # stray frame: ignore
            (acked,) = struct.unpack("<Q", payload)
            dst = self._sock_dst.get(s)
            tx = self._sess_tx.get(dst) if dst else None
            if tx is not None:
                while tx["unacked"] and tx["unacked"][0][0] <= acked:
                    tx["unacked"].popleft()

    def _handle_auth_frame(self, s: socket.socket, op: int,
                           payload: bytes) -> None:
        """Inbound auth control frame on an accepted socket."""
        from ..auth import AuthError
        a = self.auth
        state = self._in_auth.setdefault(s, {"authed": False})
        try:
            body = decode_blob(payload)
            if op == _A_KDC_HELLO:
                if a is None or a.server is None:
                    self._send_auth_frame(s, _A_KDC_CHALLENGE,
                                          {"error": "not a KDC"})
                    return
                try:
                    ch = a.server.get_challenge(body["entity"])
                except AuthError as e:
                    self.auth_rejects += 1
                    self._send_auth_frame(s, _A_KDC_CHALLENGE,
                                          {"error": str(e)})
                    return
                self._send_auth_frame(s, _A_KDC_CHALLENGE,
                                      {"challenge": ch})
            elif op == _A_KDC_PROVE:
                if a is None or a.server is None:
                    self._send_auth_frame(s, _A_KDC_REPLY,
                                          {"ok": False,
                                           "error": "not a KDC"})
                    return
                try:
                    blob = a.server.authenticate(
                        body["entity"], body.get("server_challenge", b""),
                        body["client_challenge"], body["proof"])
                    self._send_auth_frame(s, _A_KDC_REPLY,
                                          {"ok": True, "blob": blob})
                except AuthError as e:
                    self.auth_rejects += 1
                    self._send_auth_frame(s, _A_KDC_REPLY,
                                          {"ok": False, "error": str(e)})
            elif op == _A_AUTH_HELLO:
                ch = os.urandom(16)
                state["challenge"] = ch
                self._send_auth_frame(s, _A_AUTH_CHALLENGE,
                                      {"challenge": ch})
            elif op == _A_AUTHORIZER:
                if a is None:
                    self._send_auth_frame(
                        s, _A_AUTH_REPLY,
                        {"ok": False, "error": "auth disabled here"})
                    return
                a.ensure_verifier()
                if a.verifier is None:
                    self._send_auth_frame(
                        s, _A_AUTH_REPLY,
                        {"ok": False, "error": "no rotating keys yet"})
                    return
                ch = state.pop("challenge", None)
                if ch is None:
                    self.auth_rejects += 1
                    self._send_auth_frame(
                        s, _A_AUTH_REPLY,
                        {"ok": False, "error": "no challenge issued on "
                         "this connection"})
                    return
                try:
                    entity, sk, reply = \
                        a.verifier.verify_authorizer(body, ch)
                except AuthError as e:
                    self.auth_rejects += 1
                    self._send_auth_frame(s, _A_AUTH_REPLY,
                                          {"ok": False, "error": str(e)})
                    return
                state.update(authed=True, sk=sk, entity=entity)
                self._send_auth_frame(s, _A_AUTH_REPLY,
                                      {"ok": True, "reply": reply})
        except Exception as e:
            # malformed payloads (struct.error, UnicodeDecodeError, bad
            # TLV...) come straight off the network: drop, never die
            self.auth_rejects += 1
            from ..common.dout import dlog
            dlog("msg", 0, f"auth frame error: {e!r}")

    def _handle_session_frame(self, s: socket.socket, op: int,
                              payload: bytes) -> None:
        """Session hello on an accepted socket: bind the peer entity
        (for seq bookkeeping) and return its delivered high-water mark
        so a reconnecting sender can trim its resend queue."""
        try:
            if op != _S_HELLO:
                return
            body = decode_blob(payload)
            entity = body.get("entity")
            err = None
            if not isinstance(entity, str) or not entity:
                err = "session hello without entity"
            elif self.auth is not None:
                st = self._in_auth.get(s)
                if st is None or not st.get("authed") or \
                        st.get("entity") != entity:
                    err = "session hello does not match " \
                          "authenticated principal"
            if err:
                self.auth_rejects += 1
                out = encode_blob({"error": err})
            else:
                self._sess_peer[s] = entity
                nonce = int(body.get("nonce", 0))
                if self._sess_rx_nonce.get(entity) != nonce:
                    # new incarnation of this peer: its seq space
                    # restarted, so the old high-water mark is void
                    self._sess_rx_nonce[entity] = nonce
                    self._sess_rx[entity] = 0
                out = encode_blob(
                    {"last_seq": self._sess_rx.get(entity, 0)})
            s.sendall(_HDR.pack(len(out), _SESS_DLEN, _S_HELLO_ACK)
                      + out)
        except Exception as e:
            from ..common.dout import dlog
            dlog("msg", 0, f"session frame error: {e!r}")

    def _drain_frames(self, s: socket.socket, buf: bytearray) -> int:
        n = 0
        trailer = _SIG_LEN if self.auth is not None else 0
        ack_entity = None
        while len(buf) >= _HDR.size:
            plen, dlen, comp_id = _HDR.unpack_from(buf, 0)
            if dlen in (_AUTH_DLEN, _SESS_DLEN, _ACK_DLEN):
                # control frames: no dst name, no signature trailer
                total = _HDR.size + plen
                if len(buf) < total:
                    break
                payload = bytes(buf[_HDR.size:total])
                del buf[:total]
                if dlen == _AUTH_DLEN:
                    self._handle_auth_frame(s, comp_id, payload)
                elif dlen == _SESS_DLEN:
                    self._handle_session_frame(s, comp_id, payload)
                # _ACK_DLEN rides outbound sockets; ignore here
                continue
            seq_wrapped = dlen == _SEQ_DLEN
            body_len = plen if seq_wrapped else dlen + plen
            total = _HDR.size + body_len + trailer
            if len(buf) < total:
                break
            frame_bytes = bytes(buf[:total - trailer])
            body = frame_bytes[_HDR.size:]
            sig = bytes(buf[total - trailer:total])
            del buf[:total]
            # auth gate FIRST: nothing from an unauthenticated or
            # forged frame (including its dst name) gets interpreted
            if trailer:
                state = self._in_auth.get(s)
                if state is None or not state.get("authed"):
                    self.auth_rejects += 1
                    self.dropped += 1
                    from ..common.dout import dlog
                    dlog("msg", 0, "dropping frame: "
                         "connection not authenticated")
                    continue
                from ..auth import hmac_tag
                if sig != hmac_tag(state["sk"], frame_bytes, _SIG_LEN):
                    self.auth_rejects += 1
                    self.dropped += 1
                    from ..common.dout import dlog
                    dlog("msg", 0, "dropping frame: "
                         "bad frame signature")
                    continue
            seq = 0
            if seq_wrapped:
                if len(body) < 10:
                    self.dropped += 1
                    continue
                seq, ndlen = struct.unpack_from("<Q H", body, 0)
                dst_raw = body[10:10 + ndlen]
                payload = body[10 + ndlen:]
            else:
                dst_raw = body[:dlen]
                payload = body[dlen:]
            try:
                dst = dst_raw.decode()
            except UnicodeDecodeError as e:
                self.dropped += 1
                from ..common.dout import dlog
                dlog("msg", 0, f"dropped frame with undecodable dst "
                     f"name: {e!r}")
                continue
            if seq_wrapped:
                # seq bookkeeping BEFORE decode: an undecodable payload
                # (codec mismatch, corrupt TLV) must still advance the
                # ack high-water mark — resending it forever would
                # wedge the session head-of-line; the loss is counted
                # and logged below instead of silently un-acked
                ent = self._sess_peer.get(s)
                if ent is None:
                    # no session hello on this connection yet
                    self.dropped += 1
                    continue
                if seq <= self._sess_rx.get(ent, 0):
                    self.dup_dropped += 1      # reconnect resend overlap
                    ack_entity = ent
                    continue
                self._sess_rx[ent] = seq
                ack_entity = ent
            try:
                if comp_id:
                    dec = self._decomps.get(comp_id)
                    if dec is None:
                        from ..common.dout import dlog
                        from ..compressor import create_compressor
                        try:
                            dec = create_compressor(
                                _COMP_NAMES.get(comp_id, f"#{comp_id}"))
                        except KeyError:
                            # peer uses a codec this environment lacks:
                            # dropping silently would hang its ops with
                            # zero diagnostics — log loudly every time
                            dlog("msg", 0,
                                 f"dropping frame for {dst}: peer codec "
                                 f"id {comp_id} unavailable here")
                            self.dropped += 1
                            continue
                        self._decomps[comp_id] = dec
                    payload = dec.decompress(payload)
                msg = decode_message(payload)
            except Exception as e:  # corrupt frame or codec error
                                # (zlib.error etc. — each codec raises
                                # its own type)
                # count it dropped and keep pumping, but make sustained
                # failure streams (e.g. a peer speaking an older frame
                # layout) discoverable: log the first drop and then
                # every 100th
                self.dropped += 1
                if self.dropped == 1 or self.dropped % 100 == 0:
                    from ..common.dout import dlog
                    dlog("msg", 0,
                         f"dropped undecodable frame for {dst} "
                         f"({self.dropped} total; possible peer wire-"
                         f"format mismatch): {e!r}")
                continue
            if not isinstance(getattr(msg, "src", None), str):
                # src drives hashed routing/filter lookups everywhere;
                # a non-string here is a malformed/hostile frame
                self.dropped += 1
                continue
            if trailer:
                # the signature binds the frame to the connection's
                # authenticated principal; spoofed src names (a client
                # key claiming to be an osd/mon) get dropped here
                from ..auth import entity_service
                state = self._in_auth.get(s) or {}
                if entity_service(msg.src) != \
                        entity_service(state.get("entity", "")):
                    self.auth_rejects += 1
                    self.dropped += 1
                    from ..common.dout import dlog
                    dlog("msg", 0,
                         f"dropping frame: src {msg.src!r} outside "
                         f"authenticated service of "
                         f"{state.get('entity')!r}")
                    continue
            # enqueue like a local delivery (fault injection still applies)
            self.queue.append((msg.src, dst, msg))
            n += 1
        if ack_entity is not None:
            try:
                s.sendall(_HDR.pack(8, _ACK_DLEN, 0)
                          + struct.pack("<Q", self._sess_rx[ack_entity]))
            except OSError:
                pass
        return n

    # ---- pumping -----------------------------------------------------------
    def pump(self, max_msgs: int = 100000, quiesce: float = 0.05,
             deadline: float = 5.0) -> int:
        """Drain local queue + sockets until no traffic arrives for
        *quiesce* seconds (bounded by *deadline*)."""
        total = 0
        t_end = time.monotonic() + deadline
        idle_since = None
        while time.monotonic() < t_end:
            self._flush_lossless()
            moved = super().pump(max_msgs)
            moved += self._poll_sockets(0.005)
            total += moved
            if moved:
                idle_since = None
                continue
            if idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since >= quiesce:
                break
        return total

    def close(self) -> None:
        for s in [self._listener, *self._accepted,
                  *self._conns.values()]:
            try:
                s.close()
            except OSError:
                pass
