"""TCP messenger — the cross-process transport (AsyncMessenger role).

The reference runs epoll-driven AsyncMessengers speaking a framed wire
protocol between daemon processes (src/msg/async/AsyncMessenger.h:74);
this is the equivalent thin shim: a ``TcpNetwork`` extends the in-process
fabric so entities living in *other* processes are reachable through
length-prefixed wire frames (msg/wire.py) over plain sockets.

Topology is static like a mon map: every process knows the
entity -> (host, port) directory.  Local sends short-circuit through the
in-process queue; remote sends frame and ship.  ``pump()`` drains both
the local queue and any readable sockets until traffic quiesces, so the
callers' deterministic pump loops keep working across processes.
"""
from __future__ import annotations

import socket
import struct
import time
from typing import Dict, Optional, Tuple

from .messenger import Network
from .messages import Message
from .wire import decode_message, encode_message

_HDR = struct.Struct("<I H B")   # frame length, dst-name length, comp algo

# frame compression algorithm ids (Compressor::COMP_ALG_* role); the
# receiver decodes by the frame's id, so peers may use different configs
_COMP_IDS = {"none": 0, "zlib": 1, "snappy": 2, "zstd": 3, "lz4": 4}
_COMP_NAMES = {v: k for k, v in _COMP_IDS.items()}


class TcpNetwork(Network):
    """One per process: hosts local entities, routes to remote ones.

    ``compression`` compresses outbound frame payloads at least
    ``compress_min`` bytes long (ms_compress role; BlueStore-style
    plugin via ceph_tpu.compressor)."""

    def __init__(self, listen_addr: Tuple[str, int],
                 directory: Dict[str, Tuple[str, int]],
                 compression: str = "none", compress_min: int = 1024):
        super().__init__()
        from ..compressor import create_compressor
        self.compression = compression
        self.compress_min = compress_min
        self._comp = create_compressor(compression)
        self._comp_id = _COMP_IDS[compression]
        self._decomps = {0: create_compressor("none")}
        self.directory = dict(directory)
        self.listen_addr = listen_addr
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(listen_addr)
        self._listener.listen(16)
        self._listener.setblocking(False)
        self._conns: Dict[Tuple[str, int], socket.socket] = {}
        self._accepted: list = []
        self._rxbuf: Dict[socket.socket, bytearray] = {}

    # ---- sending -----------------------------------------------------------
    # Network.send enqueues everything; pump() applies the fault-injection
    # filters and calls _route_remote for non-local destinations, so
    # down/blackhole/drop semantics are identical across the boundary.
    def _route_remote(self, src: str, dst: str, msg: Message) -> bool:
        addr = self.directory.get(dst)
        if addr is None or tuple(addr) == tuple(self.listen_addr):
            return False  # unknown, or points back here with no endpoint
        payload = encode_message(msg)
        comp_id = 0
        if self._comp_id and len(payload) >= self.compress_min:
            compressed = self._comp.compress(payload)
            # keep the raw buffer when compression doesn't help
            # (incompressible EC shard data expands under zlib)
            if len(compressed) < len(payload):
                payload = compressed
                comp_id = self._comp_id
        dname = dst.encode()
        frame = _HDR.pack(len(payload), len(dname), comp_id) \
            + dname + payload
        addr = tuple(addr)
        try:
            self._peer(addr).sendall(frame)
            return True
        except OSError:
            s = self._conns.pop(addr, None)
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
            return False

    def _peer(self, addr: Tuple[str, int]) -> socket.socket:
        s = self._conns.get(addr)
        if s is None:
            s = socket.create_connection(addr, timeout=5.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[addr] = s
        return s

    # ---- receiving ---------------------------------------------------------
    def _poll_sockets(self, wait: float) -> int:
        import select
        socks = [self._listener] + self._accepted
        try:
            readable, _, _ = select.select(socks, [], [], wait)
        except OSError:
            return 0
        n = 0
        for s in readable:
            if s is self._listener:
                try:
                    conn, _peer = self._listener.accept()
                    conn.setblocking(False)
                    self._accepted.append(conn)
                    self._rxbuf[conn] = bytearray()
                except OSError:
                    pass
                continue
            try:
                data = s.recv(1 << 20)
            except OSError:
                data = b""
            if not data:
                self._accepted.remove(s)
                self._rxbuf.pop(s, None)
                continue
            buf = self._rxbuf[s]
            buf.extend(data)
            n += self._drain_frames(buf)
        return n

    def _drain_frames(self, buf: bytearray) -> int:
        n = 0
        while len(buf) >= _HDR.size:
            plen, dlen, comp_id = _HDR.unpack_from(buf, 0)
            total = _HDR.size + dlen + plen
            if len(buf) < total:
                break
            dst = bytes(buf[_HDR.size:_HDR.size + dlen]).decode()
            payload = bytes(buf[_HDR.size + dlen:total])
            del buf[:total]
            try:
                if comp_id:
                    dec = self._decomps.get(comp_id)
                    if dec is None:
                        from ..common.dout import dlog
                        from ..compressor import create_compressor
                        try:
                            dec = create_compressor(
                                _COMP_NAMES.get(comp_id, f"#{comp_id}"))
                        except KeyError:
                            # peer uses a codec this environment lacks:
                            # dropping silently would hang its ops with
                            # zero diagnostics — log loudly every time
                            dlog("msg", 0,
                                 f"dropping frame for {dst}: peer codec "
                                 f"id {comp_id} unavailable here")
                            self.dropped += 1
                            continue
                        self._decomps[comp_id] = dec
                    payload = dec.decompress(payload)
                msg = decode_message(payload)
            except Exception as e:  # corrupt frame or codec error
                                # (zlib.error etc. — each codec raises
                                # its own type)
                # count it dropped and keep pumping, but make sustained
                # failure streams (e.g. a peer speaking an older frame
                # layout) discoverable: log the first drop and then
                # every 100th
                self.dropped += 1
                if self.dropped == 1 or self.dropped % 100 == 0:
                    from ..common.dout import dlog
                    dlog("msg", 0,
                         f"dropped undecodable frame for {dst} "
                         f"({self.dropped} total; possible peer wire-"
                         f"format mismatch): {e!r}")
                continue
            # enqueue like a local delivery (fault injection still applies)
            self.queue.append((msg.src, dst, msg))
            n += 1
        return n

    # ---- pumping -----------------------------------------------------------
    def pump(self, max_msgs: int = 100000, quiesce: float = 0.05,
             deadline: float = 5.0) -> int:
        """Drain local queue + sockets until no traffic arrives for
        *quiesce* seconds (bounded by *deadline*)."""
        total = 0
        t_end = time.monotonic() + deadline
        idle_since = None
        while time.monotonic() < t_end:
            moved = super().pump(max_msgs)
            moved += self._poll_sockets(0.005)
            total += moved
            if moved:
                idle_since = None
                continue
            if idle_since is None:
                idle_since = time.monotonic()
            elif time.monotonic() - idle_since >= quiesce:
                break
        return total

    def close(self) -> None:
        for s in [self._listener, *self._accepted,
                  *self._conns.values()]:
            try:
                s.close()
            except OSError:
                pass
