"""Wire format for the message types (src/include/encoding.h role).

Every M* dataclass encodes to a self-describing length-prefixed binary
frame so messages can leave the process (msg/tcp.py's transport, mon/osd
store files).  The value codec is a small tagged TLV scheme — ints,
strs, bytes, bools, floats, lists, tuples, dicts — mirroring how the
reference's encode/decode pairs compose from primitive encoders
(src/msg/Message.h:254 header/payload framing).

OSDMap Incrementals ride inside MOSDMap; they serialize through the
structured dict codecs (osdmap/encoding.py), the same representation the
mon store persists.
"""
from __future__ import annotations

import struct
from typing import Any, Dict

from . import messages as M

_MSG_CLASSES = {
    name: cls for name, cls in vars(M).items()
    if isinstance(cls, type) and issubclass(cls, M.Message)}

# value tags
_T_NONE, _T_INT, _T_FLOAT, _T_TRUE, _T_FALSE = b"N", b"I", b"F", b"T", b"f"
_T_STR, _T_BYTES, _T_LIST, _T_TUPLE, _T_DICT = b"S", b"Y", b"L", b"U", b"D"


def _enc_value(v: Any, out: list) -> None:
    if v is None:
        out.append(_T_NONE)
    elif v is True:
        out.append(_T_TRUE)
    elif v is False:
        out.append(_T_FALSE)
    elif isinstance(v, int):
        out.append(_T_INT)
        out.append(struct.pack("<q", v))
    elif isinstance(v, float):
        out.append(_T_FLOAT)
        out.append(struct.pack("<d", v))
    elif isinstance(v, str):
        b = v.encode()
        out.append(_T_STR)
        out.append(struct.pack("<I", len(b)))
        out.append(b)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(_T_BYTES)
        out.append(struct.pack("<I", len(b)))
        out.append(b)
    elif isinstance(v, (list, tuple)):
        out.append(_T_LIST if isinstance(v, list) else _T_TUPLE)
        out.append(struct.pack("<I", len(v)))
        for item in v:
            _enc_value(item, out)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        out.append(struct.pack("<I", len(v)))
        for k in v:
            _enc_value(k, out)
            _enc_value(v[k], out)
    else:
        raise TypeError(f"unencodable value type {type(v)!r}")


def _dec_value(buf: bytes, pos: int):
    tag = buf[pos:pos + 1]
    pos += 1
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_INT:
        return struct.unpack_from("<q", buf, pos)[0], pos + 8
    if tag == _T_FLOAT:
        return struct.unpack_from("<d", buf, pos)[0], pos + 8
    if tag in (_T_STR, _T_BYTES):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        b = buf[pos:pos + n]
        return (b.decode() if tag == _T_STR else b), pos + n
    if tag in (_T_LIST, _T_TUPLE):
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            v, pos = _dec_value(buf, pos)
            items.append(v)
        return (items if tag == _T_LIST else tuple(items)), pos
    if tag == _T_DICT:
        (n,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _dec_value(buf, pos)
            v, pos = _dec_value(buf, pos)
            d[k] = v
        return d, pos
    raise ValueError(f"bad wire tag {tag!r} at {pos - 1}")


def encode_blob(value: Any) -> bytes:
    """Plain value -> TLV bytes (auth tickets, small control blobs)."""
    out: list = []
    _enc_value(value, out)
    return b"".join(out)


def decode_blob(buf: bytes) -> Any:
    value, _pos = _dec_value(buf, 0)
    return value


def encode_message(msg: M.Message) -> bytes:
    """Message -> framed bytes (class name + field dict)."""
    fields: Dict[str, Any] = dict(vars(msg))
    if not fields.get("parent_span_id"):
        # optional tracing header: only on the wire when set, so frames
        # with tracing off — and the archived encoding corpus — stay
        # byte-identical to the pre-tracing format (decode fills the
        # dataclass default 0)
        fields.pop("parent_span_id", None)
    if fields.get("repair_for", -1) < 0:
        # optional repair-read selector (MOSDECSubOpRead): only on the
        # wire for sub-chunk repair rounds — plain reads and the
        # archived corpus encode byte-identically (decode fills the
        # dataclass default -1)
        fields.pop("repair_for", None)
    if not fields.get("retry_after"):
        # optional QoS throttle hint (MOSDOpReply): same
        # omitted-when-default contract as parent_span_id — unthrottled
        # replies and the archived corpus encode byte-identically
        fields.pop("retry_after", None)
    # the stage-latency ledger (trace/oplat.py) rides messages as an
    # in-process annotation only: never on the wire, so real-TCP
    # frames and the pinned corpus stay byte-identical (a receiver
    # opens a fresh ledger at intake instead)
    fields.pop("_oplat", None)
    for key, v in fields.items():
        if hasattr(v, "materialize"):
            # device-resident payloads (os_store DeviceShard) leave
            # the process as plain bytes: the handle is an in-process
            # fast path only, frames stay byte-identical either way
            fields[key] = v.materialize()
    if isinstance(msg, M.MOSDMap):
        from ..osdmap.encoding import incremental_to_dict
        fields["incrementals"] = [incremental_to_dict(i)
                                  for i in msg.incrementals]
    if isinstance(msg, M.MOSDOp) and msg.ops:
        fields["ops"] = [dict(vars(o)) for o in msg.ops]
    out: list = []
    name = type(msg).__name__.encode()
    out.append(struct.pack("<H", len(name)))
    out.append(name)
    _enc_value(fields, out)
    return b"".join(out)


def decode_message(buf: bytes) -> M.Message:
    (nlen,) = struct.unpack_from("<H", buf, 0)
    name = buf[2:2 + nlen].decode()
    cls = _MSG_CLASSES.get(name)
    if cls is None:
        raise ValueError(f"unknown message class {name!r}")
    fields, _pos = _dec_value(buf, 2 + nlen)
    if cls is M.MOSDMap:
        from ..osdmap.encoding import incremental_from_dict
        fields["incrementals"] = [incremental_from_dict(d)
                                  for d in fields["incrementals"]]
    if cls is M.MOSDOp and fields.get("ops"):
        fields["ops"] = [M.OSDOp(**d) for d in fields["ops"]]
    msg = cls()
    for k, v in fields.items():
        setattr(msg, k, v)
    return msg
