from .messages import (
    MOSDECSubOpRead, MOSDECSubOpReadReply, MOSDECSubOpWrite,
    MOSDECSubOpWriteReply, MOSDMap, MOSDOp, MOSDOpReply, MOSDPGInfo,
    MOSDPGNotify, MOSDPGQuery, MOSDPGRemove, MOSDPGScan,
    MOSDPGScanReply, MOSDPing, MOSDRepScrub,
    MOSDRepScrubMap, Message,
    MOSDFailure, CEPH_OSD_OP_READ, CEPH_OSD_OP_WRITE, CEPH_OSD_OP_WRITEFULL,
    CEPH_OSD_OP_APPEND, CEPH_OSD_OP_DELETE, CEPH_OSD_OP_STAT,
)
from .messenger import Connection, Dispatcher, Messenger, Network

__all__ = [
    "MOSDECSubOpRead", "MOSDECSubOpReadReply", "MOSDECSubOpWrite",
    "MOSDECSubOpWriteReply", "MOSDMap", "MOSDOp", "MOSDOpReply",
    "MOSDPGInfo", "MOSDPGNotify", "MOSDPGQuery", "MOSDPGRemove",
    "MOSDPGScan", "MOSDPGScanReply",
    "MOSDPing", "MOSDRepScrub", "MOSDRepScrubMap",
    "Message", "MOSDFailure", "Connection", "Dispatcher",
    "Messenger", "Network", "CEPH_OSD_OP_READ", "CEPH_OSD_OP_WRITE",
    "CEPH_OSD_OP_WRITEFULL", "CEPH_OSD_OP_APPEND", "CEPH_OSD_OP_DELETE",
    "CEPH_OSD_OP_STAT",
]
