"""cephfs-lite client: POSIX-shaped file operations over two pools.

The reference cephfs (src/client, 24k LoC + src/mds, 77k) resolves
paths dentry-by-dentry against MDS caches and stripes file data into a
data pool via the file layout (osdc/Striper).  This client keeps that
exact storage shape — metadata-pool dir objects with dentry omaps
(cls_fs), ``%llx.%08llx`` data objects — and performs each metadata
mutation as one atomic server-side class method, so concurrency is
serialized by the directory object's PG instead of MDS locks.

Scope-outs vs the reference (see cls_fs for the rationale): client
capabilities/leases and delegations, the MDS journal + standby-replay,
and multi-MDS subtree partitioning.  Snapshots exist at whole-fs scope
(the SnapRealm hierarchy collapsed to one domain; see snap_create).
Hard links use
remote dentries with a back-pointer list on the primary (promotion on
primary unlink replaces the MDS stray-directory migration).  stat() is lstat-shaped (final-component symlinks
are not followed); intermediate symlinks resolve like the kernel
client's path walk.  Cross-directory rename is dst-link-then-src-unlink —
two PG-atomic steps, briefly observable as a double link, never a loss
(the reference orders the same two events through its journal).
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from ..client.rados import RadosClient
from .cls_fs import ROOT_INO, INOTABLE_OID, dir_oid, file_oid


class FsError(IOError):
    def __init__(self, api: str, result: int):
        super().__init__(f"cephfs {api}: error {result}")
        self.result = result


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _absent(e: IOError) -> bool:
    return getattr(e, "errno", None) == 2


DEFAULT_ORDER = 22                # 4 MiB objects (file_layout_t default)


class CephFS:
    """A mounted filesystem (libcephfs ceph_mount shape)."""

    def __init__(self, client: RadosClient, metadata_pool: str,
                 data_pool: str):
        self.client = client
        self.mdpool = metadata_pool
        self.dpool = data_pool
        # set on snapshot VIEWS (CephFS.snapshot()): reads resolve
        # against these snap ids; mutations are refused EROFS
        self._md_snap = None
        self._data_snap = None
        try:
            self._install_snapc()
        except KeyError:
            pass                     # pool not created yet (pre-mkfs)
        except FsError as e:
            if e.result != -2:
                # a transient failure must be LOUD: mounting with no
                # snap context would silently overwrite snapshots
                raise

    # ---- cls plumbing -----------------------------------------------------
    def _call(self, oid: str, method: str, payload=None) -> bytes:
        ret, out = self.client.exec(self.mdpool, oid, "fs", method,
                                    _j(payload or {}),
                                    snap=self._md_snap)
        if ret < 0:
            raise FsError(method, ret)
        return out

    def _rw(self) -> None:
        if self._md_snap is not None:
            raise FsError("readonly snapshot view", -30)   # EROFS

    # ---- lifecycle --------------------------------------------------------
    def mkfs(self) -> None:
        """Initialize inotable + root directory object (ceph fs new)."""
        self._call(INOTABLE_OID, "mkfs")
        # the root dir object springs into existence on first dentry;
        # create it eagerly so readdir("/") works on an empty fs
        self.client.create(self.mdpool, dir_oid(ROOT_INO),
                           exclusive=False)

    def _alloc_ino(self) -> int:
        return json.loads(self._call(INOTABLE_OID, "alloc_ino"))["ino"]

    # ---- path resolution --------------------------------------------------
    @staticmethod
    def _split(path: str) -> List[str]:
        parts = [p for p in path.split("/") if p]
        if any(p in (".", "..") for p in parts):
            raise FsError("path", -22)
        return parts

    def _resolve(self, path: str, depth: int = 0,
                 follow_final: bool = False) -> Dict:
        """Path -> inode dict; root is synthetic (the reference pins the
        root CInode in the MDS cache the same way).  Symlinks in
        intermediate components are always followed; the final
        component follows only with ``follow_final`` (stat keeps
        lstat-like semantics)."""
        if depth > 10:
            raise FsError("resolve", -40)             # ELOOP
        parts = self._split(path)
        inode = {"ino": ROOT_INO, "type": "dir", "size": 0, "mtime": 0}
        for i, name in enumerate(parts):
            if inode["type"] != "dir":
                raise FsError("resolve", -20)         # ENOTDIR
            inode = self._lookup(inode["ino"], name)
            if inode.get("type") == "remote":
                # hard link: a remote dentry IS the file (POSIX link
                # identity), unlike a symlink — always dereference
                _, _, inode = self._primary_of(0, "", inode)
            last = i == len(parts) - 1
            if inode["type"] == "symlink" and (not last or follow_final):
                target = inode["target"]
                if not target.startswith("/"):
                    base = "/".join(parts[:i])
                    target = (f"/{base}/{target}" if base
                              else f"/{target}")
                rest = "/".join(parts[i + 1:])
                full = f"{target}/{rest}" if rest else target
                return self._resolve(full, depth + 1, follow_final)
        return inode

    def _resolve_parent(self, path: str) -> Tuple[int, str]:
        parts = self._split(path)
        if not parts:
            raise FsError("resolve", -22)
        parent = "/".join(parts[:-1])
        return (self._resolve(parent, follow_final=True)["ino"],
                parts[-1])

    def _lookup(self, dir_ino: int, name: str) -> Dict:
        return json.loads(self._call(dir_oid(dir_ino), "lookup",
                                     {"name": name}))

    def _primary_of(self, dino: int, name: str, inode: Dict):
        """Resolve a remote dentry to (primary_dir, primary_name,
        primary_inode); identity for everything else (CDentry remote ->
        primary resolution in the MDS cache)."""
        if inode.get("type") != "remote":
            return dino, name, inode
        pd, pn = inode["primary"]
        return pd, pn, self._lookup(pd, pn)

    # ---- hard links (CDentry remote dentries; inode embedded in the
    # primary, back-pointer list to every remote) ----------------------
    def hardlink(self, existing: str, newpath: str) -> None:
        self._rw()
        """link(2): a new name for an existing FILE.  The new dentry is
        a remote referencing the primary; the primary records it in its
        back-pointer list FIRST, so a crash between the two steps
        leaves a recorded-but-absent link (pruned on promotion) rather
        than an untracked dangling remote."""
        ed, en = self._resolve_parent(existing)
        pd, pn, pinode = self._primary_of(ed, en, self._lookup(ed, en))
        if pinode["type"] == "dir":
            raise FsError("link", -1)            # EPERM, like the MDS
        if pinode["type"] != "file":
            raise FsError("link", -22)
        nd, nn = self._resolve_parent(newpath)
        # only roll back an entry THIS call added — a repeated
        # hardlink to the same name must not strip the original
        # back-pointer on its EEXIST failure
        added = [nd, nn] not in pinode.get("links", [])
        if added:
            self._update_links(pd, pn, add_links=[[nd, nn]])
        try:
            self._call(dir_oid(nd), "link", {"name": nn, "inode": {
                "type": "remote", "ino": pinode["ino"],
                "primary": [pd, pn]}})
        except FsError:
            if added:
                self._update_links(pd, pn, remove_links=[[nd, nn]])
            raise

    # ---- directories ------------------------------------------------------
    def mkdir(self, path: str) -> int:
        self._rw()
        dino, name = self._resolve_parent(path)
        ino = self._alloc_ino()
        # object BEFORE dentry: cls_fs refuses WR calls on a missing
        # dir object (missing == rmdir'd — the anti-resurrection
        # guard), so the object must exist from the instant the dentry
        # makes it reachable.  A crash here leaves an unreachable
        # object (fsck-collectable), never a broken directory.
        self.client.create(self.mdpool, dir_oid(ino), exclusive=False)
        try:
            self._call(dir_oid(dino), "link", {"name": name, "inode": {
                "ino": ino, "type": "dir", "size": 0, "mode": 0o755,
                "uid": 0, "gid": 0, "mtime": time.time()}})
        except FsError:
            self.client.remove(self.mdpool, dir_oid(ino))
            raise
        return ino

    def listdir(self, path: str) -> Dict[str, Dict]:
        inode = self._resolve(path, follow_final=True)
        if inode["type"] != "dir":
            raise FsError("listdir", -20)
        return json.loads(self._call(dir_oid(inode["ino"]), "readdir"))

    def rmdir(self, path: str) -> None:
        self._rw()
        dino, name = self._resolve_parent(path)
        target = self._lookup(dino, name)
        if target["type"] != "dir":
            raise FsError("rmdir", -20)
        # seal the child atomically (empty-check + refuse-new-links in
        # one PG-serialized call) BEFORE touching the parent dentry, so
        # a racing create either beats the seal (rmdir fails ENOTEMPTY)
        # or loses to it (create fails ENOENT) — never gets orphaned
        self._call(dir_oid(target["ino"]), "dir_mark_dead")
        self._call(dir_oid(dino), "unlink", {"name": name})
        self.client.remove(self.mdpool, dir_oid(target["ino"]))

    # ---- files ------------------------------------------------------------
    def create(self, path: str, order: int = DEFAULT_ORDER) -> int:
        self._rw()
        dino, name = self._resolve_parent(path)
        ino = self._alloc_ino()
        self._call(dir_oid(dino), "link", {"name": name, "inode": {
            "ino": ino, "type": "file", "size": 0, "order": order,
            "mode": 0o644, "uid": 0, "gid": 0,
            "mtime": time.time()}})
        return ino

    def symlink(self, path: str, target: str) -> int:
        self._rw()
        dino, name = self._resolve_parent(path)
        ino = self._alloc_ino()
        self._call(dir_oid(dino), "link", {"name": name, "inode": {
            "ino": ino, "type": "symlink", "size": len(target),
            "target": target, "mtime": time.time()}})
        return ino

    def readlink(self, path: str) -> str:
        inode = self._resolve(path)
        if inode["type"] != "symlink":
            raise FsError("readlink", -22)
        return inode["target"]

    def setattr(self, path: str, mode: Optional[int] = None,
                uid: Optional[int] = None, gid: Optional[int] = None,
                mtime: Optional[float] = None) -> Dict:
        """chmod/chown/utimens in one verb (the MDS setattr flow):
        attribute merges happen server-side on the dentry, so two
        concurrent setattrs never lose each other's fields."""
        self._rw()
        if not self._split(path):
            # the root inode is synthetic (no dentry to store attrs
            # on); a clear error beats EINVAL from path resolution
            raise FsError("setattr on the filesystem root is not "
                          "supported (synthetic root inode)", -95)
        # follows final symlinks like chmod(2)/chown(2)
        dino, name, inode = self._resolve_dentry(path)
        attrs = {}
        if mode is not None:
            attrs["mode"] = mode & 0o7777
        if uid is not None:
            attrs["uid"] = uid
        if gid is not None:
            attrs["gid"] = gid
        if mtime is not None:
            attrs["mtime"] = mtime
        if not attrs:
            return inode            # no-op: skip the mutating RPC
        return self._update(dino, name, **attrs)

    def chmod(self, path: str, mode: int) -> None:
        self.setattr(path, mode=mode)

    def chown(self, path: str, uid: int, gid: int) -> None:
        self.setattr(path, uid=uid, gid=gid)

    def stat(self, path: str) -> Dict:
        inode = self._resolve(path)
        if inode.get("type") == "file":
            inode = dict(inode,
                         nlink=1 + len(inode.get("links", [])))
        return inode

    def _resolve_dentry(self, path: str,
                        depth: int = 0) -> Tuple[int, str, Dict]:
        """-> (dir_ino, name, inode) of the PRIMARY dentry serving
        ``path``, following final-component symlinks (like open(2)/
        chmod(2)) and dereferencing remote hard-link dentries — the
        shared resolution under _file_inode and setattr."""
        if depth > 10:
            raise FsError("resolve", -40)             # ELOOP
        dino, name = self._resolve_parent(path)
        inode = self._lookup(dino, name)
        if inode.get("type") == "remote":
            dino, name, inode = self._primary_of(dino, name, inode)
        if inode["type"] == "symlink":
            target = inode["target"]
            if not target.startswith("/"):
                # relative targets resolve against the link's parent
                # directory, like symlink(2)
                parent = "/".join(self._split(path)[:-1])
                target = (f"/{parent}/{target}" if parent
                          else f"/{target}")
            return self._resolve_dentry(target, depth + 1)
        return dino, name, inode

    def _file_inode(self, path: str) -> Tuple[int, str, Dict]:
        dino, name, inode = self._resolve_dentry(path)
        if inode["type"] != "file":
            raise FsError("open", -21)                # EISDIR
        return dino, name, inode

    def _update_links(self, dino: int, name: str, **kind) -> Dict:
        """Server-side back-pointer mutation (add_links/remove_links/
        replace_link) — atomic on the dentry, no client RMW window."""
        return json.loads(self._call(dir_oid(dino), "update_inode",
                                     {"name": name, **kind}))

    def _update(self, dino: int, name: str, **attrs) -> Dict:
        return json.loads(self._call(dir_oid(dino), "update_inode",
                                     {"name": name, "attrs": attrs}))

    def write(self, path: str, data: bytes, offset: int = 0) -> int:
        self._rw()
        dino, name, inode = self._file_inode(path)
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        pos = 0
        while pos < len(data):
            objno, ooff = divmod(offset + pos, osize)
            take = min(len(data) - pos, osize - ooff)
            r = self.client.write(self.dpool,
                                  file_oid(inode["ino"], objno),
                                  data[pos:pos + take], ooff)
            if r < 0:
                raise FsError("write", r)
            pos += take
        # the size maxes server-side (cls update_inode max_attrs), so
        # two concurrent writers can never shrink each other's growth
        self._call(dir_oid(dino), "update_inode",
                   {"name": name, "attrs": {"mtime": time.time()},
                    "max_attrs": {"size": offset + len(data)}})
        return len(data)

    def read(self, path: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        _, _, inode = self._file_inode(path)
        size = inode["size"]
        if offset >= size:
            return b""
        length = size - offset if length is None else \
            min(length, size - offset)
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        chunks = []
        remaining, pos = length, offset
        while remaining > 0:
            objno, ooff = divmod(pos, osize)
            take = min(remaining, osize - ooff)
            try:
                data = self.client.read(self.dpool,
                                        file_oid(inode["ino"], objno),
                                        offset=ooff, length=take,
                                        snap=self._data_snap)
            except IOError as e:
                if not _absent(e):
                    raise
                data = b""
            chunks.append(data.ljust(take, b"\x00"))   # sparse holes
            pos += take
            remaining -= take
        return b"".join(chunks)

    def truncate(self, path: str, size: int) -> None:
        self._rw()
        dino, name, inode = self._file_inode(path)
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        old = inode["size"]
        if size < old:
            keep = (size + osize - 1) // osize
            for objno in range(keep, (old + osize - 1) // osize):
                self.client.remove(self.dpool,
                                   file_oid(inode["ino"], objno))
            tail = size - (keep - 1) * osize
            if keep and tail < osize:
                self.client.truncate(self.dpool,
                                     file_oid(inode["ino"], keep - 1),
                                     tail)
        self._update(dino, name, size=size, mtime=time.time())

    def unlink(self, path: str) -> None:
        self._rw()
        dino, name = self._resolve_parent(path)
        gone = json.loads(self._call(dir_oid(dino), "unlink",
                                     {"name": name, "deny_dir": True}))
        self._unlinked_cleanup(gone, dino, name)

    def _unlinked_cleanup(self, gone: Dict, dino: int,
                          name: str) -> None:
        """After a dentry disappears: a remote detaches from its
        primary's back-pointer list; a primary with surviving remotes
        promotes one of them to hold the inode (the MDS migrates such
        inodes through the stray directory — here the promotion is
        direct); a sole primary purges its data."""
        if not gone:
            return
        if gone.get("type") == "remote":
            pd, pn = gone["primary"]
            try:
                self._update_links(pd, pn,
                                   remove_links=[[dino, name]])
            except FsError:
                pass                 # primary already gone
            return
        if gone.get("type") != "file":
            return
        # validate EVERY back-pointer up front (recorded-but-absent
        # entries from the documented crash window are pruned here)
        valid = []
        for ld, ln in gone.get("links", []):
            try:
                r = self._lookup(ld, ln)
            except FsError:
                continue
            if r.get("type") == "remote" and r.get("ino") == gone["ino"]:
                valid.append([ld, ln])
        while valid:
            (ld, ln), rest = valid[0], valid[1:]
            promoted = dict(gone, links=rest)
            try:
                # guarded: only replaces the dentry if it is STILL the
                # remote we validated — a concurrent unlink of that
                # name must not be resurrected by our promotion
                self._call(dir_oid(ld), "set_dentry",
                           {"name": ln, "inode": promoted,
                            "expect_remote_ino": gone["ino"]})
            except FsError as e:
                if e.result not in (-2, -125):
                    # ambiguous (timeout): the promotion may have
                    # applied — promoting another candidate or purging
                    # could double-promote or delete live data
                    raise
                valid = rest         # candidate vanished: try the next
                continue
            for od, on in rest:      # repoint surviving remotes
                try:
                    self._update(od, on, primary=[ld, ln])
                except FsError:
                    pass
            return
        self._purge_file(gone)

    def _purge_file(self, inode: Dict) -> None:
        """Delete the data objects of an unlinked file (the reference
        delegates this to the MDS PurgeQueue)."""
        if not inode or inode.get("type") != "file":
            return
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        for objno in range((inode["size"] + osize - 1) // osize):
            self.client.remove(self.dpool,
                               file_oid(inode["ino"], objno))

    def rename(self, src: str, dst: str) -> None:
        self._rw()
        """rename(2): atomic within one directory (single cls call);
        across directories it is dst-link + src-unlink — two atomic
        steps with a transient double-link window, never a loss."""
        sparts, dparts = self._split(src), self._split(dst)
        if sparts == dparts:
            self._resolve(src)               # still ENOENT if absent
            return                           # rename(p, p): no-op
        sdino, sname = self._resolve_parent(src)
        ddino, dname = self._resolve_parent(dst)
        moving = self._lookup(sdino, sname)
        try:
            existing_dst = self._lookup(ddino, dname)
        except FsError:
            existing_dst = None
        if existing_dst is not None and \
                existing_dst.get("ino") == moving.get("ino") and \
                moving.get("type") in ("file", "remote"):
            # rename between two names of the same file is a POSIX
            # no-op (both dentries survive) — proceeding would displace
            # the primary and purge the data
            return
        if moving["type"] == "dir" and \
                self._subtree_contains(moving["ino"], ddino):
            # moving a directory into its own subtree would detach the
            # whole subtree forever (POSIX: EINVAL).  Checked on
            # RESOLVED inodes, not path strings, so a symlink into the
            # source subtree cannot smuggle the cycle past the guard.
            raise FsError("rename", -22)
        if sdino == ddino:
            displaced = json.loads(self._call(
                dir_oid(sdino), "rename_local",
                {"src": sname, "dst": dname, "replace": True}))
            self._unlinked_cleanup(displaced, sdino, dname)
            self._fix_link_pointers(moving, [sdino, sname],
                                    [sdino, dname])
            return
        inode = moving
        try:
            self._call(dir_oid(ddino), "link",
                       {"name": dname, "inode": inode})
        except FsError as e:
            if e.result != -17:
                raise
            # deny_dir makes replacing a directory fail EISDIR at the
            # dentry itself — a subtree can never be silently destroyed
            displaced = json.loads(self._call(
                dir_oid(ddino), "unlink",
                {"name": dname, "deny_dir": True}))
            self._unlinked_cleanup(displaced, ddino, dname)
            self._call(dir_oid(ddino), "link",
                       {"name": dname, "inode": inode})
        # pointers first, THEN the src unlink: a crash in between
        # leaves a stale duplicate NAME at src (harmless, cleaned by a
        # later unlink) instead of dangling remotes whose primary is
        # gone — names are never lost
        self._fix_link_pointers(inode, [sdino, sname], [ddino, dname])
        self._call(dir_oid(sdino), "unlink", {"name": sname})

    def _fix_link_pointers(self, moved: Dict, old_loc, new_loc) -> None:
        """A moved remote must update its primary's back-pointer; a
        moved primary must repoint every remote at its new location."""
        if moved.get("type") == "remote":
            pd, pn = moved["primary"]
            try:
                self._update_links(pd, pn,
                                   replace_link=[old_loc, new_loc])
            except FsError:
                pass
        elif moved.get("type") == "file":
            for od, on in moved.get("links", []):
                try:
                    self._update(od, on, primary=new_loc)
                except FsError:
                    pass

    def _subtree_contains(self, root_ino: int, needle_ino: int,
                          depth: int = 0) -> bool:
        """Is ``needle_ino`` the root or any descendant directory of
        ``root_ino``?  (The MDS answers this from its cache; here it is
        a readdir walk over the moved subtree.)"""
        if root_ino == needle_ino:
            return True
        if depth > 64:
            return True          # fail closed on absurd nesting
        entries = json.loads(self._call(dir_oid(root_ino), "readdir"))
        return any(info["type"] == "dir" and
                   self._subtree_contains(info["ino"], needle_ino,
                                          depth + 1)
                   for info in entries.values())

    def exists(self, path: str) -> bool:
        try:
            self._resolve(path)
            return True
        except FsError as e:
            if e.result in (-2, -20):
                return False
            raise

    # ---- recursive conveniences (libcephfs ceph_walk-ish helpers) ---------
    def walk(self, path: str = "/"):
        """Yield (dirpath, dirnames, filenames) like os.walk."""
        entries = self.listdir(path)
        dirs = sorted(n for n, i in entries.items() if i["type"] == "dir")
        files = sorted(n for n, i in entries.items()
                       if i["type"] != "dir")
        yield path, dirs, files
        for d in dirs:
            sub = path.rstrip("/") + "/" + d
            yield from self.walk(sub)

    # ---- filesystem snapshots (the .snap surface, whole-fs scope;
    # the reference's SnapServer table + SnapRealm propagation is
    # collapsed to one snapshot domain) --------------------------------
    def _snap_table(self) -> Dict:
        import json as _json
        from .cls_fs import FS_SNAPS_OID
        try:
            return _json.loads(self._call(FS_SNAPS_OID, "snap_ls"))
        except FsError as e:
            if e.result == -2:
                return {}
            raise

    def _install_snapc(self) -> None:
        """Install the fs snapshot context on BOTH pools' write paths
        (the client-side SnapContext a cephfs client gets from its MDS
        caps).  Another client's newer snapshot is picked up on its
        next refresh — mount time, snap ops, or refresh_snaps()."""
        table = self._snap_table()
        md = sorted(e["md"] for e in table.values())
        dt = sorted(e["data"] for e in table.values())
        self.client.set_write_ctx(self.mdpool, md[-1] if md else 0, md)
        self.client.set_write_ctx(self.dpool, dt[-1] if dt else 0, dt)

    refresh_snaps = _install_snapc

    def snap_create(self, name: str) -> None:
        """Snapshot the whole filesystem under ``name`` (mkdir .snap/
        name): one selfmanaged snap id per pool, registered atomically
        in the snapshot table, then installed in the write ctx so every
        later mutation clones pre-write state."""
        self._rw()
        import time as _time
        from .cls_fs import FS_SNAPS_OID
        md_sid = self.client.selfmanaged_snap_create(self.mdpool)
        data_sid = self.client.selfmanaged_snap_create(self.dpool)
        try:
            self._call(FS_SNAPS_OID, "snap_add",
                       {"name": name, "md_sid": md_sid,
                        "data_sid": data_sid, "stamp": _time.time()})
        except FsError:
            self.client.selfmanaged_snap_remove(self.mdpool, md_sid)
            self.client.selfmanaged_snap_remove(self.dpool, data_sid)
            raise
        self._install_snapc()

    def snap_remove(self, name: str) -> None:
        self._rw()
        import json as _json
        from .cls_fs import FS_SNAPS_OID
        gone = _json.loads(self._call(FS_SNAPS_OID, "snap_rm",
                                      {"name": name}))
        self.client.selfmanaged_snap_remove(self.mdpool, gone["md"])
        self.client.selfmanaged_snap_remove(self.dpool, gone["data"])
        self._install_snapc()

    def snap_list(self) -> Dict[str, Dict]:
        return self._snap_table()

    def snapshot(self, name: str) -> "CephFS":
        """A read-only view of the filesystem as of ``name`` (cd
        .snap/name): same API, reads resolve against the snapshot's
        clones, mutations fail EROFS."""
        table = self._snap_table()
        if name not in table:
            raise FsError("snapshot", -2)
        view = CephFS.__new__(CephFS)
        view.client = self.client
        view.mdpool = self.mdpool
        view.dpool = self.dpool
        view._md_snap = table[name]["md"]
        view._data_snap = table[name]["data"]
        return view

    # ---- fsck (cephfs-data-scan / scrub_path role) ------------------------
    def fsck(self, repair: bool = False) -> Dict:
        """Consistency scan over the whole tree: dangling remotes
        (primary gone), stale back-pointers (remote gone), and orphan
        data objects in the data pool (no referencing inode).  With
        ``repair`` the findings are fixed: dangling remotes unlinked,
        stale back-pointers pruned, orphan objects deleted — the
        cephfs-data-scan + 'ceph tell mds scrub_path repair' roles.
        Run it quiesced: a file created between the tree walk and the
        data-pool sweep would be misread as orphaned, exactly like
        rgw gc's in-flight-put hazard.  Returns {dangling_remotes,
        stale_backpointers, orphan_objects, missing_dirs}; when any
        directory OBJECT is missing (a lost metadata PG) the orphan
        purge is withheld even under repair — those files' data is
        what a data-scan recovery would rebuild from, never garbage."""
        report = {"dangling_remotes": [], "stale_backpointers": [],
                  "orphan_objects": [], "missing_dirs": []}
        live_inos = set()
        # pass 1: walk every directory object via readdir
        stack = [(ROOT_INO, "/")]
        seen_dirs = set()
        while stack:
            dino, dpath = stack.pop()
            if dino in seen_dirs:
                continue
            seen_dirs.add(dino)
            try:
                entries = json.loads(self._call(dir_oid(dino),
                                                "readdir"))
            except FsError as e:
                if e.result != -2:
                    # transient failure (e.g. PG down): aborting beats
                    # mistaking a whole reachable subtree for garbage
                    raise
                report["missing_dirs"].append(dpath)
                continue
            for name, inode in entries.items():
                path = dpath.rstrip("/") + "/" + name
                t = inode.get("type")
                if t == "dir":
                    stack.append((inode["ino"], path))
                elif t == "file":
                    live_inos.add(inode["ino"])
                    for ld, ln in list(inode.get("links", [])):
                        try:
                            r = self._lookup(ld, ln)
                            ok = (r.get("type") == "remote"
                                  and r.get("ino") == inode["ino"])
                        except FsError as e:
                            if e.result == -116:
                                # the remote's DIR is lost: unknowable,
                                # never repaired away (recovery may
                                # rebuild it)
                                report["missing_dirs"].append(
                                    f"dir#{ld}")
                                continue
                            if e.result != -2:
                                raise
                            ok = False
                        if not ok:
                            report["stale_backpointers"].append(
                                [path, [ld, ln]])
                            if repair:
                                self._update_links(
                                    dino, name,
                                    remove_links=[[ld, ln]])
                elif t == "remote":
                    live_inos.add(inode.get("ino", -1))
                    try:
                        pd, pn = inode["primary"]
                        pr = self._lookup(pd, pn)
                        ok = pr.get("ino") == inode["ino"]
                    except FsError as e:
                        if e.result == -116:
                            # primary's DIR is lost: this remote is
                            # the surviving namespace reference a
                            # recovery would reattach — keep it
                            report["missing_dirs"].append(f"dir#{pd}")
                            continue
                        if e.result != -2:
                            raise
                        ok = False
                    if not ok:
                        report["dangling_remotes"].append(path)
                        if repair:
                            self._call(dir_oid(dino), "unlink",
                                       {"name": name})
        # pass 2: orphan data objects (ino not referenced anywhere).
        # A missing dir object means an unknown set of inos was
        # unreachable in pass 1 — deleting "orphans" then would purge
        # the very data a recovery would rebuild from, so repair is
        # withheld for this pass.
        purge_ok = repair and not report["missing_dirs"]
        for oid in self.client.list_objects(self.dpool):
            try:
                ino = int(oid.split(".")[0], 16)
            except ValueError:
                continue             # not a cephfs data object
            if ino not in live_inos:
                report["orphan_objects"].append(oid)
                if purge_ok:
                    self.client.remove(self.dpool, oid)
        return report
