"""cephfs-lite: POSIX-shaped filesystem over rados (src/mds +
src/client at lite scale).

Importing registers the ``fs`` object class; see ``cls_fs`` for the
storage layout (reference-identical dir/file object naming) and the
design note on collapsing the MDS serialization point into PG-atomic
class methods.
"""
from . import cls_fs  # noqa: F401  (registers the cls methods)
from .client import CephFS, FsError
from .cls_fs import FS_SNAPS_OID, ROOT_INO, dir_oid, file_oid

__all__ = ["CephFS", "FsError", "ROOT_INO", "dir_oid", "file_oid"]
