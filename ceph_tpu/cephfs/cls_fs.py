"""cls_fs: server-side filesystem-metadata methods.

The reference cephfs keeps directories as rados objects in a metadata
pool — a CDir's dentries live in the omap of object ``<ino>.<frag>``
(mds/CDir.cc:1595 get_ondisk_object -> include/object.h:100
``%llx.%08llx``), each primary dentry embedding its inode (CDentry/
CInode encode into the dentry value), and allocates inode numbers from
a replicated InoTable (mds/InoTable.h).  The MDS daemon serializes
metadata mutations in front of that layout.

This lite design keeps the exact on-disk shape but moves the
serialization point INTO the OSD: every dentry/ino mutation is an
object-class method running atomically inside the op transaction on
the directory object's PG — two racing creates of the same name are
ordered by the PG, not by an MDS journal.  What the MDS daemon adds
beyond that — client capabilities/leases, a metadata journal with
replay, multi-MDS subtree balancing — is out of scope and documented
as such in ``ceph_tpu.cephfs``.

Dentry values are JSON inodes: {ino, type(dir|file|symlink), size,
mtime, order, target?}.
"""
from __future__ import annotations

import json
from typing import Dict

from ..osd.cls import (
    CLS_METHOD_RD, CLS_METHOD_WR, ClsContext, register_cls_method,
)

ROOT_INO = 1                      # CEPH_INO_ROOT, include/ceph_fs.h:29
INOTABLE_OID = "mds_inotable"     # InoTable object (mds/InoTable.h)


def dir_oid(ino: int, frag: int = 0) -> str:
    """CDir on-disk object name (include/object.h:100)."""
    return f"{ino:x}.{frag:08x}"


def file_oid(ino: int, objno: int) -> str:
    """File-data object name in the data pool (same %llx.%08llx)."""
    return f"{ino:x}.{objno:08x}"


def _j(obj) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def _parse(inp: bytes) -> Dict:
    try:
        return json.loads(inp.decode()) if inp else {}
    except ValueError:
        return {}


@register_cls_method("fs", "mkfs", CLS_METHOD_WR)
def _mkfs(ctx: ClsContext, inp: bytes):
    """Initialize the InoTable: next allocatable ino (root is pinned
    at ROOT_INO and never allocated)."""
    if ctx.exists and ctx.omap_get():
        return -17, b""                               # EEXIST
    ctx.omap_set({"next_ino": str(ROOT_INO + 1)})
    return 0, b""


@register_cls_method("fs", "alloc_ino", CLS_METHOD_WR)
def _alloc_ino(ctx: ClsContext, inp: bytes):
    """Atomically allocate the next inode number (InoTable::
    project_alloc_id)."""
    om = ctx.omap_get()
    if "next_ino" not in om:
        return -2, b""
    ino = int(om["next_ino"])
    ctx.omap_set({"next_ino": str(ino + 1)})
    return 0, _j({"ino": ino})


@register_cls_method("fs", "link", CLS_METHOD_WR)
def _link(ctx: ClsContext, inp: bytes):
    """Insert a dentry (name -> embedded inode) into this directory
    object: -EEXIST if the name is taken.  The atomicity of this check
    replaces the MDS's dentry lock.  A directory marked dead by
    dir_mark_dead refuses new dentries (-ENOENT) so rmdir cannot race
    a create."""
    if not ctx.exists:
        # directory objects are created eagerly, so a missing object
        # means rmdir already deleted it (after sealing).  A WR cls
        # method would implicitly recreate the object here — a
        # resurrected directory holding an orphaned dentry that no
        # root walk (fsck) can ever reach.  The seal must keep holding
        # after the object is gone.
        return -2, b""
    req = _parse(inp)
    name = str(req["name"])
    key = f"dn_{name}"
    om = ctx.omap_get()
    if "_dead" in om:
        return -2, b""
    if key in om:
        return -17, b""
    ctx.omap_set({key: _j(req["inode"])})
    return 0, b""


@register_cls_method("fs", "unlink", CLS_METHOD_WR)
def _unlink(ctx: ClsContext, inp: bytes):
    """Remove a dentry.  With ``deny_dir`` a directory dentry is
    refused (-EISDIR) — the unlink(2) contract, enforced where the
    dentry actually lives so no client-side stat can go stale."""
    if not ctx.exists:
        return -2, b""          # deleted dir: don't resurrect (see link)
    req = _parse(inp)
    key = f"dn_{req['name']}"
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    if req.get("deny_dir") and json.loads(om[key]).get("type") == "dir":
        return -21, b""                               # EISDIR
    ctx.omap_rm_keys([key])
    return 0, bytes(om[key])      # the unlinked inode, for cleanup


@register_cls_method("fs", "lookup")
def _lookup(ctx: ClsContext, inp: bytes):
    if not ctx.exists:
        # the directory OBJECT itself is gone (lost metadata PG) —
        # report ESTALE, not "no such dentry": callers like fsck must
        # distinguish a deleted name from an unknowable directory
        return -116, b""
    req = _parse(inp)
    v = ctx.omap_get().get(f"dn_{req['name']}")
    if v is None:
        return -2, b""
    return 0, bytes(v)


@register_cls_method("fs", "readdir")
def _readdir(ctx: ClsContext, inp: bytes):
    if not ctx.exists:
        # a LOST dir object must read as ENOENT, not as an empty
        # directory — fsck distinguishes "empty" from "unknowable"
        return -2, b""
    out = {k[3:]: json.loads(v) for k, v in ctx.omap_get().items()
           if k.startswith("dn_")}
    return 0, _j(out)


@register_cls_method("fs", "dir_empty")
def _dir_empty(ctx: ClsContext, inp: bytes):
    empty = not any(k.startswith("dn_") for k in ctx.omap_get())
    return 0, _j({"empty": empty})


@register_cls_method("fs", "dir_mark_dead", CLS_METHOD_WR)
def _dir_mark_dead(ctx: ClsContext, inp: bytes):
    """Atomically check-empty-and-seal this directory object: after it
    succeeds, link() refuses new dentries, so the rmdir sequence
    (seal child -> unlink parent dentry -> delete object) cannot lose a
    concurrently created entry (the MDS holds a dirlock for this)."""
    if not ctx.exists:
        return -2, b""          # deleted dir: don't resurrect (see link)
    if any(k.startswith("dn_") for k in ctx.omap_get()):
        return -39, b""                               # ENOTEMPTY
    ctx.omap_set({"_dead": "1"})
    return 0, b""


@register_cls_method("fs", "update_inode", CLS_METHOD_WR)
def _update_inode(ctx: ClsContext, inp: bytes):
    """Merge attribute updates (size/mtime/...) into the inode embedded
    in a dentry — the wrstat path (MDS Locker file_update_finish)."""
    req = _parse(inp)
    key = f"dn_{req['name']}"
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    inode = json.loads(om[key])
    inode.update(req.get("attrs", {}))
    # monotonic attributes (size growth from concurrent writers) max
    # against the stored value HERE, so no client read-modify-write
    # window can shrink a committed size
    for k, v in req.get("max_attrs", {}).items():
        inode[k] = max(inode.get(k, 0), v)
    # back-pointer list mutations merge HERE for the same reason: two
    # concurrent hardlink()s must both land their entries
    if req.get("add_links") or req.get("remove_links") \
            or req.get("replace_link"):
        links = list(inode.get("links", []))
        for l in req.get("add_links", []):
            if l not in links:
                links.append(l)
        links = [l for l in links
                 if l not in req.get("remove_links", [])]
        rep = req.get("replace_link")
        if rep:
            links = [rep[1] if l == rep[0] else l for l in links]
        inode["links"] = links
    ctx.omap_set({key: _j(inode)})
    return 0, _j(inode)


FS_SNAPS_OID = "fs_snaps"         # snapshot table (SnapServer role)


@register_cls_method("fs", "snap_add", CLS_METHOD_WR)
def _snap_add(ctx: ClsContext, inp: bytes):
    """Register a filesystem snapshot name -> (md_sid, data_sid)
    atomically (-EEXIST on collision) — the SnapServer's table."""
    req = _parse(inp)
    key = f"snap_{req['name']}"
    if key in ctx.omap_get():
        return -17, b""
    ctx.omap_set({key: _j({"md": int(req["md_sid"]),
                           "data": int(req["data_sid"]),
                           "stamp": float(req.get("stamp", 0))})})
    return 0, b""


@register_cls_method("fs", "snap_rm", CLS_METHOD_WR)
def _snap_rm(ctx: ClsContext, inp: bytes):
    req = _parse(inp)
    key = f"snap_{req['name']}"
    om = ctx.omap_get()
    if key not in om:
        return -2, b""
    ctx.omap_rm_keys([key])
    return 0, bytes(om[key])


@register_cls_method("fs", "snap_ls")
def _snap_ls(ctx: ClsContext, inp: bytes):
    return 0, _j({k[len("snap_"):]: json.loads(v)
                  for k, v in ctx.omap_get().items()
                  if k.startswith("snap_")})


@register_cls_method("fs", "set_dentry", CLS_METHOD_WR)
def _set_dentry(ctx: ClsContext, inp: bytes):
    """Atomically overwrite (or install) a dentry's value — the
    hard-link promotion/repoint primitive: replacing a remote dentry
    with an embedded inode must never pass through a missing-dentry
    window the way unlink+link would."""
    if not ctx.exists:
        return -2, b""          # deleted dir: don't resurrect (see link)
    req = _parse(inp)
    om = ctx.omap_get()
    if "_dead" in om:
        return -2, b""
    key = f"dn_{req['name']}"
    if "expect_remote_ino" in req:
        cur = om.get(key)
        if cur is None:
            return -2, b""
        parsed = json.loads(cur)
        if parsed.get("type") != "remote" or \
                parsed.get("ino") != req["expect_remote_ino"]:
            return -125, b""                          # ECANCELED
    ctx.omap_set({key: _j(req["inode"])})
    return 0, b""


@register_cls_method("fs", "rename_local", CLS_METHOD_WR)
def _rename_local(ctx: ClsContext, inp: bytes):
    """Same-directory rename, fully atomic on the dir object's PG.
    Overwrites dst only when ``replace`` (rename(2) semantics with the
    client checking dst type compatibility first)."""
    req = _parse(inp)
    src, dst = f"dn_{req['src']}", f"dn_{req['dst']}"
    om = ctx.omap_get()
    if src not in om:
        return -2, b""
    if src == dst:
        return 0, b"null"     # rename(p, p) is a no-op, rename(2)
    if dst in om and not req.get("replace"):
        return -17, b""
    if dst in om and json.loads(om[dst]).get("type") == "dir":
        return -21, b""   # EISDIR: never silently destroy a subtree
    displaced = om.get(dst, b"null")
    ctx.omap_set({dst: bytes(om[src])})
    ctx.omap_rm_keys([src])
    return 0, bytes(displaced)    # displaced inode, for cleanup
