"""RemoteCephFS — the MDS-mediated cephfs client (libcephfs + Client.cc
shape at lite scale).

Metadata operations cross the wire to the MDS (MClientRequest /
MClientReply, mds/server.py); FILE DATA goes straight to the OSDs with
the layout and SnapContext the MDS handed out at open — the cephfs
split exactly (src/client/Client.cc: metadata via MDS sessions, data
via the Objecter).

Capabilities: ``open(path, "w")`` asks for CEPH_CAP_FILE_BUFFER; while
held, FileHandle.write() buffers locally (write-back).  When another
client's open conflicts, the MDS revokes (MClientCaps) — the dispatcher
flushes the buffer to the data pool and acks with the wrstat payload,
exactly the Locker round the reference drives.  Snapshot reads resolve
directly against immutable clones (like data reads, they never need the
MDS's serialization)."""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from ..client.rados import RadosClient
from ..msg.messages import (
    CEPH_CAP_FILE_BUFFER, CEPH_CAP_FILE_CACHE, MClientCaps,
    MClientReply, MClientRequest, Message,
)
from .client import CephFS, FsError, _absent
from .cls_fs import ROOT_INO, dir_oid, file_oid

# the wait must outlast the MDS session_timeout (20 s): a request
# parked behind a DEAD cap holder only unblocks once the MDS evicts
# the holder.  In-process (drive set) iterations are fast; across
# processes each late iteration sleeps 0.25 s -> ~30 s worst case.
MAX_ATTEMPTS = 120
DEFAULT_ORDER = 22


class FileHandle:
    """An open file under caps: write-back buffer when BUFFER is held
    (ObjectCacher role, one-file scale)."""

    def __init__(self, fs: "RemoteCephFS", path: str, inode: Dict,
                 caps: int, snapc: Tuple[int, List[int]],
                 mds: str = "", quotas: Optional[List[Dict]] = None):
        self.fs = fs
        self.path = path
        self.inode = inode
        self.caps = caps
        self.snapc = snapc
        self.mds = mds           # the rank daemon that issued the caps
        self.buffer: List[Tuple[int, bytes]] = []
        self.size = inode["size"]
        # the quota realm chain from the open reply (the client-side
        # cache the reference keeps as in->quota/rstat): byte quotas
        # are enforced HERE, on the data path, before bytes move
        self.quotas = list(quotas or [])
        self._max_end = self.size

    def _check_byte_quota(self, end: int) -> None:
        """EDQUOT when this write's growth would push any ancestor
        realm past max_bytes (Client.cc:9137-9141
        is_quota_bytes_exceeded with the cached realm usage)."""
        growth = end - self._max_end
        if growth <= 0:
            return
        for q in self.quotas:
            if q.get("max_bytes") and \
                    q["used_bytes"] + growth > q["max_bytes"]:
                raise FsError("write", -122)         # EDQUOT

    # -- io ------------------------------------------------------------
    def write(self, data: bytes, offset: Optional[int] = None) -> int:
        off = self.size if offset is None else offset
        end = off + len(data)
        self._check_byte_quota(end)
        if end > self._max_end:
            for q in self.quotas:
                q["used_bytes"] = q.get("used_bytes", 0) + \
                    (end - self._max_end)
            self._max_end = end
        if self.caps & CEPH_CAP_FILE_BUFFER:
            self.buffer.append((off, bytes(data)))
            self.size = max(self.size, off + len(data))
            return len(data)
        self.fs._write_through(self.path, self.inode, data, off,
                               self.snapc)
        self.size = max(self.size, off + len(data))
        return len(data)

    def read(self, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        base = self.fs._read_data(self.inode, offset, length, self.size)
        if not self.buffer:
            return base
        # overlay buffered extents (our own dirty data is visible to us)
        end = offset + len(base)
        buf = bytearray(base)
        for boff, bdata in self.buffer:
            lo = max(offset, boff)
            hi = min(end, boff + len(bdata))
            if lo < hi:
                buf[lo - offset:hi - offset] = \
                    bdata[lo - boff:hi - boff]
        return bytes(buf)

    def flush(self) -> None:
        """Write back buffered extents + wrstat SYNCHRONOUSLY (the
        voluntary fsync path; revoke-driven flushes instead ride the
        MClientCaps round in RemoteCephFS.process)."""
        if self.buffer:
            for off, data in self.buffer:
                self.fs._write_data(self.inode, data, off, self.snapc)
            self.buffer = []
        self.fs._request("wrstat", path=self.path, size=self.size,
                         mtime=time.time())

    def close(self) -> None:
        self.flush()
        # release is ino-addressed (no path to route by): it must go
        # to the RANK that issued the caps, not the default target
        self.fs._request("release", ino=self.inode["ino"],
                         _target=self.mds)
        self.fs._handles.pop(self.inode["ino"], None)


class RemoteCephFS:
    """Client-side mount over an MDS session."""

    def __init__(self, client: RadosClient,
                 mds_name: Optional[str] = "mds.0",
                 metadata_pool: str = "fsmeta",
                 data_pool: str = "fsdata", drive=None):
        self.client = client
        # any falsy mds_name means "resolve the active from the fsmap"
        self._auto = not mds_name
        self.mds = mds_name or ""
        self.mdpool = metadata_pool
        self.dpool = data_pool
        # random tid base: reqids must be unique ACROSS MOUNTS of the
        # same client name, or a remount's early tids would collide
        # with a previous incarnation's completed reqids in the MDS
        # journal and be silently skipped as failover duplicates
        import secrets as _secrets
        self._tid = _secrets.randbits(40) << 8
        self._replies: Dict[int, MClientReply] = {}
        self._handles: Dict[int, FileHandle] = {}
        # multi-active routing: rank -> daemon name (from the fsmap
        # or forward replies) and learned per-directory auth hints —
        # misses self-correct via MDS_FORWARD replies
        self._ranks: Dict[int, str] = {}
        self._auth_hint: Dict[str, str] = {}
        # revokes arrive inside a network pump, where the flush's own
        # rados round trips cannot run (nested pumps no-op); they are
        # queued and drained by process() — from our request loops, or
        # the in-process scheduler
        self._pending_revokes: List[MClientCaps] = []
        # cooperative scheduler hook: in-process harnesses pass a
        # callable that runs the MDS (and peers) so a blocked request
        # can make progress; separate-process setups leave it None
        self._drive = drive
        # interpose on the rados client's dispatcher slot: MDS traffic
        # is consumed here, everything else forwards to the client
        # (the messenger holds ONE dispatcher, not a chain)
        self._inner = client
        client.messenger.add_dispatcher_head(self)

    # ---- wire --------------------------------------------------------------
    def ms_fast_dispatch(self, msg: Message) -> None:
        if isinstance(msg, MClientReply):
            self._replies[msg.tid] = msg
            return
        if isinstance(msg, MClientCaps):
            if msg.op == MClientCaps.OP_REVOKE:
                self._pending_revokes.append(msg)
            return
        self._inner.ms_fast_dispatch(msg)

    def ms_dispatch(self, msg: Message) -> None:  # pragma: no cover
        self.ms_fast_dispatch(msg)

    def process(self) -> None:
        """Service pending cap revokes: write back buffered data, then
        ack with the wrstat payload (the Locker flush round).  The
        flush answers the RANK that sent the revoke (msg.src), which
        under multi-active need not be our default target."""
        while self._pending_revokes:
            msg = self._pending_revokes.pop(0)
            revoker = getattr(msg, "src", "") or self.mds
            fh = self._handles.pop(msg.ino, None)
            if fh is not None:
                had_buffer = bool(fh.buffer)
                if fh.buffer:
                    for off, data in fh.buffer:
                        self._write_data(fh.inode, data, off, fh.snapc)
                    fh.buffer = []
                fh.caps = 0
                if had_buffer:
                    # durability first: the wrstat as a REQUEST reaches
                    # whoever is active (it re-resolves across a
                    # failover); clean read handles skip it — nothing
                    # to write back, and a stale size must not be
                    # journaled
                    try:
                        self._request("wrstat", path=fh.path,
                                      size=fh.size, mtime=time.time())
                    except FsError:
                        pass
                self._send_flush(fh, to=revoker)
            else:
                self.client.messenger.send_message(MClientCaps(
                    op=MClientCaps.OP_FLUSH, ino=msg.ino,
                    seq=msg.seq), revoker)

    def _send_flush(self, fh: FileHandle, to: str = "") -> None:
        self.client.messenger.send_message(MClientCaps(
            op=MClientCaps.OP_FLUSH, ino=fh.inode["ino"],
            data={"path": fh.path, "size": fh.size,
                  "mtime": time.time()}), to or fh.mds or self.mds)

    def _resolve_mds(self, timeout: float = 60.0) -> str:
        """The ACTIVE mds from the mon's replicated fsmap ('ceph mds
        stat'): how a client finds — and, after a failover, re-finds —
        its metadata server."""
        import time as _time
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            try:
                st = self.client.mon_command("fs_status")
                if st and st.get("active"):
                    return st["active"][0]
            except (IOError, ValueError):
                pass
            self.client.network.pump()
            _time.sleep(0.3)
        raise FsError("resolve_mds", -110)

    def _hint_key(self, op: str, args: Dict) -> Optional[str]:
        path = args.get("src" if op == "rename" else
                        "existing" if op == "hardlink" else "path")
        if not isinstance(path, str):
            return None
        parts = [p for p in path.split("/") if p]
        return "/" + "/".join(parts[:-1]) if len(parts) > 1 else "/"

    def _resolve_rank(self, rank: int, timeout: float = 60.0) -> str:
        """rank -> daemon name from the fsmap (waits through a
        failover window)."""
        import time as _time
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            try:
                st = self.client.mon_command("fs_status")
                name = (st or {}).get("ranks", {}).get(str(rank))
                if name:
                    return name
            except (IOError, ValueError):
                pass
            self.client.network.pump()
            _time.sleep(0.3)
        raise FsError("resolve_rank", -110)

    def _request(self, op: str, _refind: bool = True,
                 _reqid: str = "", _target: str = "",
                 _hops: int = 0, _rank: Optional[int] = None,
                 _trace: Optional[tuple] = None,
                 **args):
        if self._auto and not self.mds:
            self.mds = self._resolve_mds()
        self.process()          # our own pending flushes go first
        hint_key = self._hint_key(op, args)
        target = _target or \
            (self._auth_hint.get(hint_key, self.mds)
             if hint_key is not None else self.mds)
        if _rank is None:
            # remember which RANK we are talking to: a failover retry
            # must go back to the same rank (whose new holder replayed
            # that rank's journal and can dedup our reqid), not to
            # whatever rank the path's auth is after a repin
            _rank = next((r for r, n in self._ranks.items()
                          if n == target), 0)
        self._tid += 1
        tid = self._tid
        # the reqid survives a failover retry with its ORIGINAL tid, so
        # a promoted standby that replayed the dead active's journal
        # can recognize an already-applied mutation
        reqid = _reqid or f"{self.client.name}#{tid}"
        from ..msg.messages import new_trace_id
        from ..trace import g_tracer
        # ONE trace per logical request: forward/failover retries reuse
        # the root's (trace_id, span_id) so the hops stitch into one
        # tree, mirroring rados.py's retry contract
        span = None
        if _trace is None:
            span = g_tracer.begin(f"fs_request:{op}",
                                  daemon=self.client.name,
                                  trace_id=new_trace_id())
            _trace = (span.trace_id, span.span_id) \
                if span is not None else (0, 0)
        self.client.messenger.send_message(MClientRequest(
            tid=tid, op=op, args=args, reqid=reqid,
            trace_id=_trace[0], parent_span_id=_trace[1]), target)
        try:
            return self._await_reply(op, args, tid, reqid, target,
                                     hint_key, _refind, _hops, _rank,
                                     _trace)
        finally:
            g_tracer.finish(span)

    def _await_reply(self, op, args, tid, reqid, target, hint_key,
                     _refind, _hops, _rank, _trace):
        import time as _time
        for attempt in range(MAX_ATTEMPTS):
            self.client.network.pump()
            self.process()
            if self._drive is not None:
                self._drive()
                self.client.network.pump()
            rep = self._replies.pop(tid, None)
            if rep is not None:
                from ..mds.server import MDS_FORWARD
                if rep.result == MDS_FORWARD:
                    # not that rank's subtree: chase the auth rank
                    # with the SAME reqid (lite MClientRequestForward)
                    if _hops >= 4:
                        raise FsError(op, -40)       # ELOOP
                    rank = int(rep.data.get("forward_rank", 0))
                    self._ranks.update(
                        {rank: rep.data["mds"]}
                        if rep.data.get("mds") else {})
                    nxt = self._ranks.get(rank) or \
                        self._resolve_rank(rank)
                    if hint_key is not None:
                        self._auth_hint[hint_key] = nxt
                    return self._request(op, _refind=_refind,
                                         _reqid=reqid, _target=nxt,
                                         _hops=_hops + 1, _rank=rank,
                                         _trace=_trace, **args)
                if rep.result < 0:
                    raise FsError(op, rep.result)
                self._last_mds = target
                return rep.data
            if self._drive is None and attempt > 2:
                _time.sleep(0.25)   # cross-process: let the mds run
        if self._auto and _refind:
            # the target may have failed over: re-resolve and retry
            # once, carrying the SAME reqid TO THE SAME RANK — its
            # new holder replayed that rank's journal, so an op the
            # dead incumbent already journaled is answered from
            # effect, not re-executed (even if the subtree was
            # repinned in between).  Learned hints are dropped — the
            # fsmap may have reshuffled every rank.
            self._auth_hint.clear()
            self._ranks.clear()
            self.mds = self._resolve_mds()
            try:
                nxt = self._resolve_rank(_rank) if _rank else ""
            except FsError:
                nxt = ""
            return self._request(op, _refind=False, _reqid=reqid,
                                 _target=nxt, _rank=_rank,
                                 _trace=_trace, **args)
        raise FsError(op, -110)                       # ETIMEDOUT

    def _ino_of(self, op: str, rep: Dict, path: str) -> int:
        """Extract the ino from an ino-returning op's reply.

        A dedup'd duplicate answered "from effect" can arrive as
        {"replayed": true} WITHOUT an ino when the server's re-resolve
        raced a subtree repin (mds/server.py _replayed_reply).  The
        effect exists — recover the id with a read op: stat follows
        the final component, which is identity for the dir/file the
        mutation created (symlinks use the nofollow flavor so the
        link's own ino comes back, not its target's).  A retried
        mutation could NOT recover (its fresh reqid misses the dedup
        memo and the server answers EEXIST forever); a stat that races
        the repin raises a retryable FsError and callers' retry loops
        converge."""
        ino = rep.get("ino")
        if ino is not None:
            return ino
        if rep.get("replayed"):
            nofollow = op == "symlink"
            return self._request("stat", path=path,
                                 nofollow=nofollow)["inode"]["ino"]
        raise FsError(f"{op} (replayed, ino unresolved)", -11)

    # ---- metadata surface (all via the MDS) --------------------------------
    def mkdir(self, path: str) -> int:
        return self._ino_of("mkdir", self._request("mkdir", path=path),
                            path)

    def create(self, path: str, order: Optional[int] = None) -> int:
        # order None lets the MDS apply the inherited dir layout
        # (an explicit order overrides it, like a file vxattr would)
        return self._ino_of("create",
                            self._request("create", path=path,
                                          order=order), path)

    def symlink(self, path: str, target: str) -> int:
        return self._ino_of("symlink",
                            self._request("symlink", path=path,
                                          target=target), path)

    def readlink(self, path: str) -> str:
        return self._request("readlink", path=path)["target"]

    def hardlink(self, existing: str, newpath: str) -> None:
        self._request("hardlink", existing=existing, newpath=newpath)

    def unlink(self, path: str) -> None:
        self._request("unlink", path=path)

    def rmdir(self, path: str) -> None:
        self._request("rmdir", path=path)

    def rename(self, src: str, dst: str) -> None:
        self._request("rename", src=src, dst=dst)

    def setattr(self, path: str, **attrs) -> None:
        self._request("setattr", path=path, **attrs)

    def chmod(self, path: str, mode: int) -> None:
        self.setattr(path, mode=mode)

    def stat(self, path: str) -> Dict:
        return self._request("stat", path=path)["inode"]

    def listdir(self, path: str) -> Dict[str, Dict]:
        return self._request("listdir", path=path)["entries"]

    def exists(self, path: str) -> bool:
        return self._request("exists", path=path)["exists"]

    def truncate(self, path: str, size: int) -> None:
        self._request("truncate", path=path, size=size)

    def set_quota(self, path: str, max_bytes: int = 0,
                  max_files: int = 0) -> Dict:
        """setfattr ceph.quota.max_bytes/max_files on a directory
        (0 clears); enforced against the ancestor realm chain."""
        return self._request("set_quota", path=path,
                             max_bytes=max_bytes,
                             max_files=max_files)

    def get_quota(self, path: str) -> List[Dict]:
        """The quota realm chain covering *path*, with usage."""
        return self._request("get_quota", path=path)["quotas"]

    def set_layout(self, path: str, order: Optional[int] = None,
                   pool: Optional[str] = None) -> Dict:
        """setfattr ceph.dir.layout.* / ceph.file.layout.*: object
        size (order) and data pool.  Dir layouts are inherited by new
        files; a file's layout is only settable while empty."""
        return self._request("set_layout", path=path, order=order,
                             pool=pool)

    def get_layout(self, path: str) -> Dict:
        """The effective layout of a file or dir (getfattr
        ceph.file.layout)."""
        inode = self._request("stat", path=path)["inode"]
        if inode.get("type") == "dir":
            return dict(inode.get("layout") or {})
        return {"order": inode.get("order", DEFAULT_ORDER),
                "pool": inode.get("pool")}

    def set_dir_pin(self, path: str, rank: int) -> Dict:
        """Pin *path*'s subtree to an MDS rank (setfattr -n
        ceph.dir.pin): the journaled subtree handoff.  Served by the
        CURRENT auth rank, which drains caps under the subtree before
        the pin commits."""
        return self._request("set_dir_pin", path=path, rank=rank)

    # ---- caps + file io ----------------------------------------------------
    def open(self, path: str, mode: str = "r") -> FileHandle:
        """'r' wants CACHE, 'w' wants BUFFER (+creates).  The MDS
        serializes conflicting opens by revoking first — this call
        blocks (retrying) until the caps are granted."""
        want = CEPH_CAP_FILE_BUFFER if "w" in mode else \
            CEPH_CAP_FILE_CACHE
        out = self._request("open", path=path, want=want,
                            create="w" in mode)
        fh = FileHandle(self, path, out["inode"], out["caps"],
                        (out["snapc_seq"], out["snapc_snaps"]),
                        mds=getattr(self, "_last_mds", "") or self.mds,
                        quotas=out.get("quotas"))
        self._handles[out["inode"]["ino"]] = fh
        return fh

    def write(self, path: str, data: bytes, offset: int = 0) -> int:
        """Write-through convenience: open-for-write (serializing with
        any buffered writer elsewhere), write the data objects, then
        wrstat through the MDS."""
        fh = self.open(path, "w")
        try:
            fh._check_byte_quota(offset + len(data))
            self._write_data(fh.inode, data, offset, fh.snapc)
            fh.size = max(fh.size, offset + len(data))
            fh.close()
        except BaseException:
            # EDQUOT (or any data-path error) must not strand the
            # caps the open just took
            try:
                fh.close()
            except Exception:
                pass
            raise
        finally:
            self._handles.pop(fh.inode["ino"], None)
        return len(data)

    def read(self, path: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        """Read-through: opening for read first forces any conflicting
        buffered writer to flush (the caps round)."""
        fh = self.open(path, "r")
        try:
            inode = self._request("stat", path=path)["inode"]
            return self._read_data(inode, offset, length,
                                   inode["size"])
        finally:
            self._request("release", ino=fh.inode["ino"],
                          _target=fh.mds)
            self._handles.pop(fh.inode["ino"], None)

    # ---- data plumbing (direct to OSDs) ------------------------------------
    def _write_data(self, inode: Dict, data: bytes, offset: int,
                    snapc: Tuple[int, List[int]]) -> None:
        """Object writes with the file's realm SnapContext installed
        (per-file snapc is what makes per-directory snapshots work).
        The file's LAYOUT pool (ceph.file.layout.pool, fixed at
        create) overrides the mount's default data pool."""
        pool = inode.get("pool") or self.dpool
        seq, snaps = snapc
        self.client.set_write_ctx(pool, seq, snaps)
        try:
            osize = 1 << inode.get("order", DEFAULT_ORDER)
            pos = 0
            while pos < len(data):
                objno, ooff = divmod(offset + pos, osize)
                take = min(len(data) - pos, osize - ooff)
                r = self.client.write(pool,
                                      file_oid(inode["ino"], objno),
                                      data[pos:pos + take], ooff)
                if r < 0:
                    raise FsError("write", r)
                pos += take
        finally:
            self.client.set_write_ctx(pool, 0, [])

    def _write_through(self, path: str, inode: Dict, data: bytes,
                       offset: int,
                       snapc: Tuple[int, List[int]]) -> None:
        self._write_data(inode, data, offset, snapc)
        self._request("wrstat", path=path, size=offset + len(data),
                      mtime=time.time())

    def _read_data(self, inode: Dict, offset: int,
                   length: Optional[int], logical_size: int,
                   snap: Optional[int] = None) -> bytes:
        if offset >= logical_size:
            return b""
        pool = inode.get("pool") or self.dpool
        length = logical_size - offset if length is None else \
            min(length, logical_size - offset)
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        chunks = []
        remaining, pos = length, offset
        while remaining > 0:
            objno, ooff = divmod(pos, osize)
            take = min(remaining, osize - ooff)
            try:
                data = self.client.read(pool,
                                        file_oid(inode["ino"], objno),
                                        offset=ooff, length=take,
                                        snap=snap)
            except IOError as e:
                if not _absent(e):
                    raise
                data = b""
            chunks.append(data.ljust(take, b"\x00"))
            pos += take
            remaining -= take
        return b"".join(chunks)

    # ---- per-directory snapshots (SnapRealm surface) -----------------------
    def snap_create(self, path: str, name: str) -> Dict:
        """mkdir <path>/.snap/<name>: snapshot ONLY that subtree."""
        return self._request("snap_create", path=path, name=name,
                             stamp=time.time())

    def snap_remove(self, path: str, name: str) -> Dict:
        return self._request("snap_remove", path=path, name=name)

    def snap_list(self, path: str) -> Dict[str, Dict]:
        return self._request("lssnap", path=path)["snaps"]

    def snapshot(self, path: str, name: str) -> "SubtreeSnapView":
        out = self._request("lssnap", path=path)
        snaps = out["snaps"]
        if name not in snaps:
            raise FsError("snapshot", -2)
        return SubtreeSnapView(self.client, self.mdpool, self.dpool,
                               out["ino"], snaps[name]["md"],
                               snaps[name]["data"])


class SubtreeSnapView:
    """Read-only view of one realm's subtree as of a snapshot (cd
    <dir>/.snap/<name>): metadata resolves at the md snap, file data
    at the data snap — all against immutable clones, no MDS needed."""

    def __init__(self, client: RadosClient, mdpool: str, dpool: str,
                 root_ino: int, md_snap: int, data_snap: int):
        self._fs = CephFS.__new__(CephFS)
        self._fs.client = client
        self._fs.mdpool = mdpool
        self._fs.dpool = dpool
        self._fs._md_snap = md_snap
        self._fs._data_snap = data_snap
        self.root_ino = root_ino

    def _resolve(self, path: str) -> Dict:
        inode = {"ino": self.root_ino, "type": "dir", "size": 0}
        for name in CephFS._split(path):
            if inode["type"] != "dir":
                raise FsError("resolve", -20)
            inode = self._fs._lookup(inode["ino"], name)
            if inode.get("type") == "remote":
                _, _, inode = self._fs._primary_of(0, "", inode)
        return inode

    def listdir(self, path: str = "/") -> Dict[str, Dict]:
        inode = self._resolve(path)
        if inode["type"] != "dir":
            raise FsError("listdir", -20)
        return json.loads(self._fs._call(dir_oid(inode["ino"]),
                                         "readdir"))

    def stat(self, path: str) -> Dict:
        return self._resolve(path)

    def exists(self, path: str) -> bool:
        try:
            self._resolve(path)
            return True
        except FsError:
            return False

    def read(self, path: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        inode = self._resolve(path)
        if inode["type"] != "file":
            raise FsError("read", -21)
        size = inode["size"]
        if offset >= size:
            return b""
        length = size - offset if length is None else \
            min(length, size - offset)
        osize = 1 << inode.get("order", DEFAULT_ORDER)
        chunks = []
        remaining, pos = length, offset
        while remaining > 0:
            objno, ooff = divmod(pos, osize)
            take = min(remaining, osize - ooff)
            try:
                data = self._fs.client.read(
                    self._fs.dpool, file_oid(inode["ino"], objno),
                    offset=ooff, length=take,
                    snap=self._fs._data_snap)
            except IOError as e:
                if not _absent(e):
                    raise
                data = b""
            chunks.append(data.ljust(take, b"\x00"))
            pos += take
            remaining -= take
        return b"".join(chunks)
