"""Per-codec-signature circuit breakers — degrade to the CPU twin.

The one luxury this reproduction has over generic serving stacks: every
device codec has a byte-identical CPU reference path (the isa/jerasure
matrix semantics the device path was built to match, proven by the
parity suites).  So "degraded" here costs throughput, never
correctness — in the spirit of straggler-tolerant coded computation
(arxiv 1804.10331), where work lost to a slow/broken worker is served
from redundancy instead of failing the request.

State machine, keyed by the dispatch scheduler's codec signature
(family, k, m, technique, w, packetsize, mapping):

- CLOSED: device allowed.  ``ec_breaker_threshold`` CONSECUTIVE
  failures trip the breaker.
- OPEN: device refused — ``ErasureCodeMatrixRS._use_device`` routes
  every call to the host matrix path.  After ``ec_breaker_cooldown_s``
  the breaker is HALF-OPEN.
- HALF-OPEN (derived: open + cooldown elapsed): device allowed again,
  so the next call is a live probe.  Success restores CLOSED
  (``breaker_restores``); failure re-arms the cooldown.

Health: any open breaker surfaces as the ``TPU_CODEC_DEGRADED``
warning through the mgr's health checks (mon cluster log on
transitions) and as a gauge on the Prometheus surface.
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock
import time
from typing import Dict, List, Tuple

from ..common.config import g_conf
from ..trace import g_tracer
from ..trace.journal import g_journal
from .registry import (fault_perf_counters, l_fault_breaker_restores,
                       l_fault_breaker_trips, l_fault_degraded)

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"


class _Breaker:
    __slots__ = ("sig", "consecutive_failures", "open_since", "is_open",
                 "trips", "restores", "last_error")

    def __init__(self, sig: Tuple):
        self.sig = sig
        self.consecutive_failures = 0
        self.is_open = False
        self.open_since = 0.0
        self.trips = 0
        self.restores = 0
        self.last_error = ""

    def state(self, now: float, cooldown: float) -> str:
        if not self.is_open:
            return STATE_CLOSED
        if now - self.open_since >= cooldown:
            return STATE_HALF_OPEN
        return STATE_OPEN

    def dump(self, now: float, cooldown: float) -> dict:
        return {"signature": [str(x) for x in self.sig],
                "state": self.state(now, cooldown),
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips,
                "restores": self.restores,
                "open_for_s": round(now - self.open_since, 3)
                if self.is_open else 0.0,
                "last_error": self.last_error}


class BreakerBoard:
    """Process-wide breaker registry (one accelerator per process, so
    one board covers every daemon — like g_dispatcher)."""

    def __init__(self):
        self._breakers: Dict[Tuple, _Breaker] = {}
        self._lock = DebugLock("CircuitBreakers::lock")

    # ---- options (read live so `config set` applies) ----------------------
    @staticmethod
    def _opts() -> Tuple[int, float]:
        return (int(g_conf.get_val("ec_breaker_threshold")),
                float(g_conf.get_val("ec_breaker_cooldown_s")))

    # ---- hot path ---------------------------------------------------------
    def allow_device(self, sig: Tuple) -> bool:
        """May this signature's next call use the device?  CLOSED and
        HALF-OPEN say yes (the half-open call IS the probe); OPEN
        within its cooldown says no.  The steady-state path (no entry,
        or a long-healed one) is a lock-free dict read — a racing
        trip/restore just moves one call to the other backend, which
        is always correct."""
        br = self._breakers.get(sig) if self._breakers else None
        if br is None or not br.is_open:
            return True
        with self._lock:
            br = self._breakers.get(sig)
            if br is None or not br.is_open:
                return True
            _thr, cooldown = self._opts()
            return br.state(time.monotonic(), cooldown) \
                == STATE_HALF_OPEN

    def record_success(self, sig: Tuple) -> None:
        """A device call for *sig* completed: reset the failure run;
        restore an open breaker (the half-open probe succeeded).
        Healthy entries (closed, no failure run) return without the
        lock so a long-ago transient doesn't tax every later call."""
        br = self._breakers.get(sig) if self._breakers else None
        if br is None or (not br.is_open
                          and br.consecutive_failures == 0):
            return
        restored = False
        with self._lock:
            br = self._breakers.get(sig)
            if br is None:
                return
            br.consecutive_failures = 0
            if br.is_open:
                br.is_open = False
                br.restores += 1
                restored = True
        if restored:
            pc = fault_perf_counters()
            pc.inc(l_fault_breaker_restores)
            pc.set(l_fault_degraded, self._n_open())
            g_tracer.event("breaker_restore", signature=str(sig))
            g_journal.emit("fault", "breaker_restore",
                           signature=str(sig))

    def record_failure(self, sig: Tuple, error: str = "") -> bool:
        """A device attempt for *sig* failed; returns True when further
        retries are pointless — this failure TRIPPED the breaker, or it
        was a failed HALF-OPEN probe against an already-open one (the
        device is still dead; re-arm the cooldown and let the CPU path
        serve)."""
        threshold, _cooldown = self._opts()
        tripped = False
        probe_failed = False
        with self._lock:
            br = self._breakers.get(sig)
            if br is None:
                br = self._breakers[sig] = _Breaker(sig)
            br.consecutive_failures += 1
            br.last_error = error
            if br.is_open:
                # a failed half-open probe: re-arm the cooldown
                br.open_since = time.monotonic()
                probe_failed = True
            elif br.consecutive_failures >= threshold:
                br.is_open = True
                br.open_since = time.monotonic()
                br.trips += 1
                tripped = True
        if tripped:
            pc = fault_perf_counters()
            pc.inc(l_fault_breaker_trips)
            pc.set(l_fault_degraded, self._n_open())
            g_tracer.event("breaker_trip", signature=str(sig),
                           error=error)
            g_journal.emit("fault", "breaker_trip",
                           signature=str(sig), error=error)
        elif probe_failed:
            g_journal.emit("fault", "breaker_half_open",
                           signature=str(sig), error=error)
        return tripped or probe_failed

    def _n_open(self) -> int:
        with self._lock:
            return sum(1 for br in self._breakers.values()
                       if br.is_open)

    # ---- introspection ----------------------------------------------------
    def degraded(self) -> List[dict]:
        """Breakers currently refusing (or probing) the device — the
        TPU_CODEC_DEGRADED health payload."""
        if not self._breakers:
            return []
        now = time.monotonic()
        _thr, cooldown = self._opts()
        with self._lock:
            return [br.dump(now, cooldown)
                    for br in self._breakers.values() if br.is_open]

    def dump(self) -> dict:
        now = time.monotonic()
        threshold, cooldown = self._opts()
        with self._lock:
            entries = [br.dump(now, cooldown)
                       for br in self._breakers.values()]
        return {"options": {"ec_breaker_threshold": threshold,
                            "ec_breaker_cooldown_s": cooldown},
                "breakers": entries}

    def reset(self) -> None:
        """Forget every breaker (tests; `fault clear` leaves breakers
        alone — degradation outlives the injection that caused it)."""
        with self._lock:
            self._breakers.clear()
        fault_perf_counters().set(l_fault_degraded, 0)


# process-wide board, like g_dispatcher
g_breakers = BreakerBoard()
