"""DeviceGuard — the one funnel every device codec call goes through.

Wraps a device-path callable with the three robustness mechanisms, in
order:

1. fault-injection site check (``g_faults``) — so chaos tests exercise
   exactly the path production errors take;
2. bounded retry with exponential backoff for transient errors, plus a
   per-call watchdog deadline (``ec_device_watchdog_ms``) that converts
   an overlong call into a failure instead of letting one wedged
   dispatch stall the op pipeline forever;
3. per-signature circuit-breaker accounting (``g_breakers``) — N
   consecutive failures trip the signature to the CPU matrix path.

Retryable = RuntimeError lineage: the injected device/timeout kinds and
jaxlib's XlaRuntimeError both subclass it, while semantic errors
(IOError "not enough chunks", ValueError misalignment) do NOT and
propagate to the caller unchanged on the first throw.

After the retry budget (or an early breaker trip) the guard raises
``DeviceUnavailable``; ``ErasureCodeMatrixRS`` catches exactly that and
serves the call from the byte-identical host matrix path, so a client
op never fails because the device did.

Cost contract: with nothing armed and no watchdog the per-call overhead
is one try/except frame and two clock reads — no locks, no device
syncs.
"""
from __future__ import annotations

import time
from typing import Callable, Tuple

from ..common.config import g_conf
from ..trace import g_tracer
from .breaker import g_breakers
from .registry import (InjectedTimeout, fault_perf_counters, g_faults,
                       l_fault_device_errors, l_fault_device_retries,
                       l_fault_watchdog_timeouts)


class DeviceUnavailable(RuntimeError):
    """The device path is (transiently or persistently) failing for
    this call; the caller should serve it from the CPU twin."""

    def __init__(self, site: str, cause: BaseException):
        super().__init__(f"device path unavailable at {site}: {cause!r}")
        self.site = site
        self.cause = cause


class DeviceWatchdogTimeout(InjectedTimeout):
    """A device call exceeded the per-call watchdog deadline.  The
    result (if any) is discarded and the attempt counts as a failure;
    in-process we cannot abort the call, but we CAN refuse to trust a
    device that wedges and route around it."""


def _opts() -> Tuple[int, float, float]:
    return (max(int(g_conf.get_val("ec_device_retry_max")), 0),
            int(g_conf.get_val("ec_device_retry_backoff_us")) / 1e6,
            float(g_conf.get_val("ec_device_watchdog_ms")) / 1e3)


def run_device_call(sig: Tuple, site: str, fn: Callable):
    """Execute *fn* (a zero-arg device-path closure) under the
    site/retry/watchdog/breaker policy for codec signature *sig*.

    Raises DeviceUnavailable after the retry budget, or immediately
    once a failure trips the breaker (further retries are pointless —
    the CPU path will serve this and every following call)."""
    retries, backoff, watchdog = _opts()
    pc = fault_perf_counters()
    last: BaseException = None
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        try:
            if g_faults.site_armed(site):
                g_faults.check(site, ctx=str(sig))
            out = fn()
            if watchdog > 0 and time.perf_counter() - t0 > watchdog:
                raise DeviceWatchdogTimeout(site, "watchdog deadline")
        except RuntimeError as e:       # XlaRuntimeError + injected kinds
            last = e
            pc.inc(l_fault_device_errors)
            if isinstance(e, DeviceWatchdogTimeout):
                pc.inc(l_fault_watchdog_timeouts)
            # True = retries are pointless: this failure tripped the
            # breaker, or it was a failed half-open probe against an
            # already-open one — either way the CPU path serves now
            give_up = g_breakers.record_failure(sig, error=repr(e))
            if give_up or attempt >= retries:
                g_tracer.event("device_error", site=site,
                               attempt=attempt, error=repr(e))
                raise DeviceUnavailable(site, e) from e
            pc.inc(l_fault_device_retries)
            g_tracer.event("device_retry", site=site, attempt=attempt,
                           error=repr(e))
            if backoff > 0:
                time.sleep(backoff * (2 ** attempt))
            continue
        g_breakers.record_success(sig)
        return out
    raise DeviceUnavailable(site, last)    # unreachable; loop covers it
