"""Device-path fault injection and graceful degradation.

Three layers (docs/ROBUSTNESS.md):

- ``registry``: config-driven fault-injection sites (probabilistic /
  every-Nth / one-shot triggers, deterministic seeding) with the admin
  socket's ``fault inject|list|clear`` control surface — the device
  path's answer to Ceph's ``ms inject socket failures`` /
  ``bluestore_debug_inject_read_err``.
- ``guard``: bounded retry + exponential backoff + per-call watchdog
  deadline around every device codec call.
- ``breaker``: per-codec-signature circuit breakers that trip persistent
  failures onto the byte-identical CPU matrix path, surface
  ``TPU_CODEC_DEGRADED`` on health/Prometheus, and half-open-probe the
  device to auto-restore.
"""
from .breaker import BreakerBoard, g_breakers
from .guard import DeviceUnavailable, DeviceWatchdogTimeout, \
    run_device_call
from .registry import (FaultRegistry, FaultSpec, InjectedDeviceError,
                       InjectedFault, InjectedTimeout, SITE_CATALOG,
                       fault_perf_counters, g_faults, l_fault_cpu_fallbacks,
                       l_fault_eio_injected, l_fault_eio_reconstructs,
                       l_fault_msg_drops)

__all__ = [
    "BreakerBoard", "g_breakers",
    "DeviceUnavailable", "DeviceWatchdogTimeout", "run_device_call",
    "FaultRegistry", "FaultSpec", "InjectedDeviceError", "InjectedFault",
    "InjectedTimeout", "SITE_CATALOG", "fault_perf_counters", "g_faults",
    "l_fault_cpu_fallbacks", "l_fault_eio_injected",
    "l_fault_eio_reconstructs", "l_fault_msg_drops",
]
