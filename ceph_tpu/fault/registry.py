"""Fault-injection registry — named sites, armed triggers, zero-cost off.

Ceph treats injected faults as a first-class test surface (`ms inject
socket failures`, `bluestore_debug_inject_read_err`,
`osd_debug_inject_dispatch_delay_*` in src/common/options.cc); the
messenger fabric already carries the Thrasher hooks
(msg/messenger.py:78).  This module gives the DEVICE path the same
treatment: code declares named injection sites in a catalog, operators
arm them at runtime (admin socket ``fault inject|list|clear``), and the
armed trigger decides per check whether the fault fires.

Triggers:

- ``mode=prob p=0.2 [seed=N]``: fire with probability p, from a
  per-site ``random.Random(seed)`` so runs are reproducible.
- ``mode=nth n=3``: fire on every Nth matching check (3, 6, 9, ...).
- ``mode=once``: fire on the first matching check, then disarm.
- ``mode=always``: fire on every matching check.
- ``count=K``: disarm after K fires (any mode).
- ``match=substr``: only checks whose context string contains *substr*
  participate (e.g. scope ``msg.drop`` to ``match="MOSDOp "``).

Cost contract (the acceptance gate): with NO site armed, ``should_fire``
is one truthiness test of an empty dict — no locks, no RNG, no
counters — so production paths can consult sites unconditionally.
"""
from __future__ import annotations

import random
import threading

from ..common.lockdep import DebugLock
import zlib
from typing import Dict, Optional, Tuple

from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.journal import g_journal

# ---- injected error kinds --------------------------------------------------


class InjectedFault(RuntimeError):
    """Base of every injected error; carries the site that fired."""

    def __init__(self, site: str, detail: str = ""):
        super().__init__(f"injected fault at {site}"
                         + (f": {detail}" if detail else ""))
        self.site = site


class InjectedDeviceError(InjectedFault):
    """A transient device-dispatch failure (the retry/backoff and
    circuit-breaker target)."""


class InjectedTimeout(InjectedFault):
    """A wedged device call — what the per-call watchdog deadline
    converts a silent hang into."""


ERROR_KINDS = {"device": InjectedDeviceError,
               "timeout": InjectedTimeout}

# ---- the site catalog ------------------------------------------------------
# One place so `fault list` enumerates every site the build understands
# (docs/ROBUSTNESS.md mirrors this table).
SITE_CATALOG: Dict[str, str] = {
    "control.actuate":
        "mgr control-plane config injection (ceph_tpu/control): a "
        "firing fails ONE knob actuation; the controller retries "
        "mgr_control_actuate_retries times within the tick, then "
        "drops the move and re-derives it next tick — context is "
        "'<knob>=<value> (<option>)' for match= scoping",
    "device.encode_batch":
        "batched EC encode device call (matrix_plugin.encode_batch)",
    "device.decode_batch":
        "batched EC decode/reconstruct device call "
        "(matrix_plugin.decode_batch)",
    "device.encode_chunks":
        "per-stripe encode device call (matrix_plugin.encode_chunks)",
    "tpu.encode_batch_device":
        "device-resident encode entry point (tpu_plugin, mesh/bench)",
    "tpu.decode_batch_device":
        "device-resident decode entry point (tpu_plugin, mesh/bench)",
    "dispatch.batch":
        "coalesced flush execution (scheduler._execute run_group) — "
        "exercises the per-request fallback isolation",
    "mesh.encode_batch":
        "mesh-sharded flush execution (ceph_tpu/mesh runtime) — "
        "exhaustion degrades the flush to the single-device path",
    "mesh.decode_batch":
        "mesh-sharded decode/reconstruct/repair execution "
        "(ceph_tpu/mesh runtime decode_stacked) — exhaustion degrades "
        "the group to the single-device path and journals "
        "mesh_decode_degraded",
    "mesh.chip_slowdown":
        "per-chip straggler injection (ceph_tpu/mesh/chipstat): delays "
        "the matching chip's probe readback by delay_us; context is "
        "'chip=<i>/<mesh size>' so match='chip=3/' scopes one chip",
    "mesh.chip_fail":
        "hard per-chip failure mid-flush (ceph_tpu/mesh/rateless): the "
        "matching chip's coded blocks become erasures the subset "
        "completion re-solves around; context is 'chip=<i>/<mesh "
        "size>' for match= scoping, count= bounds the failed flushes",
    "mgr.incident_capture":
        "incident bundle snapshot on a health-check raise "
        "(ceph_tpu/mgr/incident): a firing drops that bundle — the "
        "raise is journaled, the tick proceeds, and the NEXT raise "
        "captures normally; context is the triggering check name",
    "osd.shard_read_eio":
        "shard-side EC read returns EIO (bluestore_debug_inject_read_err "
        "role) — the primary must reconstruct from surviving shards",
    "store.shard_corrupt":
        "flip one byte of a stored shard body at read time (memstore) — "
        "the shard-side crc32c verify must catch it and return EIO, "
        "whether the body is host bytes or a device-resident handle; "
        "context is '<coll>/<oid>' for match= scoping",
    "recovery.repair_read":
        "sub-chunk repair round start (recovery scheduler) — firing "
        "degrades the repair to the full-stripe decode path",
    "recovery.helper_fetch":
        "helper-side repair contribution read (handle_sub_read) — a "
        "dropped helper fails the round and the orchestrator falls "
        "back to full-stripe decode",
    "msg.drop":
        "drop a fabric message (ms inject socket failures role); "
        "context is '<MsgType> <src>><dst>' for match= scoping",
}

# ---- fault perf counters ---------------------------------------------------
FAULT_FIRST = 92000
l_fault_injected = 92001          # armed-site fires, all sites
l_fault_device_errors = 92002     # failed device attempts (any cause)
l_fault_device_retries = 92003    # attempts retried after backoff
l_fault_watchdog_timeouts = 92004  # calls past the watchdog deadline
l_fault_cpu_fallbacks = 92005     # device calls served by the CPU twin
l_fault_breaker_trips = 92006     # signature breakers tripped open
l_fault_breaker_restores = 92007  # breakers restored via half-open probe
l_fault_eio_injected = 92008      # shard reads failed by injection
l_fault_eio_reconstructs = 92009  # reads recovered by EC reconstruct
l_fault_msg_drops = 92010         # messages dropped by the msg.drop site
l_fault_degraded = 92011          # gauge: codec signatures currently open
FAULT_LAST = 92020

_fault_pc: Optional[PerfCounters] = None
_fault_pc_lock = DebugLock("fault_pc::init")


def fault_perf_counters() -> PerfCounters:
    """The robustness layer's counter logger (perf dump / Prometheus
    `ceph_daemon_fault_*`)."""
    global _fault_pc
    if _fault_pc is not None:
        return _fault_pc
    with _fault_pc_lock:
        if _fault_pc is None:
            b = PerfCountersBuilder("fault", FAULT_FIRST, FAULT_LAST)
            b.add_u64_counter(l_fault_injected, "injected",
                              "armed injection sites fired")
            b.add_u64_counter(l_fault_device_errors, "device_errors",
                              "failed device-call attempts")
            b.add_u64_counter(l_fault_device_retries, "device_retries",
                              "device attempts retried after backoff")
            b.add_u64_counter(l_fault_watchdog_timeouts,
                              "watchdog_timeouts",
                              "device calls past the watchdog deadline")
            b.add_u64_counter(l_fault_cpu_fallbacks, "cpu_fallbacks",
                              "device calls served by the CPU matrix "
                              "path instead")
            b.add_u64_counter(l_fault_breaker_trips, "breaker_trips",
                              "codec-signature circuit breakers tripped")
            b.add_u64_counter(l_fault_breaker_restores,
                              "breaker_restores",
                              "breakers closed again by a half-open "
                              "probe")
            b.add_u64_counter(l_fault_eio_injected, "eio_injected",
                              "shard reads failed by injection")
            b.add_u64_counter(l_fault_eio_reconstructs,
                              "eio_reconstructs",
                              "client reads served by EC reconstruction "
                              "after a shard EIO")
            b.add_u64_counter(l_fault_msg_drops, "msg_drops",
                              "fabric messages dropped by the msg.drop "
                              "site")
            b.add_u64(l_fault_degraded, "degraded",
                      "codec signatures currently tripped to the CPU "
                      "path (gauge)")
            _fault_pc = b.create_perf_counters()
    return _fault_pc


# ---- armed trigger ---------------------------------------------------------


class FaultSpec:
    """One armed site: trigger mode + bookkeeping."""

    __slots__ = ("site", "mode", "p", "n", "seed", "count", "error",
                 "match", "delay_us", "fires", "checks", "_rng")

    def __init__(self, site: str, mode: str = "always", p: float = 1.0,
                 n: int = 1, seed: Optional[int] = None, count: int = 0,
                 error: str = "device", match: str = "",
                 delay_us: int = 0):
        if mode not in ("prob", "nth", "once", "always"):
            raise ValueError(f"unknown fault mode '{mode}'")
        if error not in ERROR_KINDS:
            # reply-shaping sites (osd.shard_read_eio, msg.drop) never
            # consult the error kind — their effect IS the EIO/drop —
            # so only the check-style kinds are valid here
            raise ValueError(f"unknown fault error kind '{error}'")
        self.site = site
        self.mode = mode
        self.p = float(p)
        self.n = max(int(n), 1)
        self.seed = None if seed is None else int(seed)
        # once = a count-limited always
        self.count = 1 if mode == "once" else max(int(count), 0)
        self.error = error
        self.match = match
        # delay-shaping sites (mesh.chip_slowdown): how long the
        # matching check stalls when the trigger fires; check-style
        # sites ignore it
        self.delay_us = max(int(delay_us), 0)
        self.fires = 0
        self.checks = 0
        # deterministic per-site stream, cross-process: an explicit
        # seed (0 included) is honored, the default derives from a
        # STABLE digest of the site name (str hash() is salted per
        # process and would break run-to-run reproducibility)
        self._rng = random.Random(
            self.seed if self.seed is not None
            else zlib.crc32(site.encode()))

    def decide(self) -> bool:
        """One matching check: does the fault fire?  Caller holds the
        registry lock."""
        self.checks += 1
        if self.mode == "prob":
            fire = self._rng.random() < self.p
        elif self.mode == "nth":
            fire = self.checks % self.n == 0
        else:                      # once / always
            fire = True
        if fire:
            self.fires += 1
        return fire

    def exhausted(self) -> bool:
        return bool(self.count) and self.fires >= self.count

    def dump(self) -> dict:
        return {"mode": self.mode, "p": self.p, "n": self.n,
                "seed": self.seed, "count": self.count,
                "error": self.error, "match": self.match,
                "delay_us": self.delay_us,
                "fires": self.fires, "checks": self.checks}


class FaultRegistry:
    """Process-wide site catalog + armed triggers (like g_conf)."""

    def __init__(self):
        self._armed: Dict[str, FaultSpec] = {}
        self._lock = DebugLock("FaultRegistry::lock")

    # ---- hot path ---------------------------------------------------------
    def site_armed(self, site: str) -> bool:
        """Lock-free armed probe for hot paths that would otherwise pay
        to BUILD the context string (message pump, shard reads): dict
        membership is atomic in CPython, and a racing inject/clear just
        moves the decision to the next check."""
        return bool(self._armed) and site in self._armed

    def _decide(self, site: str, ctx: str) -> Tuple[bool, str]:
        """One locked fire decision; returns (fired, error kind) from
        the SAME spec so a concurrent re-arm cannot split the decision
        from the error it raises."""
        with self._lock:
            spec = self._armed.get(site)
            if spec is None:
                return False, ""
            if spec.match and spec.match not in ctx:
                return False, ""
            fired = spec.decide()
            error = spec.error
            if spec.exhausted():
                del self._armed[site]
        if fired:
            fault_perf_counters().inc(l_fault_injected)
            g_journal.emit("fault", "fault_fire", site=site)
        return fired, error

    def should_fire(self, site: str, ctx: str = "") -> bool:
        """True when *site* is armed and its trigger fires for this
        check.  The nothing-armed fast path is one dict truthiness
        test — the production cost of carrying injection sites."""
        if not self._armed:
            return False
        return self._decide(site, ctx)[0]

    def check(self, site: str, ctx: str = "") -> None:
        """Raise the armed error kind when the site fires (device-path
        sites); sites that shape a reply instead (EIO, drops) use
        ``should_fire`` directly."""
        if not self._armed:
            return
        fired, error = self._decide(site, ctx)
        if fired:
            raise ERROR_KINDS.get(error, InjectedDeviceError)(site, ctx)

    # ---- control surface (admin socket `fault ...`) ------------------------
    def inject(self, name: str, **kw) -> FaultSpec:
        if name not in SITE_CATALOG:
            raise ValueError(f"unknown fault site '{name}' (see "
                             f"'fault list')")
        spec = FaultSpec(name, **kw)
        with self._lock:
            self._armed[name] = spec
        g_journal.emit("fault", "fault_arm", site=name, mode=spec.mode)
        return spec

    def clear(self, name: str = "") -> int:
        with self._lock:
            if name:
                cleared = 1 if self._armed.pop(name, None) is not None \
                    else 0
            else:
                cleared = len(self._armed)
                self._armed.clear()
        if cleared:
            g_journal.emit("fault", "fault_clear", site=name or "*",
                           cleared=cleared)
        return cleared

    def armed(self, site: str) -> Optional[FaultSpec]:
        with self._lock:
            return self._armed.get(site)

    def sites(self) -> Dict[str, str]:
        """Machine-readable site catalog (name -> description) — the
        enumeration surface the chaos composer samples primitives from
        (ceph_tpu/chaos/scenario.py); a copy, so callers cannot mutate
        the build's catalog."""
        return dict(SITE_CATALOG)

    def list_sites(self) -> list:
        """Structured per-site records, sorted by name — the ``fault
        list format=json`` shape: one row per registered site with its
        armed trigger (or null), so tooling iterates a stable list
        instead of string-keyed prose."""
        with self._lock:
            armed = {s: spec.dump() for s, spec in self._armed.items()}
        return [{"name": name, "description": desc,
                 "armed": armed.get(name)}
                for name, desc in sorted(SITE_CATALOG.items())]

    def dump(self) -> dict:
        with self._lock:
            armed = {s: spec.dump() for s, spec in self._armed.items()}
        return {"sites": dict(SITE_CATALOG), "armed": armed}


# process-wide registry, like g_conf / g_tracer
g_faults = FaultRegistry()
