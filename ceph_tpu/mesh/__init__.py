"""Mesh execution runtime: one dispatch scheduler feeding N chips.

See runtime.py for the design; docs/DISPATCH.md "Mesh-sharded
dispatch" for the operator story.
"""
from .pool import StagingPool
from .runtime import (MeshRuntime, ShardingPlan, chip_occupancy_axes,
                      g_mesh, mesh_perf_counters)
from .topology import BATCH_AXIS, addressable_devices, batch_mesh

__all__ = [
    "BATCH_AXIS", "MeshRuntime", "ShardingPlan", "StagingPool",
    "addressable_devices", "batch_mesh", "chip_occupancy_axes",
    "g_mesh", "mesh_perf_counters",
]
