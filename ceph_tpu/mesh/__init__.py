"""Mesh execution runtime: one dispatch scheduler feeding N chips.

See runtime.py for the design; docs/DISPATCH.md "Mesh-sharded
dispatch" for the operator story.
"""
from .chipstat import (ChipStat, chip_latency_axes, g_chipstat,
                       mesh_chip_perf_counters)
from .pool import StagingPool
from .rateless import (RatelessCoder, RatelessPlan,
                       rateless_perf_counters)
from .runtime import (DecodeShardingPlan, MeshRuntime, ShardingPlan,
                      chip_occupancy_axes, g_mesh,
                      membership_perf_counters,
                      mesh_decode_perf_counters, mesh_perf_counters)
from .topology import BATCH_AXIS, addressable_devices, batch_mesh

__all__ = [
    "BATCH_AXIS", "ChipStat", "DecodeShardingPlan", "MeshRuntime",
    "RatelessCoder", "RatelessPlan", "ShardingPlan", "StagingPool",
    "addressable_devices", "batch_mesh", "chip_latency_axes",
    "chip_occupancy_axes", "g_chipstat", "g_mesh",
    "membership_perf_counters", "mesh_chip_perf_counters",
    "mesh_decode_perf_counters", "mesh_perf_counters",
    "rateless_perf_counters",
]
