"""MeshRuntime — one scheduler feeding N chips.

The dispatch scheduler (ceph_tpu/dispatch) coalesces concurrent EC
requests into one padded device call, but until this subsystem that
call landed on a single device: "more traffic" could never become
"more chips".  The runtime threads a mesh layer between the batch
assembler and the codec backends:

- **topology**: a 1-D ``("batch",)`` mesh over the addressable devices
  (``ec_mesh_chips``; CPU smoke via
  ``XLA_FLAGS=--xla_force_host_platform_device_count=8``).  Mesh size 1
  — or ``ec_mesh_chips=0``, the default — is the existing
  single-device path BY CONSTRUCTION: ``encode_stacked`` returns None
  and the assembler runs today's code, so nothing changes until an
  operator turns the knob.
- **sharding-plan cache**: keyed by (codec signature, chunk bucket) —
  the same key space the dispatch queues use — each plan holds the
  ``NamedSharding(mesh, PartitionSpec("batch"))`` input placement, the
  mesh-replicated encode bit-matrix, and the jitted sharded matmul.
  The batch (stripe) axis pads to the next power of two rounded up to
  a mesh-size multiple, so the jit cache stays O(log S) per plan and
  every chip takes an equal row slice.
- **donated staging pool**: the padded batch buffer is acquired from a
  per-shape pool (reused across flushes instead of re-allocated;
  pool.py) and the sharded matmul donates its input
  (``donate_argnums=(0,)``) where the backend supports donation (not
  cpu), so the device-side padded buffer is recycled into the output
  instead of doubling HBM per flush.  Donation changes allocation
  only, never the data path — the copy-budget gate holds it to zero
  new host copies.
- **accounting**: per-chip occupancy (stripes of real — non-pad —
  work each chip received per flush) lands in the 2-D
  ``dispatch_chip_occupancy_histogram`` and a per-chip totals table;
  ``mesh`` perf counters ride perf dump / Prometheus
  (``ceph_daemon_mesh_*``) and ``dispatch dump`` carries the whole
  runtime state.

Failure policy: the sharded call runs under the fault guard
(``run_device_call`` — injection sites ``mesh.encode_batch`` and
``mesh.decode_batch``, bounded retry, watchdog, per-signature
breaker).  ``DeviceUnavailable`` degrades to the single-device path
(which itself degrades to the host matrix twin), so a sick mesh costs
throughput, never an op.

Scope: BOTH matmul kinds ride the mesh.  The write path shards
flushed encode groups (``encode_stacked``); the READ path shards
decode/reconstruct groups and the product-matrix repair solve
(``decode_stacked``) — GF(2^8) decode is the same bit-matmul with the
host-inverted survivor matrix (``parallel/ec.py``'s ShardedRS decode
is the layout proof), so decode plans reuse the plan cache, the
staging pool, the scoreboard probes and the rateless coder
(DECODE_SITES) verbatim.  A repair solve's single stripe folds its
byte axis into extra batch rows first (GF matmuls are columnwise
independent) so even S=1 work spreads across the chips.  Decode plans
live in the SAME ``_plans`` dict as encode plans, so an elastic-
membership transition invalidates both; the transition additionally
waits out IN-FLIGHT decode/repair calls (recovery's repair solves
enter here directly, not through the dispatcher queues) before the
rebuild.
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock, DebugRLock
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common.config import g_conf
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.devprof import g_devprof
from ..trace.journal import g_journal
from ..trace.histogram import (PerfHistogramAxis, SCALE_LINEAR,
                               g_perf_histograms)
from .pool import StagingPool
from .topology import BATCH_AXIS, batch_mesh

# ---- perf counters (perf dump / Prometheus ceph_daemon_mesh_*) -------------
MESH_FIRST = 98000
l_mesh_dispatches = 98001      # flushes executed through the mesh
l_mesh_reqs = 98002            # coalesced requests through mesh flushes
l_mesh_stripes = 98003         # real (non-pad) stripes sharded
l_mesh_pad_stripes = 98004     # zero-pad lanes added for divisibility
l_mesh_bytes = 98005           # payload bytes through mesh flushes
l_mesh_plan_builds = 98006     # sharding plans compiled (cache misses)
l_mesh_plan_hits = 98007       # sharding-plan cache hits
l_mesh_pool_hits = 98008       # staging buffers served from the pool
l_mesh_pool_misses = 98009     # staging buffers freshly allocated
l_mesh_fallbacks = 98010       # flushes degraded to the single-device path
l_mesh_chips = 98011           # gauge: current mesh size
MESH_LAST = 98020

_mesh_pc: Optional[PerfCounters] = None
_mesh_pc_lock = DebugLock("mesh_pc::init")


def mesh_perf_counters() -> PerfCounters:
    """The mesh runtime's counter logger (perf dump / Prometheus)."""
    global _mesh_pc
    if _mesh_pc is not None:
        return _mesh_pc
    with _mesh_pc_lock:
        if _mesh_pc is None:
            b = PerfCountersBuilder("mesh", MESH_FIRST, MESH_LAST)
            b.add_u64_counter(l_mesh_dispatches, "dispatches",
                              "flushes executed through the mesh")
            b.add_u64_counter(l_mesh_reqs, "reqs",
                              "coalesced requests through mesh flushes")
            b.add_u64_counter(l_mesh_stripes, "stripes",
                              "real stripes sharded across the mesh")
            b.add_u64_counter(l_mesh_pad_stripes, "pad_stripes",
                              "zero-pad stripe lanes added for batch-"
                              "axis divisibility")
            b.add_u64_counter(l_mesh_bytes, "bytes",
                              "payload bytes through mesh flushes")
            b.add_u64_counter(l_mesh_plan_builds, "plan_builds",
                              "sharding plans built (cache misses)")
            b.add_u64_counter(l_mesh_plan_hits, "plan_hits",
                              "sharding-plan cache hits")
            b.add_u64_counter(l_mesh_pool_hits, "pool_hits",
                              "staging buffers reused from the pool")
            b.add_u64_counter(l_mesh_pool_misses, "pool_misses",
                              "staging buffers freshly allocated")
            b.add_u64_counter(l_mesh_fallbacks, "fallbacks",
                              "mesh flushes degraded to the single-"
                              "device path")
            b.add_u64(l_mesh_chips, "chips",
                      "devices in the active dispatch mesh")
            _mesh_pc = b.create_perf_counters()
    return _mesh_pc


# ---- decode-path counters (ceph_daemon_mesh_decode_*) ----------------------
MESH_DECODE_FIRST = 98300
l_mdec_dispatches = 98301    # decode/reconstruct/repair groups meshed
l_mdec_stripes = 98302       # real (non-pad) decode rows sharded
l_mdec_pad_stripes = 98303   # zero-pad decode rows for divisibility
l_mdec_bytes = 98304         # survivor bytes through meshed decodes
l_mdec_plan_builds = 98305   # decode sharding plans built (cache misses)
l_mdec_plan_hits = 98306     # decode sharding-plan cache hits
l_mdec_fallbacks = 98307     # meshed decodes degraded to single-device
l_mdec_repair_solves = 98308  # regenerating repair solves meshed
l_mdec_col_folds = 98309     # byte-axis folds applied to thin batches
l_mdec_inflight = 98310      # gauge: mesh calls executing right now
MESH_DECODE_LAST = 98320

_mdec_pc: Optional[PerfCounters] = None
_mdec_pc_lock = DebugLock("mesh_decode_pc::init")


def mesh_decode_perf_counters() -> PerfCounters:
    """The meshed READ path's counter logger (perf dump / Prometheus
    ``ceph_daemon_mesh_decode_*``): decode/reconstruct groups and
    product-matrix repair solves sharded across the chips."""
    global _mdec_pc
    if _mdec_pc is not None:
        return _mdec_pc
    with _mdec_pc_lock:
        if _mdec_pc is None:
            b = PerfCountersBuilder("mesh_decode", MESH_DECODE_FIRST,
                                    MESH_DECODE_LAST)
            b.add_u64_counter(l_mdec_dispatches, "dispatches",
                              "decode/reconstruct/repair groups "
                              "executed across the mesh")
            b.add_u64_counter(l_mdec_stripes, "stripes",
                              "real decode rows sharded across the "
                              "mesh")
            b.add_u64_counter(l_mdec_pad_stripes, "pad_stripes",
                              "zero-pad decode rows added for batch-"
                              "axis divisibility")
            b.add_u64_counter(l_mdec_bytes, "bytes",
                              "survivor bytes through meshed decodes")
            b.add_u64_counter(l_mdec_plan_builds, "plan_builds",
                              "decode sharding plans built (cache "
                              "misses)")
            b.add_u64_counter(l_mdec_plan_hits, "plan_hits",
                              "decode sharding-plan cache hits")
            b.add_u64_counter(l_mdec_fallbacks, "fallbacks",
                              "meshed decodes degraded to the single-"
                              "device path")
            b.add_u64_counter(l_mdec_repair_solves, "repair_solves",
                              "regenerating repair solves executed "
                              "across the mesh")
            b.add_u64_counter(l_mdec_col_folds, "col_folds",
                              "byte-axis folds applied so thin decode "
                              "batches still spread across the chips")
            b.add_u64(l_mdec_inflight, "inflight",
                      "mesh device calls executing right now (the "
                      "membership drain waits this to zero)")
            _mdec_pc = b.create_perf_counters()
    return _mdec_pc


# ---- elastic-membership counters (ceph_daemon_mesh_membership_*) ----------
MEMBER_FIRST = 98200
l_member_transitions = 98201     # applied ec_mesh_chips topology changes
l_member_chip_adds = 98202       # chips added across all transitions
l_member_chip_retires = 98203    # chips retired across all transitions
l_member_drained_reqs = 98204    # queued requests drained on the OLD mesh
l_member_plans_dropped = 98205   # sharding plans invalidated by transitions
l_member_pool_dropped = 98206    # staging buffers released by transitions
l_member_suspect_retires = 98207  # retired chips the scoreboard had SUSPECT
l_member_target_chips = 98208    # gauge: configured ec_mesh_chips target
MEMBER_LAST = 98220

_member_pc: Optional[PerfCounters] = None
_member_pc_lock = DebugLock("mesh_membership_pc::init")


def membership_perf_counters() -> PerfCounters:
    """The elastic-membership counter logger: every injectargs-driven
    ``ec_mesh_chips`` transition (drain, invalidation, add/retire
    accounting) lands here, so a chaos storyline's mesh_chip_add /
    mesh_chip_retire legs are visible on perf dump and Prometheus."""
    global _member_pc
    if _member_pc is not None:
        return _member_pc
    with _member_pc_lock:
        if _member_pc is None:
            b = PerfCountersBuilder("mesh_membership", MEMBER_FIRST,
                                    MEMBER_LAST)
            b.add_u64_counter(l_member_transitions, "transitions",
                              "applied ec_mesh_chips topology changes")
            b.add_u64_counter(l_member_chip_adds, "chip_adds",
                              "chips added across membership "
                              "transitions")
            b.add_u64_counter(l_member_chip_retires, "chip_retires",
                              "chips retired across membership "
                              "transitions")
            b.add_u64_counter(l_member_drained_reqs, "drained_reqs",
                              "queued requests drained on the old "
                              "mesh before a rebuild")
            b.add_u64_counter(l_member_plans_dropped, "plans_dropped",
                              "sharding plans invalidated by "
                              "membership transitions")
            b.add_u64_counter(l_member_pool_dropped, "pool_dropped",
                              "staging buffers released by "
                              "membership transitions")
            b.add_u64_counter(l_member_suspect_retires,
                              "suspect_retires",
                              "retired chips the skew scoreboard "
                              "held SUSPECT at retire time")
            b.add_u64(l_member_target_chips, "target_chips",
                      "configured ec_mesh_chips target")
            _member_pc = b.create_perf_counters()
    return _member_pc


def chip_occupancy_axes() -> List[PerfHistogramAxis]:
    """2-D per-chip occupancy: axis 0 = real stripes a chip received
    in one mesh flush (linear unit buckets, 0..64 individually visible
    like the batch-occupancy axis), axis 1 = the chip's index in the
    mesh (linear, chips 0..63 individually visible — a pod-slice-sized
    bound; larger meshes merge the tail into the overflow bucket, and
    the exact per-chip totals stay on ``dispatch dump``'s per_chip
    table either way).  Both axes are dimensionless, so the mgr
    renderer exports raw edges."""
    return [PerfHistogramAxis("chip_stripes", min=0, quant_size=1,
                              buckets=67, scale_type=SCALE_LINEAR),
            PerfHistogramAxis("chip_index", min=0, quant_size=1,
                              buckets=66, scale_type=SCALE_LINEAR)]


class ShardingPlan:
    """One compiled placement for a (codec signature, chunk bucket):
    input rows sharded over the batch axis, bit-matrix replicated,
    output rows sharded in place.  ``rateless`` holds the lazily-built
    coding geometry for the rateless path (rateless.py) — same cache
    entry, same lifetime."""

    __slots__ = ("mesh", "in_sharding", "enc_bits", "fn", "donated",
                 "hits", "rateless")

    def __init__(self, mesh, backend, donate: bool):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ops.gf_matmul import gf_bit_matmul
        self.mesh = mesh
        self.in_sharding = NamedSharding(mesh, P(BATCH_AXIS, None, None))
        # the bit-matrix is the contraction operand: replicate it so the
        # forward path needs zero collectives (parallel/ec.py's layout)
        self.enc_bits = jax.device_put(
            backend._enc_bits, NamedSharding(mesh, P(None, None)))
        out_sharding = NamedSharding(mesh, P(BATCH_AXIS, None, None))
        # donation recycles the padded input rows into the output on
        # backends that support aliasing (tpu/gpu); cpu would ignore it
        # with a per-call warning, so the plan records what it got
        self.donated = bool(donate)
        donate_argnums = (0,) if self.donated else ()
        self.fn = jax.jit(gf_bit_matmul, out_shardings=out_sharding,
                          donate_argnums=donate_argnums)
        self.hits = 0
        self.rateless = None     # (n_parity, RatelessPlan), lazy


class DecodeShardingPlan:
    """One compiled placement for a decode-kind matmul: the bit-matrix
    is the host-INVERTED survivor matrix (``DeviceRSBackend``'s
    ``_decode_bits_for`` construction), keyed by the erasure signature
    (srcs, want_rows) on top of the codec signature — the recovery
    shape repeats one erasure across many stripes, so the key space
    stays as small as the decode-bits LRU's.  Everything else mirrors
    ShardingPlan: rows sharded over the batch axis, bit-matrix
    replicated (zero collectives), output sharded in place, and the
    ``rateless`` slot carries the decode-bits coding geometry for the
    rateless path — GF-linearity makes the parity-combination trick
    bit-matrix-agnostic."""

    __slots__ = ("key", "mesh", "in_sharding", "dec_bits", "bits_np",
                 "fn", "donated", "hits", "rateless")

    def __init__(self, key, mesh, bits_np, donate: bool):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..ops.gf_matmul import gf_bit_matmul
        self.key = key
        self.mesh = mesh
        self.bits_np = bits_np
        self.in_sharding = NamedSharding(mesh, P(BATCH_AXIS, None, None))
        self.dec_bits = jax.device_put(
            bits_np, NamedSharding(mesh, P(None, None)))
        out_sharding = NamedSharding(mesh, P(BATCH_AXIS, None, None))
        self.donated = bool(donate)
        donate_argnums = (0,) if self.donated else ()
        self.fn = jax.jit(gf_bit_matmul, out_shardings=out_sharding,
                          donate_argnums=donate_argnums)
        self.hits = 0
        self.rateless = None     # (n_parity, RatelessPlan), lazy


class MeshRuntime:
    """The dispatch scheduler's device back end when a mesh is up."""

    def __init__(self):
        from .rateless import RatelessCoder
        self._lock = DebugRLock("MeshRuntime::lock")
        self._mesh = None
        self._mesh_n = None          # ec_mesh_chips the mesh was built for
        self._plans: Dict[Tuple, ShardingPlan] = {}
        self._pool = StagingPool()
        self._chips: Dict[int, Dict[str, int]] = {}
        self._rateless = RatelessCoder()
        # mesh device calls currently executing (encode AND decode/
        # repair): the membership drain waits this to zero after the
        # dispatcher flush, because repair solves enter decode_stacked
        # directly — they are never queued, so flush() cannot see them
        self._inflight = 0
        # while held, topology() keeps serving the CURRENT mesh even if
        # ec_mesh_chips changed underneath — the membership transition
        # sets this so the dispatcher drain completes every in-flight
        # flush against the mesh it was admitted under
        self._hold = False
        self._transitions = 0
        # injectargs-live membership: the observer fires synchronously
        # from config set / injectargs, drains, and rebuilds eagerly
        g_conf.add_observer("ec_mesh_chips", self._on_chips_changed)

    # ---- options (read live so `config set` applies without restart) ------
    @staticmethod
    def _opts() -> Tuple[int, int, bool]:
        return (int(g_conf.get_val("ec_mesh_chips")),
                int(g_conf.get_val("ec_mesh_pool_buffers")),
                bool(g_conf.get_val("ec_mesh_donate")))

    @property
    def _hist(self):
        return g_perf_histograms.get(
            "dispatch", "dispatch_chip_occupancy_histogram",
            chip_occupancy_axes)

    # ---- topology ----------------------------------------------------------
    def topology(self):
        """The current batch mesh, rebuilt when ``ec_mesh_chips``
        changes (plans are placement-bound, so they drop with it).

        While ``_hold`` is set (a membership transition is draining the
        dispatcher) the EXISTING mesh keeps being served, so every
        queued flush completes against the topology it was admitted
        under; the rebuild happens when the transition releases the
        hold and calls back in."""
        chips, pool_cap, _donate = self._opts()
        transition = None
        with self._lock:
            if self._mesh is not None and (self._mesh_n == chips
                                           or self._hold):
                # ec_mesh_pool_buffers stays live even when the
                # topology is unchanged (guarded: one unlocked read
                # per flush, the trim only runs on an actual change)
                if self._pool._per_shape != max(int(pool_cap), 1):
                    self._pool.set_capacity(pool_cap)
                return self._mesh
            prev_n = self._mesh_n
            prev_size = 0 if self._mesh is None else self._mesh.size
            plans_dropped = len(self._plans)
            self._plans.clear()
            pool_dropped = self._pool.clear()
            self._pool.set_capacity(pool_cap)
            self._chips.clear()
            if chips == 0:
                self._mesh, self._mesh_n = None, 0
            else:
                self._mesh = batch_mesh(chips)
                self._mesh_n = chips
            new_size = 0 if self._mesh is None else self._mesh.size
            mesh_perf_counters().set(l_mesh_chips, new_size)
            if (prev_n is not None and prev_size > 0 and new_size > 0
                    and prev_size != new_size):
                # a live mesh changed size — a membership transition
                # (mesh up 0->N and mesh down N->0 are lifecycle, not
                # membership).  Stash the facts, account outside the
                # lock (journal and scoreboard take their own locks).
                self._transitions += 1
                transition = (prev_size, new_size, plans_dropped,
                              pool_dropped)
            mesh = self._mesh
        if transition is not None:
            self._member_transition(*transition)
        return mesh

    def _on_chips_changed(self, _name: str, value) -> None:
        """``ec_mesh_chips`` config observer (registered at
        construction): makes membership injectargs-live.  Drain first —
        hold the old topology so ``g_dispatcher.flush()`` completes
        every queued request (encode AND decode groups share the
        dispatcher queues) on the mesh it was admitted under (the
        rateless path finishes from the first sufficient subset, so a
        retiring chip that is already failing costs bandwidth, never a
        flush), then wait out IN-FLIGHT mesh calls — repair solves and
        direct decodes enter ``decode_stacked`` without queuing, so
        the flush cannot see them — and only then release and rebuild
        eagerly via ``topology()``, which does the invalidation (both
        plan kinds live in ``_plans``) + add/retire accounting."""
        try:
            target = int(value)
        except (TypeError, ValueError):
            return
        membership_perf_counters().set(l_member_target_chips,
                                       max(target, 0))
        with self._lock:
            if self._mesh_n is None or self._mesh_n == target:
                return          # never built, or an idempotent re-set
            self._hold = True
        try:
            from ..dispatch import g_dispatcher
            drained = g_dispatcher.flush()
            self._wait_inflight()
        finally:
            with self._lock:
                self._hold = False
        if drained:
            membership_perf_counters().inc(l_member_drained_reqs,
                                           int(drained))
        self.topology()

    # bound on the in-flight wait: generous next to any real device
    # call, tiny next to the watchdog ladder — a wedged call is the
    # fault guard's problem, not the membership transition's
    INFLIGHT_DRAIN_S = 5.0

    def _wait_inflight(self) -> None:
        """Poll the in-flight gauge to zero (bounded) while ``_hold``
        keeps the old topology alive: every admitted call completes on
        the mesh it started on, so a membership flip mid-decode can
        never reshard half an erasure group."""
        import time
        from .chipstat import ChipStat
        deadline = time.perf_counter() + self.INFLIGHT_DRAIN_S
        while time.perf_counter() < deadline:
            with self._lock:
                if self._inflight <= 0:
                    return
            time.sleep(ChipStat.PROBE_POLL_S)

    def _inflight_add(self, delta: int) -> None:
        with self._lock:
            self._inflight += delta
            n = max(self._inflight, 0)
        mesh_decode_perf_counters().set(l_mdec_inflight, n)

    def _member_transition(self, prev_size: int, new_size: int,
                           plans_dropped: int, pool_dropped: int
                           ) -> None:
        """Post-rebuild accounting for one membership transition:
        counters, the mesh_chip_add / mesh_chip_retire journal events
        (the composable storyline steps, docs/CHAOS.md), and the
        scoreboard epoch roll — chip indices re-map with the topology,
        so a retired chip's skew streak must not indict its successor.
        Runs OUTSIDE MeshRuntime::lock."""
        from .chipstat import g_chipstat
        pc = membership_perf_counters()
        pc.inc(l_member_transitions)
        if plans_dropped:
            pc.inc(l_member_plans_dropped, plans_dropped)
        if pool_dropped:
            pc.inc(l_member_pool_dropped, pool_dropped)
        if new_size > prev_size:
            pc.inc(l_member_chip_adds, new_size - prev_size)
            g_journal.emit("mesh", "mesh_chip_add",
                           chips_from=prev_size, chips_to=new_size,
                           added=new_size - prev_size,
                           plans_dropped=plans_dropped)
        elif new_size < prev_size:
            retired = list(range(new_size, prev_size))
            suspects = sorted(g_chipstat.suspect_set()
                              & set(retired))
            pc.inc(l_member_chip_retires, prev_size - new_size)
            if suspects:
                pc.inc(l_member_suspect_retires, len(suspects))
            g_journal.emit("mesh", "mesh_chip_retire",
                           chips_from=prev_size, chips_to=new_size,
                           retired=retired, suspects_retired=suspects,
                           plans_dropped=plans_dropped)
        g_chipstat.reset()

    def active(self) -> bool:
        """True when flushes should shard: a mesh of >= 2 devices is
        up.  ``ec_mesh_chips=0`` (default) or a 1-device topology keeps
        the single-device path by construction."""
        mesh = self.topology()
        return mesh is not None and mesh.size > 1

    # ---- the flush entry point (dispatch/batch.py assembly) ---------------
    def encode_stacked(self, leader, stripes_list: List[np.ndarray],
                       bucket_c: int) -> Optional[np.ndarray]:
        """Shard one flushed encode group across the mesh.

        *stripes_list* holds each request's (S_i, k, C_i) uint8 view
        (C_i <= *bucket_c*; the assembler's column-pad contract).
        Returns the coalesced coding rows (S_pad, m, bucket_c) — the
        caller slices each request's rows/columns back out exactly as
        on the single-device path — or None when the mesh is down,
        the codec has no plain bit-matrix backend, or the guarded
        device call exhausted its retries (the caller then runs the
        single-device path, which itself degrades to the host twin)."""
        if not self.active():
            return None
        backend = self._bit_backend(leader)
        if backend is None:
            return None
        from ..dispatch.signature import codec_signature
        from ..fault import DeviceUnavailable, run_device_call
        sig = codec_signature(leader)
        self._inflight_add(1)
        try:
            return run_device_call(
                sig, "mesh.encode_batch",
                lambda: self._encode(sig, backend, stripes_list,
                                     bucket_c))
        except DeviceUnavailable:
            mesh_perf_counters().inc(l_mesh_fallbacks)
            return None
        finally:
            self._inflight_add(-1)

    # ---- the decode entry point (plugin decode_batch / repair) -------------
    def decode_stacked(self, leader, survivors: np.ndarray,
                       srcs, want_rows,
                       repair: bool = False) -> Optional[np.ndarray]:
        """Shard one decode-kind matmul across the mesh.

        *survivors* is the (S, n_src, C) uint8 stack in *srcs* order —
        exactly what ``DeviceRSBackend.decode_data`` consumes — and
        the return is the requested rows (S, len(want_rows), C),
        byte-identical to the single-device call.  *srcs*/*want_rows*
        index the leader backend's full (k+m, k)-style matrix, so the
        same entry serves plain-RS reconstruct (matrix rows), the
        regenerating ≥d decode (Ψ rows) and the d×d repair solve
        (*repair* marks the latter for the counters).

        Returns None — the caller then runs the existing single-device
        path — when the mesh is off/size-1 (BY CONSTRUCTION nothing
        changes), when the codec's decode is not mesh-shardable, or
        when the guarded call exhausted its retries: a sick mesh costs
        throughput, never an op, and the degradation is journaled."""
        if not self.active():
            return None
        backend = self._decode_backend(leader)
        if backend is None:
            return None
        if survivors.size == 0 or not want_rows:
            return None
        from ..dispatch.signature import codec_signature
        from ..fault import DeviceUnavailable, run_device_call
        sig = codec_signature(leader)
        srcs = tuple(int(i) for i in srcs)
        want_rows = tuple(int(i) for i in want_rows)
        self._inflight_add(1)
        try:
            return run_device_call(
                sig, "mesh.decode_batch",
                lambda: self._decode(sig, backend, survivors, srcs,
                                     want_rows, repair))
        except DeviceUnavailable:
            mesh_decode_perf_counters().inc(l_mdec_fallbacks)
            g_journal.emit("mesh", "mesh_decode_degraded",
                           signature=list(map(str, sig)),
                           stripes=int(survivors.shape[0]),
                           repair=bool(repair))
            return None
        finally:
            self._inflight_add(-1)

    @staticmethod
    def _bit_backend(leader):
        """The leader's plain GF(2^8) bit-matmul backend, or None for
        codecs whose device layout is not row-shardable by this plan
        shape.  TWO gates, both required: the codec must declare
        ``mesh_row_shardable`` (its encode_batch is the plain matmul
        on raw chunks — jerasure's bitmatrix/word layouts transform
        the data first and override it to False) and the backend must
        be a plain :class:`DeviceRSBackend` (word codes ride
        DeviceWordRSBackend)."""
        from ..ops.gf_matmul import DeviceRSBackend
        if not getattr(leader, "mesh_row_shardable", False):
            return None
        dev_fn = getattr(leader, "device", None)
        if dev_fn is None:
            return None
        try:
            backend = dev_fn()
        except Exception:
            return None
        return backend if type(backend) is DeviceRSBackend else None

    @staticmethod
    def _decode_backend(leader):
        """The leader's backend when its DECODE is mesh-shardable.
        Same two gates as ``_bit_backend`` but on the codec's
        ``mesh_decode_shardable`` declaration: decode is the plain
        inverted-matrix matmul for RS-matrix codes AND for the
        regenerating family (whose encode is not row-shardable, but
        whose ≥d decode and repair solve are plain survivor matmuls
        over [[I],[Ψ]] rows)."""
        from ..ops.gf_matmul import DeviceRSBackend
        if not getattr(leader, "mesh_decode_shardable", False):
            return None
        dev_fn = getattr(leader, "device", None)
        if dev_fn is None:
            return None
        try:
            backend = dev_fn()
        except Exception:
            return None
        return backend if type(backend) is DeviceRSBackend else None

    def _decode(self, sig: Tuple, backend, survivors: np.ndarray,
                srcs: Tuple[int, ...], want_rows: Tuple[int, ...],
                repair: bool) -> np.ndarray:
        import jax
        from .rateless import DECODE_SITES, rateless_opts
        mesh = self.topology()
        s_orig, n_src, c_orig = survivors.shape
        pc = mesh_decode_perf_counters()
        # byte-axis folding: GF matmuls are columnwise independent, so
        # a batch thinner than the mesh (the repair solve is S=1 by
        # shape) folds chunk bytes into extra rows and every chip
        # still gets real work; non-divisible widths just ride the row
        # pad (correct, some chips idle on pad lanes)
        fold = 1
        if s_orig < mesh.size and c_orig % mesh.size == 0:
            fold = mesh.size
            survivors = np.ascontiguousarray(
                survivors
                .reshape(s_orig, n_src, fold, c_orig // fold)
                .transpose(0, 2, 1, 3)
                .reshape(s_orig * fold, n_src, c_orig // fold))
            pc.inc(l_mdec_col_folds)
        s_total, _n, cb = survivors.shape
        s_pad = self._pad_rows(s_total, mesh.size)
        plan = self._decode_plan(sig, cb, srcs, want_rows, backend,
                                 mesh)
        mpc = mesh_perf_counters()
        buf, pooled = self._pool.acquire((s_pad, n_src, cb))
        mpc.inc(l_mesh_pool_hits if pooled else l_mesh_pool_misses)
        chip_real = None
        try:
            buf[:s_total] = survivors
            g_devprof.account_host_copy("mesh.decode_assemble",
                                        buf.nbytes)
            g_devprof.install_compile_listener()
            from ..common.kernel_trace import g_kernel_timer
            from .chipstat import g_chipstat
            probe = g_chipstat.should_probe()
            if rateless_opts()[0]:
                # the encode engine verbatim — it reads the bit-matrix
                # only out of the RatelessPlan, and GF-linearity makes
                # parity combinations valid for ANY bit-matrix; the
                # DECODE_SITES triple keeps the bandwidth separable
                rplan = self._decode_rateless_plan(plan, mesh)
                with g_devprof.stage("mesh.decode"):
                    rec, chip_real = g_kernel_timer.timed(
                        "ec_decode_batch_mesh_rateless",
                        lambda: self._rateless.encode(
                            plan, rplan, buf, mesh, probe, s_total,
                            sites=DECODE_SITES))
            else:
                g_devprof.account_h2d("mesh.decode", buf.nbytes)
                with g_devprof.stage("mesh.decode"):
                    def sharded_call():
                        dev_in = jax.device_put(buf, plan.in_sharding)
                        out = plan.fn(dev_in, plan.dec_bits)
                        if probe:
                            g_chipstat.probe(out, mesh)
                        return np.asarray(out)
                    rec = g_kernel_timer.timed(
                        "ec_decode_batch_mesh", sharded_call)
                g_devprof.account_d2h("mesh.decode", rec.nbytes)
        finally:
            self._pool.release(buf)
        self._account_decode(mesh, s_total, s_pad,
                             int(survivors.nbytes), chip_real, repair)
        rec = rec[:s_total]
        if fold > 1:
            w = rec.shape[1]
            rec = np.ascontiguousarray(
                rec.reshape(s_orig, fold, w, cb)
                .transpose(0, 2, 1, 3)
                .reshape(s_orig, w, c_orig))
        return rec

    def _decode_plan(self, sig: Tuple, cb: int,
                     srcs: Tuple[int, ...], want_rows: Tuple[int, ...],
                     backend, mesh) -> DecodeShardingPlan:
        _chips, _cap, donate_opt = self._opts()
        platform = getattr(np.asarray(mesh.devices).ravel()[0],
                           "platform", "cpu")
        donate = donate_opt and platform != "cpu"
        key = ("decode", sig, cb, srcs, want_rows, donate)
        pc = mesh_decode_perf_counters()
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None and plan.mesh is mesh:
                plan.hits += 1
                pc.inc(l_mdec_plan_hits)
                return plan
        from ..gf.matrices import gf_invert_matrix
        from ..gf.tables import expand_to_bitmatrix
        inv = gf_invert_matrix(backend.matrix[list(srcs), :])
        bits_np = expand_to_bitmatrix(
            inv[list(want_rows), :]).astype(np.int8)
        plan = DecodeShardingPlan(key, mesh, bits_np, donate)
        with self._lock:
            self._plans[key] = plan
        pc.inc(l_mdec_plan_builds)
        return plan

    def _decode_rateless_plan(self, plan: DecodeShardingPlan, mesh):
        """The decode plan's rateless geometry — the decode bit-matrix
        in a RatelessPlan, cached on the plan entry like the encode
        twin (same lifetime, same membership invalidation)."""
        from .rateless import RatelessCoder, RatelessPlan
        n_sys, n_parity = RatelessCoder.tasks_for(mesh.size)
        with self._lock:
            cached = plan.rateless
            if cached is not None and cached[0] == n_parity:
                return cached[1]
        rplan = RatelessPlan(plan.key, n_sys, n_parity, plan.bits_np)
        with self._lock:
            plan.rateless = (n_parity, rplan)
        return rplan

    def _account_decode(self, mesh, s_total: int, s_pad: int,
                        nbytes: int,
                        chip_real: Optional[Dict[int, int]],
                        repair: bool) -> None:
        """Decode-side occupancy: the ``mesh_decode_*`` counters plus
        the 2-D ``mesh_decode_chip_occupancy_histogram`` and the
        per-chip table's decode columns — the same receipt surfaces
        the encode path feeds, kept separable so a degraded-read storm
        is visible as READ work."""
        pc = mesh_decode_perf_counters()
        pc.inc(l_mdec_dispatches)
        pc.inc(l_mdec_stripes, s_total)
        pc.inc(l_mdec_pad_stripes, s_pad - s_total)
        pc.inc(l_mdec_bytes, nbytes)
        if repair:
            pc.inc(l_mdec_repair_solves)
        rows = s_pad // mesh.size
        hist = g_perf_histograms.get(
            "mesh", "mesh_decode_chip_occupancy_histogram",
            chip_occupancy_axes)
        devices = np.asarray(mesh.devices).ravel()
        with self._lock:
            for i in range(mesh.size):
                if chip_real is not None:
                    real = int(chip_real.get(i, 0))
                else:
                    real = min(max(s_total - i * rows, 0), rows)
                hist.inc(real, i)
                c = self._chips.get(i)
                if c is None:
                    c = self._chips[i] = self._chip_row(devices[i])
                c["decode_stripes"] += real
                c["decode_dispatches"] += 1

    @staticmethod
    def _chip_row(device) -> Dict[str, int]:
        """One per-chip totals row: encode and decode columns side by
        side, so the occupancy receipt shows BOTH kinds of work a chip
        carried."""
        return {"stripes": 0, "dispatches": 0,
                "decode_stripes": 0, "decode_dispatches": 0,
                "device": str(device)}

    def _encode(self, sig: Tuple, backend, stripes_list, bucket_c: int
                ) -> np.ndarray:
        import jax
        from .rateless import rateless_opts
        mesh = self.topology()
        plan = self._plan(sig, bucket_c, backend, mesh)
        k = backend.k
        s_total = sum(int(st.shape[0]) for st in stripes_list)
        s_pad = self._pad_rows(s_total, mesh.size)
        pc = mesh_perf_counters()
        buf, pooled = self._pool.acquire((s_pad, k, bucket_c))
        pc.inc(l_mesh_pool_hits if pooled else l_mesh_pool_misses)
        chip_real = None
        try:
            # assembly: every request's rows land directly in the
            # padded staging buffer — the old path's pad_cols + stack
            # + pad_stripes chain (up to three accounted copies)
            # collapses into ONE
            off = 0
            nbytes = 0
            for st in stripes_list:
                s_i, _k, c_i = st.shape
                buf[off:off + s_i, :, :c_i] = st
                off += s_i
                nbytes += st.nbytes
            g_devprof.account_host_copy("mesh.assemble", buf.nbytes)
            g_devprof.install_compile_listener()
            from ..common.kernel_trace import g_kernel_timer
            from .chipstat import g_chipstat
            # sampled fenced probe (chipstat.py): every Nth flush the
            # coalesced output is drained one element per chip BEFORE
            # the full materialization, so each chip's completion
            # delta lands on the skew scoreboard; off (the default
            # cadence counter not due) this is one int check
            probe = g_chipstat.should_probe()
            if rateless_opts()[0]:
                # rateless coded path (rateless.py): over-decomposed
                # per-chip block calls, subset completion, h2d/d2h
                # accounted per block inside the coder; on probe
                # flushes the drain itself feeds the scoreboard
                rplan = self._rateless_plan(sig, bucket_c, plan,
                                            backend, mesh)
                with g_devprof.stage("mesh.encode"):
                    coding, chip_real = g_kernel_timer.timed(
                        "ec_encode_batch_mesh_rateless",
                        lambda: self._rateless.encode(
                            plan, rplan, buf, mesh, probe, s_total))
            else:
                g_devprof.account_h2d("mesh.encode", buf.nbytes)
                with g_devprof.stage("mesh.encode"):
                    def sharded_call():
                        dev_in = jax.device_put(buf, plan.in_sharding)
                        out = plan.fn(dev_in, plan.enc_bits)
                        if probe:
                            g_chipstat.probe(out, mesh)
                        # np.asarray gathers every shard to the host —
                        # the materialization IS the completion fence
                        # (each chip's rows cross back; the bench twin
                        # drains per-shard via parallel.drain_sharded)
                        return np.asarray(out)
                    coding = g_kernel_timer.timed(
                        "ec_encode_batch_mesh", sharded_call)
                g_devprof.account_d2h("mesh.encode", coding.nbytes)
        finally:
            # release on failure too: the fault-guard retry path must
            # not turn every failed attempt into a leaked buffer
            self._pool.release(buf)
        self._account_chips(mesh, s_total, s_pad,
                            len(stripes_list), nbytes,
                            chip_real=chip_real)
        return coding

    def _rateless_plan(self, sig: Tuple, bucket_c: int, plan, backend,
                       mesh):
        """The plan-cache entry's rateless geometry, (re)built when
        ``ec_mesh_rateless_tasks`` changes the block count — built
        alongside the encode bit-matrix, same lifetime."""
        from ..gf.tables import expand_to_bitmatrix
        from .rateless import RatelessCoder, RatelessPlan
        n_sys, n_parity = RatelessCoder.tasks_for(mesh.size)
        with self._lock:
            cached = plan.rateless
            if cached is not None and cached[0] == n_parity:
                return cached[1]
        bits_np = expand_to_bitmatrix(
            backend.matrix[backend.k:]).astype(np.int8)
        rplan = RatelessPlan((sig, bucket_c), n_sys, n_parity, bits_np)
        with self._lock:
            plan.rateless = (n_parity, rplan)
        return rplan

    @staticmethod
    def _pad_rows(s: int, mesh_size: int) -> int:
        """Batch-axis pad target: the next power of two (O(log S) jit
        cache, like the single-device stripe pad) rounded up to a
        mesh-size multiple (equal row slices per chip)."""
        from ..dispatch.signature import next_pow2
        p = max(next_pow2(max(s, 1)), mesh_size)
        return ((p + mesh_size - 1) // mesh_size) * mesh_size

    def _plan(self, sig: Tuple, bucket_c: int, backend, mesh
              ) -> ShardingPlan:
        _chips, _cap, donate_opt = self._opts()
        platform = getattr(np.asarray(mesh.devices).ravel()[0],
                           "platform", "cpu")
        donate = donate_opt and platform != "cpu"
        # the donate flag is part of the key, so toggling
        # ec_mesh_donate takes effect on the next flush (a plan bakes
        # donate_argnums into its jit) instead of waiting for a
        # topology rebuild
        key = (sig, bucket_c, donate)
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None and plan.mesh is mesh:
                plan.hits += 1
                mesh_perf_counters().inc(l_mesh_plan_hits)
                return plan
        plan = ShardingPlan(mesh, backend, donate)
        with self._lock:
            self._plans[key] = plan
        mesh_perf_counters().inc(l_mesh_plan_builds)
        return plan

    def _account_chips(self, mesh, s_total: int, s_pad: int,
                       n_reqs: int, nbytes: int,
                       chip_real: Optional[Dict[int, int]] = None
                       ) -> None:
        """Per-chip occupancy: *chip_real* (the rateless path's
        scoreboard-weighted placement) when given, else the SPMD
        path's contiguous block-sharded layout."""
        pc = mesh_perf_counters()
        pc.inc(l_mesh_dispatches)
        pc.inc(l_mesh_reqs, n_reqs)
        pc.inc(l_mesh_stripes, s_total)
        pc.inc(l_mesh_pad_stripes, s_pad - s_total)
        pc.inc(l_mesh_bytes, nbytes)
        rows = s_pad // mesh.size
        hist = self._hist
        devices = np.asarray(mesh.devices).ravel()
        with self._lock:
            for i in range(mesh.size):
                if chip_real is not None:
                    real = int(chip_real.get(i, 0))
                else:
                    real = min(max(s_total - i * rows, 0), rows)
                hist.inc(real, i)
                c = self._chips.get(i)
                if c is None:
                    c = self._chips[i] = self._chip_row(devices[i])
                c["stripes"] += real
                c["dispatches"] += 1

    # ---- introspection -----------------------------------------------------
    def per_chip(self) -> Dict[int, Dict[str, int]]:
        """Per-chip totals (copy) — the occupancy receipt the bench and
        the tier-1 mesh smoke read before/after a batched write."""
        with self._lock:
            return {i: dict(v) for i, v in sorted(self._chips.items())}

    def dump(self) -> Dict:
        chips, pool_cap, donate = self._opts()
        mesh = self.topology()
        with self._lock:
            plans = []
            for key, p in sorted(self._plans.items(),
                                 key=lambda kv: str(kv[0])):
                if key[0] == "decode":
                    plans.append({"kind": "decode",
                                  "signature": list(map(str, key[1])),
                                  "bucket_chunk_size": key[2],
                                  "srcs": list(key[3]),
                                  "want_rows": list(key[4]),
                                  "donated": p.donated,
                                  "hits": p.hits})
                else:
                    plans.append({"kind": "encode",
                                  "signature": list(map(str, key[0])),
                                  "bucket_chunk_size": key[1],
                                  "donated": p.donated,
                                  "hits": p.hits})
            transitions, hold = self._transitions, self._hold
            inflight = self._inflight
        from .chipstat import g_chipstat
        return {
            "options": {"ec_mesh_chips": chips,
                        "ec_mesh_pool_buffers": pool_cap,
                        "ec_mesh_donate": donate},
            "active": self.active(),
            "size": 0 if mesh is None else mesh.size,
            "axis": BATCH_AXIS,
            "per_chip": self.per_chip(),
            "plans": plans,
            "pool": self._pool.dump(),
            "counters": mesh_perf_counters().dump(),
            # the meshed READ path (decode/reconstruct/repair):
            # in-flight gauge the membership drain waits on, plus the
            # mesh_decode_* counter family
            "decode": {"inflight": inflight,
                       "counters": mesh_decode_perf_counters().dump()},
            # elastic membership (injectargs-live ec_mesh_chips):
            # transition count, the drain hold flag, and the
            # mesh_membership counter family
            "membership": {"transitions": transitions, "hold": hold,
                           "counters":
                               membership_perf_counters().dump()},
            # the rateless coded-encode pane (rateless.py): options,
            # coding geometry for the live mesh, and the
            # mesh_rateless_* counter family
            "rateless": self._rateless.dump(
                0 if mesh is None else mesh.size),
            # the chip-health scoreboard (chipstat.py): per-chip probe
            # EWMAs, skew ratios and suspects — the full table with
            # percentiles lives on `mesh skew dump`
            "skew": g_chipstat.summary(),
        }


# process-wide runtime, like g_dispatcher: one accelerator complex per
# process, shared by every daemon the mini-cluster hosts
g_mesh = MeshRuntime()
