"""Staging-buffer pool: padded batch buffers reused across flushes.

Every mesh flush assembles its requests into ONE padded (S_pad, k, Cb)
host buffer before the sharded device_put.  Allocating that buffer per
flush is exactly the churn the zero-copy ROADMAP item indicts (the
allocation is invisible to the copy ledger but very visible to the
allocator); the pool keeps a small free list per shape so steady-state
traffic reuses the same staging memory flush after flush.

Buffers are handed out EXCLUSIVELY (acquire/release), so concurrent
flushes of different signature queues can never scribble over each
other's staging rows; a released buffer is zeroed lazily by the next
acquirer (the pad lanes must read zero — GF-coding zero rows encode to
zero rows, which the slicing discards).
"""
from __future__ import annotations

import threading

from ..common.lockdep import DebugLock
from typing import Dict, List, Tuple

import numpy as np


class StagingPool:
    """Per-shape free lists of C-contiguous uint8 staging buffers."""

    def __init__(self, per_shape: int = 4):
        self._lock = DebugLock("MeshBufferPool::lock")
        self._free: Dict[Tuple[int, ...], List[np.ndarray]] = {}
        self._per_shape = max(int(per_shape), 1)
        self.hits = 0
        self.misses = 0

    def acquire(self, shape: Tuple[int, ...]) -> Tuple[np.ndarray, bool]:
        """-> (zeroed buffer, came_from_pool).  A fresh buffer is born
        zeroed (np.zeros); a reused one is memset back to zero here —
        a fill, not a data copy, so it never lands on the copy ledger
        (np.pad zeroed its pad lanes the same way on the old path)."""
        with self._lock:
            lst = self._free.get(tuple(shape))
            buf = lst.pop() if lst else None
        if buf is not None:
            buf.fill(0)
            with self._lock:
                self.hits += 1
            return buf, True
        with self._lock:
            self.misses += 1
        return np.zeros(shape, dtype=np.uint8), False

    def release(self, buf: np.ndarray) -> None:
        key = buf.shape
        with self._lock:
            lst = self._free.setdefault(key, [])
            if len(lst) < self._per_shape:
                lst.append(buf)

    def set_capacity(self, per_shape: int) -> None:
        with self._lock:
            self._per_shape = max(int(per_shape), 1)
            for lst in self._free.values():
                del lst[self._per_shape:]

    def clear(self) -> int:
        """Drop every pooled buffer; returns how many were released so
        the elastic-membership transition can account the staging
        memory a topology change returns to the allocator."""
        with self._lock:
            dropped = sum(len(lst) for lst in self._free.values())
            self._free.clear()
            self.hits = 0
            self.misses = 0
        return dropped

    def dump(self) -> Dict:
        with self._lock:
            return {
                "shapes": {str(list(k)): len(v)
                           for k, v in sorted(self._free.items())},
                "hits": self.hits,
                "misses": self.misses,
                "per_shape": self._per_shape,
            }
