"""Rateless coded mesh encode — straggler-proof flushes.

The block-sharded SPMD path (runtime.py) gives every chip exactly one
row slice of the flushed batch, so the SLOWEST chip gates the whole
flush: at production scale the p999 IS the straggler, and the PR 14
chip-health scoreboard (chipstat.py) is the ruler that proves it.
This module is the fix named in PAPERS.md — rateless codes for
near-perfect load balancing in distributed matrix-vector
multiplication (arXiv 1804.10331): over-decompose the coded work so a
slow or dead chip costs bandwidth, never latency; the XOR-EC
program-optimization results (arXiv 2108.02692) price the extra coded
rows as cheap next to the data movement the flush already pays.

How one flush runs (``ec_mesh_rateless``; off = the SPMD path):

- **over-decomposition**: the padded (S_pad, k, Cb) batch splits along
  the stripe axis into ``n_sys`` = mesh-size SYSTEMATIC row-blocks
  plus ``n_parity`` PARITY blocks (``ec_mesh_rateless_tasks`` total;
  0 = auto, mesh size + 2).  Each parity block is a GF(2^8)
  random-combination of the systematic blocks with nonzero
  coefficients drawn from a per-plan deterministic stream — and
  because the GF bit-matmul is GF(2^8)-linear, ``encode(Σ cᵢ⊗Xᵢ) =
  Σ cᵢ⊗encode(Xᵢ)``: a parity INPUT block's coding rows are the same
  combination of the systematic OUTPUT blocks, byte-exactly.
- **scoreboard-weighted placement**: blocks are assigned per chip
  using the PR 14 scoreboard — SUSPECT chips get at most one parity
  block (parity-only keeps them probed so they can clear; zero
  critical blocks means their loss costs nothing) and never a
  systematic block; the telemetry finally actuates
  (``suspect_deweights``).
- **subset completion**: every chip launches, and the flush completes
  from the FIRST subset of blocks that spans the systematic space
  (incremental GF Gaussian elimination decides spanning as blocks
  complete, via the readiness-POLLING drain proven in chipstat.py —
  ``Array.is_ready``, order-free, zero ``block_until_ready``).
  Missing systematic blocks are re-solved on the host from the coded
  blocks (``gf_invert_matrix`` over the chosen coefficient rows) —
  byte-identical by construction, GF arithmetic is exact.
- **failure = erasure**: a chip that fails mid-flush (fault site
  ``mesh.chip_fail``, or a real launch/fetch error) just erases its
  blocks; the flush still completes whenever the surviving blocks
  span.  Only when they cannot does the encode raise and the guard
  degrade the GROUP to the single-device path (which itself degrades
  to the host twin) — the PR 11 ladder, one rung earlier.

Probe semantics (the ruler keeps working WITH the fix active): on
probe flushes the drain itself is the probe — each chip's completion
delta feeds the scoreboard through ``chipstat.record_deltas``.  A
chip still pending once the subset completed is polled a little
longer, up to ``CENSOR_MARGIN × threshold × median`` past launch:
completing inside that cap records its exact delta, still pending at
the cap records a CENSORED breach (the delta is provably at least the
cap — no fabricated breach can ever hit a merely-last healthy chip,
and no straggler escapes by being abandoned).  Chips already SUSPECT
are never waited for (their absence records nothing; clearing rides
the exact deltas their parity block produces once they heal), so the
cap-wait is paid only during the sustain window — the bounded
detection transient the straggler workload receipts.

``mesh.chip_slowdown`` gates real completion here (not just the probe
view): an armed delay holds the matching chip's blocks not-complete
until ``delay_us`` past launch, so the flush either routes around the
straggler (enough parity) or genuinely waits (not enough) — exactly
the production choice the over-decomposition knob buys.
"""
from __future__ import annotations

import time
import zlib

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..gf.matrices import gf_invert_matrix
from ..gf.tables import gf_mul_scalar
from ..trace.devprof import g_devprof
# the drain shares the probe's readiness-polling granularity
# (ChipStat.PROBE_POLL_S) and median rule — one tuning point, the two
# surfaces cannot drift
from .chipstat import ChipStat

# censored-breach margin: a chip still pending this far past
# threshold x the probe median has PROVEN its delta breaches with
# slack (the recorded EWMA ratio clears the threshold instead of
# riding its boundary); it also bounds the detection-window cap-wait
CENSOR_MARGIN = 1.25

# devprof call-sites for one rateless execution: (block h2d/d2h,
# parity assembly+h2d, host re-solve).  The coder is GF-matmul-generic
# — the SAME engine runs the encode bit-matrix and the inverted
# survivor (decode) bit-matrix — so the runtime passes the site triple
# matching the kind of work, keeping encode and decode bandwidth
# separable on the devflow ledger (the degraded-read workload's
# bandwidth_overhead reads the decode sites alone)
ENCODE_SITES = ("mesh.encode", "mesh.rateless_parity",
                "mesh.rateless_solve")
DECODE_SITES = ("mesh.decode", "mesh.decode_parity",
                "mesh.decode_solve")


# ---- perf counters (perf dump / Prometheus ceph_daemon_mesh_rateless_*) ----
RATELESS_FIRST = 98100
l_rl_flushes = 98101             # rateless-coded mesh flushes executed
l_rl_coded_tasks = 98102         # coded row-blocks launched (sys + parity)
l_rl_parity_tasks = 98103        # parity row-blocks launched
l_rl_wasted_blocks = 98104       # launched blocks never consumed
l_rl_subset_completions = 98105  # flushes completed before every block
l_rl_host_resolves = 98106       # systematic blocks re-solved on host
l_rl_suspect_deweights = 98107   # placement decisions that deweighted
                                 # a SUSPECT chip
l_rl_chip_failures = 98108       # chips erased mid-flush (fault/error)
l_rl_insufficient = 98109        # flushes whose survivors could not span
RATELESS_LAST = 98120

_rl_pc: Optional[PerfCounters] = None
_rl_pc_lock = DebugLock("mesh_rateless_pc::init")


def rateless_perf_counters() -> PerfCounters:
    """The rateless coder's counter logger (perf dump / Prometheus
    ``ceph_daemon_mesh_rateless_*``)."""
    global _rl_pc
    if _rl_pc is not None:
        return _rl_pc
    with _rl_pc_lock:
        if _rl_pc is None:
            b = PerfCountersBuilder("mesh_rateless", RATELESS_FIRST,
                                    RATELESS_LAST)
            b.add_u64_counter(l_rl_flushes, "flushes",
                              "rateless-coded mesh flushes executed")
            b.add_u64_counter(l_rl_coded_tasks, "coded_tasks",
                              "coded row-blocks launched (systematic "
                              "plus parity)")
            b.add_u64_counter(l_rl_parity_tasks, "parity_tasks",
                              "GF random-combination parity row-blocks "
                              "launched")
            b.add_u64_counter(l_rl_wasted_blocks, "wasted_blocks",
                              "launched blocks the subset completion "
                              "never consumed (the bandwidth price of "
                              "straggler protection)")
            b.add_u64_counter(l_rl_subset_completions,
                              "subset_completions",
                              "flushes completed from a strict subset "
                              "of their coded blocks")
            b.add_u64_counter(l_rl_host_resolves, "host_resolves",
                              "systematic output blocks re-solved on "
                              "the host from coded blocks")
            b.add_u64_counter(l_rl_suspect_deweights,
                              "suspect_deweights",
                              "placement decisions that gave a SUSPECT "
                              "chip parity-only or no blocks")
            b.add_u64_counter(l_rl_chip_failures, "chip_failures",
                              "chips whose blocks became erasures "
                              "mid-flush (mesh.chip_fail or a real "
                              "device error)")
            b.add_u64_counter(l_rl_insufficient, "insufficient",
                              "flushes whose surviving blocks could "
                              "not span (degraded to the single-"
                              "device path)")
            _rl_pc = b.create_perf_counters()
    return _rl_pc


def rateless_opts() -> Tuple[bool, int]:
    """(enabled, total coded tasks; 0 = auto) read live."""
    return (bool(g_conf.get_val("ec_mesh_rateless")),
            int(g_conf.get_val("ec_mesh_rateless_tasks") or 0))


class _GFBasis:
    """Incremental GF(2^8) Gaussian elimination over block coefficient
    vectors: decides — as blocks complete, in completion order —
    whether a block adds rank, and when the collected set spans the
    systematic space."""

    def __init__(self, n: int):
        self.n = n
        # pivot column -> row reduced+normalized to pivot coefficient 1
        self._rows: Dict[int, np.ndarray] = {}

    def _reduce(self, vec: np.ndarray) -> np.ndarray:
        v = vec.copy()
        for pivot, row in self._rows.items():
            c = int(v[pivot])
            if c:
                v ^= gf_mul_scalar(c, row)
        return v

    def admits(self, vec: np.ndarray) -> bool:
        """True when *vec* would increase the rank (pure check — an
        erased fetch must leave the basis untouched)."""
        return bool(self._reduce(vec).any())

    def add(self, vec: np.ndarray) -> bool:
        v = self._reduce(vec)
        nz = np.flatnonzero(v)
        if nz.size == 0:
            return False
        pivot = int(nz[0])
        from ..gf.tables import gf_inv
        self._rows[pivot] = gf_mul_scalar(gf_inv(int(v[pivot])), v)
        return True

    @property
    def rank(self) -> int:
        return len(self._rows)

    def spans(self) -> bool:
        return len(self._rows) >= self.n


class RatelessPlan:
    """The coding geometry for one sharding-plan cache entry: the
    parity coefficient matrix (deterministic per plan — the same
    stream every run, like the fault registry's seeded triggers) and
    the per-device replicas of the encode bit-matrix."""

    __slots__ = ("n_sys", "n_parity", "coeffs", "vectors", "_dev_bits",
                 "_bits_np", "_lock")

    def __init__(self, key, n_sys: int, n_parity: int, bits_np):
        self.n_sys = n_sys
        self.n_parity = n_parity
        rng = np.random.default_rng(
            zlib.crc32(repr((key, n_sys, n_parity)).encode()))
        # nonzero coefficients: every parity block touches every
        # systematic block, so any single missing systematic block is
        # recoverable from any surviving parity block
        self.coeffs = rng.integers(1, 256, size=(n_parity, n_sys),
                                   dtype=np.uint8)
        # block id -> coefficient vector over the systematic space
        eye = np.eye(n_sys, dtype=np.uint8)
        self.vectors = [eye[i] for i in range(n_sys)] + \
            [self.coeffs[j] for j in range(n_parity)]
        self._bits_np = bits_np
        self._dev_bits: Dict[int, object] = {}
        self._lock = DebugLock("RatelessPlan::dev_bits")

    def bits_for(self, dev_index: int, device):
        """The encode bit-matrix committed to *device* (cached — one
        upload per device per plan, like the SPMD replication)."""
        with self._lock:
            hit = self._dev_bits.get(dev_index)
        if hit is not None:
            return hit
        import jax
        bits = jax.device_put(self._bits_np, device)
        with self._lock:
            self._dev_bits[dev_index] = bits
        return bits


class _Block:
    """One coded row-block in flight on one chip."""

    __slots__ = ("bid", "chip", "vec", "out", "erased", "systematic",
                 "t_launch", "t_ready")

    def __init__(self, bid: int, chip: int, vec: np.ndarray,
                 systematic: bool):
        self.bid = bid
        self.chip = chip
        self.vec = vec
        self.out = None          # the launched device array
        self.erased = False
        self.systematic = systematic
        self.t_launch = 0.0      # stamped at THIS block's dispatch
        self.t_ready = 0.0       # stamped at readiness observation

    def elapsed_us(self, now: float) -> float:
        return (now - self.t_launch) * 1e6


class RatelessCoder:
    """The mesh runtime's rateless execution engine (one per runtime;
    plans ride the runtime's sharding-plan cache entries)."""

    class Insufficient(RuntimeError):
        """Fewer than a sufficient subset of chips answered — the
        guard turns this into DeviceUnavailable and the group degrades
        to the single-device path."""

    @staticmethod
    def tasks_for(mesh_size: int) -> Tuple[int, int]:
        """(n_sys, n_parity) for the live options: n_sys is always the
        mesh size (same row granularity as the SPMD path, so S_pad
        needs no new padding rule), parity is the over-decomposition.
        Auto (tasks=0) adds 2 parity blocks — any single chip's loss
        is coverable even when one parity block rode the lost chip,
        at 1 + 2/mesh-size bandwidth overhead."""
        _enabled, tasks = rateless_opts()
        n_sys = mesh_size
        if tasks <= 0:
            n_parity = 2
        else:
            n_parity = max(int(tasks) - n_sys, 1)
        return n_sys, n_parity

    # ---- placement ---------------------------------------------------------
    @staticmethod
    def assign(n_sys: int, n_parity: int, n_chips: int,
               suspects: Set[int], rotation: int) -> Dict[int, int]:
        """block id -> chip.  Healthy chips share the systematic
        blocks round-robin (rotated per flush so any extra load
        spreads); SUSPECT chips get at most ONE parity block each —
        parity-only keeps a suspect probed (it can prove itself clean
        and clear) while its loss costs nothing — and remaining parity
        lands on healthy chips as the actual redundancy."""
        healthy = [c for c in range(n_chips) if c not in suspects]
        if not healthy:          # every chip suspect: nothing to avoid
            healthy = list(range(n_chips))
        owner: Dict[int, int] = {}
        for b in range(n_sys):
            owner[b] = healthy[(b + rotation) % len(healthy)]
        sus = sorted(c for c in suspects if c < n_chips)
        slots: List[int] = [sus[(rotation + i) % len(sus)]
                            for i in range(min(len(sus), n_parity))]
        i = 0
        while len(slots) < n_parity:
            slots.append(healthy[(rotation + n_sys + i) % len(healthy)])
            i += 1
        for j in range(n_parity):
            owner[n_sys + j] = slots[j]
        return owner

    # ---- the flush ---------------------------------------------------------
    def encode(self, plan, rplan: RatelessPlan, buf: np.ndarray, mesh,
               probe: bool, s_total: int,
               sites: Tuple[str, str, str] = ENCODE_SITES
               ) -> Tuple[np.ndarray, Dict[int, int]]:
        """Run one rateless-coded flush over *buf* (S_pad, k, Cb);
        returns the coalesced coding rows (S_pad, m, Cb) —
        byte-identical to the single-device call — plus each chip's
        real (non-pad) systematic stripe count for the occupancy
        surfaces.  Raises Insufficient when the surviving blocks
        cannot span.

        The engine never reads the bit-matrix out of *plan* — only out
        of *rplan* — so it is generic over WHICH GF matmul it protects:
        the runtime's decode path hands it an inverted-survivor-matrix
        RatelessPlan plus the DECODE_SITES triple and gets the same
        subset-completion semantics on reconstruct/repair work."""
        import jax
        from ..fault import g_faults
        from ..ops.gf_matmul import gf_bit_matmul
        from .chipstat import g_chipstat, slowdown_delays

        pc = rateless_perf_counters()
        devices = np.asarray(mesh.devices).ravel()
        n_chips = len(devices)
        n_sys, n_parity = rplan.n_sys, rplan.n_parity
        rows = buf.shape[0] // n_sys
        suspects = g_chipstat.suspect_set()
        pc.inc(l_rl_flushes)
        if suspects:
            pc.inc(l_rl_suspect_deweights,
                   len([c for c in suspects if c < n_chips]))
        owner = self.assign(n_sys, n_parity, n_chips, suspects,
                            rotation=pc.get(l_rl_flushes) % max(n_chips,
                                                                1))
        # ---- per-chip fault decisions, once, before the clock starts ------
        # (slowdown decisions via chipstat's shared pass — the ctx
        # format and slowdowns_injected accounting cannot drift from
        # the SPMD probe's); chip_fail consults only chips that OWN
        # blocks this flush, so a deweighted chip never burns the
        # trigger's count= budget on a no-op
        delay_until = slowdown_delays(n_chips)
        failed: Set[int] = set()
        if g_faults.site_armed("mesh.chip_fail"):
            for c in sorted(set(owner.values())):
                if g_faults.should_fire("mesh.chip_fail",
                                        ctx=f"chip={c}/{n_chips}"):
                    failed.add(c)
        # ---- launch: suspects first (their parity is the clear probe),
        # then everything else in block order.  Each block stamps its
        # OWN launch time: per-chip service deltas must not charge one
        # chip the host parity-assembly time spent launching another
        blocks = [_Block(b, owner[b], rplan.vectors[b], b < n_sys)
                  for b in sorted(owner)]
        blocks.sort(key=lambda bl: (0 if bl.chip in suspects else 1,
                                    bl.bid))
        for bl in blocks:
            if bl.chip in failed:
                bl.erased = True
                continue
            try:
                if bl.systematic:
                    src = buf[bl.bid * rows:(bl.bid + 1) * rows]
                    g_devprof.account_h2d(sites[0], src.nbytes)
                else:
                    src = self._parity_block(buf, rplan,
                                             bl.bid - n_sys, rows,
                                             sites[1])
                    g_devprof.account_h2d(sites[1], src.nbytes)
                dev_in = jax.device_put(src, devices[bl.chip])
                bl.t_launch = time.perf_counter()
                bl.out = gf_bit_matmul(
                    dev_in, rplan.bits_for(bl.chip, devices[bl.chip]))
            except RuntimeError:
                bl.erased = True
        # chip_failures counts CHIPS (the counter's contract); the
        # drain adds fetch-time failures for chips not already counted
        counted_chips = failed | {bl.chip for bl in blocks
                                  if bl.erased}
        if counted_chips:
            pc.inc(l_rl_chip_failures, len(counted_chips))
        pc.inc(l_rl_coded_tasks,
               sum(1 for bl in blocks if not bl.erased))
        pc.inc(l_rl_parity_tasks,
               sum(1 for bl in blocks
                   if not bl.erased and not bl.systematic))
        out = self._drain(blocks, n_sys, rows, buf.shape, probe,
                          suspects, delay_until, pc, counted_chips,
                          sites)
        # occupancy: real (non-pad) stripes per chip from the
        # scoreboard-weighted placement — the deweighting is visible
        # on the same per-chip surfaces the SPMD layout fed.  Erased
        # blocks credit nothing: a dead chip must read as idle on the
        # very surface that shows the flush routed around it
        chip_real = {c: 0 for c in range(n_chips)}
        for bl in blocks:
            if bl.systematic and not bl.erased:
                real = min(max(s_total - bl.bid * rows, 0), rows)
                chip_real[bl.chip] += real
        return out, chip_real

    @staticmethod
    def _parity_block(buf: np.ndarray, rplan: RatelessPlan, j: int,
                      rows: int, site: str) -> np.ndarray:
        """Parity input block j = Σᵢ cⱼᵢ ⊗ sys-blockᵢ on the host —
        the extra coded rows the over-decomposition pays for (h2d +
        one host pass; arXiv 2108.02692's accounting says this is the
        cheap part)."""
        acc = None
        for i in range(rplan.n_sys):
            term = gf_mul_scalar(int(rplan.coeffs[j, i]),
                                 buf[i * rows:(i + 1) * rows])
            acc = term if acc is None else acc ^ term
        g_devprof.account_host_copy(site, acc.nbytes)
        return acc

    # ---- the readiness-polling drain ---------------------------------------
    @staticmethod
    def _block_ready(bl: _Block, now: float,
                     delay_until: Dict[int, float]) -> bool:
        if bl.elapsed_us(now) < delay_until.get(bl.chip, 0.0):
            return False         # injected straggler: not complete yet
        ready = getattr(bl.out, "is_ready", None)
        return ready is None or bool(ready())

    def _drain(self, blocks: List[_Block], n_sys: int,
               rows: int, in_shape, probe: bool, suspects: Set[int],
               delay_until: Dict[int, float], pc,
               counted_chips: Set[int],
               sites: Tuple[str, str, str] = ENCODE_SITES
               ) -> np.ndarray:
        from .chipstat import g_chipstat

        basis = _GFBasis(n_sys)
        chosen: List[_Block] = []
        pending = [bl for bl in blocks if not bl.erased]
        # per-chip service bookkeeping (probe flushes feed the
        # scoreboard): a chip's delta is the LARGEST per-block
        # launch→ready time over its blocks, stamped at readiness
        # observation — BEFORE any fetch, so one chip's delta never
        # carries another block's d2h time or the launch loop's host
        # parity-assembly time (the order-free discipline the SPMD
        # probe polls for)
        chip_pending: Dict[int, int] = {}
        for bl in pending:
            chip_pending[bl.chip] = chip_pending.get(bl.chip, 0) + 1
        chip_done_us: Dict[int, float] = {}

        def sweep() -> None:
            # pass 1: stamp readiness (cheap polls, no fetches)
            ready: List[_Block] = []
            for bl in list(pending):
                now = time.perf_counter()
                if not self._block_ready(bl, now, delay_until):
                    continue
                bl.t_ready = now
                pending.remove(bl)
                ready.append(bl)
                chip_pending[bl.chip] -= 1
                if chip_pending[bl.chip] == 0:
                    chip_done_us[bl.chip] = max(
                        b.elapsed_us(b.t_ready) for b in blocks
                        if b.chip == bl.chip and not b.erased)
            # pass 2: fetch the rank-increasing completions
            for bl in ready:
                if basis.spans() or not basis.admits(bl.vec):
                    continue
                try:
                    bl.out = np.asarray(bl.out)
                    g_devprof.account_d2h(sites[0], bl.out.nbytes)
                    basis.add(bl.vec)
                    chosen.append(bl)
                except RuntimeError:
                    bl.erased = True
                    if bl.chip not in counted_chips:
                        counted_chips.add(bl.chip)
                        pc.inc(l_rl_chip_failures)

        # ---- phase 1: poll until the completed blocks span ----------------
        while True:
            sweep()
            if basis.spans() or not pending:
                break
            time.sleep(ChipStat.PROBE_POLL_S)
        if not basis.spans():
            pc.inc(l_rl_insufficient)
            if probe:
                g_chipstat.record_deltas(dict(chip_done_us))
            raise self.Insufficient(
                f"{basis.rank}/{n_sys} independent blocks from "
                f"surviving chips")
        # a subset completion is any flush that did not need every
        # coded block it assigned: blocks still in flight at spanning
        # (the straggler case) OR blocks erased outright (the dead-chip
        # case — whose survivors may well all be done by now)
        subset = bool(pending) or any(bl.erased for bl in blocks)
        if subset:
            pc.inc(l_rl_subset_completions)
        pc.inc(l_rl_wasted_blocks,
               sum(1 for bl in blocks if not bl.erased
                   and bl not in chosen))
        out = self._solve(chosen, n_sys, rows, in_shape, pc, sites[2])
        # ---- phase 2 (probe flushes): finish the per-chip observation -----
        if probe:
            self._observe_stragglers(pending, suspects, delay_until,
                                     chip_done_us)
            g_chipstat.record_deltas(chip_done_us)
        return out

    def _observe_stragglers(self, pending: List[_Block],
                            suspects: Set[int],
                            delay_until: Dict[int, float],
                            chip_done_us: Dict[int, float]) -> None:
        """Bounded post-subset observation, probe flushes only: chips
        completing inside CENSOR_MARGIN × threshold × median record
        exact per-block service deltas; a NON-suspect chip whose every
        pending block has waited past that cap records a censored
        breach (its delta is provably >= the cap); suspect chips are
        never waited for — no record, stickiness by absence, clearing
        rides the exact deltas their parity block produces once they
        heal."""
        from .chipstat import g_chipstat

        if not pending:
            return
        _every, threshold = g_chipstat._opts()
        med = ChipStat._median(chip_done_us.values())
        if threshold <= 0 or med <= 0:
            return
        cap_us = CENSOR_MARGIN * threshold * med
        while True:
            for bl in list(pending):
                now = time.perf_counter()
                if self._block_ready(bl, now, delay_until):
                    bl.t_ready = now
                    pending.remove(bl)
                    if all(p.chip != bl.chip for p in pending):
                        chip_done_us.setdefault(
                            bl.chip, bl.elapsed_us(bl.t_ready))
            waiting = {bl.chip for bl in pending} - suspects
            if not waiting:
                break
            # censor once every waiting chip's LEAST-waited pending
            # block has provably breached the cap
            now = time.perf_counter()
            floors = {chip: min(bl.elapsed_us(now) for bl in pending
                                if bl.chip == chip)
                      for chip in waiting}
            if all(v >= cap_us for v in floors.values()):
                for chip, v in floors.items():
                    chip_done_us[chip] = max(v, cap_us)
                break
            time.sleep(ChipStat.PROBE_POLL_S)

    # ---- the host twin re-solve --------------------------------------------
    @staticmethod
    def _solve(chosen: List[_Block], n_sys: int, rows: int, in_shape,
               pc, site: str = ENCODE_SITES[2]) -> np.ndarray:
        """Reassemble the (S_pad, m, Cb) coding rows from the chosen
        spanning set: present systematic blocks land directly, missing
        ones are re-solved as E = A⁻¹ Y over GF(2^8) — exact
        arithmetic, so byte-identical to the single-device call by
        construction."""
        s_pad = in_shape[0]
        m = chosen[0].out.shape[1]
        cb = chosen[0].out.shape[2]
        out = np.empty((s_pad, m, cb), dtype=np.uint8)
        present = {bl.bid for bl in chosen if bl.systematic}
        for bl in chosen:
            if bl.systematic:
                out[bl.bid * rows:(bl.bid + 1) * rows] = bl.out
        missing = [i for i in range(n_sys) if i not in present]
        if missing:
            a = np.stack([bl.vec for bl in chosen])
            inv = gf_invert_matrix(a)
            for i in missing:
                acc = None
                for b, bl in enumerate(chosen):
                    c = int(inv[i, b])
                    if c == 0:
                        continue
                    term = gf_mul_scalar(c, bl.out)
                    acc = term if acc is None else acc ^ term
                out[i * rows:(i + 1) * rows] = acc
                g_devprof.account_host_copy(site, acc.nbytes)
            pc.inc(l_rl_host_resolves, len(missing))
        return out

    # ---- introspection -----------------------------------------------------
    @staticmethod
    def dump(mesh_size: int = 0) -> Dict:
        enabled, tasks = rateless_opts()
        out: Dict = {
            "options": {"ec_mesh_rateless": enabled,
                        "ec_mesh_rateless_tasks": tasks},
            "counters": rateless_perf_counters().dump(),
        }
        if mesh_size > 1:
            n_sys, n_parity = RatelessCoder.tasks_for(mesh_size)
            out["n_sys"] = n_sys
            out["n_parity"] = n_parity
        return out
