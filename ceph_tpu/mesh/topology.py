"""Topology discovery: ONE 1-D batch-axis mesh over the addressable chips.

The dispatch mesh is deliberately one-dimensional: a flushed batch of
coalesced EC requests is (S, k, C) with stripes as the abundant axis,
so ``NamedSharding(mesh, PartitionSpec("batch"))`` over the stripe rows
spreads the whole flush across every chip with zero collectives on the
forward path (the SNIPPETS.md [2] shape).  The 2-D ``(stripe, shard)``
mesh in ``parallel/mesh.py`` stays the research surface for
column-sharded decode; the dispatch runtime wants the simplest layout
that makes "more traffic" become "more chips".

CPU smoke rides the virtual host platform
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``); discovery
falls back to it exactly like :func:`ceph_tpu.parallel.mesh.make_mesh`
when the default backend has fewer devices than requested.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

BATCH_AXIS = "batch"


def addressable_devices(n: Optional[int] = None) -> List:
    """The devices a dispatch mesh may span: whatever the default
    backend exposes, full stop.

    Requesting more than exist CLAMPS (batch_mesh) — the mesh must
    never silently relocate off an accelerator onto virtual host CPUs
    because an operator over-asked.  A multi-device CPU smoke mesh is
    an environment contract, not a runtime trick:
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` must be set
    at process start (tests/conftest.py and ``bench --smoke`` both do;
    a late ``jax_num_cpu_devices`` config flip cannot work here — the
    cpu backend is already initialized the moment the default platform
    is cpu, and the pinned jax does not expose the knob at all)."""
    import jax
    del n   # the request is a clamp bound, not a growth target
    return list(jax.devices())


def batch_mesh(n: Optional[int] = None):
    """A 1-D ``("batch",)`` mesh over *n* devices (``None``/-1 = all
    addressable).  Requests beyond what the process can see CLAMP to
    the available device count rather than raising: capacity is an
    operator knob (``ec_mesh_chips``) and a misconfigured count must
    degrade to a smaller mesh, never take the write path down."""
    from jax.sharding import Mesh
    want = None if n is None or n < 0 else max(int(n), 1)
    devices = addressable_devices(want)
    if want is not None:
        devices = devices[:want]
    return Mesh(np.array(devices), (BATCH_AXIS,))
