"""Per-chip timing telemetry — the chip-health scoreboard.

The mesh runtime (runtime.py) shards every flushed encode batch across
N chips, but until this module the observability stack stopped at the
device boundary: oplat's ``device_call`` stage is one number for the
whole mesh and ``dispatch_chip_occupancy_histogram`` counts stripes,
not microseconds — a chip running 10x slow was invisible.  The
straggler-proof rateless-coding ROADMAP item (arxiv 1804.10331) needs
exactly that signal, and this repo's discipline is
build-the-ruler-before-the-fix (devprof before zero-copy, oplat before
mesh): this is the chip-level ruler, in the spirit of per-worker
straggler detection in coded-computation systems (arxiv 2108.02692:
movement/imbalance, not math, dominates at small chunks).

Three pieces:

- **Sampled fenced probes**: every Nth mesh flush
  (``ec_mesh_skew_sample_every``; 0 = off) the runtime drains ONE
  element from each chip's shard of the coalesced output — the
  ``parallel/ec.py`` ``drain_sharded`` one-readback-per-shard trick —
  and records each chip's completion delta (launch → that chip's
  readback returning) into the 2-D ``mesh_chip_latency_histogram``
  (usec × chip_index) and the per-chip totals table.  Probe readbacks
  are devprof-accounted under the dedicated ``mesh.skew_probe`` site
  and EXCLUDED from the copy-budget gate (calibration flow, the same
  policy as drain fences — devprof.CALIBRATION_SITES).  The OSD tick
  arms a cadence floor: traffic that flushed since the last probe
  guarantees the NEXT flush probes, so a low flush rate cannot starve
  the signal.
- **Chip-health scoreboard**: an EWMA of each chip's probe delta vs
  the mesh median yields the REPORTED per-chip skew ratio; the
  sustain/clear streaks count each probe's INSTANTANEOUS delta vs
  that probe's median (one spiked probe can never ride a decaying
  EWMA through the sustain window).  A chip breaching
  ``ec_mesh_skew_threshold`` on ``SKEW_SUSTAIN_PROBES`` consecutive
  probes is marked SUSPECT, and clears only after
  ``SKEW_CLEAR_PROBES`` consecutive clean probes — the circuit
  breaker's sustain/clear hysteresis discipline applied to chip
  health.  Surfaces: ``ceph_daemon_mesh_chip_*`` counters, the
  ``mesh skew dump`` asok command, the skew block on
  ``dispatch dump``'s mesh pane, and the hysteretic ``TPU_MESH_SKEW``
  health check the mgr raises (mgr.check_mesh_skew) naming the
  suspect chip and its ratio.
- **The straggler ruler**: the ``ec_mesh_skew`` bench workload runs
  the mesh twin healthy vs one-chip-slowed (fault site
  ``mesh.chip_slowdown``) and bench/regress.py's SKEW GATE asserts
  detection fires within K probes while the healthy run stays quiet —
  the acceptance instrument the rateless straggler PR is gated on.

Probing never changes the data path: the drained elements come from
the same coalesced output the flush materializes anyway, so mesh-on
clusters with sampling enabled stay byte-exact (property-tested).
CPU-smoke caveat: the 8 virtual host devices share one core, so
healthy-run skew there is calibration only — the real spread is a
live-TPU capture (ROADMAP backlog).
"""
from __future__ import annotations

import time

from ..common.lockdep import DebugLock
from typing import Any, Dict, List, Optional

from ..common.config import g_conf
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.histogram import (PerfHistogramAxis, SCALE_LINEAR,
                               SCALE_LOG2, g_perf_histograms,
                               percentiles_from_counts)
from ..trace.journal import g_journal

# hysteresis discipline (the breaker's sustain/clear shape): a chip
# must breach the threshold on this many CONSECUTIVE probes to be
# marked suspect, and produce this many consecutive clean probes to
# clear — a single slow probe (GC pause, tunnel hiccup) never flaps it
SKEW_SUSTAIN_PROBES = 3
SKEW_CLEAR_PROBES = 3

# EWMA smoothing for per-chip service time: responsive enough that a
# genuinely slow chip dominates its average within the sustain window,
# smooth enough that one outlier probe cannot breach alone
EWMA_ALPHA = 0.4

# ---- perf counters (perf dump / Prometheus ceph_daemon_mesh_chip_*) --------
MESH_CHIP_FIRST = 99000
l_chip_probes = 99001            # probe flushes executed
l_chip_samples = 99002           # per-chip completion deltas recorded
l_chip_slowdowns_injected = 99003  # mesh.chip_slowdown fires observed
l_chip_suspects_marked = 99004   # chips marked suspect (sustained skew)
l_chip_suspects_cleared = 99005  # suspects cleared (sustained clean)
l_chip_suspect_chips = 99006     # gauge: chips currently suspect
l_chip_max_skew_permille = 99007  # gauge: worst chip EWMA/median, 1/1000
MESH_CHIP_LAST = 99010

_chip_pc: Optional[PerfCounters] = None
_chip_pc_lock = DebugLock("mesh_chip_pc::init")


def mesh_chip_perf_counters() -> PerfCounters:
    """The chip-health scoreboard's counter logger (perf dump /
    Prometheus ``ceph_daemon_mesh_chip_*``)."""
    global _chip_pc
    if _chip_pc is not None:
        return _chip_pc
    with _chip_pc_lock:
        if _chip_pc is None:
            b = PerfCountersBuilder("mesh_chip", MESH_CHIP_FIRST,
                                    MESH_CHIP_LAST)
            b.add_u64_counter(l_chip_probes, "probes",
                              "sampled fenced skew probes executed")
            b.add_u64_counter(l_chip_samples, "samples",
                              "per-chip completion deltas recorded")
            b.add_u64_counter(l_chip_slowdowns_injected,
                              "slowdowns_injected",
                              "mesh.chip_slowdown fault fires observed "
                              "during probes")
            b.add_u64_counter(l_chip_suspects_marked, "suspects_marked",
                              "chips marked suspect after sustained "
                              "skew over the threshold")
            b.add_u64_counter(l_chip_suspects_cleared,
                              "suspects_cleared",
                              "suspect chips cleared after sustained "
                              "clean probes")
            b.add_u64(l_chip_suspect_chips, "suspect_chips",
                      "chips currently marked suspect (gauge)")
            b.add_u64(l_chip_max_skew_permille, "max_skew_permille",
                      "worst per-chip EWMA/median skew ratio in "
                      "thousandths (gauge)")
            _chip_pc = b.create_perf_counters()
    return _chip_pc


def chip_latency_axes() -> List[PerfHistogramAxis]:
    """2-D per-chip probe latency: axis 0 = the chip's completion
    delta in usec (log2 — the ``_usec`` suffix makes the mgr renderer
    export the edges scaled to seconds like every latency family),
    axis 1 = the chip's index in the mesh (linear unit buckets,
    dimensionless name so the renderer exports RAW edges — the
    chip-occupancy axis convention)."""
    return [PerfHistogramAxis("probe_usec", min=0, quant_size=2,
                              buckets=32, scale_type=SCALE_LOG2),
            PerfHistogramAxis("chip_index", min=0, quant_size=1,
                              buckets=66, scale_type=SCALE_LINEAR)]


def slowdown_delays(n_chips: int) -> Dict[int, float]:
    """THE ``mesh.chip_slowdown`` decision pass, shared by the SPMD
    probe (ChipStat.probe) and the rateless drain (rateless.py) so
    the ctx format (``chip=<i>/<n>`` — what ``match=`` scopes) and
    the ``slowdowns_injected`` accounting cannot drift: one decision
    per chip per probe/flush, before the clock starts, returning
    chip index -> hold-not-complete-for microseconds."""
    from ..fault import g_faults
    delay_until: Dict[int, float] = {}
    if g_faults.site_armed("mesh.chip_slowdown"):
        spec = g_faults.armed("mesh.chip_slowdown")
        delay_us = spec.delay_us if spec is not None else 0
        pc = mesh_chip_perf_counters()
        for i in range(n_chips):
            if g_faults.should_fire("mesh.chip_slowdown",
                                    ctx=f"chip={i}/{n_chips}"):
                pc.inc(l_chip_slowdowns_injected)
                delay_until[i] = delay_us
    return delay_until


class ChipStat:
    """Per-chip probe recorder + hysteretic skew scoreboard."""

    def __init__(self):
        self._lock = DebugLock("ChipStat::lock")
        self._flushes = 0            # mesh flushes seen (probe cadence)
        self._probes = 0             # probe flushes executed
        self._flushes_since_probe = 0
        self._force_probe = False    # OSD-tick cadence floor
        # chip index -> scoreboard row
        self._chips: Dict[int, Dict[str, Any]] = {}
        # chip index -> per-axis0-bucket counts (per-chip percentiles;
        # the 2-D histogram grid serves the export surfaces)
        self._buckets: Dict[int, List[int]] = {}
        self._axis0 = chip_latency_axes()[0]

    # ---- options (read live so `config set` applies without restart) ------
    @staticmethod
    def _opts() -> tuple:
        return (int(g_conf.get_val("ec_mesh_skew_sample_every") or 0),
                float(g_conf.get_val("ec_mesh_skew_threshold") or 0.0))

    @property
    def _hist(self):
        return g_perf_histograms.get("mesh",
                                     "mesh_chip_latency_histogram",
                                     chip_latency_axes)

    # ---- probe cadence -----------------------------------------------------
    def should_probe(self) -> bool:
        """Called once per mesh flush by the runtime: True when this
        flush should drain per-chip probes.  Cadence is every Nth
        flush (``ec_mesh_skew_sample_every``; 0 = off) plus the OSD
        tick's cadence floor (``tick_kick``)."""
        every, _thr = self._opts()
        with self._lock:
            self._flushes += 1
            if every <= 0:
                self._force_probe = False
                self._flushes_since_probe += 1
                return False
            if self._force_probe or self._flushes % every == 0:
                self._force_probe = False
                return True
            self._flushes_since_probe += 1
            return False

    def tick_kick(self) -> None:
        """The OSD tick's probe-cadence floor: when sampling is on and
        traffic has flushed since the last probe, arm the NEXT flush
        to probe regardless of the Nth-flush counter — a low flush
        rate (long windows, quiet cluster) must not starve the skew
        signal.  Pure int reads; zero cost with sampling off."""
        every, _thr = self._opts()
        if every <= 0:
            return
        with self._lock:
            if self._flushes_since_probe > 0:
                self._force_probe = True

    # ---- the probe itself --------------------------------------------------
    # polling granularity for the readiness loop: coarse enough that a
    # probe costs microseconds of host time, fine next to the 10x-class
    # deltas the scoreboard exists to catch
    PROBE_POLL_S = 1e-4

    def probe(self, out, mesh) -> None:
        """Drain one element from every chip's shard of *out* (the
        coalesced sharded output, pre-materialization) and record each
        chip's completion delta.  The readback from chip i's buffer is
        the only proof chip i finished (drain_sharded's contract), but
        a fixed-order drain would charge a straggler's stall to every
        chip drained after it — so the probe POLLS readiness
        (``Array.is_ready``, non-blocking) and reads each shard back
        the moment it completes: the delta is launch-to-THAT-chip's
        completion, order-free.  Each tiny fetch is accounted under
        the ``mesh.skew_probe`` devprof site — a CALIBRATION site the
        copy-budget gate excludes, like the bench drain fences.

        The ``mesh.chip_slowdown`` fault site fires here, scoped by
        ``match=`` on the ``chip=<i>/<n>`` context: an armed trigger
        holds the matching chip "not complete" for ``delay_us`` past
        launch (the probe — and the flush behind it — genuinely waits),
        simulating a straggling chip for the skew workload and tests.
        Injection is probe-observed by design: this PR builds the
        ruler, not the fix."""
        import numpy as np
        from ..trace.devprof import g_devprof

        shards = getattr(out, "addressable_shards", None)
        if not shards:
            return
        n_shards = len(shards)
        # one injection decision per chip per probe, before the clock
        # starts (a mid-poll re-arm must not split one probe's view)
        delay_until = slowdown_delays(n_shards)
        pending = {i: sh.data for i, sh in enumerate(shards)}
        deltas: Dict[int, float] = {}
        t0 = time.perf_counter()
        while pending:
            elapsed_us = (time.perf_counter() - t0) * 1e6
            for i in sorted(pending):
                if elapsed_us < delay_until.get(i, 0.0):
                    continue    # injected straggler: not complete yet
                piece = pending[i]
                ready = getattr(piece, "is_ready", None)
                if ready is not None and not ready():
                    continue
                try:
                    one = piece.ravel()[:1]
                except Exception:
                    one = piece
                np.asarray(one)   # THE fence: chip i's d2h readback
                g_devprof.account_d2h("mesh.skew_probe", 1)
                deltas[i] = (time.perf_counter() - t0) * 1e6
                del pending[i]
            if pending:
                time.sleep(self.PROBE_POLL_S)
        self._record(deltas)

    def record_deltas(self, deltas: Dict[int, float]) -> None:
        """The rateless drain's probe entry (rateless.py): on probe
        flushes the subset-completion drain measures each chip's
        completion delta itself — same scoreboard, same hysteresis,
        no separate element readbacks (the drain's fetches ARE the
        data path).  Censoring policy lives in the drain: a recorded
        delta is either exact or provably-at-least (never fabricated),
        so the sustain/clear semantics are unchanged."""
        self._record(deltas)

    def suspect_set(self) -> set:
        """Chip indices currently marked suspect — the placement
        feedback the rateless coder deweights by (cheap locked read,
        once per flush)."""
        with self._lock:
            return {i for i, r in self._chips.items() if r["suspect"]}

    def _record(self, deltas: Dict[int, float]) -> None:
        every, threshold = self._opts()
        pc = mesh_chip_perf_counters()
        pc.inc(l_chip_probes)
        pc.inc(l_chip_samples, len(deltas))
        hist = self._hist
        with self._lock:
            self._probes += 1
            self._flushes_since_probe = 0
            probe_seq = self._probes
            for i, usec in deltas.items():
                hist.inc(usec, i)
                row = self._chips.get(i)
                if row is None:
                    row = self._chips[i] = {
                        "probes": 0, "total_usec": 0.0,
                        "last_usec": 0.0, "ewma_usec": 0.0,
                        "skew_ratio": 0.0, "suspect": False,
                        "streak": 0, "clean": 0,
                        "suspect_since_probe": 0}
                row["probes"] += 1
                row["total_usec"] += usec
                row["last_usec"] = round(usec, 1)
                row["ewma_usec"] = usec if row["probes"] == 1 else (
                    EWMA_ALPHA * usec
                    + (1.0 - EWMA_ALPHA) * row["ewma_usec"])
                b = self._axis0.bucket_for(usec)
                counts = self._buckets.get(i)
                if counts is None:
                    counts = self._buckets[i] = \
                        [0] * self._axis0.buckets
                counts[b] += 1
            self._score(probe_seq, threshold, pc, deltas)

    @staticmethod
    def _median(values) -> float:
        vs = sorted(values)
        n = len(vs)
        if not n:
            return 0.0
        return vs[n // 2] if n % 2 \
            else 0.5 * (vs[n // 2 - 1] + vs[n // 2])

    def _score(self, probe_seq: int, threshold: float, pc,
               deltas: Dict[int, float]) -> None:
        """One scoreboard pass (caller holds the lock).

        TWO ratios, two jobs: the REPORTED ``skew_ratio`` is the
        chip's EWMA service time over the mesh's EWMA median (the
        smoothed figure the health check and dumps name); the
        sustain/clear STREAKS count THIS probe's instantaneous delta
        over this probe's median — one spiked probe breaches exactly
        one streak tick and resets on the next clean probe, it can
        never ride a decaying EWMA through the sustain window (the
        breaker's consecutive-failures discipline, counted in
        probes)."""
        rows = [r for r in self._chips.values() if r["probes"] > 0]
        if len(rows) < 2:
            return
        ewma_median = self._median(r["ewma_usec"] for r in rows)
        inst_median = self._median(deltas.values())
        if ewma_median <= 0 or inst_median <= 0:
            return
        worst = 0.0
        for i, row in self._chips.items():
            ratio = row["ewma_usec"] / ewma_median
            row["skew_ratio"] = round(ratio, 3)
            worst = max(worst, ratio)
            if threshold <= 0 or i not in deltas:
                continue
            if deltas[i] / inst_median >= threshold:
                row["streak"] += 1
                row["clean"] = 0
            else:
                row["streak"] = 0
                row["clean"] += 1
            if not row["suspect"] \
                    and row["streak"] >= SKEW_SUSTAIN_PROBES:
                row["suspect"] = True
                row["suspect_since_probe"] = probe_seq
                pc.inc(l_chip_suspects_marked)
                # journal emit takes only the journal's own lock
                # (ChipStat::lock -> EventJournal::lock is the one
                # nesting this module introduces)
                g_journal.emit("mesh", "chip_suspect_mark", chip=i,
                               probe=probe_seq,
                               skew_ratio=row["skew_ratio"])
            elif row["suspect"] and row["clean"] >= SKEW_CLEAR_PROBES:
                row["suspect"] = False
                row["suspect_since_probe"] = 0
                pc.inc(l_chip_suspects_cleared)
                g_journal.emit("mesh", "chip_suspect_clear", chip=i,
                               probe=probe_seq)
        pc.set(l_chip_suspect_chips,
               sum(1 for r in self._chips.values() if r["suspect"]))
        pc.set(l_chip_max_skew_permille, int(worst * 1000))

    # ---- views -------------------------------------------------------------
    def suspects(self) -> List[Dict[str, Any]]:
        """Chips currently marked suspect, worst first — the mgr's
        TPU_MESH_SKEW source and the tpu status pane."""
        with self._lock:
            out = [{"chip": i, "skew_ratio": r["skew_ratio"],
                    "ewma_usec": round(r["ewma_usec"], 1),
                    "since_probe": r["suspect_since_probe"]}
                   for i, r in sorted(self._chips.items())
                   if r["suspect"]]
        out.sort(key=lambda s: -s["skew_ratio"])
        return out

    def per_chip_percentiles(self, qs=(0.5, 0.99)) -> Dict[int, Dict]:
        """Per-chip probe-latency percentiles from the per-chip bucket
        series (same edges as the 2-D histogram's usec axis) — the
        p99-spread figure the skew workload reports."""
        edges = self._axis0.upper_edges()
        with self._lock:
            snap = {i: list(c) for i, c in self._buckets.items()}
        return {i: percentiles_from_counts(c, edges, qs)
                for i, c in sorted(snap.items())}

    def summary(self) -> Dict[str, Any]:
        """The compact scoreboard block (``dispatch dump``'s mesh pane
        and ``tpu status``): options, probe counts, per-chip EWMA /
        ratio / suspect rows, current suspects."""
        every, threshold = self._opts()
        with self._lock:
            per_chip = {
                i: {"probes": r["probes"],
                    "last_usec": r["last_usec"],
                    "ewma_usec": round(r["ewma_usec"], 1),
                    "skew_ratio": r["skew_ratio"],
                    "suspect": r["suspect"]}
                for i, r in sorted(self._chips.items())}
            flushes, probes = self._flushes, self._probes
        return {
            "options": {"ec_mesh_skew_sample_every": every,
                        "ec_mesh_skew_threshold": threshold},
            "sustain_probes": SKEW_SUSTAIN_PROBES,
            "clear_probes": SKEW_CLEAR_PROBES,
            "flushes": flushes,
            "probes": probes,
            "per_chip": per_chip,
            "suspects": self.suspects(),
        }

    def dump(self) -> Dict[str, Any]:
        """The ``mesh skew dump`` admin-socket shape: the summary plus
        per-chip percentiles and the counter logger."""
        out = self.summary()
        out["per_chip_percentiles"] = {
            str(i): p for i, p in self.per_chip_percentiles().items()}
        out["counters"] = mesh_chip_perf_counters().dump()
        return out

    def reset(self) -> None:
        """``mesh skew reset``: drop the scoreboard, the per-chip
        series, the 2-D histogram and the counter logger (probe
        cadence restarts too)."""
        with self._lock:
            self._flushes = 0
            self._probes = 0
            self._flushes_since_probe = 0
            self._force_probe = False
            self._chips.clear()
            self._buckets.clear()
        self._hist.reset()
        pc = mesh_chip_perf_counters()
        for idx in range(MESH_CHIP_FIRST + 1, MESH_CHIP_LAST):
            try:
                pc.set(idx, 0)
            except (KeyError, AssertionError):
                pass


# process-wide scoreboard, like g_mesh: one accelerator complex per
# process, shared by every daemon the mini-cluster hosts
g_chipstat = ChipStat()
