"""ceph_tpu.chaos — seeded composed-chaos scenario engine.

Deterministic multi-fault storylines sampled over the cluster's
primitive inventory (fault sites, topology events, the abusive-client
dial, elastic mesh membership, controller flips), executed on a
ticking MiniCluster under open-loop harness traffic and judged against
the UNIVERSAL acceptance: byte-exact ops, raise-and-clear health, a
finalized incident bundle that tells the storyline back, zero wedges.
See docs/CHAOS.md.
"""
from .engine import (CHECK_CHAINS, chaos_perf_counters, run_scenario,
                     run_seed)
from .engine import dump as engine_dump
from .scenario import (BASE_MESH_CHIPS, LEG_BUILDERS, ScenarioEvent,
                       ScenarioSpec, compose_scenario, leg_names)

__all__ = [
    "BASE_MESH_CHIPS", "CHECK_CHAINS", "LEG_BUILDERS", "ScenarioEvent",
    "ScenarioSpec", "chaos_perf_counters", "compose_scenario",
    "engine_dump", "leg_names", "run_scenario", "run_seed",
]
