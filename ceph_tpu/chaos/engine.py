"""Composed-chaos execution engine + the universal acceptance oracle.

``run_scenario(spec)`` executes one :class:`ScenarioSpec` end to end:

1. **base knobs** — an 8-chip mesh with the rateless coder on, the
   skew scoreboard probing every flush, the SLO controller live, and a
   journal ring / incident timeline long enough that the whole
   storyline stays in the black box;
2. **compile** — the declarative schedule becomes ``TrafficSpec``
   machinery: osd/membership steps ride ``TrafficSpec.events`` (the
   first-class topology events), fault arm/clear and conf flips become
   ``TrafficSpec.hooks`` (each fire journals a ``chaos_event``, so the
   executed storyline is itself on the timeline);
3. **run** — open-loop harness traffic over a real EC pool on a
   ticking MiniCluster; every read byte-verifies against the client's
   committed payload;
4. **settle** — synthetic oracle flushes + ticks on the cluster clock
   until every expected health check RAISED (the phased clears for
   hysteretic checks disarm only after detection), then until every
   raise CLEARED, bounded by ``chaos_settle_ticks_max`` (budget
   exhausted = WEDGED, an acceptance failure, never a hang);
5. **judge** — the UNIVERSAL acceptance: every op byte-exact, every
   expected check raised AND cleared, zero wedges, and every raise
   yields a finalized incident bundle whose gseq-ordered timeline
   tells the injected storyline back (the hand-built twin of this
   oracle is pinned in tests/test_incident.py).

Everything runs on the deterministic cluster clock (harness rounds +
``cluster.tick``); wall time appears only inside measured latencies.
This module imports numpy for the settle-phase oracle flushes but
never jax — composing and judging are host work (the fence-count
extension in tests/test_observability.py pins zero device syncs).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common.config import g_conf
from ..common.lockdep import DebugLock
from ..common.perf_counters import PerfCounters, PerfCountersBuilder
from ..trace.journal import g_journal
from .scenario import BASE_MESH_CHIPS, ScenarioSpec, compose_scenario

# ---- perf counters (perf dump / Prometheus ceph_daemon_chaos_*) ------------
CHAOS_FIRST = 90100
l_chaos_scenarios = 90101      # storylines executed end to end
l_chaos_legs = 90102           # legs across all executed storylines
l_chaos_events = 90103         # scheduled storyline steps fired
l_chaos_faults_armed = 90104   # fault arms performed by storylines
l_chaos_faults_cleared = 90105  # fault clears performed by storylines
l_chaos_checks_raised = 90106  # health raises observed under storylines
l_chaos_checks_cleared = 90107  # health clears observed under storylines
l_chaos_accept_pass = 90108    # storylines that passed universal acceptance
l_chaos_accept_fail = 90109    # storylines that failed universal acceptance
l_chaos_wedges = 90110         # storylines that exhausted the settle budget
l_chaos_active = 90111         # gauge: a storyline is executing right now
CHAOS_LAST = 90120

_chaos_pc: Optional[PerfCounters] = None
_chaos_pc_lock = DebugLock("chaos_pc::init")


def chaos_perf_counters() -> PerfCounters:
    """The scenario engine's counter logger (perf dump / Prometheus)."""
    global _chaos_pc
    if _chaos_pc is not None:
        return _chaos_pc
    with _chaos_pc_lock:
        if _chaos_pc is None:
            b = PerfCountersBuilder("chaos", CHAOS_FIRST, CHAOS_LAST)
            b.add_u64_counter(l_chaos_scenarios, "scenarios",
                              "composed storylines executed end to end")
            b.add_u64_counter(l_chaos_legs, "legs",
                              "legs across all executed storylines")
            b.add_u64_counter(l_chaos_events, "events",
                              "scheduled storyline steps fired")
            b.add_u64_counter(l_chaos_faults_armed, "faults_armed",
                              "fault arms performed by storylines")
            b.add_u64_counter(l_chaos_faults_cleared, "faults_cleared",
                              "fault clears performed by storylines")
            b.add_u64_counter(l_chaos_checks_raised, "checks_raised",
                              "health raises observed under storylines")
            b.add_u64_counter(l_chaos_checks_cleared, "checks_cleared",
                              "health clears observed under storylines")
            b.add_u64_counter(l_chaos_accept_pass, "accept_pass",
                              "storylines that passed the universal "
                              "acceptance")
            b.add_u64_counter(l_chaos_accept_fail, "accept_fail",
                              "storylines that failed the universal "
                              "acceptance")
            b.add_u64_counter(l_chaos_wedges, "wedges",
                              "storylines that exhausted the settle "
                              "budget")
            b.add_u64(l_chaos_active, "active",
                      "a storyline is executing right now")
            _chaos_pc = b.create_perf_counters()
    return _chaos_pc


# conf the engine pins for a run and restores after (mirrors the
# hand-built twin's TOUCHED list in tests/test_incident.py)
TOUCHED = (
    "ec_mesh_chips", "ec_mesh_rateless", "ec_mesh_rateless_tasks",
    "ec_mesh_skew_sample_every", "ec_mesh_skew_threshold",
    "ec_dispatch_batch_max", "ec_dispatch_batch_window_us",
    "mgr_control_enable", "mgr_control_cooldown_ticks",
    "mgr_incident_timeline_tail", "mgr_journal_ring_size",
)

# per-check causal chains the finalized bundle must tell back, in
# strictly increasing gseq order (the storyline-told oracle)
CHECK_CHAINS: Dict[str, Tuple[Tuple[str, Dict[str, Any]], ...]] = {
    "TPU_MESH_SKEW": (
        ("fault_fire", {"site": "mesh.chip_slowdown"}),
        ("chip_suspect_mark", {}),
        ("health_raise", {"check": "TPU_MESH_SKEW"}),
        ("health_clear", {"check": "TPU_MESH_SKEW"}),
    ),
}


def _compile(spec: ScenarioSpec, pool: str, n_clients: int,
             ops_per_client: int, rate: float):
    """Declarative schedule -> TrafficSpec: topology/membership steps
    become first-class ``events``, fault and conf steps become
    ``hooks`` (each fire journals a chaos_event so the executed
    storyline rides the same causally-ordered timeline it is judged
    against)."""
    from ..fault import g_faults
    from ..load import TrafficSpec
    events: List[Tuple[int, str, int]] = []
    hooks: List[Tuple[int, Callable]] = []
    pc = chaos_perf_counters()
    for ev in spec.events:
        d = dict(ev.detail)
        if ev.action in ("osd_kill", "osd_down", "osd_out",
                         "osd_revive", "osd_in"):
            events.append((ev.round, ev.action, int(d["osd"])))
        elif ev.action in ("mesh_chip_add", "mesh_chip_retire"):
            events.append((ev.round, ev.action, int(d["chips"])))
        elif ev.action == "fault_arm":
            def arm(cluster, d=d, rnd=ev.round):
                kw = {k: v for k, v in d.items() if k != "site"}
                g_journal.emit("chaos", "chaos_event", step="fault_arm",
                               site=d["site"], round=rnd)
                g_faults.inject(d["site"], **kw)
                pc.inc(l_chaos_events)
                pc.inc(l_chaos_faults_armed)
            hooks.append((ev.round, arm))
        elif ev.action == "fault_clear":
            def clear(cluster, d=d, rnd=ev.round):
                g_journal.emit("chaos", "chaos_event",
                               step="fault_clear", site=d["site"],
                               round=rnd)
                g_faults.clear(d["site"])
                pc.inc(l_chaos_events)
                pc.inc(l_chaos_faults_cleared)
            hooks.append((ev.round, clear))
        elif ev.action == "conf_set":
            def flip(cluster, d=d, rnd=ev.round):
                g_journal.emit("chaos", "chaos_event", step="conf_set",
                               option=d["option"], value=d["value"],
                               round=rnd)
                g_conf.set_checked(d["option"], d["value"])
                pc.inc(l_chaos_events)
            hooks.append((ev.round, flip))
        elif ev.action == "traffic_abuse":
            pass        # compose-time traffic shape (rate_multipliers)
        else:
            raise ValueError(
                f"unknown storyline action '{ev.action}'")
    return TrafficSpec(
        pool=pool, n_clients=n_clients, ops_per_client=ops_per_client,
        read_fraction=0.4, keys_per_client=8, mode="open", rate=rate,
        rate_multipliers=spec.rate_multipliers, seed=spec.seed,
        tick_every=8, events=tuple(events), hooks=tuple(hooks))


def _oracle_flush_fn():
    """A synthetic byte-exact flush for the settle phase: every call
    submits payloads through the dispatch/mesh path and compares the
    coding against the pure host oracle — the same per-flush receipt
    the hand-built twin uses, so settling doubles as a byte-exactness
    probe while the health machinery converges."""
    import numpy as np
    from ..dispatch import g_dispatcher
    from ..ec.tpu_plugin import ErasureCodeTpu
    from ..osd.ecutil import encode as eu_encode, stripe_info_t
    impl = ErasureCodeTpu()
    impl.init({"k": "4", "m": "2", "technique": "reed_sol_van"})
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(20260807)

    def flush() -> bool:
        payloads = [rng.integers(0, 256, size=2 * 4 * 1024,
                                 dtype=np.uint8) for _ in range(3)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, oracle in zip(futs, oracles):
            res = f.result()
            if sorted(res) != sorted(oracle):
                return False
        return True

    return flush


def _settle(c, spec: ScenarioSpec, gseq0: int, flush) -> Dict[str, Any]:
    """Drive oracle flushes + cluster ticks until every expected check
    raised, disarm the phased clears, then until every raise cleared
    and the health board is empty — bounded by the settle budget."""
    from ..fault import g_faults
    budget = max(int(g_conf.get_val("chaos_settle_ticks_max")), 1)
    disarmed = False
    oracles_ok = True
    wedged = True
    ticks = 0
    for _ in range(budget):
        ticks += 1
        oracles_ok = flush() and oracles_ok
        c.tick(dt=1.0)
        since = g_journal.merged_since(gseq0)
        raised = {e.get("check") for e in since
                  if e["type"] == "health_raise"}
        cleared = {e.get("check") for e in since
                   if e["type"] == "health_clear"}
        if not disarmed and all(chk in raised
                                for chk in spec.expected_checks):
            for site in spec.settle_clears:
                g_journal.emit("chaos", "chaos_event",
                               step="settle_clear", site=site)
            # disarm EVERYTHING: detection happened, nothing may stay
            # armed into the clear phase (scheduled clears already
            # fired during traffic; this catches phased stragglers)
            g_faults.clear()
            disarmed = True
        if disarmed and raised <= cleared and not c.mgr.health_checks:
            wedged = False
            break
    return {"ticks": ticks, "oracles_ok": oracles_ok, "wedged": wedged,
            "disarmed": disarmed}


def _bundle_ok(c, check: str, spec: ScenarioSpec, since: List[dict],
               chain: bool = True, gseq0: int = -1) -> bool:
    """One raise's forensic receipt: a FINALIZED bundle exists for
    *check*, its timeline is strictly gseq-ordered, and (for checks
    with a pinned chain) it tells the injected storyline back in
    causal order.  A missing bundle passes only when the storyline
    armed ``mgr.incident_capture`` AND the drop was journaled."""
    listing = c.admin_socket.execute("tpu incident list")["incidents"]
    rows = [r for r in listing if r["trigger"] == check]
    if not rows:
        return (spec.tolerates_missing_bundle
                and any(e["type"] == "incident_drop" for e in since))
    b = c.admin_socket.execute(
        "tpu incident dump", {"id": str(rows[-1]["id"])})["incident"]
    if b["state"] != "resolved":
        return False
    tl = b["timeline"]
    gseqs = [e["gseq"] for e in tl]
    if gseqs != sorted(gseqs) or len(set(gseqs)) != len(gseqs):
        return False
    if chain and check in CHECK_CHAINS:
        # forward-scanning subsequence match anchored at the
        # scenario's journal watermark: each stage must be told by an
        # event AFTER the previous stage (a bundle timeline tail may
        # legitimately carry pre-scenario events of the same types)
        last = gseq0
        for etype, match in CHECK_CHAINS[check]:
            g = next((e["gseq"] for e in tl
                      if e["gseq"] > last and e["type"] == etype
                      and all(e.get(k) == v
                              for k, v in match.items())), None)
            if g is None:
                return False
            last = g
    return True


def _acceptance(c, spec: ScenarioSpec, res, settle: Dict[str, Any],
                gseq0: int, fallbacks0: int) -> Dict[str, Any]:
    """The universal acceptance judgment — one receipt per storyline."""
    from ..mesh.runtime import l_mesh_fallbacks, mesh_perf_counters
    pc = chaos_perf_counters()
    since = g_journal.merged_since(gseq0)
    present = {e["type"] for e in since}
    raises = [e for e in since if e["type"] == "health_raise"]
    cleared = {e.get("check") for e in since
               if e["type"] == "health_clear"}
    pc.inc(l_chaos_checks_raised, len(raises))
    pc.inc(l_chaos_checks_cleared, len(cleared))
    checks: Dict[str, Dict[str, bool]] = {}
    checks_ok = True
    for chk in spec.expected_checks:
        row = {"raised": any(e.get("check") == chk for e in raises),
               "cleared": chk in cleared,
               "bundle_ok": _bundle_ok(c, chk, spec, since,
                                       gseq0=gseq0)}
        checks[chk] = row
        checks_ok = checks_ok and all(row.values())
    # EVERY raise — expected or collateral — must clear and leave a
    # finalized bundle (or a journaled drop when capture was the leg)
    all_raises_ok = True
    for e in raises:
        chk = e.get("check")
        if chk not in cleared or not _bundle_ok(c, chk, spec, since,
                                                chain=False):
            all_raises_ok = False
    storyline_ok = all(t in present for t in spec.journal_expect)
    byte_exact = bool(res.byte_exact) and settle["oracles_ok"]
    wedged = settle["wedged"] or res.rounds >= res.spec.max_rounds
    if wedged:
        pc.inc(l_chaos_wedges)
    accepted = (byte_exact and not wedged and checks_ok
                and all_raises_ok and storyline_ok)
    listing = c.admin_socket.execute("tpu incident list")
    return {
        "seed": spec.seed,
        "legs": list(spec.legs),
        "accepted": accepted,
        "byte_exact": byte_exact,
        "wedged": wedged,
        "checks": checks,
        "all_raises_resolved": all_raises_ok,
        "storyline_told": storyline_ok,
        "rounds": res.rounds,
        "ops_completed": res.completed,
        "settle_ticks": settle["ticks"],
        "mesh_fallbacks": mesh_perf_counters().get(l_mesh_fallbacks)
        - fallbacks0,
        "journal_events": len(since),
        "incidents": {"captures_total": listing["captures_total"],
                      "bundles": [{"id": r["id"],
                                   "trigger": r["trigger"],
                                   "state": r["state"]}
                                  for r in listing["incidents"]]},
    }


def run_scenario(spec: ScenarioSpec, n_osds: int = 6, k: int = 3,
                 m: int = 2, n_clients: int = 6,
                 ops_per_client: int = 12, rate: float = 3.0,
                 progress=None) -> Dict[str, Any]:
    """Execute one composed storyline end to end; returns the
    universal-acceptance receipt.  Owns the cluster and every process
    singleton it touches (conf saved/restored, faults/breakers/
    dispatcher/mesh/scoreboard reset after), so scenarios compose into
    soaks without bleeding state."""
    from ..cluster import MiniCluster
    from ..dispatch import g_dispatcher
    from ..fault import g_breakers, g_faults
    from ..mesh import g_chipstat, g_mesh
    from ..mesh.runtime import l_mesh_fallbacks, mesh_perf_counters
    pc = chaos_perf_counters()
    saved = {n: g_conf.values.get(n) for n in TOUCHED}
    pc.set(l_chaos_active, 1)
    try:
        g_conf.set_val("ec_mesh_chips", BASE_MESH_CHIPS)
        g_conf.set_val("ec_mesh_rateless", True)
        g_conf.rm_val("ec_mesh_rateless_tasks")
        g_conf.set_val("ec_mesh_skew_sample_every", 1)
        g_conf.set_val("ec_mesh_skew_threshold", 3.0)
        # a non-zero window routes encodes through the coalescing +
        # mesh path (window=0 is the exact passthrough); correctness
        # never waits on the timer — result() force-flushes its queue
        g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
        g_conf.set_val("ec_dispatch_batch_max", 64)
        g_conf.set_val("mgr_incident_timeline_tail", 512)
        g_conf.set_val("mgr_journal_ring_size", 2048)
        g_faults.clear()
        g_breakers.reset()
        g_dispatcher.flush()
        g_mesh.topology()
        c = MiniCluster(n_osds=n_osds)
        c.create_ec_pool("chaos", k=k, m=m, pg_num=8)
        g_conf.set_val("mgr_control_enable", True)
        g_conf.set_val("mgr_control_cooldown_ticks", 1)
        flush = _oracle_flush_fn()
        flush()                          # compile warmup off the clock
        g_chipstat.reset()
        gseq0 = g_journal.last_gseq()
        fallbacks0 = mesh_perf_counters().get(l_mesh_fallbacks)
        pc.inc(l_chaos_scenarios)
        pc.inc(l_chaos_legs, len(spec.legs))
        g_journal.emit("chaos", "chaos_scenario_start", seed=spec.seed,
                       legs=list(spec.legs), events=len(spec.events))
        from ..load import run_traffic
        tspec = _compile(spec, "chaos", n_clients, ops_per_client,
                         rate)
        res = run_traffic(c, tspec, progress=progress)
        settle = _settle(c, spec, gseq0, flush)
        receipt = _acceptance(c, spec, res, settle, gseq0, fallbacks0)
        g_journal.emit("chaos", "chaos_scenario_end", seed=spec.seed,
                       accepted=receipt["accepted"],
                       byte_exact=receipt["byte_exact"],
                       wedged=receipt["wedged"])
        pc.inc(l_chaos_accept_pass if receipt["accepted"]
               else l_chaos_accept_fail)
        return receipt
    finally:
        pc.set(l_chaos_active, 0)
        for name, v in saved.items():
            if v is None:
                g_conf.rm_val(name)
            else:
                g_conf.set_val(name, v)
        g_faults.clear()
        g_breakers.reset()
        g_dispatcher.flush()
        g_mesh.topology()
        g_chipstat.reset()


def run_seed(seed: int, legs: Tuple[str, ...] = None,
             **kw) -> Dict[str, Any]:
    """Compose + execute in one call — the bench / asok entry point."""
    return run_scenario(compose_scenario(seed, legs=legs), **kw)


def dump() -> Dict[str, Any]:
    """`chaos dump` asok pane: the composable primitive catalog (legs
    + fault sites) and the engine counters."""
    from ..fault import g_faults
    from .scenario import leg_names
    return {
        "legs": leg_names(),
        "fault_sites": g_faults.sites(),
        "options": {
            "chaos_storyline_legs_max":
                int(g_conf.get_val("chaos_storyline_legs_max")),
            "chaos_settle_ticks_max":
                int(g_conf.get_val("chaos_settle_ticks_max")),
        },
        "counters": chaos_perf_counters().dump(),
    }
